"""End-to-end distributed tracing: spans, W3C propagation, a bounded
flight recorder — the per-RPC latency breakdown the reference gets for
free from CockroachDB SQL tracing, rebuilt for a stack where one
request crosses up to four process boundaries (shm worker -> device
owner over the seqlock ring, loopback write proxy, federation peers,
region log).

Design rules, in order:

  NEAR-ZERO COST WHEN OFF.  Tracing is active only when
  DSS_TRACE_SAMPLE > 0 or DSS_TRACE_SLOW_MS > 0.  Every seam is gated
  on one module-global bool read (`current()` returns None immediately
  when off), the same discipline as chaos.fault_point, and the
  recorder counts its buffer allocations (`dss_trace_allocs_total`) so
  the disabled path is COUNTER-VERIFIED to allocate nothing — not
  assumed to.

  ONE TRACE ID END TO END.  The trace id IS the X-Request-Id: HTTP
  hops carry W3C `traceparent` (+ X-Request-Id for humans), the shm
  ring carries the id + sampled bit in reserved slot words
  (parallel/shmring.py), and every hop echoes the id on error
  responses, so grep-by-trace works across all process logs of one
  front.

  HEAD SAMPLING + TAIL CAPTURE.  A trace is recorded when its head
  decision sampled it (deterministic in the trace id, so a propagated
  decision is consistent across processes) OR — retroactively — when
  the root span breaches DSS_TRACE_SLOW_MS: spans are buffered per
  trace until the root finishes, then kept or dropped.  The p99
  breaches you are hunting are exactly the traces you keep.

  BOUNDED EVERYTHING.  Pending buffers are capped (traces and spans
  per trace), the kept-trace ring is a fixed-size flight recorder
  (DSS_TRACE_RING), and every drop is counted — the
  DssTraceRecorderSaturated alert reads those counters.

Span starts are wall-clock ns (so trees from different processes line
up on one axis); durations are measured with the caller's own timer.
The span-tree JSON is served from the worker-local
`/aux/v1/debug/traces` endpoint (api/app.py).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TraceContext",
    "SpanHandle",
    "TraceRecorder",
    "configure",
    "env_config",
    "enabled",
    "parse_traceparent",
    "format_traceparent",
    "trace_id_from_request_id",
    "new_trace",
    "current",
    "use",
    "span",
    "add_span",
    "finish_root",
    "propagation_headers",
    "begin_collect",
    "end_collect",
    "recorder",
    "stats",
    "OWNER_SLOTS",
    "owner_slot_vector",
]

# The fixed owner-side span vocabulary carried back across the shm
# ring as 8 reserved response words (duration ns per slot, see
# parallel/shmring.py): the owner cannot ship arbitrary span names
# through fixed-layout slots, so the names ARE the indices.  Order is
# wire format — append only.
OWNER_SLOTS = (
    "owner.queue_wait",   # slot claim -> serve thread pickup
    "admission",          # coalescer admission gate
    "cache.lookup",       # owner-side read-cache consult
    "plan",               # planner decision
    "device.dispatch",    # fused submit (+ wait) — the chaos seam
    "collect",            # device wait + decode + overlay merge
    "host.scan",          # forced/auto host route scan
    "owner.serve",        # whole serve_fn envelope
)
_OWNER_SLOT_INDEX = {n: i for i, n in enumerate(OWNER_SLOTS)}


# -- configuration -----------------------------------------------------------

def _env(name: str, default, conv):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return conv(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid {conv.__name__}"
        )


def _env_float(name: str, default: float) -> float:
    return _env(name, default, float)


def _env_int(name: str, default: int) -> int:
    return _env(name, default, int)


def env_config() -> dict:
    """The DSS_TRACE_* knob surface (docs/OPERATIONS.md)."""
    return {
        "sample": _env_float("DSS_TRACE_SAMPLE", 0.0),
        "slow_ms": _env_float("DSS_TRACE_SLOW_MS", 0.0),
        "ring": _env_int("DSS_TRACE_RING", 256),
        "max_spans": _env_int("DSS_TRACE_MAX_SPANS", 256),
        "max_pending": _env_int("DSS_TRACE_MAX_PENDING", 1024),
    }


_SAMPLE = 0.0
_SLOW_MS = 0.0
_ENABLED = False  # mirror of (sample > 0 or slow_ms > 0): the one gate

_tls = threading.local()


class TraceContext:
    """One request's trace identity: the 32-hex trace id (also the
    X-Request-Id), the root span id, the head-sampling decision, and
    whether spans should be recorded at all (sampled, or armed for
    tail capture)."""

    __slots__ = ("trace_id", "root_span_id", "sampled", "recording",
                 "start_ns")

    def __init__(self, trace_id: str, root_span_id: str, sampled: bool,
                 recording: bool, start_ns: int):
        self.trace_id = trace_id
        self.root_span_id = root_span_id
        self.sampled = sampled
        self.recording = recording
        self.start_ns = start_ns


class SpanHandle:
    """What `current()` hands a cross-thread consumer: the context plus
    the span id that was active at capture time — child spans recorded
    through the handle parent there, so a coalescer batch span lands
    under the request's service span, not floating at the root."""

    __slots__ = ("ctx", "span_id")

    def __init__(self, ctx: TraceContext, span_id: str):
        self.ctx = ctx
        self.span_id = span_id


# span ids: cheap per-process counter over a random 64-bit base (no
# per-span entropy draw on the hot path)
_sid_lock = threading.Lock()
_sid_next = random.getrandbits(63) | 1


def _next_span_id() -> str:
    global _sid_next
    with _sid_lock:
        _sid_next = (_sid_next + 1) & ((1 << 64) - 1) or 1
        return format(_sid_next, "016x")


# -- W3C traceparent ---------------------------------------------------------

_HEX = set("0123456789abcdef")


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(value) -> Optional[Tuple[str, str, bool]]:
    """-> (trace_id, parent_span_id, sampled) or None for anything
    malformed.  Strict W3C: version-ff rejected, all-zero ids
    rejected, exact field widths."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid, flags = parts[0], parts[1], parts[2], parts[3]
    if len(ver) != 2 or not _is_hex(ver) or ver == "ff":
        return None
    if ver == "00" and len(parts) != 4:
        return None
    if len(tid) != 32 or not _is_hex(tid) or tid == "0" * 32:
        return None
    if len(sid) != 16 or not _is_hex(sid) or sid == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return tid, sid, bool(int(flags, 16) & 1)


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def trace_id_from_request_id(rid: str) -> str:
    """Coerce a legacy X-Request-Id into a 32-hex trace id: hex ids
    are zero-padded/truncated (so the id stays greppable across logs
    that saw the original), anything else is hashed."""
    s = (rid or "").strip().lower().replace("-", "")
    if _is_hex(s) and s != "":
        s = s[:32].rjust(32, "0")
        if s != "0" * 32:
            return s
    # stable digest of the opaque id
    import hashlib

    return hashlib.sha1((rid or "").encode()).hexdigest()[:32]


def _mint_trace_id() -> str:
    tid = format(random.getrandbits(128), "032x")
    return tid if tid != "0" * 32 else _mint_trace_id()


def _head_sampled(trace_id: str) -> bool:
    """Deterministic in the trace id: every process of the front makes
    the same decision for the same id, so a propagated trace never
    records on one hop and drops on the next."""
    if _SAMPLE <= 0.0:
        return False
    if _SAMPLE >= 1.0:
        return True
    return (int(trace_id[-8:], 16) / float(1 << 32)) < _SAMPLE


# -- the flight recorder -----------------------------------------------------

# span tuple layout (kept tiny; dict trees are built only for KEPT
# traces): (span_id, parent_id, name, start_ns, dur_ms, attrs|None)


class TraceRecorder:
    """Bounded per-process recorder: pending span buffers per live
    trace, a fixed-capacity ring of kept traces, and counters for
    every allocation and drop (the zero-alloc-when-disabled and
    saturation assertions read these)."""

    def __init__(self, capacity: int = 256, max_spans: int = 256,
                 max_pending: int = 1024):
        self.capacity = max(1, int(capacity))
        self.max_spans = max(8, int(max_spans))
        self.max_pending = max(4, int(max_pending))
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, List[tuple]]" = OrderedDict()
        self._ring: deque = deque(maxlen=self.capacity)
        # counters (monotonic; exported as dss_trace_* in /metrics)
        self.allocs = 0          # pending buffers created — THE zero-
        #                          alloc-when-disabled assertion target
        self.started = 0
        self.kept_sampled = 0
        self.kept_slow = 0
        self.dropped_fast = 0    # finished unsampled, under the bound
        self.dropped_pending = 0  # pending cap hit: trace untracked
        self.dropped_spans = 0   # per-trace span cap hit
        self.evicted = 0         # ring evictions (oldest kept trace)

    def begin(self, trace_id: str) -> bool:
        """Start buffering a trace.  False when the pending cap is hit
        — the trace still propagates, it just cannot be recorded here
        (counted, alert-visible)."""
        with self._lock:
            self.started += 1
            if trace_id in self._pending:
                return True
            if len(self._pending) >= self.max_pending:
                self.dropped_pending += 1
                return False
            self._pending[trace_id] = []
            self.allocs += 1
            return True

    def add(self, trace_id: str, span: tuple) -> None:
        with self._lock:
            buf = self._pending.get(trace_id)
            if buf is None:
                return
            if len(buf) >= self.max_spans:
                self.dropped_spans += 1
                return
            buf.append(span)

    def abandon(self, trace_id: str) -> None:
        """Drop a pending trace without a keep decision (a hop that
        only collects — the shm owner — or an aborted request)."""
        with self._lock:
            self._pending.pop(trace_id, None)

    def finish(self, ctx: TraceContext, root_name: str, dur_ms: float,
               status=None, attrs: Optional[dict] = None) -> bool:
        """Root span finished: keep (sampled, or tail-captured past
        the slow bound) or drop.  -> whether the trace was kept."""
        slow = _SLOW_MS > 0.0 and dur_ms >= _SLOW_MS
        keep = ctx.sampled or slow
        with self._lock:
            spans = self._pending.pop(ctx.trace_id, None)
            if not keep:
                self.dropped_fast += 1
                return False
            if ctx.sampled:
                self.kept_sampled += 1
            if slow:
                self.kept_slow += 1
            if len(self._ring) >= self.capacity:
                self.evicted += 1
            root_attrs = dict(attrs or {})
            if status is not None:
                root_attrs["status"] = status
            root = (
                ctx.root_span_id, None, root_name, ctx.start_ns,
                round(dur_ms, 3), root_attrs or None,
            )
            self._ring.append({
                "trace_id": ctx.trace_id,
                "kept": "slow" if (slow and not ctx.sampled)
                else "sampled",
                "duration_ms": round(dur_ms, 3),
                "spans": [root] + (spans or []),
            })
        return True

    # -- introspection -------------------------------------------------------

    @staticmethod
    def _tree(entry: dict) -> dict:
        """Span tuples -> nested span tree (children under parents;
        orphans — a parent span that was dropped by the span cap —
        attach to the root)."""
        spans = entry["spans"]
        nodes = {}
        for sid, parent, name, start_ns, dur_ms, attrs in spans:
            nodes[sid] = {
                "span_id": sid,
                "name": name,
                "start_ns": int(start_ns),
                "duration_ms": dur_ms,
                **({"attrs": attrs} if attrs else {}),
                "children": [],
            }
        root_sid = spans[0][0]
        for sid, parent, *_ in spans[1:]:
            host = nodes.get(parent) if parent is not None else None
            if host is None or host is nodes[sid]:
                host = nodes[root_sid]
            host["children"].append(nodes[sid])
        for n in nodes.values():
            n["children"].sort(key=lambda c: c["start_ns"])
        return {
            "trace_id": entry["trace_id"],
            "kept": entry["kept"],
            "duration_ms": entry["duration_ms"],
            "root": nodes[root_sid],
        }

    def traces(self, limit: int = 0) -> List[dict]:
        """Kept traces as span trees, newest last."""
        with self._lock:
            entries = list(self._ring)
        if limit > 0:
            entries = entries[-limit:]
        return [self._tree(e) for e in entries]

    def find(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            for e in self._ring:
                if e["trace_id"] == trace_id:
                    return self._tree(e)
        return None

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._ring.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "dss_trace_enabled": int(_ENABLED),
                "dss_trace_sample_rate": _SAMPLE,
                "dss_trace_slow_ms": _SLOW_MS,
                "dss_trace_started_total": self.started,
                "dss_trace_kept_sampled_total": self.kept_sampled,
                "dss_trace_kept_slow_total": self.kept_slow,
                "dss_trace_dropped_total": (
                    self.dropped_pending + self.dropped_spans
                    + self.evicted
                ),
                "dss_trace_pending": len(self._pending),
                "dss_trace_ring_depth": len(self._ring),
                "dss_trace_ring_cap": self.capacity,
                "dss_trace_allocs_total": self.allocs,
            }


_RECORDER = TraceRecorder(**{
    k: v for k, v in env_config().items()
    if k in ("max_spans", "max_pending")
} | {"capacity": env_config()["ring"]})


def recorder() -> TraceRecorder:
    return _RECORDER


def stats() -> dict:
    return _RECORDER.stats()


def configure(sample: Optional[float] = None,
              slow_ms: Optional[float] = None,
              ring: Optional[int] = None,
              max_spans: Optional[int] = None,
              max_pending: Optional[int] = None) -> None:
    """Runtime/test configuration; None leaves a knob unchanged.
    Resizing the ring replaces the recorder's deque (kept traces
    survive up to the new capacity)."""
    global _SAMPLE, _SLOW_MS, _ENABLED, _RECORDER
    if sample is not None:
        _SAMPLE = max(0.0, float(sample))
    if slow_ms is not None:
        _SLOW_MS = max(0.0, float(slow_ms))
    if ring is not None or max_spans is not None or max_pending is not None:
        old = _RECORDER
        _RECORDER = TraceRecorder(
            capacity=ring if ring is not None else old.capacity,
            max_spans=max_spans if max_spans is not None else old.max_spans,
            max_pending=(
                max_pending if max_pending is not None
                else old.max_pending
            ),
        )
    _ENABLED = _SAMPLE > 0.0 or _SLOW_MS > 0.0


def enabled() -> bool:
    return _ENABLED


# load the env knobs once at import (server boot reads the same env)
configure(**{
    k: v for k, v in env_config().items() if k in ("sample", "slow_ms")
})


# -- per-thread context ------------------------------------------------------

def new_trace(traceparent: Optional[str] = None,
              request_id: Optional[str] = None) -> Optional[TraceContext]:
    """Start (or join) a trace for an inbound request.  None when
    tracing is disabled — callers fall back to plain X-Request-Id
    minting, and no recorder state is touched (the zero-alloc path).

    The sampling decision is LOCAL POLICY, recomputed from the trace
    id: because _head_sampled is deterministic in the id, every
    process of a front running the same DSS_TRACE_SAMPLE reaches the
    same decision without trusting the wire — and an external
    client's traceparent sampled flag can NOT override the local rate
    (an OTel-instrumented USS sending flag=01 on every request would
    otherwise churn the flight recorder and evict exactly the
    tail-captured breaches an operator armed DSS_TRACE_SLOW_MS to
    hunt).  Spans are buffered only when the trace can actually be
    kept: head-sampled, or tail capture armed."""
    if not _ENABLED:
        return None
    parsed = parse_traceparent(traceparent)
    if parsed is not None:
        tid = parsed[0]
    elif request_id:
        tid = trace_id_from_request_id(request_id)
    else:
        tid = _mint_trace_id()
    sampled = _head_sampled(tid)
    recording = sampled or _SLOW_MS > 0.0
    ctx = TraceContext(
        trace_id=tid,
        root_span_id=_next_span_id(),
        sampled=sampled,
        recording=recording,
        start_ns=time.time_ns(),
    )
    if recording and not _RECORDER.begin(tid):
        ctx.recording = False
    return ctx


def current() -> Optional[SpanHandle]:
    """The active (recording) span handle on this thread, or None —
    ONE attribute read when tracing is disabled or inactive here."""
    if not _ENABLED:
        return None
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or not ctx.recording:
        return None
    return SpanHandle(ctx, getattr(_tls, "parent", None)
                      or ctx.root_span_id)


class _Use:
    """Context manager installing a handle's context on this thread
    (the executor-handoff seam: api/app._call sets it on the worker
    thread so service-layer spans parent correctly)."""

    __slots__ = ("_handle", "_prev")

    def __init__(self, handle):
        self._handle = handle

    def __enter__(self):
        self._prev = (
            getattr(_tls, "ctx", None), getattr(_tls, "parent", None)
        )
        if self._handle is not None:
            _tls.ctx = self._handle.ctx
            _tls.parent = self._handle.span_id
        else:
            # clear: a pooled executor thread must never inherit a
            # previous request's context
            _tls.ctx = None
            _tls.parent = None
        return self._handle

    def __exit__(self, *exc):
        _tls.ctx, _tls.parent = self._prev
        return False


def use(handle: Optional[SpanHandle]) -> _Use:
    return _Use(handle)


class _NoopSpan:
    __slots__ = ()

    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    """A live span: context manager measuring its own duration and
    parenting children opened on the same thread while it is open."""

    __slots__ = ("name", "span_id", "_parent", "_ctx", "_attrs",
                 "_t0", "_start_ns", "_prev_parent")

    def __init__(self, ctx, parent, name, attrs):
        self._ctx = ctx
        self._parent = parent
        self.name = name
        self._attrs = attrs
        self.span_id = _next_span_id()

    def __enter__(self):
        self._start_ns = time.time_ns()
        self._t0 = time.perf_counter()
        self._prev_parent = getattr(_tls, "parent", None)
        _tls.parent = self.span_id
        return self

    def set(self, **attrs):
        if self._attrs is None:
            self._attrs = attrs
        else:
            self._attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb):
        _tls.parent = self._prev_parent
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        _emit(
            self._ctx, self.span_id, self._parent, self.name,
            self._start_ns, dur_ms, self._attrs,
        )
        return False


def span(name: str, **attrs):
    """Open a child span of this thread's current span.  A reusable
    no-op when tracing is inactive here (one branch, no allocation)."""
    if not _ENABLED:
        return _NOOP
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or not ctx.recording:
        return _NOOP
    return _Span(
        ctx, getattr(_tls, "parent", None) or ctx.root_span_id,
        name, attrs or None,
    )


def _emit(ctx, span_id, parent, name, start_ns, dur_ms, attrs) -> None:
    collector = getattr(_tls, "collect", None)
    rec = (
        span_id, parent, name, int(start_ns), round(dur_ms, 3),
        attrs or None,
    )
    if collector is not None:
        collector.append(rec)
        return
    _RECORDER.add(ctx.trace_id, rec)


def add_span(handle: Optional[SpanHandle], name: str, start_ns: int,
             dur_ms: float, attrs: Optional[dict] = None,
             parent: Optional[str] = None) -> Optional[str]:
    """Record an externally-measured span under `handle` (the cross-
    thread seam: the coalescer's pipeline stamps batch timings onto
    items, and the caller's thread records them through the handle it
    captured at admission).  -> the new span id (for chaining
    children), or None when not recording."""
    if handle is None:
        return None
    sid = _next_span_id()
    _emit(
        handle.ctx, sid, parent or handle.span_id, name, start_ns,
        dur_ms, attrs,
    )
    return sid


def finish_root(ctx: Optional[TraceContext], name: str, dur_ms: float,
                status=None, attrs: Optional[dict] = None) -> bool:
    """Finish a request's root span and let the recorder keep or drop
    the trace (head sample / tail capture)."""
    if ctx is None:
        return False
    if not ctx.recording:
        _RECORDER.abandon(ctx.trace_id)
        return False
    return _RECORDER.finish(ctx, name, dur_ms, status=status,
                            attrs=attrs)


def propagation_headers(
    handle: Optional[SpanHandle] = None,
) -> Dict[str, str]:
    """Outbound headers for a cross-process hop: W3C traceparent (the
    current span becomes the remote's parent) + X-Request-Id (the
    trace id, for log grep).  {} when tracing is inactive here."""
    if handle is None:
        handle = current()
        if handle is None:
            return {}
    return {
        "traceparent": format_traceparent(
            handle.ctx.trace_id, handle.span_id, handle.ctx.sampled
        ),
        "X-Request-Id": handle.ctx.trace_id,
    }


# -- collector mode (the shm owner) ------------------------------------------


class _Collect:
    """Thread-state token for a collect-mode activation (the shm
    owner serves a worker's request and ships span timings back in
    fixed response words instead of recording locally)."""

    __slots__ = ("spans", "_prev")


def begin_collect(trace_id: str, sampled: bool = True) -> _Collect:
    """Activate a collect-mode context on this thread: spans emitted
    by the serve path land in a local list (no recorder allocation),
    to be encoded into shm response words by the caller."""
    tok = _Collect()
    tok.spans = []
    tok._prev = (
        getattr(_tls, "ctx", None), getattr(_tls, "parent", None),
        getattr(_tls, "collect", None),
    )
    ctx = TraceContext(
        trace_id=trace_id, root_span_id=_next_span_id(),
        sampled=sampled, recording=True, start_ns=time.time_ns(),
    )
    _tls.ctx = ctx
    _tls.parent = ctx.root_span_id
    _tls.collect = tok.spans
    return tok


def end_collect(tok: _Collect) -> List[tuple]:
    """Deactivate collect mode -> the collected span tuples."""
    _tls.ctx, _tls.parent, _tls.collect = tok._prev
    return tok.spans


def owner_slot_vector(spans: Sequence[tuple],
                      extra: Optional[Dict[str, float]] = None
                      ) -> List[int]:
    """Fold collected spans into the fixed OWNER_SLOTS duration vector
    (ns per slot; duplicate names sum).  `extra` adds slot durations
    measured outside the collected region (owner.queue_wait,
    owner.serve) in milliseconds."""
    vec = [0] * len(OWNER_SLOTS)
    for _sid, _parent, name, _start, dur_ms, _attrs in spans:
        idx = _OWNER_SLOT_INDEX.get(name)
        if idx is not None:
            vec[idx] += int(dur_ms * 1e6)
    if extra:
        for name, ms in extra.items():
            idx = _OWNER_SLOT_INDEX.get(name)
            if idx is not None:
                vec[idx] += int(ms * 1e6)
    return vec
