"""Spherical geometry for the DAR: S2 cells at level 13.

The DSS stores only an S2-cell covering of each entity footprint at a
fixed level (reference: pkg/geo/s2.go:16-25), so this package provides:

  - s2cell: cell-id math (lat/lng -> leaf cell, parents, corners,
    neighbors) as vectorized numpy, mirroring the public S2 geometry
    scheme (quadratic ST<->UV projection, Hilbert-curve cell ids).
  - covering: polygon / circle / polyline coverings at level 13 with the
    reference's validation semantics (max area, winding-order retry,
    degenerate-loop polyline fallback; reference pkg/geo/s2.go:97-166).
"""

from dss_tpu.geo.s2cell import (  # noqa: F401
    MAX_LEVEL,
    DAR_LEVEL,
    cell_id_from_latlng,
    cell_id_from_point,
    cell_to_dar_key,
    dar_key_to_cell,
    cell_level,
    cell_parent,
    cell_corners,
    cell_center,
    cell_token,
    latlng_to_xyz,
    xyz_to_latlng,
)
from dss_tpu.geo.covering import (  # noqa: F401
    MAX_AREA_KM2,
    AreaTooLargeError,
    BadAreaError,
    covering_from_loop_points,
    covering_polygon,
    covering_circle,
    area_to_cell_ids,
    loop_area_km2,
    validate_cell,
)
