"""S2 cell-id math, vectorized in numpy.

The reference delegates this to github.com/golang/geo/s2 (see
/root/reference/pkg/geo/s2.go); here it is implemented from the public
S2 geometry scheme so the framework is self-contained:

  - unit sphere <-> cube-face (u,v) via the quadratic projection,
  - (face, i, j) <-> 64-bit Hilbert-curve cell ids,
  - parents / levels / corners / centers / tokens,
  - same-level neighbor enumeration (with cross-face wrap via an
    XYZ round-trip).

The DAR stores footprints at the fixed level 13 (~1 km^2 cells;
reference pkg/geo/s2.go:16-25).  Level-13 cell ids occupy only the top
30 bits of the 64-bit id (3 face bits + 26 position bits + the lsb
marker at bit 34), so they compress losslessly to an int32 "DAR key"
(cell_to_dar_key) — the on-device representation used by the conflict
kernels in dss_tpu.ops.
"""

from __future__ import annotations

import numpy as np

MAX_LEVEL = 30
DAR_LEVEL = 13
_LOOKUP_BITS = 4
_SWAP_MASK = 1
_INVERT_MASK = 2

# Hilbert curve traversal tables (public S2 scheme).
_POS_TO_IJ = np.array(
    [[0, 1, 3, 2], [0, 2, 3, 1], [3, 2, 0, 1], [3, 1, 0, 2]], dtype=np.int64
)
_POS_TO_ORIENTATION = np.array(
    [_SWAP_MASK, 0, 0, _INVERT_MASK | _SWAP_MASK], dtype=np.int64
)

_lookup_pos = np.zeros(1 << (2 * _LOOKUP_BITS + 2), dtype=np.int64)
_lookup_ij = np.zeros(1 << (2 * _LOOKUP_BITS + 2), dtype=np.int64)


def _init_lookup(level, i, j, orig_orientation, pos, orientation):
    if level == _LOOKUP_BITS:
        ij = (i << _LOOKUP_BITS) + j
        _lookup_pos[(ij << 2) + orig_orientation] = (pos << 2) + orientation
        _lookup_ij[(pos << 2) + orig_orientation] = (ij << 2) + orientation
        return
    level += 1
    i <<= 1
    j <<= 1
    pos <<= 2
    r = _POS_TO_IJ[orientation]
    for index in range(4):
        _init_lookup(
            level,
            i + (int(r[index]) >> 1),
            j + (int(r[index]) & 1),
            orig_orientation,
            pos + index,
            orientation ^ int(_POS_TO_ORIENTATION[index]),
        )


_init_lookup(0, 0, 0, 0, 0, 0)
_init_lookup(0, 0, 0, _SWAP_MASK, 0, _SWAP_MASK)
_init_lookup(0, 0, 0, _INVERT_MASK, 0, _INVERT_MASK)
_init_lookup(0, 0, 0, _SWAP_MASK | _INVERT_MASK, 0, _SWAP_MASK | _INVERT_MASK)


# ---------------------------------------------------------------------------
# Sphere <-> cube-face projections
# ---------------------------------------------------------------------------


def st_to_uv(s):
    """Quadratic ST->UV projection (monotonic, extends smoothly outside [0,1])."""
    s = np.asarray(s, dtype=np.float64)
    return np.where(
        s >= 0.5, (1.0 / 3.0) * (4.0 * s * s - 1.0), (1.0 / 3.0) * (1.0 - 4.0 * (1.0 - s) * (1.0 - s))
    )


def uv_to_st(u):
    u = np.asarray(u, dtype=np.float64)
    return np.where(
        u >= 0.0,
        0.5 * np.sqrt(np.maximum(1.0 + 3.0 * u, 0.0)),
        1.0 - 0.5 * np.sqrt(np.maximum(1.0 - 3.0 * u, 0.0)),
    )


def latlng_to_xyz(lat_deg, lng_deg):
    """Degrees lat/lng -> unit XYZ. Broadcasts; returns (..., 3) float64."""
    lat = np.deg2rad(np.asarray(lat_deg, dtype=np.float64))
    lng = np.deg2rad(np.asarray(lng_deg, dtype=np.float64))
    cos_lat = np.cos(lat)
    return np.stack(
        [cos_lat * np.cos(lng), cos_lat * np.sin(lng), np.sin(lat)], axis=-1
    )


def xyz_to_latlng(p):
    p = np.asarray(p, dtype=np.float64)
    lat = np.rad2deg(np.arctan2(p[..., 2], np.hypot(p[..., 0], p[..., 1])))
    lng = np.rad2deg(np.arctan2(p[..., 1], p[..., 0]))
    return lat, lng


def xyz_to_face_uv(p):
    """Unit XYZ -> (face, u, v)."""
    p = np.asarray(p, dtype=np.float64)
    x, y, z = p[..., 0], p[..., 1], p[..., 2]
    ax, ay, az = np.abs(x), np.abs(y), np.abs(z)
    axis = np.where(ax >= ay, np.where(ax >= az, 0, 2), np.where(ay >= az, 1, 2))
    comp = np.take_along_axis(
        np.stack([x, y, z], axis=-1), axis[..., None], axis=-1
    )[..., 0]
    face = np.where(comp >= 0, axis, axis + 3)
    u = np.empty_like(x)
    v = np.empty_like(x)
    # per-face (u, v) from xyz (standard S2 face frames)
    for f, (ufn, vfn) in enumerate(
        [
            (lambda: y / x, lambda: z / x),      # face 0 (+x)
            (lambda: -x / y, lambda: z / y),     # face 1 (+y)
            (lambda: -x / z, lambda: -y / z),    # face 2 (+z)
            (lambda: z / x, lambda: y / x),      # face 3 (-x)
            (lambda: z / y, lambda: -x / y),     # face 4 (-y)
            (lambda: -y / z, lambda: -x / z),    # face 5 (-z)
        ]
    ):
        m = face == f
        if np.any(m):
            with np.errstate(divide="ignore", invalid="ignore"):
                u = np.where(m, ufn(), u)
                v = np.where(m, vfn(), v)
    return face.astype(np.int64), u, v


def face_uv_to_xyz(face, u, v):
    """(face, u, v) -> XYZ (not normalized)."""
    face = np.asarray(face, dtype=np.int64)
    u = np.asarray(u, dtype=np.float64)
    v = np.asarray(v, dtype=np.float64)
    one = np.ones(np.broadcast_shapes(face.shape, u.shape, v.shape), dtype=np.float64)
    u = np.broadcast_to(u, one.shape)
    v = np.broadcast_to(v, one.shape)
    xs = [
        (one, u, v),        # face 0
        (-u, one, v),       # face 1
        (-u, -v, one),      # face 2
        (-one, -v, -u),     # face 3
        (v, -one, -u),      # face 4
        (v, u, -one),       # face 5
    ]
    x = np.zeros_like(one)
    y = np.zeros_like(one)
    z = np.zeros_like(one)
    for f, (fx, fy, fz) in enumerate(xs):
        m = face == f
        x = np.where(m, fx, x)
        y = np.where(m, fy, y)
        z = np.where(m, fz, z)
    out = np.stack([x, y, z], axis=-1)
    return out / np.linalg.norm(out, axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# (face, i, j) <-> cell id
# ---------------------------------------------------------------------------


def from_face_ij(face, i, j):
    """(face, i[30-bit], j[30-bit]) -> leaf cell id. Vectorized, uint64."""
    face = np.asarray(face, dtype=np.uint64)
    i = np.asarray(i, dtype=np.uint64)
    j = np.asarray(j, dtype=np.uint64)
    n = face << np.uint64(60)
    bits = (face & np.uint64(_SWAP_MASK)).astype(np.int64)
    mask = np.uint64((1 << _LOOKUP_BITS) - 1)
    for k in range(7, -1, -1):
        ki = ((i >> np.uint64(k * _LOOKUP_BITS)) & mask).astype(np.int64)
        kj = ((j >> np.uint64(k * _LOOKUP_BITS)) & mask).astype(np.int64)
        idx = bits + (ki << (_LOOKUP_BITS + 2)) + (kj << 2)
        bits = _lookup_pos[idx]
        n |= (bits.astype(np.uint64) >> np.uint64(2)) << np.uint64(k * 2 * _LOOKUP_BITS)
        bits = bits & (_SWAP_MASK | _INVERT_MASK)
    return n * np.uint64(2) + np.uint64(1)


def to_face_ij(cell_id):
    """Leaf-or-any cell id -> (face, i, j, orientation) of its leaf-center ij.

    For non-leaf cells, (i, j) is the leaf ij of the cell's min leaf with
    the standard S2 correction (matches S2CellId::ToFaceIJOrientation for
    the purposes of bound computation: callers mask by cell size).
    """
    cid = np.asarray(cell_id, dtype=np.uint64)
    face = (cid >> np.uint64(61)).astype(np.int64)
    bits = face & _SWAP_MASK
    i = np.zeros_like(cid)
    j = np.zeros_like(cid)
    for k in range(7, -1, -1):
        nbits = MAX_LEVEL - 7 * _LOOKUP_BITS if k == 7 else _LOOKUP_BITS
        chunk = (
            (cid >> np.uint64(k * 2 * _LOOKUP_BITS + 1))
            & np.uint64((1 << (2 * nbits)) - 1)
        ).astype(np.int64)
        idx = bits + (chunk << 2)
        bits = _lookup_ij[idx]
        i += (bits >> (_LOOKUP_BITS + 2)).astype(np.uint64) << np.uint64(k * _LOOKUP_BITS)
        j += ((bits >> 2) & ((1 << _LOOKUP_BITS) - 1)).astype(np.uint64) << np.uint64(
            k * _LOOKUP_BITS
        )
        bits = bits & (_SWAP_MASK | _INVERT_MASK)
    return face, i.astype(np.int64), j.astype(np.int64), bits


def cell_lsb(cell_id):
    cid = np.asarray(cell_id, dtype=np.uint64)
    neg = (~cid) + np.uint64(1)
    return cid & neg


def cell_level(cell_id):
    """Level of cell id(s), via position of the lsb marker bit."""
    lsb = cell_lsb(cell_id)
    # log2 of a power of two up to 2^60: float64 conversion is exact.
    tz = np.round(np.log2(lsb.astype(np.float64))).astype(np.int64)
    return MAX_LEVEL - (tz >> 1)


def cell_parent(cell_id, level):
    """Parent of cell id(s) at 'level' (must be <= current level).
    `level` may be a scalar or an array broadcastable against cell_id."""
    cid = np.asarray(cell_id, dtype=np.uint64)
    shift = (
        2 * (MAX_LEVEL - np.asarray(level, dtype=np.int64))
    ).astype(np.uint64)
    new_lsb = np.uint64(1) << shift
    neg = (~new_lsb) + np.uint64(1)  # two's complement of new_lsb
    return (cid & neg) | new_lsb


def cell_id_from_point(p, level=None):
    """Unit XYZ point(s) -> cell id at 'level' (leaf if None)."""
    face, u, v = xyz_to_face_uv(p)
    s = uv_to_st(u)
    t = uv_to_st(v)
    lim = np.int64((1 << MAX_LEVEL) - 1)
    i = np.clip(np.floor(s * (1 << MAX_LEVEL)).astype(np.int64), 0, lim)
    j = np.clip(np.floor(t * (1 << MAX_LEVEL)).astype(np.int64), 0, lim)
    cid = from_face_ij(face, i, j)
    if level is not None:
        cid = cell_parent(cid, level)
    return cid


def cell_id_from_latlng(lat_deg, lng_deg, level=None):
    return cell_id_from_point(latlng_to_xyz(lat_deg, lng_deg), level=level)


# ---------------------------------------------------------------------------
# Cell geometry
# ---------------------------------------------------------------------------


def cell_ij_bounds(cell_id):
    """(face, i_lo, j_lo, size) of the cell's ij square at leaf resolution."""
    cid = np.asarray(cell_id, dtype=np.uint64)
    level = cell_level(cid)
    size = np.int64(1) << (MAX_LEVEL - level)
    face, i, j, _ = to_face_ij(cid)
    i_lo = i & ~(size - 1)
    j_lo = j & ~(size - 1)
    return face, i_lo, j_lo, size


def cell_uv_bounds(cell_id):
    face, i_lo, j_lo, size = cell_ij_bounds(cell_id)
    scale = 1.0 / (1 << MAX_LEVEL)
    u_lo = st_to_uv(i_lo * scale)
    u_hi = st_to_uv((i_lo + size) * scale)
    v_lo = st_to_uv(j_lo * scale)
    v_hi = st_to_uv((j_lo + size) * scale)
    return face, u_lo, u_hi, v_lo, v_hi


def cell_corners(cell_id):
    """4 unit-XYZ corners in CCW order: (..., 4, 3)."""
    face, u_lo, u_hi, v_lo, v_hi = cell_uv_bounds(cell_id)
    us = np.stack([u_lo, u_hi, u_hi, u_lo], axis=-1)
    vs = np.stack([v_lo, v_lo, v_hi, v_hi], axis=-1)
    f = np.broadcast_to(np.asarray(face)[..., None], us.shape)
    return face_uv_to_xyz(f, us, vs)


def cell_center(cell_id):
    face, u_lo, u_hi, v_lo, v_hi = cell_uv_bounds(cell_id)
    return face_uv_to_xyz(face, 0.5 * (u_lo + u_hi), 0.5 * (v_lo + v_hi))


def cell_neighbors8(cell_id):
    """The (up to) 8 same-level neighbors of a single cell id.

    Cross-face wrap is handled by projecting a point just beyond the face
    boundary back onto the sphere and re-looking-up its cell, so corner
    cells naturally yield their (possibly < 8) distinct neighbors.
    """
    cid = np.uint64(cell_id)
    level = int(cell_level(cid))
    face, i_lo, j_lo, size = cell_ij_bounds(cid)
    face, i_lo, j_lo, size = int(face), int(i_lo), int(j_lo), int(size)
    lim = 1 << MAX_LEVEL
    out = []
    scale = 1.0 / lim
    for di in (-1, 0, 1):
        for dj in (-1, 0, 1):
            if di == 0 and dj == 0:
                continue
            ni = i_lo + di * size
            nj = j_lo + dj * size
            if 0 <= ni < lim and 0 <= nj < lim:
                nid = cell_parent(from_face_ij(face, ni + size // 2, nj + size // 2), level)
            else:
                # step off the face: project the would-be cell center
                s = (ni + size / 2.0) * scale
                t = (nj + size / 2.0) * scale
                u = st_to_uv(s)
                v = st_to_uv(t)
                p = face_uv_to_xyz(face, u, v)
                nid = cell_id_from_point(p, level=level)
            out.append(np.uint64(nid))
    # dedup while preserving order
    seen = set()
    uniq = []
    for c in out:
        ci = int(c)
        if ci not in seen and ci != int(cid):
            seen.add(ci)
            uniq.append(c)
    return uniq


def cell_neighbors8_many(cell_ids):
    """All 8 same-level neighbors of each cell id, vectorized: (M, 8)
    uint64 (duplicates possible at face corners; callers np.unique).
    Each neighbor is produced at its input cell's own level (like the
    scalar cell_neighbors8).

    Uniform path for in-face and cross-face steps: the would-be
    neighbor's center (i, j) maps through st->uv->xyz (st_to_uv
    extrapolates monotonically beyond [0, 1], landing the point on the
    adjacent face) and back through cell_id_from_point."""
    cids = np.asarray(cell_ids, dtype=np.uint64)
    level = cell_level(cids)  # (M,)
    face, i_lo, j_lo, size = cell_ij_bounds(cids)
    scale = 1.0 / (1 << MAX_LEVEL)
    offs = np.array(
        [(di, dj) for di in (-1, 0, 1) for dj in (-1, 0, 1)
         if not (di == 0 and dj == 0)],
        dtype=np.int64,
    )  # (8, 2)
    s = (i_lo[..., None] + offs[None, :, 0] * size[..., None]
         + size[..., None] / 2.0) * scale
    t = (j_lo[..., None] + offs[None, :, 1] * size[..., None]
         + size[..., None] / 2.0) * scale
    u = st_to_uv(s)
    v = st_to_uv(t)
    f = np.broadcast_to(np.asarray(face)[..., None], u.shape)
    p = face_uv_to_xyz(f, u, v)  # (M, 8, 3)
    return cell_id_from_point(p, level=np.asarray(level)[..., None])


def cell_token(cell_id):
    """Hex token of a cell id with trailing zeros stripped (S2 convention)."""
    cid = int(np.uint64(cell_id))
    if cid == 0:
        return "X"
    return f"{cid:016x}".rstrip("0")


def cell_from_token(token):
    return np.uint64(int(token.ljust(16, "0"), 16))


# ---------------------------------------------------------------------------
# DAR keys: level-13 cells as int32
# ---------------------------------------------------------------------------

_DAR_SHIFT = 2 * (MAX_LEVEL - DAR_LEVEL)  # 34: lsb bit position at level 13


def cell_to_dar_key(cell_id):
    """Level-13 cell id(s) -> int32 DAR key (top 30 bits, lossless)."""
    cid = np.asarray(cell_id, dtype=np.uint64)
    return (cid >> np.uint64(_DAR_SHIFT)).astype(np.int32)


def dar_key_to_cell(key):
    """int32 DAR key(s) -> level-13 cell id(s)."""
    k = np.asarray(key, dtype=np.int64).astype(np.uint64)
    return k << np.uint64(_DAR_SHIFT)
