"""Level-13 cell coverings of footprints, with the reference's semantics.

Mirrors the behavior of /root/reference/pkg/geo/s2.go and
pkg/models/geo.go:

  - coverings are computed at the fixed DAR level 13 (s2.go:16-25);
  - the area limit is 2500 "km^2" computed with the reference's exact
    formula  loop_area_km2 = steradians * 510072000 / 4 * pi
    (s2.go:89-95 — note the formula multiplies rather than divides by
    pi; we reproduce it verbatim for parity);
  - if the loop exceeds the limit the vertex order is reversed once and
    retried (winding-order auto-fix, s2.go:100-110);
  - a degenerate (zero-area) loop falls back to covering the polyline
    of its vertices (s2.go:116-120);
  - circles are covered via an inscribed 20-vertex regular loop
    (pkg/models/geo.go:224-239);
  - "area" strings are 'lat0,lon0,lat1,lon1,...' (s2.go:124-166).

The covering itself is the set of level-13 cells that intersect the
region — the same set an S2 RegionCoverer with MinLevel=MaxLevel=13
produces — computed by a seeded BFS flood fill over the level-13 grid
with spherical cell/loop intersection tests.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict

import numpy as np

try:
    from dss_tpu import native as _native
except Exception:  # pragma: no cover — native layer is optional
    _native = None

from dss_tpu.geo import s2cell
from dss_tpu.geo.s2cell import (
    DAR_LEVEL,
    cell_corners,
    cell_id_from_point,
    cell_level,
    latlng_to_xyz,
    st_to_uv,
    uv_to_st,
    xyz_to_face_uv,
)

MAX_AREA_KM2 = 2500.0
EARTH_AREA_KM2 = 510072000.0
RADIUS_EARTH_METER = 6371010.0
# Safety valve: densest legal covering is ~MAX_AREA cells plus boundary.
_MAX_COVERING_CELLS = 100_000


def canonical_cells(cells) -> np.ndarray:
    """THE canonical covering form: sorted, deduped uint64 cell ids.

    Applied once at query ingress (RID `_area_to_cells`, SCD
    `Volume3D.calculate_covering`) and assumed by everything
    downstream — the read cache keys on the covering's bytes and the
    DAR pack path sorts per-row — so two syntactically different
    requests for the same area hit the same cache line and the same
    pack layout.  Already-canonical input (the common case: the BFS
    coverings come out sorted-unique) is returned as-is, no copy."""
    a = np.ascontiguousarray(np.asarray(cells, dtype=np.uint64).ravel())
    if len(a) > 1 and not bool(np.all(a[1:] > a[:-1])):
        return np.unique(a)
    return a


class AreaTooLargeError(Exception):
    """Requested area exceeds MAX_AREA_KM2 (maps to HTTP 413)."""


class BadAreaError(Exception):
    """Coordinates did not create a well-formed area."""


# ---------------------------------------------------------------------------
# Spherical predicates (double precision)
# ---------------------------------------------------------------------------


def _cross3(a, b):
    """Manual cross product: identical math to np.cross but without its
    ~50us call overhead (the covering's predicates run on tiny arrays
    where that overhead dominates).  Supports (..., 3) broadcasting."""
    a = np.asarray(a)
    b = np.asarray(b)
    out = np.empty(np.broadcast_shapes(a.shape, b.shape), dtype=np.float64)
    a0, a1, a2 = a[..., 0], a[..., 1], a[..., 2]
    b0, b1, b2 = b[..., 0], b[..., 1], b[..., 2]
    out[..., 0] = a1 * b2 - a2 * b1
    out[..., 1] = a2 * b0 - a0 * b2
    out[..., 2] = a0 * b1 - a1 * b0
    return out


def _sign(a, b, c):
    """Sign of det(a, b, c): +1 if c is left of a->b (CCW), else -1/0."""
    d = np.dot(_cross3(a, b), c)
    if d > 0:
        return 1
    if d < 0:
        return -1
    return 0


def _ordered_ccw(a, b, c, o):
    """True if (a, b, c) appear in CCW order as seen around o."""
    k = 0
    if _sign(b, o, a) >= 0:
        k += 1
    if _sign(c, o, b) >= 0:
        k += 1
    if _sign(a, o, c) > 0:
        k += 1
    return k >= 2


def _same(p, q):
    return bool(np.all(p == q))


def _edges_cross(a, b, c, d):
    """True if great-circle arcs AB and CD (each < pi) cross at an interior
    point.  Computes the great-circle intersection and checks it lies
    strictly within both arcs (robust for long arcs, unlike pure
    side-of-plane tests)."""
    n1 = _cross3(a, b)
    n2 = _cross3(c, d)
    x = _cross3(n1, n2)
    norm = np.linalg.norm(x)
    if norm < 1e-30:
        return False  # coplanar / degenerate
    x = x / norm
    dab = np.dot(a, b)
    dcd = np.dot(c, d)
    for s in (1.0, -1.0):
        p = s * x
        if (
            np.dot(p, a) > dab
            and np.dot(p, b) > dab
            and np.dot(p, c) > dcd
            and np.dot(p, d) > dcd
        ):
            return True
    return False


def _vertex_crossing(a, b, c, d):
    """S2 VertexCrossing semantics for arcs sharing an endpoint: defines a
    consistent parity so a path through a shared vertex counts once."""
    if _same(a, b) or _same(c, d):
        return False
    if _same(a, d):
        return _ordered_ccw(_ortho(a), c, b, a)
    if _same(b, c):
        return _ordered_ccw(_ortho(b), d, a, b)
    if _same(a, c):
        return _ordered_ccw(_ortho(a), d, b, a)
    if _same(b, d):
        return _ordered_ccw(_ortho(b), c, a, b)
    return False


def _edge_or_vertex_crossing(a, b, c, d):
    if _same(a, c) or _same(a, d) or _same(b, c) or _same(b, d):
        return _vertex_crossing(a, b, c, d)
    return _edges_cross(a, b, c, d)


def _ortho(p):
    """A unit vector orthogonal to p."""
    k = int(np.argmin(np.abs(p)))
    axis = np.zeros(3)
    axis[k] = 1.0
    o = _cross3(p, axis)
    return o / np.linalg.norm(o)


class Loop:
    """A closed spherical loop; the interior is on the left of the edges.

    Implements containment via edge-crossing parity from a fixed origin
    point, with the origin's own containment bootstrapped from the
    vertex-1 interior-angle test (the standard S2 construction).
    """

    def __init__(self, vertices_xyz):
        v = np.asarray(vertices_xyz, dtype=np.float64)
        if v.ndim != 2 or v.shape[-1] != 3:
            raise ValueError("vertices must be (N, 3)")
        self.v = v
        self.n = len(v)
        self._origin = np.array([-0.0099994664, 0.0025924542, 0.9999466])
        self._origin /= np.linalg.norm(self._origin)
        # the origin-containment bootstrap costs a scalar crossing walk;
        # computed lazily so area-only uses (the winding/limit checks)
        # never pay it
        self._origin_inside_cache = None

    @property
    def _origin_inside(self) -> bool:
        if self._origin_inside_cache is None:
            if self.n >= 3:
                v1_inside = _ordered_ccw(
                    _ortho(self.v[1]), self.v[0], self.v[2], self.v[1]
                )
                contains_v1 = self._contains_assuming_origin_outside(
                    self.v[1]
                )
                self._origin_inside_cache = v1_inside != contains_v1
            else:
                self._origin_inside_cache = False
        return self._origin_inside_cache

    def _crossing_parity(self, p):
        """Number of loop edges crossed by segment origin->p, mod 2
        (edge-or-vertex crossing semantics)."""
        crossings = 0
        o = self._origin
        for k in range(self.n):
            a = self.v[k]
            b = self.v[(k + 1) % self.n]
            if _edge_or_vertex_crossing(o, p, a, b):
                crossings ^= 1
        return crossings

    def _contains_assuming_origin_outside(self, p):
        return self._crossing_parity(p) == 1

    def contains(self, p):
        """True if unit point p is inside the loop interior."""
        return self._origin_inside != (self._crossing_parity(p) == 1)

    def signed_area(self):
        """Signed spherical area (steradians); positive for CCW loops."""
        if self.n < 3:
            return 0.0
        total = 0.0
        v0 = self.v[0]
        for k in range(1, self.n - 1):
            a, b, c = v0, self.v[k], self.v[k + 1]
            triple = np.dot(_cross3(a, b), c)
            denom = 1.0 + np.dot(a, b) + np.dot(b, c) + np.dot(c, a)
            total += 2.0 * math.atan2(triple, denom)
        return total

    def area(self):
        """Interior area in steradians (interior = left of edges), [0, 4pi]."""
        s = self.signed_area()
        return s if s >= 0 else 4.0 * math.pi + s


def loop_area_km2(loop: Loop) -> float:
    """The reference's loop-area formula, reproduced exactly.

    pkg/geo/s2.go:89-95:  (area_sr * 510072000) / 4 * pi
    (multiplies by pi — the reference's quirk is part of the contract:
    it determines which areas pass the 2500 'km^2' validation gate).
    """
    if loop.n == 0:
        return 0.0
    return (loop.area() * EARTH_AREA_KM2) / 4.0 * math.pi


# ---------------------------------------------------------------------------
# Cell / loop intersection
# ---------------------------------------------------------------------------


def _point_in_cell(p, face, u_lo, u_hi, v_lo, v_hi):
    """True if unit point p lies within the given face-uv rectangle."""
    pf, pu, pv = xyz_to_face_uv(p)
    if int(pf) == int(face):
        return u_lo <= pu <= u_hi and v_lo <= pv <= v_hi
    # p may project onto the cell across a face boundary only at the exact
    # edge; treat different-face points as outside (BFS neighbors cover
    # the adjacent face's cells anyway).
    return False


def _cell_intersects_loop(cell_id, loop: Loop, loop_vertex_cells) -> bool:
    """Conservative-exact test: does the level-13 cell intersect the loop?

    True iff (a) any cell corner is inside the loop, (b) any loop vertex
    lies in the cell, or (c) any loop edge crosses any cell edge.
    """
    corners = cell_corners(cell_id)  # (4, 3)
    for k in range(4):
        if loop.contains(corners[k]):
            return True
    if int(np.uint64(cell_id)) in loop_vertex_cells:
        return True
    face, u_lo, u_hi, v_lo, v_hi = s2cell.cell_uv_bounds(cell_id)
    for k in range(loop.n):
        if _point_in_cell(loop.v[k], face, u_lo, u_hi, v_lo, v_hi):
            return True
    for k in range(loop.n):
        a = loop.v[k]
        b = loop.v[(k + 1) % loop.n]
        for e in range(4):
            c = corners[e]
            d = corners[(e + 1) % 4]
            if _edges_cross(a, b, c, d):
                return True
    return False


def _segment_intersects_cell(a, b, cell_id) -> bool:
    corners = cell_corners(cell_id)
    face, u_lo, u_hi, v_lo, v_hi = s2cell.cell_uv_bounds(cell_id)
    if _point_in_cell(a, face, u_lo, u_hi, v_lo, v_hi):
        return True
    if _point_in_cell(b, face, u_lo, u_hi, v_lo, v_hi):
        return True
    for e in range(4):
        c = corners[e]
        d = corners[(e + 1) % 4]
        if _edges_cross(a, b, c, d):
            return True
    return False


# ---------------------------------------------------------------------------
# Vectorized predicates (batch over candidate cells)
# ---------------------------------------------------------------------------


def _arcs_cross_many(a, b, c, d):
    """Vectorized _edges_cross: arcs A[k]->B[k] vs C[j]->D[j] for every
    (k, j) pair -> bool (K, J).  Same math and strict inequalities as
    the scalar version (identical verdicts)."""
    a = np.atleast_2d(a)
    b = np.atleast_2d(b)
    c = np.atleast_2d(c)
    d = np.atleast_2d(d)
    n1 = _cross3(a, b)  # (K, 3)
    n2 = _cross3(c, d)  # (J, 3)
    x = _cross3(n1[:, None, :], n2[None, :, :])  # (K, J, 3)
    norm = np.linalg.norm(x, axis=-1)
    ok = norm >= 1e-30
    with np.errstate(divide="ignore", invalid="ignore"):
        x = x / np.where(norm[..., None] == 0.0, 1.0, norm[..., None])
    dab = np.sum(a * b, axis=-1)  # (K,)
    dcd = np.sum(c * d, axis=-1)  # (J,)
    out = np.zeros(ok.shape, dtype=bool)
    for s in (1.0, -1.0):
        p = s * x  # (K, J, 3)
        out |= (
            (np.sum(p * a[:, None, :], axis=-1) > dab[:, None])
            & (np.sum(p * b[:, None, :], axis=-1) > dab[:, None])
            & (np.sum(p * c[None, :, :], axis=-1) > dcd[None, :])
            & (np.sum(p * d[None, :, :], axis=-1) > dcd[None, :])
        )
    return out & ok


def _points_in_loop(loop: Loop, pts) -> np.ndarray:
    """Vectorized Loop.contains for (P, 3) points -> bool (P,).

    Points exactly equal to a loop vertex (or the parity origin) need
    the vertex-crossing tie-break — those few fall back to the scalar
    path; everything else is one batched crossing-parity computation."""
    pts = np.atleast_2d(np.asarray(pts, dtype=np.float64))
    P = len(pts)
    if P == 0:
        return np.zeros(0, dtype=bool)
    edges_a = loop.v
    edges_b = np.roll(loop.v, -1, axis=0)
    o = loop._origin
    # exact endpoint sharing -> scalar tie-break path
    shared = (
        np.all(pts[:, None, :] == loop.v[None, :, :], axis=-1).any(axis=1)
        | np.all(pts == o, axis=-1)
    )
    arcs_a = np.broadcast_to(o, pts.shape)
    cross = _arcs_cross_many(arcs_a, pts, edges_a, edges_b)  # (P, N)
    parity = (np.sum(cross, axis=1) & 1).astype(bool)
    inside = parity != loop._origin_inside
    if shared.any():
        for k in np.flatnonzero(shared):
            inside[k] = loop.contains(pts[k])
    return inside


def _cells_intersect_loop(cids, loop: Loop, loop_vertex_cells) -> np.ndarray:
    """Vectorized _cell_intersects_loop over (M,) cell ids -> bool (M,)."""
    cids = np.asarray(cids, dtype=np.uint64)
    M = len(cids)
    if M == 0:
        return np.zeros(0, dtype=bool)
    corners = s2cell.cell_corners(cids)  # (M, 4, 3)
    # (a) any corner inside the loop
    hit = _points_in_loop(loop, corners.reshape(-1, 3)).reshape(M, 4).any(axis=1)
    # (b) cell contains a loop vertex (by vertex-cell id)
    if loop_vertex_cells:
        vc = np.fromiter(loop_vertex_cells, dtype=np.uint64,
                         count=len(loop_vertex_cells))
        hit |= np.isin(cids, vc)
    # (c) any loop vertex projects inside the cell's face-uv rect
    face, u_lo, u_hi, v_lo, v_hi = s2cell.cell_uv_bounds(cids)
    pf, pu, pv = xyz_to_face_uv(loop.v)  # (N,)
    in_rect = (
        (np.asarray(face)[:, None] == pf[None, :])
        & (np.asarray(u_lo)[:, None] <= pu[None, :])
        & (pu[None, :] <= np.asarray(u_hi)[:, None])
        & (np.asarray(v_lo)[:, None] <= pv[None, :])
        & (pv[None, :] <= np.asarray(v_hi)[:, None])
    )
    hit |= in_rect.any(axis=1)
    # (d) any loop edge crosses any cell edge
    todo = ~hit
    if todo.any():
        sub = corners[todo]  # (S, 4, 3)
        ca = sub.reshape(-1, 3)  # cell edge starts
        cb = np.roll(sub, -1, axis=1).reshape(-1, 3)  # cell edge ends
        ea = loop.v
        eb = np.roll(loop.v, -1, axis=0)
        cross = _arcs_cross_many(ca, cb, ea, eb)  # (S*4, N)
        hit[todo] = cross.reshape(-1, 4, loop.n).any(axis=(1, 2))
    return hit


def _cells_intersect_segment(cids, a, b) -> np.ndarray:
    """Vectorized _segment_intersects_cell over (M,) cells."""
    cids = np.asarray(cids, dtype=np.uint64)
    M = len(cids)
    if M == 0:
        return np.zeros(0, dtype=bool)
    face, u_lo, u_hi, v_lo, v_hi = s2cell.cell_uv_bounds(cids)
    ends = np.stack([a, b])  # (2, 3)
    pf, pu, pv = xyz_to_face_uv(ends)
    in_rect = (
        (np.asarray(face)[:, None] == pf[None, :])
        & (np.asarray(u_lo)[:, None] <= pu[None, :])
        & (pu[None, :] <= np.asarray(u_hi)[:, None])
        & (np.asarray(v_lo)[:, None] <= pv[None, :])
        & (pv[None, :] <= np.asarray(v_hi)[:, None])
    )
    hit = in_rect.any(axis=1)
    todo = ~hit
    if todo.any():
        corners = s2cell.cell_corners(cids[todo])  # (S, 4, 3)
        ca = corners.reshape(-1, 3)
        cb = np.roll(corners, -1, axis=1).reshape(-1, 3)
        cross = _arcs_cross_many(ca, cb, a[None, :], b[None, :])
        hit[todo] = cross.reshape(-1, 4).any(axis=1)
    return hit


# ---------------------------------------------------------------------------
# Coverings
# ---------------------------------------------------------------------------


def _flood_fill(seeds, batch_predicate):
    """Wave BFS over the level-13 grid from seed cells: each wave of
    candidate cells is tested by ONE vectorized batch_predicate call,
    and the kept cells' 8-neighborhoods form the next wave.  Returns a
    sorted uint64 array."""
    wave = np.unique(np.asarray(list(seeds), dtype=np.uint64))
    seen = set(int(c) for c in wave)
    result = []
    n_result = 0
    while wave.size:
        keep = batch_predicate(wave)
        kept = wave[keep]
        if kept.size:
            result.append(kept)
            n_result += kept.size
            if n_result > _MAX_COVERING_CELLS:
                raise AreaTooLargeError("covering exceeds maximum cell count")
            nbrs = np.unique(
                s2cell.cell_neighbors8_many(kept).ravel()
            )
            fresh = [int(c) for c in nbrs if int(c) not in seen]
            seen.update(fresh)
            wave = np.array(fresh, dtype=np.uint64)
        else:
            wave = np.array([], dtype=np.uint64)
    if not result:
        return np.array([], dtype=np.uint64)
    return np.sort(np.concatenate(result))


def covering_polyline(points_xyz) -> np.ndarray:
    """Level-13 cells intersecting the polyline through the given points."""
    pts = np.asarray(points_xyz, dtype=np.float64)
    if len(pts) == 0:
        return np.array([], dtype=np.uint64)
    result = set()
    for k in range(max(1, len(pts) - 1)):
        a = pts[k]
        b = pts[min(k + 1, len(pts) - 1)]
        seeds = [
            cell_id_from_point(a, level=DAR_LEVEL),
            cell_id_from_point(b, level=DAR_LEVEL),
        ]
        cells = _flood_fill(
            seeds, lambda wave: _cells_intersect_segment(wave, a, b)
        )
        result.update(int(c) for c in cells)
    return np.sort(np.array(sorted(result), dtype=np.uint64))


_RECT_MAX_CELLS = 1 << 16  # rect fast-path cap; beyond it BFS is better
_RECT_CHUNK = 1 << 14  # candidate cells per predicate batch (memory)


def _loop_covering_bfs(loop: Loop, loop_vertex_cells) -> np.ndarray:
    """The wave-BFS covering (handles face wrap exactly); also the
    differential reference for the rect fast path."""
    seeds = [np.uint64(c) for c in loop_vertex_cells]
    return _flood_fill(
        seeds,
        lambda wave: _cells_intersect_loop(wave, loop, loop_vertex_cells),
    )


def _loop_covering(loop: Loop, area_km2: Optional[float] = None) -> np.ndarray:
    # callers have usually just computed the loop area for the
    # winding/limit checks — reuse it (signed_area costs ~8 numpy
    # dispatches per vertex)
    if area_km2 is None:
        area_km2 = loop_area_km2(loop)
    area_ok = area_km2 <= MAX_AREA_KM2

    # native fast path: the C++ kernel implements exactly the
    # single-face rect covering below (bit-identical predicates; pinned
    # by tests/test_native_covering.py) in ~20 us instead of ~5 ms of
    # numpy small-op dispatch.  It returns None whenever any of the
    # fallback conditions hold, and this function continues unchanged.
    if _native is not None and _native.available():
        try:
            cells = _native.loop_covering(loop.v, area_ok)
        except _native.CoveringTooLarge:
            raise AreaTooLargeError("covering exceeds maximum cell count")
        if cells is not None:
            return cells

    vertex_ids = cell_id_from_point(loop.v, level=DAR_LEVEL)
    loop_vertex_cells = {int(c) for c in np.atleast_1d(vertex_ids)}

    # Single-face fast path: every cube face is a gnomonic plane, so a
    # loop edge is a straight segment in UV and stays inside its
    # endpoints' uv bbox; st(u) is monotonic per axis, so the whole
    # boundary lies within the vertices' ij bounding rectangle.  The
    # INTERIOR is only bbox-bounded when it is the small side of the
    # boundary (<= the area gate) — a huge-interior loop (e.g. a circle
    # built around the antipode, which never passes the polygon
    # winding normalization) must take the BFS, where the cell-count
    # cap raises AreaTooLarge instead of silently under-covering.
    # One vectorized predicate pass over the rect (+1-cell touch
    # margin), chunked for bounded temporaries, replaces the wave BFS —
    # 3-4x faster for typical entity footprints.  Oversized rects
    # (legal thin diagonal slivers) stay on the BFS, which only visits
    # cells near the strip.
    faces, i_lo, j_lo, size = s2cell.cell_ij_bounds(
        np.atleast_1d(vertex_ids)
    )
    if (
        len(set(int(f) for f in np.atleast_1d(faces))) == 1
        and area_ok
    ):
        step = int(np.atleast_1d(size)[0])
        lim = 1 << s2cell.MAX_LEVEL
        imin = max(int(i_lo.min()) - step, 0)
        imax = min(int(i_lo.max()) + step, lim - step)
        jmin = max(int(j_lo.min()) - step, 0)
        jmax = min(int(j_lo.max()) + step, lim - step)
        ni = (imax - imin) // step + 1
        nj = (jmax - jmin) // step + 1
        if (
            ni * nj <= _RECT_MAX_CELLS
            and imin > 0
            and jmin > 0
            and imax < lim - step
            and jmax < lim - step
        ):
            ii = imin + np.arange(ni, dtype=np.int64) * step
            jj = jmin + np.arange(nj, dtype=np.int64) * step
            cand = s2cell.cell_parent(
                s2cell.from_face_ij(
                    int(np.atleast_1d(faces)[0]),
                    np.repeat(ii, nj) + step // 2,
                    np.tile(jj, ni) + step // 2,
                ),
                DAR_LEVEL,
            )
            kept = []
            for lo in range(0, len(cand), _RECT_CHUNK):
                chunk = cand[lo : lo + _RECT_CHUNK]
                keep = _cells_intersect_loop(
                    chunk, loop, loop_vertex_cells
                )
                kept.append(chunk[keep])
            out = np.unique(np.concatenate(kept))
            if len(out) > _MAX_COVERING_CELLS:
                raise AreaTooLargeError(
                    "covering exceeds maximum cell count"
                )
            return out

    return _loop_covering_bfs(loop, loop_vertex_cells)


def covering_from_loop_points(points_xyz) -> np.ndarray:
    """Covering of the loop through the given points, with the reference's
    winding-retry / area-limit / polyline-fallback semantics
    (pkg/geo/s2.go:97-122)."""
    # native fast path: winding retry + area gate + rect covering in
    # ONE call (same op order as the code below; differentially pinned
    # by tests/test_native_covering.py).  None -> run the full Python
    # path (multi-face, face-edge margin, oversized rect, no lib).
    if _native is not None and _native.available():
        arr = np.ascontiguousarray(points_xyz, dtype=np.float64)
        try:
            cells = _native.points_covering(arr, MAX_AREA_KM2)
            if cells is not None:
                return cells
        except _native.AreaTooLarge as e:
            raise AreaTooLargeError(
                f"area is too large ({e.area:f}km² > {MAX_AREA_KM2:f}km²)"
            )
        except _native.Degenerate:
            return covering_polyline(arr)
        except _native.CoveringTooLarge:
            raise AreaTooLargeError("covering exceeds maximum cell count")

    pts = list(np.asarray(points_xyz, dtype=np.float64))
    loop = Loop(np.asarray(pts))
    area = loop_area_km2(loop)
    if area > MAX_AREA_KM2:
        pts.reverse()
        loop = Loop(np.asarray(pts))
    area = loop_area_km2(loop)
    if area > MAX_AREA_KM2:
        raise AreaTooLargeError(
            f"area is too large ({area:f}km² > {MAX_AREA_KM2:f}km²)"
        )
    if area <= 0:
        return covering_polyline(np.asarray(pts))
    return _loop_covering(loop, area_km2=area)


def covering_polygon(vertices_latlng) -> np.ndarray:
    """Covering of a lat/lng polygon (list of (lat, lng) degrees).

    Validation per pkg/models/geo.go:252-268.
    """
    pts = []
    for lat, lng in vertices_latlng:
        if lat > 90.0 or lat < -90.0 or lng > 180.0 or lng < -180.0:
            raise BadAreaError("coordinates did not create a well formed area")
        pts.append(latlng_to_xyz(lat, lng))
    if len(pts) < 3:
        raise BadAreaError("not enough points in polygon")
    return covering_from_loop_points(np.asarray(pts))


def covering_circle(lat, lng, radius_meter) -> np.ndarray:
    """Covering of a circle via an inscribed 20-vertex regular loop
    (pkg/models/geo.go:224-239)."""
    if lat > 90.0 or lat < -90.0 or lng > 180.0 or lng < -180.0:
        raise BadAreaError("coordinates did not create a well formed area")
    if not radius_meter > 0:
        raise BadAreaError("radius must be larger than 0")
    center = latlng_to_xyz(lat, lng)
    radius_angle = radius_meter / RADIUS_EARTH_METER
    # regular loop: 20 vertices CCW around center at the given angular radius
    z = center
    x = _ortho(z)
    y = _cross3(z, x)
    y /= np.linalg.norm(y)
    cos_r = math.cos(radius_angle)
    sin_r = math.sin(radius_angle)
    pts = []
    for k in range(20):
        theta = 2.0 * math.pi * k / 20.0
        p = cos_r * z + sin_r * (math.cos(theta) * x + math.sin(theta) * y)
        pts.append(p / np.linalg.norm(p))
    loop = Loop(np.asarray(pts))
    area = loop_area_km2(loop)
    if area <= 0:
        return covering_polyline(np.asarray(pts))
    return _loop_covering(loop, area_km2=area)


_CACHE_MAX_ENTRIES = 1024
_CACHE_MAX_CELLS_PER_ENTRY = 4096  # bounds worst-case cache to ~32 MB
_area_cache: "OrderedDict[str, np.ndarray]" = OrderedDict()
_area_cache_lock = threading.Lock()


def area_to_cell_ids(area: str) -> np.ndarray:
    """Parse 'lat0,lng0,lat1,lng1,...' and return its covering
    (pkg/geo/s2.go:124-166).

    Memoized (LRU 1024, small results only): USS monitoring traffic
    polls the same operating areas over and over, and the covering is a
    pure function of the string.  Oversized coverings (> a few thousand
    cells) are never cached so distinct large areas can't pin hundreds
    of MB.  Cached arrays are returned read-only (shared across
    callers); parse/area failures are not cached."""
    with _area_cache_lock:
        hit = _area_cache.get(area)
        if hit is not None:
            _area_cache.move_to_end(area)
            return hit
    cells = _area_to_cell_ids_impl(area)
    cells.setflags(write=False)
    if len(cells) <= _CACHE_MAX_CELLS_PER_ENTRY:
        with _area_cache_lock:
            _area_cache[area] = cells
            while len(_area_cache) > _CACHE_MAX_ENTRIES:
                _area_cache.popitem(last=False)
    return cells


def _area_to_cell_ids_impl(area: str) -> np.ndarray:
    parts = area.split(",") if area else []
    if len(parts) % 2 == 1:
        raise BadAreaError("odd number of coordinates in area string")
    if len(parts) // 2 < 3:
        raise BadAreaError("not enough points in polygon")
    coords = []
    for raw in parts:
        try:
            coords.append(float(raw.strip()))
        except ValueError:
            raise BadAreaError("coordinates did not create a well formed area")
    # one vectorized conversion (scalar latlng_to_xyz per vertex costs
    # ~25 us each in numpy dispatch — this path is per-request hot)
    pts = latlng_to_xyz(coords[0::2], coords[1::2])
    return covering_from_loop_points(pts)


def validate_cell(cell_id) -> None:
    """Cells handled by the DAR must be at level 13 (pkg/geo/s2.go:50-55)."""
    lvl = int(cell_level(cell_id))
    if lvl != DAR_LEVEL:
        raise BadAreaError("cells must be at level 13 at current implementation")
