"""Clock abstraction so every layer can run against a fake clock.

The reference swaps a fake clock into package-level DefaultClock vars in
tests (pkg/rid/application/application_test.go:9-10,43); here the clock
is injected explicitly and a FakeClock is provided for tests.
Times are timezone-aware UTC datetimes everywhere.
"""

from __future__ import annotations

import threading
from datetime import datetime, timedelta, timezone


def utcnow() -> datetime:
    return datetime.now(timezone.utc)


_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


def to_nanos(t: datetime) -> int:
    """Datetime -> unix nanoseconds (int, exact). Naive treated as UTC."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    micros = (t - _EPOCH) // timedelta(microseconds=1)
    return micros * 1000


def from_nanos(ns: int) -> datetime:
    return datetime.fromtimestamp(ns / 1e9, tz=timezone.utc)


class Clock:
    """Real wall clock."""

    def now(self) -> datetime:
        return utcnow()


class FakeClock(Clock):
    """Settable clock for tests."""

    def __init__(self, start: datetime | None = None):
        self._lock = threading.Lock()
        self._now = start or datetime(2026, 1, 1, tzinfo=timezone.utc)

    def now(self) -> datetime:
        with self._lock:
            return self._now

    def advance(self, **kwargs):
        with self._lock:
            self._now += timedelta(**kwargs)

    def set(self, t: datetime):
        with self._lock:
            self._now = t if t.tzinfo else t.replace(tzinfo=timezone.utc)


SYSTEM_CLOCK = Clock()
