"""One retry policy for the whole stack.

Before this module, three transports each grew their own loop:
RegionClient hand-rolled `min(0.05 * 2**attempt, 0.5) * (0.5 + rand)`,
the mirror sender hand-rolled `min(0.1 * 2**fails, 2.0) * (0.5+rand)`,
and the region coordinator slept a FIXED 2.0 s after every optimistic
conflict — so two coordinators that collided once re-collided in
lockstep forever.  All three now share:

  RetryPolicy       jittered exponential backoff with a cap and an
                    optional deadline budget, deterministic when
                    seeded (the chaos tests replay exact schedules)
  CircuitBreaker    per-remote closed/open/half-open, feeding the
                    dss_breaker_state{remote} gauge and driving the
                    degradation ladder (all endpoints open ==
                    REGION_LOG_DOWN)
  BreakerRegistry   the keyed family of breakers for one client

The breaker is deliberately advisory on single-path transports: it
never blocks the ONLY endpoint (an open breaker there just means every
attempt is a half-open probe), it reorders multi-endpoint rotation
away from open remotes, and its state is the operator signal.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

__all__ = [
    "RetryPolicy",
    "Deadline",
    "CircuitBreaker",
    "BreakerRegistry",
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
]

# numeric gauge values for dss_breaker_state{remote}
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2


class RetryPolicy:
    """Jittered exponential backoff: attempt k (0-based) sleeps
    min(base * multiplier**k, cap) * uniform(1-jitter, 1+jitter).
    Stateless between calls — the caller owns the attempt counter —
    so one policy object can serve many concurrent loops."""

    __slots__ = ("base_s", "cap_s", "multiplier", "jitter", "_rng",
                 "_lock")

    def __init__(
        self,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.5,
        seed: Optional[int] = None,
    ):
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.multiplier = float(multiplier)
        self.jitter = max(0.0, min(1.0, float(jitter)))
        self._rng = random.Random(seed) if seed is not None else random
        self._lock = threading.Lock()

    def raw_backoff_s(self, attempt: int) -> float:
        """The un-jittered curve (its cap is the honest Retry-After
        quote for 'come back when the breaker may have reset').  The
        exponent is clamped BEFORE exponentiating: callers feed
        unbounded failure streaks (a mirror flapping for an hour), and
        multiplier**1075 would raise OverflowError inside the very
        retry loop that must never die — any clamped value is already
        far past the cap."""
        return min(
            self.base_s
            * self.multiplier ** min(64, max(0, int(attempt))),
            self.cap_s,
        )

    def backoff_s(self, attempt: int) -> float:
        raw = self.raw_backoff_s(attempt)
        j = self.jitter
        if j <= 0.0:
            return raw
        with self._lock:  # seeded Random is not thread-safe
            u = self._rng.random()
        return raw * (1.0 - j + 2.0 * j * u)

    def sleep(self, attempt: int, deadline: "Optional[Deadline]" = None,
              sleep_fn=time.sleep) -> float:
        """Sleep the attempt's backoff, clipped to the deadline budget.
        Returns the seconds actually slept (0.0 when the deadline is
        already spent — the caller's loop condition should then bail)."""
        d = self.backoff_s(attempt)
        if deadline is not None:
            d = min(d, max(0.0, deadline.remaining_s()))
        if d > 0.0:
            sleep_fn(d)
        return d


class Deadline:
    """A wall-clock retry budget (monotonic)."""

    __slots__ = ("_at", "_clock")

    def __init__(self, budget_s: float, clock=time.monotonic):
        self._clock = clock
        self._at = clock() + float(budget_s)

    def remaining_s(self) -> float:
        return self._at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self._at


class CircuitBreaker:
    """Closed/open/half-open per remote.

    `fail_threshold` consecutive failures opens the breaker for
    `reset_s`; after the cooldown the next allow() is a half-open
    probe — success closes, failure re-opens (a fresh cooldown).
    Thread-safe; the clock is injectable for deterministic tests."""

    __slots__ = ("fail_threshold", "reset_s", "_clock", "_lock",
                 "_fails", "_state", "_open_until", "trips")

    def __init__(
        self,
        fail_threshold: int = 5,
        reset_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.fail_threshold = max(1, int(fail_threshold))
        self.reset_s = float(reset_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._fails = 0
        self._state = BREAKER_CLOSED
        self._open_until = 0.0
        self.trips = 0  # times the breaker opened

    def _state_locked(self) -> int:
        if (
            self._state == BREAKER_OPEN
            and self._clock() >= self._open_until
        ):
            self._state = BREAKER_HALF_OPEN
        return self._state

    @property
    def state(self) -> int:
        with self._lock:
            return self._state_locked()

    def allow(self) -> bool:
        """May a call go to this remote right now?  Open -> no;
        half-open/closed -> yes (each half-open call is a probe)."""
        with self._lock:
            return self._state_locked() != BREAKER_OPEN

    def record_success(self) -> None:
        with self._lock:
            self._fails = 0
            self._state = BREAKER_CLOSED

    def record_failure(self) -> None:
        with self._lock:
            st = self._state_locked()
            self._fails += 1
            if st == BREAKER_HALF_OPEN or self._fails >= self.fail_threshold:
                if st != BREAKER_OPEN:
                    self.trips += 1
                self._state = BREAKER_OPEN
                self._open_until = self._clock() + self.reset_s

    def cooldown_remaining_s(self) -> float:
        """Seconds until a half-open probe is allowed (0 when not
        open) — the honest Retry-After for callers shed by an outage."""
        with self._lock:
            if self._state_locked() != BREAKER_OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())


class BreakerRegistry:
    """The per-remote breaker family for one client; states() feeds
    the dss_breaker_state{remote} gauge family."""

    def __init__(
        self,
        fail_threshold: int = 5,
        reset_s: float = 5.0,
        clock=time.monotonic,
    ):
        self._kw = dict(
            fail_threshold=fail_threshold, reset_s=reset_s, clock=clock
        )
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, remote: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(remote)
            if b is None:
                b = CircuitBreaker(**self._kw)
                self._breakers[remote] = b
            return b

    def states(self) -> Dict[str, int]:
        with self._lock:
            return {r: b.state for r, b in self._breakers.items()}

    def all_open(self) -> bool:
        """Every known remote refused past its threshold — the signal
        that flips the ladder to REGION_LOG_DOWN."""
        with self._lock:
            if not self._breakers:
                return False
            return all(
                b.state == BREAKER_OPEN for b in self._breakers.values()
            )

    def min_cooldown_s(self, default: float = 1.0) -> float:
        """The soonest any remote allows a probe — the Retry-After an
        all-breakers-open outage quotes to shed writers."""
        with self._lock:
            if not self._breakers:
                return default
            vals = [
                b.cooldown_remaining_s() for b in self._breakers.values()
            ]
        live = [v for v in vals if v > 0.0]
        return min(live) if live else default
