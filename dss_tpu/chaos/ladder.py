"""The graceful-degradation ladder: one explicit store-level mode.

Every fallback the stack can take used to be local knowledge — the
planner knew about host_only, the mesh replica knew about degraded
mode, the region coordinator knew about dirty state.  The ladder makes
the store's health ONE explicit state machine:

    HEALTHY (0) -> PUSH_DEGRADED (1) -> DEVICE_LOST (2)
                -> MESH_DEGRADED (3) -> FEDERATION_DEGRADED (4)
                -> REGION_LOG_DOWN (5)

driven by condition signals (enter/exit), where the MODE is the worst
active condition.  Effects, wired in dar/dss_store.py + the planner:

  PUSH_DEGRADED     the push delivery queue is saturated or every
                    delivery breaker is open (dss_tpu/push/): writes
                    and reads serve normally and matched notifications
                    are still durably enqueued — only webhook fan-out
                    is behind.  The mildest rung on purpose: losing
                    push delivery never degrades the core serving
                    contract, it degrades the no-polling add-on.
  DEVICE_LOST       the planner's device / resident / mesh routes are
                    inadmissible (ModelState.device_ok=False);
                    hostchunk + inline keep serving — the same
                    reasoning 1403.0802 applies to heterogeneous
                    geospatial backends: lose an executor, remap the
                    work to the next-cheapest one.  The coalescer
                    absorbs in-flight device failures (host re-run,
                    no caller 5xx) and reports the condition.
  MESH_DEGRADED     the multihost mesh lost a peer (the existing
                    MultihostRuntime watchdog flags it); the mesh
                    route is already inadmissible via mesh_fresh —
                    the ladder makes the mode visible stack-wide.
  FEDERATION_DEGRADED  a remote federated region is unreachable (its
                    peer breaker opened — region/federation.py).
                    Local-airspace serving is untouched; cross-region
                    reads degrade to declared-lag stale answers from
                    the local follower mirror (or 503 with the breaker
                    cooldown as Retry-After once past the bound), and
                    writes to remote-owned cells 503 honestly.
                    Recovery re-syncs the follower tail BEFORE the
                    condition clears, so remote routes re-admit with a
                    warm mirror behind them.
  REGION_LOG_DOWN   the region log is unreachable (client breakers
                    open): writes answer 503 with an honest
                    Retry-After (breaker cooldown) while reads keep
                    serving fenced cache/snapshot answers; surfaced
                    in X-DSS-Freshness and /status.

Recovery walks the ladder back DOWN: exit(condition) runs the
registered on_recover callbacks (re-warm the AOT grid, re-prime the
cache) BEFORE clearing the condition, so a route is only re-admitted
once its warm state exists again.  Dwell time per condition is
accounted for bench.py --leg chaos's degraded-mode dwell report.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "HEALTHY",
    "PUSH_DEGRADED",
    "DEVICE_LOST",
    "MESH_DEGRADED",
    "FEDERATION_DEGRADED",
    "REGION_LOG_DOWN",
    "CONDITIONS",
    "MODE_NAMES",
    "DegradationLadder",
]

log = logging.getLogger("dss.chaos")

HEALTHY = 0
PUSH_DEGRADED = 1
DEVICE_LOST = 2
MESH_DEGRADED = 3
FEDERATION_DEGRADED = 4
REGION_LOG_DOWN = 5

# condition name -> ladder severity (mode = max of active conditions).
# Ordered by how much of the serving contract is lost: push fan-out
# lag costs nothing but notification latency, a dead region log costs
# write availability.  Compare modes via the symbolic constants — the
# numbering shifts when a rung is inserted (PR 13 and this one both
# did).
CONDITIONS: Dict[str, int] = {
    "push_degraded": PUSH_DEGRADED,
    "device_lost": DEVICE_LOST,
    "mesh_degraded": MESH_DEGRADED,
    "federation_degraded": FEDERATION_DEGRADED,
    "region_log_down": REGION_LOG_DOWN,
}

MODE_NAMES: Dict[int, str] = {
    HEALTHY: "healthy",
    PUSH_DEGRADED: "push_degraded",
    DEVICE_LOST: "device_lost",
    MESH_DEGRADED: "mesh_degraded",
    FEDERATION_DEGRADED: "federation_degraded",
    REGION_LOG_DOWN: "region_log_down",
}


class DegradationLadder:
    """Thread-safe condition set + severity view + recovery hooks.

    enter() is idempotent (re-entering an active condition only
    refreshes its reason); exit() runs the condition's on_recover
    callbacks with the lock RELEASED (re-warm does device work), then
    clears the condition — so the route a recovery re-admits never
    races its own warm-up."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        # condition -> (entered_at_monotonic, reason)
        self._active: Dict[str, Tuple[float, str]] = {}
        self._recover_cbs: Dict[str, List[Callable[[], None]]] = {}
        self._enter_cbs: Dict[str, List[Callable[[str], None]]] = {}
        self.transitions = 0  # enter+exit edges (the alert's rate basis)
        # per-condition cumulative dwell seconds (closed episodes)
        self._dwell_s: Dict[str, float] = {c: 0.0 for c in CONDITIONS}

    # -- signals -----------------------------------------------------------

    def enter(self, condition: str, reason: str = "") -> bool:
        """Activate a condition.  Returns True on the ENTER edge
        (False when it was already active)."""
        if condition not in CONDITIONS:
            raise ValueError(f"unknown ladder condition {condition!r}")
        with self._lock:
            fresh = condition not in self._active
            if fresh:
                self._active[condition] = (self._clock(), reason)
                self.transitions += 1
            else:
                self._active[condition] = (
                    self._active[condition][0], reason or
                    self._active[condition][1],
                )
            cbs = list(self._enter_cbs.get(condition, ())) if fresh else ()
        if fresh:
            log.error(
                "degradation ladder: ENTER %s (%s) -> mode %s",
                condition, reason or "unspecified", self.mode_name(),
            )
            for fn in cbs:
                try:
                    fn(reason)
                except Exception:  # noqa: BLE001 — degrading must not cascade
                    log.exception("ladder enter callback failed")
        return fresh

    def exit(self, condition: str) -> bool:
        """Recover from a condition: run its on_recover hooks (re-warm
        BEFORE re-admission), then clear it.  Returns True on the EXIT
        edge (False when it was not active)."""
        if condition not in CONDITIONS:
            raise ValueError(f"unknown ladder condition {condition!r}")
        with self._lock:
            if condition not in self._active:
                return False
            cbs = list(self._recover_cbs.get(condition, ()))
        for fn in cbs:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a failed re-warm must not
                # block recovery: the route re-admits and warms lazily
                log.exception("ladder recovery callback failed")
        with self._lock:
            entry = self._active.pop(condition, None)
            if entry is None:
                return False  # raced another exit
            self._dwell_s[condition] += self._clock() - entry[0]
            self.transitions += 1
        log.warning(
            "degradation ladder: EXIT %s -> mode %s",
            condition, self.mode_name(),
        )
        return True

    def on_recover(self, condition: str, fn: Callable[[], None]) -> None:
        """Register a re-warm hook run on exit(condition), before the
        condition clears (AOT grid recompiles, cache re-prime)."""
        self._recover_cbs.setdefault(condition, []).append(fn)

    def on_enter(self, condition: str, fn: Callable[[str], None]) -> None:
        self._enter_cbs.setdefault(condition, []).append(fn)

    # -- views -------------------------------------------------------------

    def mode(self) -> int:
        with self._lock:
            if not self._active:
                return HEALTHY
            return max(CONDITIONS[c] for c in self._active)

    def mode_name(self) -> str:
        return MODE_NAMES[self.mode()]

    def is_active(self, condition: str) -> bool:
        with self._lock:
            return condition in self._active

    def device_ok(self) -> bool:
        return not self.is_active("device_lost")

    def region_ok(self) -> bool:
        return not self.is_active("region_log_down")

    def active(self) -> Dict[str, dict]:
        """Operator view (rides /status): condition -> {since_s,
        reason}."""
        now = self._clock()
        with self._lock:
            return {
                c: {"since_s": round(now - t, 3), "reason": r}
                for c, (t, r) in self._active.items()
            }

    def dwell_s(self, condition: Optional[str] = None) -> float:
        """Cumulative seconds spent in a condition (closed episodes
        plus the live one) — the chaos bench's dwell-time report."""
        now = self._clock()
        with self._lock:
            def one(c):
                d = self._dwell_s.get(c, 0.0)
                if c in self._active:
                    d += now - self._active[c][0]
                return d

            if condition is not None:
                return one(condition)
            return sum(one(c) for c in CONDITIONS)

    def stats(self) -> dict:
        """Gauges for /metrics (dss_store stats namespace)."""
        return {
            "dss_degraded_mode": float(self.mode()),
            "dss_degraded_transitions": float(self.transitions),
            "dss_degraded_dwell_s": round(self.dwell_s(), 3),
        }
