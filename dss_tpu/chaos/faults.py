"""Deterministic fault injection: named seams + a seeded schedule.

Five rounds of serving machinery (mirror replication, the elastic
multihost mesh, the resident device stream, the version-fenced cache,
the planner) created a dozen failure seams that could only be
exercised by hand-written e2e kills.  This module makes every seam a
NAMED FAULT SITE that consults one process-global schedule:

    from dss_tpu import chaos
    chaos.fault_point("wal.fsync")          # sync seams
    await chaos.async_fault_point(          # event-loop seams
        "region.mirror.replicate", detail=url)

A site is a no-op (one module-global bool read) unless a FaultPlan is
installed, so the instrumented hot paths pay nothing in production.
Plans come from the DSS_FAULT_PLAN environment variable (inline JSON,
or a path to a JSON file) or programmatically via install_plan():

    {"seed": 7, "events": [
       {"site": "device.dispatch", "action": "device_lost",
        "after": 10, "count": 3},
       {"site": "region.mirror.replicate", "match": "/replicate",
        "action": "delay", "delay_s": 0.2, "count": 5},
       {"site": "wal.fsync", "action": "delay", "delay_s": 0.05,
        "count": -1, "p": 0.5}]}

Determinism contract: events trigger on per-site HIT COUNTS (`after`
skips the first N matching hits, `count` bounds injections; -1 =
forever), and probabilistic events (`p` < 1) draw from a
random.Random seeded by (plan seed, site, event index) — so the same
plan against the same hit sequence injects the same faults, byte for
byte.  That is what lets test_store_fuzz compare a faulted run against
a no-fault oracle and lets bench.py's chaos scenarios replay.

Actions:
  error        raise FaultError at the site (generic failure)
  partition    raise FaultError(kind="partition") — transports treat
               it exactly like a connection error (retry/failover)
  device_lost  raise DeviceLostError — the coalescer absorbs it,
               reports DEVICE_LOST to the degradation ladder, and
               re-serves the batch on the host route (no caller 5xx)
  delay        sleep delay_s at the site (stall injection; async
               sites await instead of blocking the loop)

Registered sites (grep for the literal to find the seam):
  wal.append / wal.fsync          dar/wal.py
  region.client.request           region/client.py (per attempt)
  region.mirror.replicate         region/mirror.py (sender pushes)
  multihost.barrier / .refresh    parallel/multihost.py
  device.dispatch                 dar/coalesce.py (cold fused submit)
  resident.submit                 ops/resident.py (stream feeder)
  aot.compile                     ops/resident.py (AOT bucket build)
  cache.populate                  dar/dss_store.py (read-cache insert)
  region.federation.request       region/federation.py (peer calls)
  region.federation.sync          region/federation.py (mirror refresh)
  push.match                      push/match.py (reverse-query batch)
  push.deliver                    push/deliver.py (webhook attempt)
  tune.apply                      tune/controller.py (knob hot-swap;
                                  the mid-swap crash drill — the
                                  controller must revert, never leave
                                  half a proposal live)
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "FaultError",
    "DeviceLostError",
    "FaultEvent",
    "FaultPlan",
    "FaultRegistry",
    "registry",
    "install_plan",
    "clear_plan",
    "fault_point",
    "async_fault_point",
    "is_device_loss",
    "load_env_plan",
]

ENV_PLAN = "DSS_FAULT_PLAN"

ACTIONS = ("error", "partition", "device_lost", "delay")


class FaultError(RuntimeError):
    """An injected fault.  `site` names the seam, `kind` the action
    ("error" | "partition" | "device_lost")."""

    def __init__(self, site: str, message: str = "", kind: str = "error"):
        super().__init__(
            message or f"injected fault at {site} ({kind})"
        )
        self.site = site
        self.kind = kind


class DeviceLostError(FaultError):
    """Injected device loss: the serving stack must absorb this (host
    fallback + DEVICE_LOST ladder entry), never surface it as a 5xx."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(site, message, kind="device_lost")


def is_device_loss(e: BaseException) -> bool:
    """Is this exception a device-loss signal the coalescer should
    absorb (host fallback + ladder report) rather than deliver?
    Injected DeviceLostError always; a real backend's device-loss
    shapes can be added here without touching any call site."""
    return isinstance(e, DeviceLostError)


class FaultEvent:
    """One scheduled event: matched by site (exact) and optional
    `match` substring against the hit's detail string; triggers on the
    site's matching-hit counter (`after` skipped first, then up to
    `count` injections; -1 = unbounded), thinned by `p` via the plan's
    deterministic RNG."""

    __slots__ = (
        "site", "action", "after", "count", "delay_s", "p", "match",
        "message", "injected", "seen", "_rng",
    )

    def __init__(
        self,
        site: str,
        action: str = "error",
        *,
        after: int = 0,
        count: int = 1,
        delay_s: float = 0.0,
        p: float = 1.0,
        match: Optional[str] = None,
        message: str = "",
    ):
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; one of {ACTIONS}"
            )
        self.site = str(site)
        self.action = action
        self.after = int(after)
        self.count = int(count)
        self.delay_s = float(delay_s)
        self.p = float(p)
        self.match = match
        self.message = message
        self.injected = 0  # times this event fired
        self.seen = 0  # matching hits observed (drives after/count)
        self._rng: Optional[random.Random] = None

    def bind(self, seed: int, index: int) -> None:
        """Give the event its deterministic RNG (seeded per plan seed
        + site + event index, so reordering unrelated events does not
        perturb this one's draws)."""
        self._rng = random.Random(f"{seed}:{self.site}:{index}")

    def matches(self, detail: Optional[str]) -> bool:
        if self.match is None:
            return True
        return self.match in (detail or "")

    def fire(self, detail: Optional[str]):
        """-> ("error"/"partition"/"device_lost"/"delay", event) when
        this hit injects, else None.  Mutates the hit counters — call
        exactly once per site hit (under the registry lock)."""
        if not self.matches(detail):
            return None
        self.seen += 1
        if self.seen <= self.after:
            return None
        if self.count >= 0 and self.injected >= self.count:
            return None
        if self.p < 1.0:
            rng = self._rng or random
            if rng.random() >= self.p:
                return None
        self.injected += 1
        return self.action

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(
            d["site"],
            d.get("action", "error"),
            after=d.get("after", 0),
            count=d.get("count", 1),
            delay_s=d.get("delay_s", 0.0),
            p=d.get("p", 1.0),
            match=d.get("match"),
            message=d.get("message", ""),
        )


class FaultPlan:
    """A seeded schedule of fault events, replayable byte-for-byte."""

    def __init__(self, events: List[FaultEvent], seed: int = 0):
        self.seed = int(seed)
        self.events = list(events)
        by_site: Dict[str, List[FaultEvent]] = {}
        for i, ev in enumerate(self.events):
            ev.bind(self.seed, i)
            by_site.setdefault(ev.site, []).append(ev)
        self._by_site = by_site

    def events_for(self, site: str) -> List[FaultEvent]:
        return self._by_site.get(site, ())

    @property
    def sites(self):
        return tuple(sorted(self._by_site))

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(
            [FaultEvent.from_dict(e) for e in d.get("events", [])],
            seed=d.get("seed", 0),
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_env(cls, raw: str) -> "FaultPlan":
        """DSS_FAULT_PLAN value: inline JSON (starts with '{') or the
        path of a JSON file."""
        raw = raw.strip()
        if raw.startswith("{"):
            return cls.from_json(raw)
        with open(raw, "r", encoding="utf-8") as fh:
            return cls.from_json(fh.read())


class FaultRegistry:
    """Process-global fault-site registry: per-site hit and injection
    counters (the dss_fault_injected_total{site} gauge family) plus
    the installed plan.  check() is only reached when a plan is
    installed — fault_point() gates on the module flag first, so an
    uninstrumented deployment pays one global read per site hit."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plan: Optional[FaultPlan] = None
        self.hits: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    @property
    def plan(self) -> Optional[FaultPlan]:
        return self._plan

    def install(self, plan: Optional[FaultPlan]) -> None:
        global _ACTIVE
        with self._lock:
            self._plan = plan
        _ACTIVE = plan is not None

    def clear(self) -> None:
        self.install(None)

    def reset_counters(self) -> None:
        with self._lock:
            self.hits.clear()
            self.injected.clear()

    def injected_by_site(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.injected)

    def hits_by_site(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.hits)

    def check(self, site: str, detail: Optional[str] = None):
        """Count the hit and consult the plan -> (action, event) to
        perform, or None.  The caller performs the action (raise /
        sleep / await) so sync and async sites share this core."""
        with self._lock:
            self.hits[site] = self.hits.get(site, 0) + 1
            plan = self._plan
            if plan is None:
                return None
            for ev in plan.events_for(site):
                action = ev.fire(detail)
                if action is not None:
                    self.injected[site] = self.injected.get(site, 0) + 1
                    return (action, ev)
        return None

    def _raise_for(self, site: str, action: str, ev: FaultEvent):
        if action == "device_lost":
            raise DeviceLostError(site, ev.message)
        raise FaultError(site, ev.message, kind=action)

    def fire(self, site: str, detail: Optional[str] = None) -> None:
        hit = self.check(site, detail)
        if hit is None:
            return
        action, ev = hit
        if action == "delay":
            time.sleep(ev.delay_s)
            return
        self._raise_for(site, action, ev)

    async def fire_async(
        self, site: str, detail: Optional[str] = None
    ) -> None:
        hit = self.check(site, detail)
        if hit is None:
            return
        action, ev = hit
        if action == "delay":
            import asyncio

            await asyncio.sleep(ev.delay_s)
            return
        self._raise_for(site, action, ev)


_REGISTRY = FaultRegistry()
_ACTIVE = False  # mirror of "a plan is installed": the zero-overhead gate


def registry() -> FaultRegistry:
    return _REGISTRY


def install_plan(plan) -> None:
    """Install a FaultPlan (or a dict / JSON text coerced into one)."""
    if isinstance(plan, str):
        plan = FaultPlan.from_json(plan)
    elif isinstance(plan, dict):
        plan = FaultPlan.from_dict(plan)
    _REGISTRY.install(plan)


def clear_plan() -> None:
    _REGISTRY.clear()


def fault_point(site: str, detail: Optional[str] = None) -> None:
    """THE sync seam instrumentation call.  One global-bool read when
    no plan is installed (the production case)."""
    if not _ACTIVE:
        return
    _REGISTRY.fire(site, detail)


async def async_fault_point(
    site: str, detail: Optional[str] = None
) -> None:
    """fault_point for event-loop seams: delay events await instead of
    blocking the loop."""
    if not _ACTIVE:
        return
    await _REGISTRY.fire_async(site, detail)


def load_env_plan() -> bool:
    """Install the DSS_FAULT_PLAN plan if the env var is set (called
    at import so any process — server, region server, bench, test —
    honors the schedule).  Returns whether a plan was installed."""
    raw = os.environ.get(ENV_PLAN)
    if not raw:
        return False
    _REGISTRY.install(FaultPlan.from_env(raw))
    return True


load_env_plan()
