"""Deterministic fault injection + the graceful-degradation ladder.

Three pieces (see each module's docstring):

  faults   named fault sites (`chaos.fault_point("wal.fsync")`)
           consulting a seeded, replayable FaultPlan loaded from
           DSS_FAULT_PLAN — zero overhead when no plan is installed
  retry    ONE jittered-backoff policy + per-remote circuit breakers,
           replacing the three divergent ad-hoc retry loops
           (RegionClient transport, mirror sender, coordinator
           conflict cool-down)
  ladder   the store-level degradation state machine
           (HEALTHY -> DEVICE_LOST -> MESH_DEGRADED ->
           FEDERATION_DEGRADED -> REGION_LOG_DOWN) with
           re-warm-before-re-admit recovery

Import cost matters (dar/wal.py imports this): no jax, no numpy,
stdlib only.
"""

from dss_tpu.chaos.faults import (  # noqa: F401
    DeviceLostError,
    FaultError,
    FaultEvent,
    FaultPlan,
    FaultRegistry,
    async_fault_point,
    clear_plan,
    fault_point,
    install_plan,
    is_device_loss,
    load_env_plan,
    registry,
)
from dss_tpu.chaos.ladder import (  # noqa: F401
    CONDITIONS,
    DEVICE_LOST,
    FEDERATION_DEGRADED,
    HEALTHY,
    MESH_DEGRADED,
    MODE_NAMES,
    PUSH_DEGRADED,
    REGION_LOG_DOWN,
    DegradationLadder,
)
from dss_tpu.chaos.retry import (  # noqa: F401
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BreakerRegistry,
    CircuitBreaker,
    Deadline,
    RetryPolicy,
)
