"""Process-level runtime hygiene shared by the server binaries."""

from __future__ import annotations

import gc
import logging

log = logging.getLogger("dss.runtime")


def freeze_boot_heap() -> int:
    """Park the boot-time heap outside GC scans and return the frozen
    object count.

    The objects alive once a binary finishes booting (store records
    replayed from the WAL, packed index arrays, compiled-code caches)
    dominate the process object count; every gen2 collection rescans
    them and stalls serving ~8 ms at the 1M-intent scale (measured:
    closed-loop serving 8.2k -> 9.5k qps with the scan removed).
    gc.freeze() moves them to the permanent generation: refcounting
    still frees dead ones, only CYCLES among frozen objects would
    leak, and the stores' records are acyclic (dicts/arrays/
    dataclasses) — the Instagram-style trade.

    Call AFTER boot work has finished (WAL replay, replica start,
    warmup compile): freezing mid-boot both pins boot transients
    forever and leaves the still-growing heap unfrozen.
    """
    gc.collect()
    gc.freeze()
    n = gc.get_freeze_count()
    log.info("gc: froze %d boot objects out of collection scans", n)
    return n
