"""Reverse-query matching: the write-side half of the fused kernel.

A subscription-notification lookup is the same geometry problem as a
search with the roles swapped: the write's 4D volume (cells + altitude
band + time window) is the QUERY, the subscription class's DAR is the
DATA.  MatchStage batches those write-side queries and routes them
through the planner's `rqmatch` candidate (plan/planner.py) — one
fused DarTable.query_many launch per batch when the device class is
admissible, chunked exact host scans (bit-identical by construction)
when it is not: DEVICE_LOST, the memory backend, or an injected
`push.match` fault, which is absorbed onto the host oracle exactly
like the coalescer absorbs device loss (a notification miss is a
correctness bug; a slower match is a latency note).

The stage shares the subscription-class coalescer's Planner when one
exists, so rqmatch plans land in the same co_plan_* counters the read
routes use (dss_dar_scd_sub_co_plan_rqmatch in /metrics) and rqmatch
cost observations feed the same CostModel's est_rq_* keys.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from dss_tpu import chaos
from dss_tpu.geo import s2cell
from dss_tpu.obs import stages
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO
from dss_tpu.plan.planner import BatchShape, Planner

__all__ = ["MatchQuery", "MatchStage"]

# (cells_u64, alt_lo | None, alt_hi | None, t_start_ns | None,
#  t_end_ns | None) — one write's match volume
MatchQuery = Tuple[np.ndarray, Optional[float], Optional[float],
                   Optional[int], Optional[int]]


class MatchStage:
    """Match write volumes against one subscription class's index.

    `index` is a dar.index spatial index (TpuSpatialIndex or
    MemorySpatialIndex).  On the TPU backend the stage plans with the
    index's own coalescer Planner (shared counters + cost model); the
    memory backend gets a private Planner whose device class is never
    admissible, so every plan routes hostchunk — the oracle."""

    def __init__(self, index, *, planner: Optional[Planner] = None,
                 health=None, metrics=None):
        self._index = index
        self._table = getattr(index, "table", None)
        self._health = health
        # direct registry handle: match runs on writer/pipeline
        # threads with no thread-local stage sink, so stages.mark alone
        # would drop push_match_ms on the floor — this feeds the
        # dss_stage_duration_seconds{stage="push_match_ms"} histogram
        # (STAGE_NAMES allowlist) the same way deliver.py feeds
        # push_deliver_ms
        self._metrics = metrics
        co = getattr(index, "coalescer", None)
        if planner is not None:
            self._planner = planner
        elif co is not None:
            self._planner = co._planner
        else:
            self._planner = Planner()
        self.batches = 0
        self.queries = 0
        self.absorbed = 0  # device-class faults re-served on the host

    # -- planning ---------------------------------------------------------

    def _device_ok(self) -> bool:
        if self._table is None:
            return False
        if self._health is not None and not self._health.device_ok():
            return False
        return True

    # -- execution --------------------------------------------------------

    @staticmethod
    def _pack(queries: Sequence[MatchQuery]):
        keys_list = [
            s2cell.cell_to_dar_key(np.asarray(c, dtype=np.uint64))
            for c, _, _, _, _ in queries
        ]
        alt_lo = np.asarray(
            [-np.inf if a is None else float(a)
             for _, a, _, _, _ in queries], np.float32,
        )
        alt_hi = np.asarray(
            [np.inf if a is None else float(a)
             for _, _, a, _, _ in queries], np.float32,
        )
        t0 = np.asarray(
            [NO_TIME_LO if t is None else int(t)
             for _, _, _, t, _ in queries], np.int64,
        )
        t1 = np.asarray(
            [NO_TIME_HI if t is None else int(t)
             for _, _, _, _, t in queries], np.int64,
        )
        return keys_list, alt_lo, alt_hi, t0, t1

    def _run_table(self, queries, now_ns: int,
                   host_route: bool) -> List[List[str]]:
        keys_list, alt_lo, alt_hi, t0, t1 = self._pack(queries)
        return self._table.query_many(
            keys_list, alt_lo, alt_hi, t0, t1,
            now=int(now_ns), host_route=host_route,
        )

    def _run_oracle(self, queries, now_ns: int) -> List[List[str]]:
        if self._table is not None:
            return self._run_table(queries, now_ns, host_route=True)
        out = []
        for cells, alt_lo, alt_hi, t0, t1 in queries:
            ids = self._index.query_ids(
                np.asarray(cells, dtype=np.uint64),
                alt_lo=alt_lo, alt_hi=alt_hi,
                t_start=t0, t_end=t1, now=int(now_ns),
            )
            out.append(sorted(ids))
        return out

    # -- public -----------------------------------------------------------

    def match_many(self, queries: Sequence[MatchQuery], *,
                   now_ns: int) -> List[List[str]]:
        """Match a batch of write volumes; returns a sorted id list
        per query.  Bit-identical across routes — the rqmatch kernel,
        the forced host chunks, and the memory oracle all implement
        the same COALESCE intersection rules."""
        b = len(queries)
        if b == 0:
            return []
        t0 = time.perf_counter()
        state = self._planner.capture(device_ok=self._device_ok())
        plan = self._planner.plan(
            BatchShape(n=b, rqmatch=True), state, None
        )
        try:
            chaos.fault_point("push.match")
            if plan.route == "rqmatch":
                out = [
                    sorted(ids)
                    for ids in self._run_table(
                        queries, now_ns, host_route=False
                    )
                ]
            else:
                out = self._run_oracle(queries, now_ns)
        except Exception as e:  # noqa: BLE001 — absorb, never miss
            if not isinstance(e, chaos.FaultError) and not (
                chaos.is_device_loss(e)
            ):
                raise
            # injected fault or in-flight device loss: the host
            # oracle serves the same answer — a notification must
            # never be missed because a route died under it
            out = self._run_oracle(queries, now_ns)
            self.absorbed += 1
        dur_ms = (time.perf_counter() - t0) * 1000.0
        if plan.route == "rqmatch":
            self._planner.observe_rqmatch(b, dur_ms)
        stages.mark("push_match_ms", dur_ms)
        if self._metrics is not None:
            self._metrics.observe_stage(
                "push", "push_match_ms", dur_ms / 1000.0
            )
        self.batches += 1
        self.queries += b
        return out

    def match(self, cells, alt_lo=None, alt_hi=None, t_start_ns=None,
              t_end_ns=None, *, now_ns: int) -> List[str]:
        """Single-volume convenience (the store's write path)."""
        return self.match_many(
            [(cells, alt_lo, alt_hi, t_start_ns, t_end_ns)],
            now_ns=now_ns,
        )[0]

    def oracle_many(self, queries: Sequence[MatchQuery], *,
                    now_ns: int) -> List[List[str]]:
        """The host-oracle answer, unconditionally — what the
        bit-identity tests (and the chaos drills) compare against."""
        return self._run_oracle(queries, now_ns)

    def stats(self) -> dict:
        return {
            "match_batches": self.batches,
            "match_queries": self.queries,
            "match_absorbed": self.absorbed,
        }
