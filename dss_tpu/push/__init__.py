"""Reverse-query push pipeline: device-matched subscription
notifications with durable per-USS delivery.

The read path answers "which entities intersect this volume?" at
device-kernel throughput; this package makes the WRITE path do the
same for "which subscribers care about this write?" — a write is a
reverse query, the same fused geometry kernel with the query and data
roles swapped over the subscription classes' DAR — and then actually
tells them, instead of returning a subscriber list the USS must poll
to act on (the paper's "notify a million subscribers without polling"
capability).

Four pieces (see each module's docstring):

  match     MatchStage — write-side match batches through the planner's
            `rqmatch` route (plan/planner.py): the fused kernel over
            the subscription DAR when the device class is admissible,
            the bit-identical host oracle otherwise.
  queue     DeliveryLog — a WAL-backed per-USS notification queue with
            cursor + ack semantics: an acked notification survives any
            crash and is never redelivered; an unacked one is
            redelivered at-least-once after restart.
  deliver   DeliveryPool — webhook fan-out workers with per-USS
            circuit breakers, the shared chaos RetryPolicy, and a QoS
            tier where emergency-scenario operations preempt bulk.
  pipeline  PushPipeline — ties the stages to a DSSStore
            (DSSStore.attach_push), owns webhook registration, the
            /aux/v1/push/* surface, federation fan-out of cross-region
            events, the dss_push_* gauges, and the push_degraded
            ladder condition.

Fault sites: `push.match` (before a match batch executes; device-class
faults are absorbed onto the host oracle) and `push.deliver` (before a
webhook attempt; counted against the USS's breaker).
"""

from dss_tpu.push.match import MatchStage  # noqa: F401
from dss_tpu.push.queue import DeliveryLog, Notification  # noqa: F401
from dss_tpu.push.deliver import DeliveryPool  # noqa: F401
from dss_tpu.push.pipeline import (  # noqa: F401
    PushPipeline,
    empty_stats,
    env_knobs,
)
