"""Webhook fan-out workers: breakers, backoff, QoS, lag accounting.

The DeliveryPool drains the DeliveryLog with plain I/O threads — the
pool never touches the store lock, the device, or the coalescer, so
fan-out to any number of subscribers cannot block the device owner's
serve path (the shm-front deployment keeps its owner threads fenced
from delivery entirely: the only shared state is the WAL-backed
queue).

Per-USS flow control, all through the shared chaos machinery
(chaos/retry.py — no new retry dialect):

  breaker   one CircuitBreaker per USS (BreakerRegistry): consecutive
            webhook failures open it, and an open breaker removes the
            USS from the take() rotation — a dead USS costs zero
            attempts while every other USS keeps draining.  Surfaced
            as dss_push_breaker_state{uss}.
  backoff   the shared jittered-exponential RetryPolicy stamps a
            per-USS not-before hold after each failure, so a flapping
            USS is retried on the policy's schedule instead of
            hot-looped.
  parking   past max_attempts a notification is parked (durably acked
            so it never redelivers, counted as dss_push_parked_total)
            — the dead-letter seam, NOT a success.

QoS: the queue hands out the emergency band strictly before bulk; the
pool adds nothing — preemption is a property of what take() returns.

Every delivery POST carries the traceparent captured when the WRITE
enqueued it plus X-Request-Id, so write -> match -> deliver stitches
into one trace at the receiver; the attempt duration lands in the
dss_stage_duration_seconds{stage="push_deliver_ms"} histogram and the
enqueue->ack wall time feeds the delivery-lag reservoir behind
dss_push_delivery_lag_p50_ms/p99_ms.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from dss_tpu import chaos
from dss_tpu.push.queue import DeliveryLog, Notification

__all__ = ["DeliveryPool", "http_transport"]


def http_transport(timeout_s: float = 3.0) -> Callable:
    """The production webhook sender: POST the notification body as
    JSON.  Any non-2xx or transport error raises."""
    import urllib.request

    def send(url: str, body: dict, headers: Dict[str, str]) -> None:
        req = urllib.request.Request(
            url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **headers},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            if not (200 <= resp.status < 300):
                raise OSError(f"webhook status {resp.status}")

    return send


class DeliveryPool:
    """N worker threads draining a DeliveryLog.

    `transport(url, body, headers)` raises on failure; `sender`, when
    given, overrides transport per notification (the pipeline routes
    `@region:` pseudo-targets to federation peers through it)."""

    def __init__(self, log: DeliveryLog, *, workers: int = 2,
                 transport: Optional[Callable] = None,
                 sender: Optional[Callable] = None,
                 retry: Optional[chaos.RetryPolicy] = None,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 2.0,
                 max_attempts: int = 20,
                 metrics=None,
                 clock=time.monotonic,
                 wall_clock_ns=time.time_ns,
                 on_edge: Optional[Callable[[], None]] = None):
        self._log = log
        self._workers = max(1, int(workers))
        self._transport = transport or http_transport()
        self._sender = sender
        self._retry = retry or chaos.RetryPolicy(
            base_s=0.05, cap_s=5.0, seed=0x9157
        )
        self.breakers = chaos.BreakerRegistry(
            fail_threshold=breaker_threshold,
            reset_s=breaker_reset_s, clock=clock,
        )
        self.max_attempts = max(1, int(max_attempts))
        self._metrics = metrics
        self._clock = clock
        self._wall_ns = wall_clock_ns
        self._on_edge = on_edge  # pipeline's ladder re-evaluation hook
        self._lock = threading.Lock()
        self._holds: Dict[str, float] = {}  # uss -> not-before (mono)
        self._lags_ms: deque = deque(maxlen=4096)
        self.delivered = 0
        self.failures = 0
        self.parked = 0
        self._threads = []
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        for i in range(self._workers):
            t = threading.Thread(
                target=self._run, name=f"dss-push-deliver-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    # -- flow control ------------------------------------------------------

    def _blocked(self):
        now = self._clock()
        with self._lock:
            held = {u for u, t in self._holds.items() if t > now}
            for u in [u for u, t in self._holds.items() if t <= now]:
                del self._holds[u]
        # breaker-open USSs are skipped without an attempt; half-open
        # lets the probe through (allow() flips the state)
        for uss, state in self.breakers.states().items():
            if state == chaos.BREAKER_OPEN:
                b = self.breakers.get(uss)
                if not b.allow():
                    held.add(uss)
        return held

    def _hold(self, uss: str, attempts: int) -> None:
        with self._lock:
            self._holds[uss] = self._clock() + self._retry.backoff_s(
                min(attempts, 10)
            )

    # -- the worker loop ---------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            n = self._log.take(blocked=self._blocked(), timeout_s=0.2)
            if n is None:
                continue
            self._attempt(n)

    def _attempt(self, n: Notification) -> None:
        headers = {}
        if n.traceparent:
            headers["traceparent"] = n.traceparent
            # trace id = chars 3..35 of the traceparent; the receiver
            # greps its logs by request id exactly like our own front
            parts = n.traceparent.split("-")
            if len(parts) == 4:
                headers["X-Request-Id"] = parts[1]
        headers["X-DSS-Notification-Id"] = str(n.nid)
        breaker = self.breakers.get(n.uss)
        t0 = time.perf_counter()
        try:
            chaos.fault_point("push.deliver", detail=n.uss)
            if self._sender is not None:
                self._sender(n, headers)
            else:
                self._transport(n.target, n.body, headers)
        except Exception:  # noqa: BLE001 — any failure is a retry
            breaker.record_failure()
            self.failures += 1
            if n.attempts + 1 >= self.max_attempts:
                self._log.park(n.nid, reason="max_attempts")
                self.parked += 1
            else:
                self._hold(n.uss, n.attempts)
                self._log.requeue(n)
            if self._on_edge is not None:
                self._on_edge()
            return
        dur_s = time.perf_counter() - t0
        breaker.record_success()
        self._log.ack(n.nid)
        self.delivered += 1
        lag_ms = max(0.0, (self._wall_ns() - n.enqueued_ns) / 1e6)
        with self._lock:
            self._lags_ms.append(lag_ms)
        if self._metrics is not None:
            self._metrics.observe_stage("push", "push_deliver_ms", dur_s)
        if self._on_edge is not None:
            self._on_edge()

    # -- views -------------------------------------------------------------

    def lag_percentiles_ms(self) -> Dict[str, float]:
        with self._lock:
            lags = sorted(self._lags_ms)
        if not lags:
            return {"p50": 0.0, "p99": 0.0}

        def pct(p):
            i = min(len(lags) - 1, int(p * (len(lags) - 1)))
            return round(lags[i], 3)

        return {"p50": pct(0.50), "p99": pct(0.99)}

    def all_open(self) -> bool:
        return self.breakers.all_open()

    def stats(self) -> dict:
        lag = self.lag_percentiles_ms()
        return {
            "delivered": self.delivered,
            "failures": self.failures,
            "parked": self.parked,
            "lag_p50_ms": lag["p50"],
            "lag_p99_ms": lag["p99"],
            "breaker_state": self.breakers.states(),
        }
