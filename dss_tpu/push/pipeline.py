"""PushPipeline: the store-facing spine of the push subsystem.

Wiring (DSSStore.attach_push):

  write txn (store lock held)
    -> MatchStage.match (planner rqmatch route, bit-identical host
       fallback) — the SAME id set `_notify_subs_locked` bumps and the
       HTTP response returns, so enabling push cannot change a
       response byte
    -> bump + journal (unchanged)
    -> PushPipeline.offer(...) — O(1) per matched subscriber: resolve
       the registered webhook, append a durable push_evt, wake the
       delivery pool.  Everything slow (webhook POSTs, retries,
       breaker probes, federation hops) happens on the pool's I/O
       threads, never on the write path and never under the store
       lock.

Federation: a local write is also fanned to every remote region as a
`@region:<id>` pseudo-notification riding the same durable queue —
the owning region's /aux/v1/push/ingest re-runs the match against ITS
subscription DAR (subscriptions live where they were registered, so
the match must too) and enqueues local webhook deliveries.  Remote
ingest never bumps notification indexes (the bump belongs to the
region that owns the write txn) and never re-forwards (no loops).

Health: queue saturation (depth past DSS_PUSH_DEPTH_HIGH of the
bound) or every delivery breaker open flips the store ladder to
push_degraded — the mildest rung: serving is untouched, only webhook
fan-out is behind.  Recovery exits the condition when the depth
drains under the low-water mark and a breaker closes.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

from dss_tpu.obs import trace
from dss_tpu.push.deliver import DeliveryPool
from dss_tpu.push.match import MatchStage
from dss_tpu.push.queue import DeliveryLog

__all__ = ["PushPipeline", "empty_stats", "env_knobs"]

_REGION_PREFIX = "@region:"


def env_knobs() -> dict:
    """DSS_PUSH_* boot knobs (docs/OPERATIONS.md has the table)."""

    def _f(name, default, conv):
        v = os.environ.get(name)
        if v is None or v == "":
            return default
        try:
            return conv(v)
        except (TypeError, ValueError):
            return default

    return {
        "log_path": os.environ.get("DSS_PUSH_LOG") or None,
        "fsync": _f("DSS_PUSH_FSYNC", False, lambda v: v == "1"),
        "workers": _f("DSS_PUSH_WORKERS", 2, int),
        "max_depth": _f("DSS_PUSH_MAX_DEPTH", 100_000, int),
        "max_attempts": _f("DSS_PUSH_MAX_ATTEMPTS", 20, int),
        "breaker_threshold": _f("DSS_PUSH_BREAKER_THRESHOLD", 3, int),
        "breaker_reset_s": _f("DSS_PUSH_BREAKER_RESET_S", 2.0, float),
        "timeout_s": _f("DSS_PUSH_TIMEOUT_S", 3.0, float),
        "federate": _f("DSS_PUSH_FEDERATE", True, lambda v: v != "0"),
    }


class PushPipeline:
    """One store's push subsystem: match stages + durable queue +
    delivery pool + webhook registry."""

    def __init__(self, *, log_path: Optional[str] = None,
                 fsync: bool = False, workers: int = 2,
                 max_depth: int = 100_000, max_attempts: int = 20,
                 breaker_threshold: int = 3, breaker_reset_s: float = 2.0,
                 timeout_s: float = 3.0, federate: bool = True,
                 transport=None, metrics=None,
                 depth_high: float = 0.9, depth_low: float = 0.5):
        self.log = DeliveryLog(
            log_path, fsync=fsync, max_depth=max_depth
        )
        if transport is None:
            from dss_tpu.push.deliver import http_transport

            transport = http_transport(timeout_s)
        self.pool = DeliveryPool(
            self.log, workers=workers, transport=transport,
            sender=self._send, max_attempts=max_attempts,
            breaker_threshold=breaker_threshold,
            breaker_reset_s=breaker_reset_s, metrics=metrics,
            on_edge=self._update_health,
        )
        self._transport = transport
        self._metrics = metrics
        self._federate = bool(federate)
        self._depth_high = float(depth_high)
        self._depth_low = float(depth_low)
        self._store = None
        self._health = None
        self._stages: Dict[str, MatchStage] = {}
        self._lock = threading.Lock()
        self._degraded = False
        self.skipped = 0  # matched subs with no registered webhook
        self.fed_forwarded = 0
        self.fed_ingested = 0
        self.offers = 0

    # -- store binding -----------------------------------------------------

    def bind_store(self, store) -> None:
        """Called by DSSStore.attach_push: build a MatchStage per
        subscription class over the store's live indexes, share the
        store's health ladder, and start the delivery pool."""
        self._store = store
        self._health = store.health
        self._stages = {
            "rid_sub": MatchStage(
                store.rid._sub_index, health=store.health,
                metrics=self._metrics,
            ),
            "scd_sub": MatchStage(
                store.scd._sub_index, health=store.health,
                metrics=self._metrics,
            ),
        }
        self.pool.start()

    @property
    def bound(self) -> bool:
        return self._store is not None

    def stage(self, cls: str) -> MatchStage:
        return self._stages[cls]

    # -- matching (the store's write path) ---------------------------------

    def match_ids(self, cls: str, cells, alt_lo=None, alt_hi=None,
                  t_start_ns=None, t_end_ns=None, *,
                  now_ns: int) -> List[str]:
        """One write volume against one subscription class — the
        rqmatch route (host-oracle fallback), sorted ids."""
        return self._stages[cls].match(
            cells, alt_lo, alt_hi, t_start_ns, t_end_ns, now_ns=now_ns
        )

    # -- fan-out (called post-journal, inside the write txn) ---------------

    def offer(self, trigger: str, entity, subs, *,
              removed: bool = False, emergency: bool = False,
              alt_lo=None, alt_hi=None, t_start_ns=None,
              t_end_ns=None) -> int:
        """Durably enqueue one notification per matched+bumped
        subscriber with a registered webhook, plus one federation
        forward per remote region.  Returns notifications enqueued.
        Cheap by contract — WAL appends and a condition notify; all
        I/O happens on the pool."""
        self.offers += 1
        tp = trace.propagation_headers().get("traceparent", "")
        ent = {
            "type": trigger,
            "id": getattr(entity, "id", ""),
            "ovn": getattr(entity, "ovn", ""),
            "owner": str(getattr(entity, "owner", "")),
            "removed": bool(removed),
        }
        n_enq = 0
        for sub in subs:
            hook = self.log.hook_of(str(sub.owner))
            if hook is None:
                self.skipped += 1
                continue
            qos = "emergency" if emergency else hook["qos"]
            body = {
                "trigger": trigger,
                "entity": ent,
                "subscription": {
                    "id": sub.id,
                    "notification_index": sub.notification_index,
                },
            }
            if self.log.enqueue(
                str(sub.owner), hook["url"], body, qos=qos,
                traceparent=tp,
            ) is not None:
                n_enq += 1
        n_enq += self._forward_remote(
            trigger, entity, ent, emergency=emergency,
            alt_lo=alt_lo, alt_hi=alt_hi,
            t_start_ns=t_start_ns, t_end_ns=t_end_ns,
            traceparent=tp,
        )
        self._update_health()
        return n_enq

    def _forward_remote(self, trigger, entity, ent, *, emergency,
                        alt_lo, alt_hi, t_start_ns, t_end_ns,
                        traceparent) -> int:
        store = self._store
        if not self._federate or store is None:
            return 0
        fed = getattr(store, "federation", None)
        if fed is None or not getattr(fed, "peers", None):
            return 0
        cells = np.asarray(
            getattr(entity, "cells", ()), dtype=np.uint64
        ).ravel()
        if cells.size == 0:
            return 0
        payload = {
            "trigger": trigger,
            "entity": ent,
            "emergency": bool(emergency),
            "cells": [int(c) for c in cells],
            "alt_lo": None if alt_lo is None else float(alt_lo),
            "alt_hi": None if alt_hi is None else float(alt_hi),
            "t0_ns": None if t_start_ns is None else int(t_start_ns),
            "t1_ns": None if t_end_ns is None else int(t_end_ns),
            "origin": getattr(fed, "region_id", ""),
        }
        n = 0
        for rid in fed.peers:
            if self.log.enqueue(
                _REGION_PREFIX + rid, rid, payload,
                qos="emergency" if emergency else "bulk",
                traceparent=traceparent,
            ) is not None:
                self.fed_forwarded += 1
                n += 1
        return n

    # -- delivery sender (webhook or federation hop) -----------------------

    def _send(self, n, headers: Dict[str, str]) -> None:
        """DeliveryPool sender: `@region:` pseudo-targets hop to the
        owning region's ingest endpoint through its FederationPeer
        (breaker-counted there too); everything else is a webhook
        POST."""
        if n.uss.startswith(_REGION_PREFIX):
            fed = getattr(self._store, "federation", None)
            if fed is None:
                raise RuntimeError("federation detached")
            peer = fed.peers[n.target]
            if not peer.breaker.allow():
                raise RuntimeError(f"peer {n.target} breaker open")
            peer.call("POST", "/aux/v1/push/ingest", n.body)
            return
        self._transport(n.target, n.body, headers)

    # -- federation fan-in -------------------------------------------------

    def ingest_remote(self, payload: dict) -> dict:
        """Serve a remote region's /aux/v1/push/ingest: match the
        remote write's volume against OUR subscription DAR and enqueue
        local webhook deliveries.  No notification-index bump (the
        writing region owns the txn; our indexes advance only on local
        writes) and no re-forward (origin != local only, no loops)."""
        store = self._store
        if store is None:
            raise RuntimeError("push pipeline not bound to a store")
        trigger = payload.get("trigger", "operations")
        cls = "rid_sub" if trigger == "rid" else "scd_sub"
        cells = np.asarray(
            [int(c) for c in payload.get("cells", ())], dtype=np.uint64
        )
        if cells.size == 0:
            return {"matched": 0, "enqueued": 0}
        sub_store = store.rid if cls == "rid_sub" else store.scd
        now_ns = sub_store._now_ns()
        ids = self.match_ids(
            cls, cells,
            alt_lo=payload.get("alt_lo"), alt_hi=payload.get("alt_hi"),
            t_start_ns=payload.get("t0_ns"),
            t_end_ns=payload.get("t1_ns"), now_ns=now_ns,
        )
        want_constraints = trigger == "constraints"
        ent = dict(payload.get("entity", {}))
        ent["origin"] = payload.get("origin", "")
        emergency = bool(payload.get("emergency", False))
        tp = payload.get("traceparent", "")
        n_enq = 0
        matched = 0
        for i in sorted(ids):
            sub = sub_store._subs.get(i)
            if sub is None:
                continue
            if cls == "scd_sub":
                if want_constraints:
                    if not sub.notify_for_constraints:
                        continue
                elif not sub.notify_for_operations:
                    continue
            matched += 1
            hook = self.log.hook_of(str(sub.owner))
            if hook is None:
                self.skipped += 1
                continue
            body = {
                "trigger": trigger,
                "entity": ent,
                "subscription": {
                    "id": sub.id,
                    "notification_index": sub.notification_index,
                },
            }
            if self.log.enqueue(
                str(sub.owner), hook["url"], body,
                qos="emergency" if emergency else hook["qos"],
                traceparent=tp,
            ) is not None:
                n_enq += 1
        self.fed_ingested += 1
        self._update_health()
        return {"matched": matched, "enqueued": n_enq}

    # -- webhook registry passthrough --------------------------------------

    def register_hook(self, uss: str, url: str,
                      qos: str = "bulk") -> dict:
        return self.log.register_hook(uss, url, qos)

    def unregister_hook(self, uss: str) -> bool:
        return self.log.unregister_hook(uss)

    def hooks(self) -> Dict[str, dict]:
        return self.log.hooks()

    # -- health ------------------------------------------------------------

    def _update_health(self) -> None:
        health = self._health
        if health is None:
            return
        depth = self.log.depth()
        saturated = depth >= self._depth_high * self.log.max_depth
        starved = bool(self.log.hooks()) and self.pool.all_open()
        if saturated or starved:
            if not self._degraded:
                self._degraded = True
                health.enter(
                    "push_degraded",
                    "queue saturated" if saturated
                    else "all delivery breakers open",
                )
        elif self._degraded and depth <= (
            self._depth_low * self.log.max_depth
        ) and not self.pool.all_open():
            self._degraded = False
            health.exit("push_degraded")

    # -- lifecycle / introspection -----------------------------------------

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue is empty (tests/bench); False on
        timeout."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.log.depth() == 0:
                return True
            _time.sleep(0.005)
        return self.log.depth() == 0

    def close(self) -> None:
        self.pool.close()
        self.log.close()

    def status(self) -> dict:
        """Operator view (GET /aux/v1/push/status)."""
        q = self.log.stats()
        p = self.pool.stats()
        return {
            "hooks": self.hooks(),
            "queue": q,
            "delivered": p["delivered"],
            "failures": p["failures"],
            "parked": p["parked"],
            "delivery_lag_ms": self.pool.lag_percentiles_ms(),
            "breakers": {
                u: s for u, s in p["breaker_state"].items()
            },
            "degraded": self._degraded,
            "match": {
                cls: st.stats() for cls, st in self._stages.items()
            },
            "federation": {
                "forwarded": self.fed_forwarded,
                "ingested": self.fed_ingested,
            },
        }

    def stats(self) -> dict:
        """dss_push_* gauges — the same stable key set empty_stats()
        exports when no pipeline is attached."""
        q = self.log.stats()
        p = self.pool.stats()
        return {
            "dss_push_queue_depth": q["depth"],
            "dss_push_queue_depth_emergency": q["depth_emergency"],
            "dss_push_queue_depth_bulk": q["depth_bulk"],
            "dss_push_enqueued_total": q["enqueued"],
            "dss_push_acked_total": q["acked"],
            "dss_push_dropped_total": q["dropped"],
            "dss_push_requeued_total": q["requeued"],
            "dss_push_hooks": q["hooks"],
            "dss_push_delivered_total": p["delivered"],
            "dss_push_failures_total": p["failures"],
            "dss_push_parked_total": p["parked"],
            "dss_push_delivery_lag_p50_ms": p["lag_p50_ms"],
            "dss_push_delivery_lag_p99_ms": p["lag_p99_ms"],
            "dss_push_oldest_pending_s": round(
                self.log.oldest_pending_age_s(), 3
            ),
            "dss_push_skipped_total": self.skipped,
            "dss_push_fed_forwarded_total": self.fed_forwarded,
            "dss_push_fed_ingested_total": self.fed_ingested,
            "dss_push_match_batches_total": sum(
                st.batches for st in self._stages.values()
            ),
            "dss_push_match_queries_total": sum(
                st.queries for st in self._stages.values()
            ),
            "dss_push_match_absorbed_total": sum(
                st.absorbed for st in self._stages.values()
            ),
            "dss_push_breaker_state": dict(p["breaker_state"]),
        }


def empty_stats() -> dict:
    """The stable dss_push_* key set for stores without a pipeline —
    dashboards never miss a series (same discipline as federation and
    the shm front)."""
    return {
        "dss_push_queue_depth": 0,
        "dss_push_queue_depth_emergency": 0,
        "dss_push_queue_depth_bulk": 0,
        "dss_push_enqueued_total": 0,
        "dss_push_acked_total": 0,
        "dss_push_dropped_total": 0,
        "dss_push_requeued_total": 0,
        "dss_push_hooks": 0,
        "dss_push_delivered_total": 0,
        "dss_push_failures_total": 0,
        "dss_push_parked_total": 0,
        "dss_push_delivery_lag_p50_ms": 0.0,
        "dss_push_delivery_lag_p99_ms": 0.0,
        "dss_push_oldest_pending_s": 0.0,
        "dss_push_skipped_total": 0,
        "dss_push_fed_forwarded_total": 0,
        "dss_push_fed_ingested_total": 0,
        "dss_push_match_batches_total": 0,
        "dss_push_match_queries_total": 0,
        "dss_push_match_absorbed_total": 0,
        "dss_push_breaker_state": {},
    }
