"""The durable per-USS delivery queue: WAL-backed cursor + ack.

Durability contract (what the crash drill asserts):

  - `enqueue` appends a `push_evt` record BEFORE the notification is
    visible to any worker: a notification that was ever handed to a
    delivery worker is on disk.
  - `ack` appends a `push_ack` record when (and only when) the webhook
    POST succeeded: an acked notification survives any crash and is
    never redelivered.
  - replay reconstructs pending = enqueued − acked, so an unacked
    notification is redelivered after restart — at-least-once, the
    only honest contract a webhook can carry (the POST may have landed
    just before the crash; the receiver dedupes on the notification
    id, which is stable across redeliveries).

Webhook registrations (`push_hook` / `push_unhook`) ride the same log
so a restarted instance still knows where to deliver.

The queue is two QoS bands — "emergency" drains strictly before
"bulk" (a contingent-operation notification must not sit behind ten
thousand routine bumps) — of per-notification entries; per-USS
fairness and backoff live in deliver.py (the queue only skips USSs
the pool currently holds blocked).  Depth is bounded: past max_depth
new BULK notifications are dropped-and-counted (the saturation alert's
trigger) while emergency ones are always admitted — the bound exists
to protect the process from a dead USS, not to shed the traffic the
QoS tier exists for.

Reuses dar/wal.py's WriteAheadLog (same fsync knob, same torn-tail
recovery) rather than inventing a second record format.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from dss_tpu.dar.wal import WriteAheadLog

__all__ = ["DeliveryLog", "Notification", "QOS_BANDS"]

QOS_BANDS = ("emergency", "bulk")


@dataclasses.dataclass
class Notification:
    """One queued delivery.  `body` is the webhook payload; `target`
    is the registered webhook URL — or a `@region:<id>` pseudo-target
    for federation fan-out (pipeline.py routes those to the owning
    region's /aux/v1/push/ingest instead of a USS webhook)."""

    nid: int
    uss: str
    target: str
    qos: str
    body: dict
    traceparent: str = ""
    enqueued_ns: int = 0
    attempts: int = 0

    def to_doc(self) -> dict:
        return {
            "nid": self.nid, "uss": self.uss, "target": self.target,
            "qos": self.qos, "body": self.body,
            "tp": self.traceparent, "ts_ns": self.enqueued_ns,
        }

    @classmethod
    def from_doc(cls, d: dict) -> "Notification":
        return cls(
            nid=int(d["nid"]), uss=d["uss"], target=d["target"],
            qos=d.get("qos", "bulk"), body=d.get("body", {}),
            traceparent=d.get("tp", ""),
            enqueued_ns=int(d.get("ts_ns", 0)),
        )


class DeliveryLog:
    """WAL-backed notification queue + webhook registry."""

    def __init__(self, path: Optional[str] = None, *,
                 fsync: bool = False, max_depth: int = 100_000,
                 wall_clock_ns=time.time_ns):
        self._wal = WriteAheadLog(path, fsync=fsync)
        self._wall_ns = wall_clock_ns
        self.max_depth = max(1, int(max_depth))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # qos band -> FIFO of pending notifications
        self._pending: Dict[str, deque] = {q: deque() for q in QOS_BANDS}
        # nid -> notification, for everything enqueued-not-acked
        # (pending OR held by a worker) — the redelivery set
        self._open: Dict[int, Notification] = {}
        self._hooks: Dict[str, dict] = {}  # uss -> {url, qos}
        self._next_nid = 1
        self.enqueued = 0
        self.acked = 0
        self.dropped = 0
        self.requeued = 0
        self._closed = False
        self._replay()

    # -- boot --------------------------------------------------------------

    def _replay(self) -> None:
        acked = set()
        evts: Dict[int, Notification] = {}
        for rec in self._wal.replay():
            t = rec.get("t", "")
            if t == "push_hook":
                self._hooks[rec["uss"]] = {
                    "url": rec["url"], "qos": rec.get("qos", "bulk"),
                }
            elif t == "push_unhook":
                self._hooks.pop(rec["uss"], None)
            elif t == "push_evt":
                n = Notification.from_doc(rec)
                evts[n.nid] = n
                self._next_nid = max(self._next_nid, n.nid + 1)
            elif t == "push_ack":
                acked.add(int(rec["nid"]))
        for nid in sorted(evts):
            if nid in acked:
                continue
            n = evts[nid]
            self._open[nid] = n
            self._pending[n.qos if n.qos in QOS_BANDS else "bulk"].append(n)

    # -- webhook registry --------------------------------------------------

    def register_hook(self, uss: str, url: str,
                      qos: str = "bulk") -> dict:
        if qos not in QOS_BANDS:
            raise ValueError(f"unknown qos band {qos!r}")
        with self._lock:
            self._hooks[uss] = {"url": url, "qos": qos}
            self._wal.append({
                "t": "push_hook", "uss": uss, "url": url, "qos": qos,
            })
            return dict(self._hooks[uss])

    def unregister_hook(self, uss: str) -> bool:
        with self._lock:
            had = self._hooks.pop(uss, None) is not None
            if had:
                self._wal.append({"t": "push_unhook", "uss": uss})
            return had

    def hook_of(self, uss: str) -> Optional[dict]:
        with self._lock:
            h = self._hooks.get(uss)
            return None if h is None else dict(h)

    def hooks(self) -> Dict[str, dict]:
        with self._lock:
            return {u: dict(h) for u, h in self._hooks.items()}

    # -- queue -------------------------------------------------------------

    def enqueue(self, uss: str, target: str, body: dict, *,
                qos: str = "bulk", traceparent: str = "") -> Optional[int]:
        """Durably append + make visible to workers.  Returns the nid,
        or None when a BULK notification was shed at the depth bound
        (emergency notifications are always admitted)."""
        if qos not in QOS_BANDS:
            qos = "bulk"
        with self._lock:
            if self._closed:
                return None
            if qos == "bulk" and len(self._open) >= self.max_depth:
                self.dropped += 1
                return None
            n = Notification(
                nid=self._next_nid, uss=uss, target=target, qos=qos,
                body=body, traceparent=traceparent,
                enqueued_ns=self._wall_ns(),
            )
            self._next_nid += 1
            rec = n.to_doc()
            rec["t"] = "push_evt"
            self._wal.append(rec)
            self._open[n.nid] = n
            self._pending[qos].append(n)
            self.enqueued += 1
            self._cv.notify()
            return n.nid

    def take(self, *, blocked=(), timeout_s: float = 0.2
             ) -> Optional[Notification]:
        """Pop the next deliverable notification: the emergency band
        drains strictly before bulk, skipping USSs in `blocked` (open
        breakers / backoff holds — deliver.py's set).  Blocks up to
        timeout_s when nothing is deliverable."""
        blocked = set(blocked)
        with self._cv:
            n = self._take_locked(blocked)
            if n is None and timeout_s > 0:
                self._cv.wait(timeout_s)
                n = self._take_locked(blocked)
            return n

    def _take_locked(self, blocked) -> Optional[Notification]:
        for qos in QOS_BANDS:
            q = self._pending[qos]
            for _ in range(len(q)):
                n = q.popleft()
                if n.uss in blocked:
                    q.append(n)  # rotate past the blocked USS
                    continue
                return n
        return None

    def requeue(self, n: Notification) -> None:
        """A failed attempt: back of its band, attempts bumped (the
        pool's backoff/parking decisions read the count)."""
        with self._cv:
            if n.nid not in self._open:
                return  # acked or parked concurrently
            n.attempts += 1
            self._pending[n.qos].append(n)
            self.requeued += 1
            self._cv.notify()

    def ack(self, nid: int) -> bool:
        """Durably mark delivered.  After this record is on disk the
        notification is never handed out again — including across a
        crash+replay."""
        with self._cv:
            n = self._open.pop(nid, None)
            if n is None:
                return False
            self._wal.append({"t": "push_ack", "nid": nid})
            self.acked += 1
            return True

    def park(self, nid: int, reason: str = "") -> bool:
        """Give up on a notification (attempt cap): acked on disk so
        it never redelivers, but counted separately — parked is a
        delivery FAILURE the dead-letter gauge surfaces, not a
        success."""
        with self._cv:
            n = self._open.pop(nid, None)
            if n is None:
                return False
            self._wal.append({
                "t": "push_ack", "nid": nid, "parked": True,
                "reason": reason,
            })
            return True

    # -- views -------------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._open)

    def oldest_pending_age_s(self) -> float:
        with self._lock:
            if not self._open:
                return 0.0
            oldest = min(n.enqueued_ns for n in self._open.values())
            return max(0.0, (self._wall_ns() - oldest) / 1e9)

    @property
    def seq(self) -> int:
        return self._wal.seq

    def sync(self) -> None:
        self._wal.sync()

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._open),
                "depth_emergency": len(self._pending["emergency"]),
                "depth_bulk": len(self._pending["bulk"]),
                "enqueued": self.enqueued,
                "acked": self.acked,
                "dropped": self.dropped,
                "requeued": self.requeued,
                "hooks": len(self._hooks),
            }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._wal.close()
