"""REST gateway (aiohttp): the ASTM OpenAPI surface of the reference's
http-gateway + grpc-backend pair, collapsed into one process."""

from dss_tpu.api.app import build_app, RID_SCOPES, SCD_SCOPES

__all__ = ["build_app", "RID_SCOPES", "SCD_SCOPES"]
