"""The REST application: routes, auth enforcement, error mapping.

Route surface mirrors the reference's REST bindings:
  RID  (rid.proto:527-630):  /v1/dss/identification_service_areas,
                             /v1/dss/subscriptions
  SCD  (scd.proto:602-716):  /dss/v1/{operation_references,
                             subscriptions, constraint_references,
                             reports}
  Aux  (aux_service.proto):  /aux/v1/validate_oauth
  plus /healthy (cmds/http-gateway/main.go:82-90).

Error mapping follows myCodeToHTTPStatus/myHTTPError
(cmds/http-gateway/main.go:102-237): StatusError -> JSON
{error, message, code}; MISSING_OVNS -> HTTP 409 whose body is the
AirspaceConflictResponse itself; AREA_TOO_LARGE -> HTTP 413.

Scope tables mirror pkg/rid/server/server.go:34-49,
pkg/scd/server.go:58-76, pkg/aux_/server.go:17-21.
"""

from __future__ import annotations

import asyncio
import functools
import json
import math
import time
from typing import Optional

from aiohttp import web

from dss_tpu import errors
from dss_tpu.auth.authorizer import (
    Authorizer,
    require_all_scopes,
    require_any_scope,
)

RID_READ = "dss.read.identification_service_areas"
RID_WRITE = "dss.write.identification_service_areas"
SCD_SC = "utm.strategic_coordination"
SCD_CM = "utm.constraint_management"
SCD_CC = "utm.constraint_consumption"

_RID = "/ridpb.DiscoveryAndSynchronizationService/"
_SCD = "/scdpb.UTMAPIUSSDSSAndUSSUSSService/"
_AUX = "/auxpb.DSSAuxService/"

RID_SCOPES = {
    _RID + "CreateIdentificationServiceArea": require_all_scopes(RID_WRITE),
    _RID + "UpdateIdentificationServiceArea": require_all_scopes(RID_WRITE),
    _RID + "DeleteIdentificationServiceArea": require_all_scopes(RID_WRITE),
    _RID + "GetIdentificationServiceArea": require_all_scopes(RID_READ),
    _RID + "SearchIdentificationServiceAreas": require_all_scopes(RID_READ),
    _RID + "CreateSubscription": require_all_scopes(RID_WRITE),
    _RID + "UpdateSubscription": require_all_scopes(RID_WRITE),
    _RID + "DeleteSubscription": require_all_scopes(RID_WRITE),
    _RID + "GetSubscription": require_all_scopes(RID_READ),
    _RID + "SearchSubscriptions": require_all_scopes(RID_READ),
    _AUX + "ValidateOauth": require_all_scopes(RID_WRITE),
    _AUX + "DebugProfile": require_all_scopes(RID_WRITE),
    _AUX + "DebugTraces": require_all_scopes(RID_WRITE),
    # cross-region federation peer surface: any read scope may query;
    # sync ships full state, so it demands a read scope too
    _AUX + "FederationQuery": require_any_scope(
        RID_READ, SCD_SC, SCD_CC, SCD_CM
    ),
    _AUX + "FederationSync": require_any_scope(
        RID_READ, SCD_SC, SCD_CC, SCD_CM
    ),
    # push-pipeline surface (dss_tpu/push): a USS manages its own
    # webhook registration with any write scope; status is a read;
    # ingest is the cross-region peer hop (same trust as federation)
    _AUX + "PushPutHook": require_any_scope(
        RID_WRITE, SCD_SC, SCD_CC, SCD_CM
    ),
    _AUX + "PushStatus": require_any_scope(
        RID_READ, SCD_SC, SCD_CC, SCD_CM
    ),
    _AUX + "PushIngest": require_any_scope(
        RID_READ, SCD_SC, SCD_CC, SCD_CM
    ),
}

SCD_SCOPES = {
    _SCD + "PutOperationReference": require_any_scope(SCD_SC),
    _SCD + "GetOperationReference": require_any_scope(SCD_SC),
    _SCD + "DeleteOperationReference": require_any_scope(SCD_SC),
    _SCD + "SearchOperationReferences": require_any_scope(SCD_SC),
    _SCD + "PutSubscription": require_any_scope(SCD_SC, SCD_CC),
    _SCD + "GetSubscription": require_any_scope(SCD_SC, SCD_CC),
    _SCD + "DeleteSubscription": require_any_scope(SCD_SC, SCD_CC),
    _SCD + "QuerySubscriptions": require_any_scope(SCD_SC, SCD_CC),
    _SCD + "PutConstraintReference": require_any_scope(SCD_CM),
    _SCD + "GetConstraintReference": require_any_scope(SCD_SC, SCD_CC, SCD_CM),
    _SCD + "DeleteConstraintReference": require_any_scope(SCD_CM),
    _SCD + "QueryConstraintReferences": require_any_scope(
        SCD_SC, SCD_CC, SCD_CM
    ),
    _SCD + "MakeDssReport": require_any_scope(SCD_SC, SCD_CC, SCD_CM),
    _AUX + "ReplicaSearchOperations": require_any_scope(SCD_SC),
}


def _error_response(e: errors.StatusError) -> web.Response:
    if e.code == errors.Code.MISSING_OVNS:
        # special 409 schema: the body IS the AirspaceConflictResponse
        # (cmds/http-gateway/main.go:187-200)
        body = e.details or {"message": e.message}
        return web.json_response(body, status=e.http_status)
    headers = None
    retry_after = getattr(e, "retry_after_s", None)
    if retry_after is not None:
        # overload shed (429): tell the client when the queue should
        # have drained; well-behaved USS clients back off accordingly
        headers = {"Retry-After": str(max(1, math.ceil(retry_after)))}
    return web.json_response(
        {"error": e.message, "message": e.message, "code": int(e.code)},
        status=e.http_status,
        headers=headers,
    )


@web.middleware
async def error_middleware(request, handler):
    try:
        return await handler(request)
    except errors.StatusError as e:
        return _error_response(e)
    except web.HTTPException:
        raise
    except Exception as e:  # noqa: BLE001 — normalize to the error schema
        return _error_response(errors.internal(str(e)))


def make_trace_middleware(verbose: bool = True):
    """Per-request tracing (the reference's --trace-requests analog,
    pkg/logging/http.go:36-55, upgraded twice): assigns/propagates an
    X-Request-Id AND a W3C traceparent — the trace id IS the request
    id — opens the request's root span when the trace subsystem is
    active (obs/trace.py: head-sampled, tail-captured past
    DSS_TRACE_SLOW_MS), and returns both headers on every response,
    errors included, so one id greps across every process log of the
    front.  `verbose` additionally emits the X-Dss-Stages breakdown
    header (--trace_requests)."""
    import uuid as _uuid

    from dss_tpu.obs import trace as _trace

    def _root_name(request) -> str:
        resource = (
            request.match_info.route.resource
            if request.match_info is not None
            else None
        )
        route = (
            resource.canonical if resource is not None else "(unmatched)"
        )
        return f"http {request.method} {route}"

    @web.middleware
    async def trace_middleware(request, handler):
        ctx = _trace.new_trace(
            request.headers.get("traceparent"),
            request.headers.get("X-Request-Id"),
        )
        # a caller-SUPPLIED id is echoed verbatim (USS operators
        # correlate by exact match of their own id); only minted ids
        # are the trace id itself.  A supplied id still maps onto the
        # trace deterministically (trace_id_from_request_id), and the
        # traceparent header carries the canonical trace id either way.
        rid = request.headers.get("X-Request-Id") or (
            ctx.trace_id if ctx is not None else _uuid.uuid4().hex[:16]
        )
        request["dss_trace"] = {"request_id": rid, "ctx": ctx}
        t0 = time.perf_counter()
        status = 500
        try:
            resp = await handler(request)
            status = resp.status
        except web.HTTPException as e:
            # error responses are the ones operators most need to
            # correlate — tag them too
            status = e.status
            e.headers["X-Request-Id"] = rid
            if ctx is not None:
                e.headers["traceparent"] = _trace.format_traceparent(
                    ctx.trace_id, ctx.root_span_id, ctx.sampled
                )
            raise
        finally:
            _trace.finish_root(
                ctx, _root_name(request),
                (time.perf_counter() - t0) * 1000.0,
                status=status,
            )
        resp.headers["X-Request-Id"] = rid
        if ctx is not None:
            resp.headers["traceparent"] = _trace.format_traceparent(
                ctx.trace_id, ctx.root_span_id, ctx.sampled
            )
        stages = request.get("dss_stages")
        if verbose and stages:
            # machine-readable per-stage breakdown for callers
            # (benchmarks, USS operators correlating latency)
            resp.headers["X-Dss-Stages"] = ";".join(
                f"{k}={v}" for k, v in sorted(stages.items())
            )
        return resp

    return trace_middleware


def _trace_handle(request):
    """The request's root-span trace handle (or None): what _call
    installs on the executor thread so service-layer spans parent
    under this request."""
    from dss_tpu.obs import trace as _trace

    tr = request.get("dss_trace") if request is not None else None
    ctx = tr.get("ctx") if tr else None
    if ctx is None or not ctx.recording:
        return None
    return _trace.SpanHandle(ctx, ctx.root_span_id)


def make_timeout_middleware(timeout_s: float):
    """Per-request deadline (the reference's 10 s default RPC timeout,
    cmds/grpc-backend/main.go:48): a handler that exceeds it gets a 504
    DEADLINE_EXCEEDED and releases the connection.  The abandoned
    executor call keeps running to completion in its worker thread
    (same abandonment semantics as a Go ctx deadline firing while the
    SQL round trip is in flight); /healthy is exempt so orchestration
    probes never queue behind a wedged store."""

    # asyncio.timeout cancels in-place (no extra task per request,
    # unlike wait_for); async_timeout is the same shape for
    # Python < 3.11.  Resolved once here so a missing async_timeout
    # wheel fails at startup, not per-request at serve time.
    timeout_ctx = getattr(asyncio, "timeout", None)
    if timeout_ctx is None:
        import async_timeout

        timeout_ctx = async_timeout.timeout

    @web.middleware
    async def timeout_middleware(request, handler):
        # /debug/profile deliberately runs longer than any deadline
        if request.path in ("/healthy", "/debug/profile"):
            return await handler(request)
        # absolute per-request deadline for the serving stack: the
        # query coalescer caps its SLO-derived item deadlines with it
        # (_call installs it on the worker thread via dar/deadline.py)
        request["dss_deadline"] = time.monotonic() + timeout_s
        try:
            async with timeout_ctx(timeout_s):
                return await handler(request)
        except (TimeoutError, asyncio.TimeoutError):
            return _error_response(
                errors.deadline_exceeded(
                    f"request exceeded the {timeout_s:g}s deadline"
                )
            )

    return timeout_middleware


def _request_lag_bound(request) -> Optional[float]:
    """The request's declared staleness bound (X-DSS-Max-Lag seconds)
    for bounded-stale cross-region reads: the federation router
    tightens its configured DSS_FED_STALE_LAG_S to this — a request
    exceeding its own bound is rejected 503, never silently served
    staler.  Unparseable values are ignored (the server bound
    applies)."""
    if request is None:
        return None
    raw = request.headers.get("X-DSS-Max-Lag")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


async def _call(fn, *args, request=None):
    """Run a synchronous service call off the event loop.  The service
    layer holds the store lock and may run multi-ms TPU kernels (first
    call: a multi-second jit compile); keeping it off the loop lets
    other requests (and /healthy) proceed — the goroutine-per-RPC
    analog of grpc-go.  When `request` is given, the per-stage sink is
    installed on the worker thread so service code's covering/store/
    serialize timings land in the request's stage breakdown."""
    from dss_tpu.dar import deadline as _deadline
    from dss_tpu.dar import readcache as _readcache
    from dss_tpu.obs import stages as _stages
    from dss_tpu.obs import trace as _trace
    from dss_tpu.region import federation as _fed

    loop = asyncio.get_running_loop()
    sink = None if request is None else request.get("dss_stages")
    route_dl = None if request is None else request.get("dss_deadline")
    lag_bound = _request_lag_bound(request)
    th = _trace_handle(request)
    t0 = time.perf_counter()

    def run():
        if sink is not None:
            _stages.set_sink(sink)
        if route_dl is not None:
            _deadline.set_route_deadline(route_dl)
        _fed.set_lag_bound(lag_bound)
        _fed.take_fed_note()  # clear any stale note on this thread
        try:
            # trace handoff to the executor thread: a "service" span
            # under the request root; everything the service layer
            # opens (covering/store/serialize stages, cache lookups,
            # coalescer batch spans) parents under it
            with _trace.use(th), _trace.span("service"):
                return fn(*args)
        finally:
            # the store's search path left its freshness note on THIS
            # thread (readcache thread-local); hand it to the handler
            # for the X-DSS-Freshness response header.  take_ always
            # clears, so a pooled worker never leaks a note across
            # requests.
            note = _readcache.take_note()
            if request is not None and note is not None:
                request["dss_freshness"] = note
            fed_note = _fed.take_fed_note()
            if request is not None and fed_note is not None:
                request["dss_fed"] = fed_note
            _fed.set_lag_bound(None)
            if sink is not None:
                _stages.set_sink(None)
            if route_dl is not None:
                _deadline.set_route_deadline(None)

    try:
        return await loop.run_in_executor(None, run)
    finally:
        if sink is not None:
            sink["service_ms"] = round(
                (time.perf_counter() - t0) * 1000, 3
            )


async def _call_r(request, fn, *args):
    """Handler-side _call: threads the request through for tracing."""
    return await _call(fn, *args, request=request)


def _freshness_json_response(request, data) -> web.Response:
    """json_response carrying the X-DSS-Freshness header when the
    service call left a note: region epoch + DAR write generation +
    cache hit/miss, so operators can verify the version fence from
    the wire without reading code.  When the store's degradation
    ladder is non-healthy the header additionally carries
    `;mode=<condition>` — a degraded answer (hostchunk-only serving,
    fenced-cache reads during a region outage) is honest about it."""
    note = request.get("dss_freshness")
    fed = request.get("dss_fed")
    headers = None
    if note is None and fed is not None and fed["mode"] != "local":
        # a purely-remote federated answer never touched the local
        # read path: synthesize the base fields from the remote's
        # freshness stamp so the header still carries epoch + gen
        note = {
            "epoch": fed["epoch"], "cls": fed["cls"] or "-",
            "gen": fed["gen"], "hit": False,
        }
    if note is not None:
        val = (
            f"epoch={note['epoch'] or '-'};"
            f"class={note['cls']};gen={note['gen']};"
            f"cache={'hit' if note['hit'] else 'miss'}"
        )
        mode = None
        health_fn = request.app.get("dss_health_fn")
        if health_fn is not None:
            try:
                mode = health_fn()
            except Exception:  # noqa: BLE001 — header is best-effort
                mode = None
            if mode == "healthy":
                mode = None
        if mode is None and fed is not None and fed["mode"] == "stale":
            # a declared-lag mirror answer is honest about it even
            # when the ladder has already walked back
            mode = "stale"
        if mode:
            val += f";mode={mode}"
        if fed is not None:
            # federation provenance: serving region(s), how the
            # remote slice was served, and the worst measured lag
            val += (
                f";region={','.join(fed['regions'])}"
                f";fed={fed['mode']}"
            )
            if fed["mode"] == "stale":
                val += f";lag={fed['lag_s']:.3f}"
        headers = {"X-DSS-Freshness": val}
    return web.json_response(data, headers=headers)


# dict-valued store stats render as labeled gauge families; the label
# name is per-metric (everything else is the shard family)
_GAUGE_VEC_LABELS = {
    "dss_breaker_state": "remote",
    "dss_fault_injected_total": "site",
    "dss_fed_peer_state": "region",
    "dss_fed_mirror_lag_s": "region",
    "dss_push_breaker_state": "uss",
    # self-tuning knob families (dss_tpu/tune): active vs proposed
    # values per hot-swappable knob — the Grafana tuner panel diffs
    # the two series
    "dss_tune_knob_active": "knob",
    "dss_tune_knob_proposed": "knob",
    # shared-memory front per-worker counters (parallel/shmring.py):
    # the leader aggregates every worker's shm stats block so ONE
    # scrape sees the whole front, keyed by the worker's process id
    **{
        f"dss_shm_worker_{name}": "process"
        for name in (
            "enqueued", "served", "cache_hits", "cache_misses",
            "ring_full", "timeouts", "oversize", "proxy_fallbacks",
            "assembly_misses", "errors", "plan_shm", "plan_proxy",
        )
    },
}


# Routes a read-worker serves from its local WAL-tail replica; every
# other route is proxied to the write leader.  Searches are the hot
# path and inherently scan-like (bounded staleness = the follower poll
# interval, same contract as a region-mode non-writing instance);
# point reads and all mutations go to the leader for freshness.
WORKER_LOCAL_ROUTES = {
    ("GET", "/healthy"),
    ("GET", "/metrics"),
    ("GET", "/status"),
    # the trace flight recorder is PER PROCESS by design: the worker
    # serving this connection answers with its own recorder (the
    # stitched ring trace lives worker-side), never proxied
    ("GET", "/aux/v1/debug/traces"),
    ("GET", "/aux/v1/validate_oauth"),
    ("GET", "/v1/dss/identification_service_areas"),
    ("GET", "/v1/dss/subscriptions"),
    ("POST", "/dss/v1/operation_references/query"),
    ("POST", "/dss/v1/subscriptions/query"),
    ("POST", "/dss/v1/constraint_references/query"),
    # NOTE: the federation peer surface is deliberately NOT here —
    # worker-reader mode refuses --federation_map outright
    # (cmds/server.py): a worker's plain WAL-tail replica would serve
    # cross-region coverings partially.
}

_PROXY_SKIP_HEADERS = {
    "host", "content-length", "transfer-encoding", "connection",
}


def make_worker_proxy_middleware(leader_url: str, follower=None,
                                 costs=None):
    """Read-worker request routing: local serving for searches, proxy
    to the leader for everything else.  After a successful proxied
    mutation the worker waits (bounded) for its replica to reach the
    leader's WAL seq — read-your-writes for clients that keep their
    connection (and thus this worker) across a write->search flow.

    With the shared-memory front attached, a locally-served search
    that cannot ride the ring (ring full, owner dead, oversized
    payload, injected `shm.ring.enqueue` fault) raises ShmFallback —
    caught HERE and re-served over the loopback proxy, so ring
    saturation degrades to the old proxy cost instead of blocking or
    erroring.  `costs` (the front's WorkerCostModel) observes the
    measured proxy round trip of each such fallback search, so the
    shm-vs-proxy price comparison learns the REAL loopback cost
    instead of trusting the DSS_SHM_PROXY_MS seed forever."""
    import aiohttp as _aiohttp

    from dss_tpu.dar.shmfront import ShmFallback

    session: dict = {}

    async def _get_session():
        if "s" not in session:
            session["s"] = _aiohttp.ClientSession(
                timeout=_aiohttp.ClientTimeout(total=60)
            )
        return session["s"]

    @web.middleware
    async def worker_proxy(request, handler):
        resource = (
            request.match_info.route.resource
            if request.match_info is not None
            else None
        )
        canonical = resource.canonical if resource is not None else None
        fell_back = False
        if (request.method, canonical) in WORKER_LOCAL_ROUTES:
            try:
                return await handler(request)
            except ShmFallback:
                fell_back = True  # loopback proxy below
        sess = await _get_session()
        body = await request.read()
        t0 = time.perf_counter()
        t0_w = time.time_ns()
        headers = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in _PROXY_SKIP_HEADERS
        }
        # propagate THIS hop's trace identity instead of minting a
        # fresh id leader-side: the worker's trace middleware already
        # resolved/minted the id, and the loopback hop must carry it
        # (one grep-able id across worker AND leader access logs)
        from dss_tpu.obs import trace as _trace

        tr = request.get("dss_trace")
        if tr is not None:
            headers["X-Request-Id"] = tr["request_id"]
            ctx = tr.get("ctx")
            if ctx is not None:
                headers["traceparent"] = _trace.format_traceparent(
                    ctx.trace_id, ctx.root_span_id, ctx.sampled
                )
        try:
            async with sess.request(
                request.method,
                leader_url + request.path_qs,
                data=body,
                headers=headers,
            ) as upstream:
                payload = await upstream.read()
                seq = upstream.headers.get("X-Dss-Wal-Seq")
        except (_aiohttp.ClientError, asyncio.TimeoutError) as e:
            return _error_response(
                errors.unavailable(f"write leader unreachable: {e}")
            )
        proxy_ms = (time.perf_counter() - t0) * 1000.0
        sink = request.get("dss_stages")
        if sink is not None:
            sink["proxy_ms"] = round(
                sink.get("proxy_ms", 0.0) + proxy_ms, 3
            )
        th = _trace_handle(request)
        if th is not None:
            _trace.add_span(
                th, "proxy", t0_w, proxy_ms,
                attrs={"fallback": fell_back},
            )
        if fell_back and costs is not None:
            # a fallback-proxied SEARCH is the exact request shape the
            # ring would have served — feed its measured round trip to
            # the worker cost model (writes/other routes would skew it)
            costs.observe_proxy(proxy_ms)
        if (
            follower is not None
            and seq
            and request.method in ("PUT", "DELETE", "POST")
            and upstream.status < 400
        ):
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, functools.partial(follower.wait_for, int(seq), 1.0)
            )
        return web.Response(
            body=payload,
            status=upstream.status,
            content_type=upstream.content_type,
        )

    async def close_session(app):
        if "s" in session:
            await session["s"].close()

    worker_proxy.on_cleanup = close_session
    return worker_proxy


def make_wal_seq_middleware(wal_seq_fn):
    """Leader-side: stamp the current WAL seq on successful mutation
    responses so read workers can wait for their replica to catch up
    (read-your-writes across the proxy)."""

    @web.middleware
    async def wal_seq(request, handler):
        resp = await handler(request)
        if request.method in ("PUT", "DELETE", "POST") and resp.status < 400:
            resp.headers["X-Dss-Wal-Seq"] = str(wal_seq_fn())
        return resp

    return wal_seq


def _native_ready() -> bool:
    try:
        from dss_tpu import native

        return native.available()
    except Exception:  # pragma: no cover
        return False


async def _params(request) -> dict:
    if request.method in ("GET", "DELETE"):
        return {}
    try:
        body = await request.text()
        params = json.loads(body) if body else {}
    except ValueError as e:
        raise errors.bad_request(f"malformed request body: {e}")
    if not isinstance(params, dict):
        raise errors.bad_request("request body must be a JSON object")
    return params


def build_app(
    rid_service=None,
    scd_service=None,
    authorizer: Optional[Authorizer] = None,
    *,
    enable_scd: bool = True,
    metrics=None,
    dump_requests: bool = False,
    stats_fn=None,
    status_fn=None,  # freshness introspection: DSSStore.freshness_status
    health_fn=None,  # degradation mode: DSSStore.health.mode_name
    default_timeout_s: float = 10.0,
    replica=None,  # ShardedOpReplica: multi-chip read-replica surface
    federation=None,  # FederationRouter: peer query/sync surface
    push=None,  # PushPipeline: webhook registry + ingest surface
    trace_requests: bool = False,
    profile_dir: str = "",
    worker_proxy=None,  # read-worker mode: proxy middleware to leader
    wal_seq_fn=None,  # leader mode: stamp WAL seq on mutations
    inline_reads: bool = False,  # run read handlers on the event loop
) -> web.Application:
    from dss_tpu.obs.logging import make_access_log_middleware

    middlewares = [
        make_access_log_middleware(
            metrics, dump_requests=dump_requests, health_fn=health_fn
        ),
        # id propagation + the trace root span are ALWAYS on (near-
        # zero cost while DSS_TRACE_* is unset); --trace_requests only
        # adds the verbose X-Dss-Stages response header
        make_trace_middleware(verbose=trace_requests),
    ]
    if default_timeout_s and default_timeout_s > 0:
        middlewares.append(make_timeout_middleware(default_timeout_s))
    middlewares.append(error_middleware)
    if wal_seq_fn is not None:
        middlewares.append(make_wal_seq_middleware(wal_seq_fn))
    if worker_proxy is not None:
        # innermost: local-read routes fall through to handlers, the
        # rest forward to the leader (already wrapped by log/deadline)
        middlewares.append(worker_proxy)
    app = web.Application(middlewares=middlewares)
    if worker_proxy is not None and hasattr(worker_proxy, "on_cleanup"):
        app.on_cleanup.append(worker_proxy.on_cleanup)
    if health_fn is not None:
        # the degradation-ladder mode: read by _freshness_json_response
        # so degraded answers carry `;mode=...` in X-DSS-Freshness
        app["dss_health_fn"] = health_fn

    async def _call_read(request, fn, *args):
        """Service call for READ handlers.  With inline_reads (single-
        core hosts), runs directly on the event loop: reads are
        lock-free against the immutable store state and take ~0.3 ms,
        so on one core the two executor handoffs are pure overhead.

        Inline execution is OPTIMISTIC under a host-only budget: any
        path that would dispatch to the device, trigger an XLA
        compile, or block behind another thread's batch raises
        NeedsDevice, and the (pure) read re-runs on the executor —
        the loop never stalls on device work.  Multi-core deployments
        keep the executor throughout."""
        if not inline_reads or not _native_ready():
            # without the native covering kernel a search can fall back
            # to a multi-ms numpy BFS — keep that off the event loop
            return await _call(fn, *args, request=request)
        from dss_tpu.dar import budget as _budget
        from dss_tpu.dar import deadline as _deadline
        from dss_tpu.dar import readcache as _readcache
        from dss_tpu.obs import stages as _stages
        from dss_tpu.obs import trace as _trace
        from dss_tpu.region import federation as _fed

        sink = request.get("dss_stages")
        before = None if sink is None else dict(sink)
        route_dl = request.get("dss_deadline")
        th = _trace_handle(request)
        t0 = time.perf_counter()
        if sink is not None:
            _stages.set_sink(sink)
        if route_dl is not None:
            _deadline.set_route_deadline(route_dl)
        _budget.set_host_only(True)
        _fed.set_lag_bound(_request_lag_bound(request))
        # clear any stale freshness note on the loop thread: a prior
        # request that escalated to the executor mid-note must not
        # donate its note to this one (first-wins would keep it)
        _readcache.take_note()
        _fed.take_fed_note()
        try:
            with _trace.use(th), _trace.span("service"):
                return fn(*args)
        except _budget.NeedsDevice:
            if sink is not None:
                # drop the aborted inline attempt's partial stage
                # timings — the executor re-run records the real ones
                sink.clear()
                sink.update(before)
            # drop the aborted attempt's note BEFORE awaiting: the
            # executor re-run stashes the real one, other inline
            # requests may interleave during the await, and the
            # finally below must find this thread's slot empty
            _readcache.take_note()
            _fed.take_fed_note()
            return await _call(fn, *args, request=request)
        finally:
            _budget.set_host_only(False)
            note = _readcache.take_note()
            if note is not None:
                request["dss_freshness"] = note
            fed_note = _fed.take_fed_note()
            if fed_note is not None:
                request["dss_fed"] = fed_note
            _fed.set_lag_bound(None)
            if sink is not None:
                _stages.set_sink(None)
                sink["service_ms"] = round(
                    (time.perf_counter() - t0) * 1000, 3
                )
            if route_dl is not None:
                _deadline.set_route_deadline(None)

    def auth(request, operation: str) -> str:
        """-> owner.  No authorizer configured (unit harness) -> anon."""
        if authorizer is None:
            return "anonymous"
        t0 = time.perf_counter()
        t0_w = time.time_ns()
        try:
            owner = authorizer.authorize(
                request.headers.get("Authorization"), operation
            )
        finally:
            auth_ms = (time.perf_counter() - t0) * 1000
            sink = request.get("dss_stages")
            if sink is not None:
                sink["auth_ms"] = round(auth_ms, 3)
            th = _trace_handle(request)
            if th is not None:
                from dss_tpu.obs import trace as _trace

                _trace.add_span(th, "auth_ms", t0_w, auth_ms)
        request["dss_owner"] = owner
        return owner

    # -- health + metrics (no auth) ------------------------------------------

    async def healthy(request):
        return web.Response(text="ok")

    app.router.add_get("/healthy", healthy)

    async def status(request):
        """Freshness introspection (no auth, like /healthy): region
        epoch, per-class DAR write generation + cell-clock high-water
        mark, and read-cache counters — the operator's view of the
        version fence (docs/SERVING.md)."""
        if status_fn is None:
            return web.json_response({"ok": True})
        return web.json_response(await _call_r(request, status_fn))

    app.router.add_get("/status", status)

    async def debug_traces(request):
        """The per-process trace flight recorder as span-tree JSON:
        kept traces (head-sampled + tail-captured slow ones), newest
        last, plus the recorder counters.  ?trace_id= narrows to one
        trace; ?limit=N bounds the response.  Worker-local: each
        process of a front answers with its OWN recorder — the
        stitched worker->owner trace lives on the worker that served
        the request."""
        from dss_tpu.obs import trace as _trace

        auth(request, _AUX + "DebugTraces")
        tid = request.query.get("trace_id", "")
        if tid:
            found = _trace.recorder().find(tid.strip().lower())
            return web.json_response({
                "traces": [found] if found is not None else [],
                "stats": _trace.stats(),
            })
        try:
            limit = int(request.query.get("limit", 0))
        except ValueError:
            raise errors.bad_request("bad limit param")
        return web.json_response({
            "traces": _trace.recorder().traces(limit=limit),
            "stats": _trace.stats(),
        })

    app.router.add_get("/aux/v1/debug/traces", debug_traces)

    if metrics is not None:

        async def metrics_handler(request):
            if stats_fn is not None:
                # stats take the store lock (writers hold it across
                # device work) — keep the event loop free
                stats = await _call_r(request, stats_fn)
                for name, val in stats.items():
                    if isinstance(val, dict):
                        # keyed gauge families — dss_shard_load{shard},
                        # dss_breaker_state{remote},
                        # dss_fault_injected_total{site}
                        metrics.set_gauge_vec(
                            name,
                            _GAUGE_VEC_LABELS.get(name, "shard"),
                            val,
                        )
                    else:
                        metrics.set_gauge(name, val)
            return web.Response(
                text=metrics.render(),
                content_type="text/plain",
            )

        app.router.add_get("/metrics", metrics_handler)

    # -- aux -----------------------------------------------------------------

    async def validate_oauth(request):
        owner = auth(request, _AUX + "ValidateOauth")
        want = request.query.get("owner", "")
        if want and want != owner:
            raise errors.permission_denied(
                f"owner mismatch, required: {want}, "
                f"but oauth token has {owner}"
            )
        return web.json_response({})

    app.router.add_get("/aux/v1/validate_oauth", validate_oauth)

    if profile_dir:
        # opt-in device profiling (the reference's Cloud-Profiler
        # --gcp_prof_service_name analog, grpc-backend main.go:235-241,
        # recast TPU-native): POST /debug/profile?seconds=N captures a
        # JAX/XLA device trace into profile_dir while live traffic
        # keeps flowing; view with TensorBoard or xprof
        import concurrent.futures as _futures
        import threading as _threading

        profile_lock = _threading.Lock()
        # dedicated executor: a 60 s capture must not occupy a slot of
        # the shared pool that runs store-locked service calls
        profile_pool = _futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dss-profile"
        )

        async def debug_profile(request):
            auth(request, _AUX + "DebugProfile")
            try:
                seconds = float(request.query.get("seconds", 3.0))
            except ValueError:
                raise errors.bad_request("bad seconds param")
            if not (0.0 < seconds <= 60.0):  # also rejects NaN
                raise errors.bad_request(
                    "seconds must be in (0, 60]"
                )
            if not profile_lock.acquire(blocking=False):
                raise errors.unavailable("a profile capture is running")

            def capture():
                try:
                    import jax

                    with jax.profiler.trace(profile_dir):
                        time.sleep(seconds)
                finally:
                    profile_lock.release()

            await asyncio.get_running_loop().run_in_executor(
                profile_pool, capture
            )
            return web.json_response(
                {"profile_dir": profile_dir, "seconds": seconds}
            )

        app.router.add_post("/debug/profile", debug_profile)

    if federation is not None:
        # the cross-region peer surface (region/federation.py): a
        # remote region's router queries/syncs against the LOCAL
        # stores (never recursing through the federation layer)
        from dss_tpu.region import federation as _fedmod

        async def federation_query(request):
            auth(request, _AUX + "FederationQuery")
            payload = await _params(request)
            return web.json_response(
                await _call_r(
                    request,
                    functools.partial(
                        _fedmod.serve_query, federation, payload
                    ),
                )
            )

        async def federation_sync(request):
            auth(request, _AUX + "FederationSync")
            return web.json_response(
                await _call_r(
                    request,
                    functools.partial(_fedmod.serve_sync, federation),
                )
            )

        app.router.add_post("/aux/v1/federation/query", federation_query)
        app.router.add_get("/aux/v1/federation/sync", federation_sync)

    if push is not None:
        # the push-pipeline surface (dss_tpu/push): webhook hook
        # registry (durable in the delivery WAL), operator status, and
        # the cross-region ingest hop federation forwards ride

        async def push_put_hook(request):
            owner = auth(request, _AUX + "PushPutHook")
            uss = request.match_info["uss"]
            if authorizer is not None and owner != uss:
                raise errors.permission_denied(
                    f"hook for {uss} may only be managed by {uss}"
                )
            params = await _params(request)
            url = params.get("url", "")
            if not url:
                raise errors.bad_request("missing required url")
            try:
                hook = push.register_hook(
                    uss, url, params.get("qos", "bulk")
                )
            except ValueError as e:
                raise errors.bad_request(str(e))
            return web.json_response({"uss": uss, **hook})

        async def push_delete_hook(request):
            owner = auth(request, _AUX + "PushPutHook")
            uss = request.match_info["uss"]
            if authorizer is not None and owner != uss:
                raise errors.permission_denied(
                    f"hook for {uss} may only be managed by {uss}"
                )
            return web.json_response(
                {"uss": uss, "removed": push.unregister_hook(uss)}
            )

        async def push_get_hooks(request):
            auth(request, _AUX + "PushStatus")
            return web.json_response({"hooks": push.hooks()})

        async def push_status(request):
            auth(request, _AUX + "PushStatus")
            return web.json_response(push.status())

        async def push_ingest(request):
            auth(request, _AUX + "PushIngest")
            payload = await _params(request)
            return web.json_response(
                await _call_r(
                    request,
                    functools.partial(push.ingest_remote, payload),
                )
            )

        app.router.add_put("/aux/v1/push/hooks/{uss}", push_put_hook)
        app.router.add_delete(
            "/aux/v1/push/hooks/{uss}", push_delete_hook
        )
        app.router.add_get("/aux/v1/push/hooks", push_get_hooks)
        app.router.add_get("/aux/v1/push/status", push_status)
        app.router.add_post("/aux/v1/push/ingest", push_ingest)

    if replica is not None:
        # the multi-chip read-replica surface (SURVEY §7 step 7): area
        # searches served from the ShardedDar snapshot the replica
        # tails out of the WAL / region log
        import time as _time

        from dss_tpu.geo import covering as geo_covering
        from dss_tpu.geo import s2cell as _s2
        from dss_tpu.services import serialization as _ser

        def _now_ns_fn():
            return int(_time.time() * 1e9)

        # URL segment -> (replica class, auth operation, response key,
        # owner-scoped).  Subscription ids are owner-private: those
        # surfaces filter to the authenticated owner's entities, same
        # as the store search paths.
        replica_surfaces = {
            "operations": (
                "ops", _AUX + "ReplicaSearchOperations",
                "operation_ids", False,
            ),
            "identification_service_areas": (
                "isas",
                _RID + "SearchIdentificationServiceAreas",
                "service_area_ids", False,
            ),
            "subscriptions": (
                "rid_subs", _RID + "SearchSubscriptions",
                "subscription_ids", True,
            ),
            "scd_subscriptions": (
                "scd_subs", _SCD + "QuerySubscriptions",
                "subscription_ids", True,
            ),
            "constraints": (
                "constraints", _SCD + "QueryConstraintReferences",
                "constraint_ids", False,
            ),
        }

        async def replica_search(request):
            surface = replica_surfaces.get(request.match_info["surface"])
            if surface is None:
                raise errors.bad_request(
                    "unknown replica surface; one of: "
                    + ", ".join(sorted(replica_surfaces))
                )
            cls, operation, out_key, owner_scoped = surface
            owner = auth(request, operation)
            area = request.query.get("area", "")
            try:
                cells = geo_covering.area_to_cell_ids(area)
            except geo_covering.AreaTooLargeError as e:
                raise errors.area_too_large(str(e))
            except geo_covering.BadAreaError as e:
                raise errors.bad_request(str(e))
            keys = _s2.cell_to_dar_key(cells)

            def parse_t(name):
                raw = request.query.get(name, "")
                if not raw:
                    return None
                from dss_tpu.clock import to_nanos

                try:
                    return to_nanos(_ser.parse_time(raw))
                except (ValueError, TypeError) as e:
                    raise errors.bad_request(f"bad {name}: {e}")

            def parse_f(name):
                raw = request.query.get(name, "")
                if not raw:
                    return None
                try:
                    return float(raw)
                except ValueError:
                    raise errors.bad_request(f"bad {name}: {raw!r}")

            ids = await _call_r(request,
                functools.partial(
                    replica.query,
                    keys,
                    parse_f("altitude_lo"),
                    parse_f("altitude_hi"),
                    parse_t("earliest_time"),
                    parse_t("latest_time"),
                    now=_now_ns_fn(),
                    cls=cls,
                    owner=owner if owner_scoped else None,
                )
            )
            return web.json_response(
                {out_key: ids, "replica": replica.stats()}
            )

        app.router.add_get(
            "/aux/v1/replica/{surface}", replica_search
        )

    # -- RID -----------------------------------------------------------------

    if rid_service is not None:
        rid = rid_service

        async def isa_create(request):
            owner = auth(request, _RID + "CreateIdentificationServiceArea")
            return web.json_response(
                await _call_r(request, rid.create_isa, 
                    request.match_info["id"], await _params(request), owner
                )
            )

        async def isa_update(request):
            owner = auth(request, _RID + "UpdateIdentificationServiceArea")
            return web.json_response(
                await _call_r(request, rid.update_isa, 
                    request.match_info["id"],
                    request.match_info["version"],
                    await _params(request),
                    owner,
                )
            )

        async def isa_delete(request):
            owner = auth(request, _RID + "DeleteIdentificationServiceArea")
            return web.json_response(
                await _call_r(request, rid.delete_isa, 
                    request.match_info["id"],
                    request.match_info["version"],
                    owner,
                )
            )

        async def isa_get(request):
            auth(request, _RID + "GetIdentificationServiceArea")
            return web.json_response(await _call_read(request, rid.get_isa, request.match_info["id"]))

        async def isa_search(request):
            auth(request, _RID + "SearchIdentificationServiceAreas")
            return _freshness_json_response(
                request,
                await _call_read(request, rid.search_isas,
                    request.query.get("area", ""),
                    request.query.get("earliest_time"),
                    request.query.get("latest_time"),
                ),
            )

        async def sub_create(request):
            owner = auth(request, _RID + "CreateSubscription")
            return web.json_response(
                await _call_r(request, rid.create_subscription, 
                    request.match_info["id"], await _params(request), owner
                )
            )

        async def sub_update(request):
            owner = auth(request, _RID + "UpdateSubscription")
            return web.json_response(
                await _call_r(request, rid.update_subscription, 
                    request.match_info["id"],
                    request.match_info["version"],
                    await _params(request),
                    owner,
                )
            )

        async def sub_delete(request):
            owner = auth(request, _RID + "DeleteSubscription")
            return web.json_response(
                await _call_r(request, rid.delete_subscription, 
                    request.match_info["id"],
                    request.match_info["version"],
                    owner,
                )
            )

        async def sub_get(request):
            auth(request, _RID + "GetSubscription")
            return web.json_response(
                await _call_read(request, rid.get_subscription, request.match_info["id"])
            )

        async def sub_search(request):
            owner = auth(request, _RID + "SearchSubscriptions")
            return _freshness_json_response(
                request,
                await _call_read(request, rid.search_subscriptions, request.query.get("area", ""), owner),
            )

        base = "/v1/dss/identification_service_areas"
        app.router.add_put(base + "/{id}", isa_create)
        app.router.add_put(base + "/{id}/{version}", isa_update)
        app.router.add_delete(base + "/{id}/{version}", isa_delete)
        app.router.add_get(base + "/{id}", isa_get)
        app.router.add_get(base, isa_search)
        sbase = "/v1/dss/subscriptions"
        app.router.add_put(sbase + "/{id}", sub_create)
        app.router.add_put(sbase + "/{id}/{version}", sub_update)
        app.router.add_delete(sbase + "/{id}/{version}", sub_delete)
        app.router.add_get(sbase + "/{id}", sub_get)
        app.router.add_get(sbase, sub_search)

    # -- SCD -----------------------------------------------------------------

    if scd_service is not None and enable_scd:
        scd = scd_service

        async def op_put(request):
            owner = auth(request, _SCD + "PutOperationReference")
            return web.json_response(
                await _call_r(request, scd.put_operation, 
                    request.match_info["entityuuid"],
                    await _params(request),
                    owner,
                )
            )

        async def op_get(request):
            owner = auth(request, _SCD + "GetOperationReference")
            return web.json_response(
                await _call_read(request, scd.get_operation, request.match_info["entityuuid"], owner)
            )

        async def op_delete(request):
            owner = auth(request, _SCD + "DeleteOperationReference")
            return web.json_response(
                await _call_r(request, scd.delete_operation, request.match_info["entityuuid"], owner)
            )

        async def op_query(request):
            owner = auth(request, _SCD + "SearchOperationReferences")
            return _freshness_json_response(
                request,
                await _call_read(request, scd.search_operations, await _params(request), owner),
            )

        async def scd_sub_put(request):
            owner = auth(request, _SCD + "PutSubscription")
            return web.json_response(
                await _call_r(request, scd.put_subscription, 
                    request.match_info["subscriptionid"],
                    await _params(request),
                    owner,
                )
            )

        async def scd_sub_get(request):
            owner = auth(request, _SCD + "GetSubscription")
            return web.json_response(
                await _call_r(request, scd.get_subscription, 
                    request.match_info["subscriptionid"], owner
                )
            )

        async def scd_sub_delete(request):
            owner = auth(request, _SCD + "DeleteSubscription")
            return web.json_response(
                await _call_r(request, scd.delete_subscription, 
                    request.match_info["subscriptionid"], owner
                )
            )

        async def scd_sub_query(request):
            owner = auth(request, _SCD + "QuerySubscriptions")
            return _freshness_json_response(
                request,
                await _call_read(request, scd.query_subscriptions, await _params(request), owner),
            )

        async def constraint_put(request):
            owner = auth(request, _SCD + "PutConstraintReference")
            return web.json_response(
                await _call_r(request, scd.put_constraint,
                    request.match_info["entityuuid"],
                    await _params(request),
                    owner,
                )
            )

        async def constraint_get(request):
            owner = auth(request, _SCD + "GetConstraintReference")
            return web.json_response(
                await _call_read(request, scd.get_constraint,
                    request.match_info["entityuuid"], owner
                )
            )

        async def constraint_delete(request):
            owner = auth(request, _SCD + "DeleteConstraintReference")
            return web.json_response(
                await _call_r(request, scd.delete_constraint,
                    request.match_info["entityuuid"], owner
                )
            )

        async def constraint_query(request):
            owner = auth(request, _SCD + "QueryConstraintReferences")
            return _freshness_json_response(
                request,
                await _call_read(request, scd.query_constraints, await _params(request), owner),
            )

        async def dss_report(request):
            auth(request, _SCD + "MakeDssReport")
            return web.json_response(
                await _call_r(request, scd.make_dss_report, await _params(request))
            )

        # exact /query routes registered before the {entityuuid} patterns
        app.router.add_post("/dss/v1/operation_references/query", op_query)
        app.router.add_post("/dss/v1/subscriptions/query", scd_sub_query)
        app.router.add_post(
            "/dss/v1/constraint_references/query", constraint_query
        )
        app.router.add_post("/dss/v1/reports", dss_report)
        app.router.add_put("/dss/v1/operation_references/{entityuuid}", op_put)
        app.router.add_get("/dss/v1/operation_references/{entityuuid}", op_get)
        app.router.add_delete(
            "/dss/v1/operation_references/{entityuuid}", op_delete
        )
        app.router.add_put(
            "/dss/v1/subscriptions/{subscriptionid}", scd_sub_put
        )
        app.router.add_get(
            "/dss/v1/subscriptions/{subscriptionid}", scd_sub_get
        )
        app.router.add_delete(
            "/dss/v1/subscriptions/{subscriptionid}", scd_sub_delete
        )
        app.router.add_put(
            "/dss/v1/constraint_references/{entityuuid}", constraint_put
        )
        app.router.add_get(
            "/dss/v1/constraint_references/{entityuuid}", constraint_get
        )
        app.router.add_delete(
            "/dss/v1/constraint_references/{entityuuid}", constraint_delete
        )

    return app
