// Native window packing + hit decoding for the fused device path
// (dss_tpu/ops/fastpath.py FastTable.submit/collect).  These are the
// two host-CPU stages that bound pipelined fused throughput on a
// small host: expanding every query key's postings run into 128-lane
// device windows (~22 ms/8k-query batch in numpy: 65k binary searches
// + ragged repeats) and turning the compacted hit words back into
// (query, slot) pairs (~8 ms of popcount/ctz numpy).  Each mirrors
// the numpy math step-for-step — same integer ops on the same values,
// identical output ORDER — so results are bit-identical;
// tests/test_native_fastwin.py pins both differentially.
//
// Two-phase window build: dss_win_ranges runs the binary searches
// once and parks [lo, hi) per (query, cell) pair in caller scratch
// (plus the total window count, so Python can size the pow2-bucket
// upload buffer); dss_win_expand then fills the packed rows without
// re-searching.

#include <cstdint>

namespace {

inline int64_t lower_bound_i32(const int32_t* a, int64_t n, int32_t v) {
  int64_t lo = 0, hi = n;
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (a[mid] < v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

inline int64_t upper_bound_i32(const int32_t* a, int64_t n, int32_t v) {
  int64_t lo = 0, hi = n;
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (a[mid] <= v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

inline int64_t lower_bound_range(
    const int32_t* a, int64_t lo, int64_t hi, int32_t v) {
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (a[mid] < v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

}  // namespace

namespace {

// Run end for a key known to start at lo (host_key[lo] == k): gallop
// forward over the contiguous run — probes ride the hardware
// prefetcher instead of paying random-access misses.  Requires
// k < INT32_MAX (DAR keys are 30-bit; pads are negative and never
// reach here).
inline int64_t run_end(
    const int32_t* a, int64_t n, int64_t lo, int32_t k) {
  int64_t step = 1;
  int64_t prev = lo;
  int64_t probe = lo + 1;
  while (probe < n && a[probe] <= k) {
    prev = probe;
    step <<= 1;
    probe = lo + step;
  }
  if (probe > n) probe = n;
  return lower_bound_range(a, prev + 1, probe, k + 1);
}

}  // namespace

extern "C" {

// Shared internal (cross-TU within libdsscover.so, not a public API):
// one key's [lo, hi) postings run via the sampled two-level lower
// bound + galloping run end.  Pass n_sample = 0 for the flat search.
void dss_internal_key_run(
    const int32_t* host_key, int64_t n_post,
    const int32_t* sample, int64_t n_sample, int64_t stride,
    const int32_t* sample0, int64_t n_s0, int64_t stride0,
    int32_t k, int64_t* out_lo, int64_t* out_hi) {
  int64_t lo;
  if (n_sample > 0) {
    int64_t s_lo = 0, s_hi = n_sample;
    if (n_s0 > 0) {
      const int64_t j0 = lower_bound_i32(sample0, n_s0, k);
      s_lo = j0 == 0 ? 0 : (j0 - 1) * stride0 + 1;
      s_hi = j0 * stride0 + 1;
      if (s_hi > n_sample) s_hi = n_sample;
    }
    const int64_t j = lower_bound_range(sample, s_lo, s_hi, k);
    const int64_t leaf_lo = j == 0 ? 0 : (j - 1) * stride + 1;
    int64_t leaf_hi = j * stride + 1;
    if (leaf_hi > n_post) leaf_hi = n_post;
    lo = lower_bound_range(host_key, leaf_lo, leaf_hi, k);
  } else {
    lo = lower_bound_i32(host_key, n_post, k);
  }
  *out_lo = lo;
  *out_hi = (lo < n_post && host_key[lo] == k)
                ? run_end(host_key, n_post, lo, k)
                : lo;
}

// Postings-range lookup for n flattened query keys (pad keys -1 find
// empty ranges).  Fills out_lo/out_hi (caller scratch, length n) and
// returns the total 128-block window count over non-empty runs —
// exactly sum((hi-1)/block - lo/block + 1).
//
// A flat binary search over millions of postings is memory-latency
// bound (~8 uncached probes x ~100 ns x 65k keys ~ 20 ms/batch), so
// the caller passes a 1/stride sampled copy of the key column
// (sample[i] = host_key[i*stride]; 1M/64 = 64 KB — L2-resident).
// Each lookup searches the sample, then one stride-sized leaf slice
// (1-2 cache lines), then finds the run end by galloping forward over
// the contiguous run — ~2 cold lines per key instead of ~8.  Pass
// n_sample = 0 to fall back to the flat search (small tables).

int64_t dss_win_ranges(
    const int32_t* host_key, int64_t n_post,
    const int32_t* sample, int64_t n_sample, int64_t stride,
    const int32_t* sample0_in, int64_t n_s0_in,
    const int32_t* qkeys, int64_t n, int64_t block,
    int64_t* out_lo, int64_t* out_hi) {
  int64_t nw = 0;
  if (n_sample <= 0) {
    // small table: flat searches are already cache-resident
    for (int64_t i = 0; i < n; ++i) {
      dss_internal_key_run(
          host_key, n_post, nullptr, 0, 0, nullptr, 0, 0,
          qkeys[i], &out_lo[i], &out_hi[i]);
      const int64_t lo = out_lo[i], hi = out_hi[i];
      if (hi > lo) nw += (hi - 1) / block - lo / block + 1;
    }
    return nw;
  }
  // The per-key search is latency-bound (a dependent chain of probes,
  // half of them mispredicted branches), so run G searches in
  // lockstep: branchless (cmov) rounds over the L2-resident sample,
  // prefetch each key's leaf slice, then branchless rounds within the
  // leaf — the G keys' cache misses overlap instead of serializing.
  constexpr int G = 16;
  // At 8M postings the 1/64 sample is itself ~500 KB (bigger than
  // L2), so derive one more 1/64 level on the fly (~8 KB — L1) and
  // search top-down: L1 rounds, then one prefetched sample slice,
  // then one prefetched host_key slice, then the gallop.  Every
  // random-access stage runs G keys in lockstep so misses overlap.
  const int64_t stride0 = 64;
  int64_t n_s0 = n_s0_in;
  const int32_t* sample0 = sample0_in;
  int32_t* owned = nullptr;
  if (n_s0 <= 0) {  // caller didn't cache the top level: derive it
    n_s0 = (n_sample + stride0 - 1) / stride0;
    owned = new int32_t[n_s0 > 0 ? n_s0 : 1];
    for (int64_t i = 0; i < n_s0; ++i) owned[i] = sample[i * stride0];
    sample0 = owned;
  }
  int top_rounds = 0;
  while ((int64_t{1} << top_rounds) < n_s0 + 1) ++top_rounds;
  int64_t lo_[G], hi_[G];
  int32_t key_[G];
  for (int64_t base = 0; base < n; base += G) {
    const int g_n = static_cast<int>(n - base < G ? n - base : G);
    for (int g = 0; g < g_n; ++g) {
      key_[g] = qkeys[base + g];
      lo_[g] = 0;
      hi_[g] = n_s0;
    }
    for (int r = 0; r < top_rounds; ++r) {
      for (int g = 0; g < g_n; ++g) {
        const int64_t lo = lo_[g], hi = hi_[g];
        const int64_t mid = (lo + hi) >> 1;
        const bool active = lo < hi;
        const bool lt = active && sample0[mid] < key_[g];
        lo_[g] = lt ? mid + 1 : lo;
        hi_[g] = active && !lt ? mid : hi_[g];
      }
    }
    // sample0[j] = sample[j*stride0] is the first level-0 entry >=
    // key, so key's sample lower bound lives in the slice
    // ((j-1)*stride0, j*stride0] — prefetch all G slices, then count.
    for (int g = 0; g < g_n; ++g) {
      const int64_t j = lo_[g];
      const int64_t s_lo = j == 0 ? 0 : (j - 1) * stride0 + 1;
      int64_t s_hi = j * stride0 + 1;
      if (s_hi > n_sample) s_hi = n_sample;
      lo_[g] = s_lo;
      hi_[g] = s_hi;
      for (int64_t off = s_lo; off < s_hi; off += 16) {
        __builtin_prefetch(&sample[off]);
      }
    }
    for (int g = 0; g < g_n; ++g) {
      const int64_t s_lo = lo_[g], s_hi = hi_[g];
      const int32_t k = key_[g];
      int64_t cnt = 0;
      for (int64_t off = s_lo; off < s_hi; ++off) {
        cnt += sample[off] < k;
      }
      lo_[g] = s_lo + cnt;  // = lower_bound(sample, k)
    }
    // sample[j] = host_key[j*stride]: same bracketing one level down
    for (int g = 0; g < g_n; ++g) {
      const int64_t j = lo_[g];
      const int64_t leaf_lo = j == 0 ? 0 : (j - 1) * stride + 1;
      int64_t leaf_hi = j * stride + 1;
      if (leaf_hi > n_post) leaf_hi = n_post;
      lo_[g] = leaf_lo;
      hi_[g] = leaf_hi;
      for (int64_t off = leaf_lo; off < leaf_hi; off += 16) {
        __builtin_prefetch(&host_key[off]);
      }
    }
    for (int g = 0; g < g_n; ++g) {
      // leaf lower bound as a branchless vectorizable count of
      // elements < key: the slice's cache lines are prefetched and
      // read whole either way, and the count loop autovectorizes
      const int64_t leaf_lo = lo_[g], leaf_hi = hi_[g];
      const int32_t k = key_[g];
      int64_t cnt = 0;
      for (int64_t off = leaf_lo; off < leaf_hi; ++off) {
        cnt += host_key[off] < k;
      }
      lo_[g] = leaf_lo + cnt;
    }
    for (int g = 0; g < g_n; ++g) {
      const int64_t lo = lo_[g];
      const int32_t k = key_[g];
      const int64_t hi = (lo < n_post && host_key[lo] == k)
                             ? run_end(host_key, n_post, lo, k)
                             : lo;
      out_lo[base + g] = lo;
      out_hi[base + g] = hi;
      if (hi > lo) nw += (hi - 1) / block - lo / block + 1;
    }
  }
  delete[] owned;
  return nw;
}

// Expand the ranges into packed window rows.  wins_blk / wins_meta are
// the two rows of the (2, bucket) i32 upload (caller pre-zeroes the
// pad tail); win_q / win_blk are the host-side decode arrays.  w is
// the per-query key width (query index of pair i == i / w).  Returns
// the window count, or -1 if it would exceed cap (callers size cap
// from dss_win_ranges, so that is a programming error, not data).
int64_t dss_win_expand(
    const int64_t* lo, const int64_t* hi, int64_t n,
    int32_t w, int64_t block,
    int32_t* wins_blk, int32_t* wins_meta,
    int32_t* win_q, int32_t* win_blk, int64_t cap) {
  int64_t nw = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t l = lo[i], h = hi[i];
    if (h <= l) continue;
    const int32_t q = static_cast<int32_t>(i / w);
    const int64_t first = l / block;
    const int64_t last = (h - 1) / block;
    for (int64_t b = first; b <= last; ++b) {
      if (nw >= cap) return -1;
      const int64_t blk0 = b * block;
      int64_t s = l - blk0;
      if (s < 0) s = 0;
      int64_t e = h - blk0;
      if (e > block) e = block;
      const int32_t blk = static_cast<int32_t>(b);
      wins_blk[nw] = blk;
      wins_meta[nw] = static_cast<int32_t>(s) |
                      (static_cast<int32_t>(e) << 8) | (q << 16);
      win_q[nw] = q;
      win_blk[nw] = blk;
      ++nw;
    }
  }
  return nw;
}

// Total set bits over the hit words — the decode output capacity.
int64_t dss_hit_total(const uint32_t* bits, int64_t n_words) {
  int64_t total = 0;
  for (int64_t i = 0; i < n_words; ++i) {
    total += __builtin_popcount(bits[i]);
  }
  return total;
}

// Compacted hit words -> exact (query, slot) pairs, in the numpy
// path's order (word-major, ascending bit position), dropping pad
// lanes (offset >= n_postings) and post-build tombstones (!slot_live).
// words_shift = log2(words per window); block = postings per block.
// Returns the emitted pair count (<= cap = dss_hit_total).
int64_t dss_decode_hits(
    const int32_t* wordpos, const uint32_t* bits, int64_t n_words,
    const int32_t* win_q, const int32_t* win_blk,
    int64_t words_shift, int64_t block,
    const int32_t* host_ent, int64_t n_postings,
    const uint8_t* slot_live,
    int64_t* out_qidx, int64_t* out_slots, int64_t cap) {
  const int64_t words_mask = (int64_t{1} << words_shift) - 1;
  int64_t n_out = 0;
  for (int64_t i = 0; i < n_words; ++i) {
    const int64_t wp = wordpos[i];
    const int64_t win = wp >> words_shift;
    const int64_t lane_base = (wp & words_mask) << 5;
    const int64_t blk0 = static_cast<int64_t>(win_blk[win]) * block;
    uint32_t v = bits[i];
    while (v) {
      const int b = __builtin_ctz(v);
      v &= v - 1;
      const int64_t off = blk0 + lane_base + b;
      if (off >= n_postings) continue;
      const int32_t slot = host_ent[off];
      if (!slot_live[slot]) continue;
      if (n_out >= cap) return -1;  // unreachable when cap >= popcount
      out_qidx[n_out] = win_q[win];
      out_slots[n_out] = slot;
      ++n_out;
    }
  }
  return n_out;
}

}  // extern "C"
