// Native host-path query for FastTable (dss_tpu/ops/fastpath.py
// query_host + host_candidates): the exact small-batch answer path
// that serves point lookups and conflict prechecks without a device
// round trip.  Mirrors the numpy semantics comparison-for-comparison
// (same IEEE float/int compares on the same values — bit-identical
// verdicts); tests/test_native_hostquery.py pins it differentially.
//
// The numpy version costs ~0.2 ms at 1k entities and ~3 ms at 1M
// (dozens of array dispatches); this is one GIL-released call doing
// binary searches + a linear candidate scan (~5-40 us).

#include <cstdint>

// Shared sampled range lookup (fastwin.cc, same shared library):
// two-level lower bound + galloping run end — ~2 cold cache lines per
// key instead of ~8 flat binary-search misses at millions of postings.
extern "C" void dss_internal_key_run(
    const int32_t* host_key, int64_t n_post,
    const int32_t* sample, int64_t n_sample, int64_t stride,
    const int32_t* sample0, int64_t n_s0, int64_t stride0,
    int32_t k, int64_t* out_lo, int64_t* out_hi);

extern "C" {

// Exact host query over the sorted postings + exact slot columns.
//   qkeys: (B, W) int32, pad -1 (pads find empty ranges and drop out)
//   sample / sample0: optional cached host_key[::stride] /
//     sample[::64] index levels (n_sample = 0 -> flat searches)
//   scratch_lo / scratch_hi: caller buffers, length b*w (the ranges
//     are found once and shared by the gate and filter passes)
//   out_qidx / out_slot: caller buffers with capacity out_cap
// Returns the emitted pair count, or -1 when the candidate total
// exceeds max_candidates (caller takes the device path — the same
// HOST_MAX_CANDIDATES gate as fastpath.host_candidates).
int64_t dss_query_host(
    const int32_t* host_key, const int32_t* host_ent,
    const uint8_t* host_live, int64_t n_post,
    const uint8_t* slot_live, const float* slot_alo,
    const float* slot_ahi, const int64_t* slot_t0,
    const int64_t* slot_t1,
    const int32_t* qkeys, int32_t b, int32_t w,
    const float* q_alo, const float* q_ahi,
    const int64_t* q_t0, const int64_t* q_t1, const int64_t* q_now,
    const int32_t* sample, int64_t n_sample, int64_t stride,
    const int32_t* sample0, int64_t n_s0,
    int64_t* scratch_lo, int64_t* scratch_hi,
    int64_t max_candidates,
    int64_t* out_qidx, int32_t* out_slot, int64_t out_cap) {
  // pass 1: ranges + candidate total (the host/device routing gate)
  int64_t total = 0;
  for (int64_t i = 0; i < int64_t{b} * w; ++i) {
    dss_internal_key_run(
        host_key, n_post, sample, n_sample, stride, sample0, n_s0, 64,
        qkeys[i], &scratch_lo[i], &scratch_hi[i]);
    total += scratch_hi[i] - scratch_lo[i];
    if (total > max_candidates) return -1;
  }
  // pass 2: exact filter (identical compares to fastpath.query_host)
  int64_t n_out = 0;
  for (int32_t q = 0; q < b; ++q) {
    const float alo = q_alo[q];
    const float ahi = q_ahi[q];
    const int64_t t1min =
        q_t0[q] > q_now[q] ? q_t0[q] : q_now[q];  // max(t_start, now)
    const int64_t te = q_t1[q];
    for (int32_t j = 0; j < w; ++j) {
      const int64_t lo = scratch_lo[q * w + j];
      const int64_t hi = scratch_hi[q * w + j];
      for (int64_t off = lo; off < hi; ++off) {
        const int32_t slot = host_ent[off];
        if (!host_live[off]) continue;
        if (!slot_live[slot]) continue;
        if (!(slot_ahi[slot] >= alo)) continue;
        if (!(slot_alo[slot] <= ahi)) continue;
        if (!(slot_t1[slot] >= t1min)) continue;
        if (!(slot_t0[slot] <= te)) continue;
        if (n_out >= out_cap) return -1;  // cap: route to the device
        out_qidx[n_out] = q;
        out_slot[n_out] = slot;
        ++n_out;
      }
    }
  }
  return n_out;
}

}  // extern "C"
