// Native host-path query for FastTable (dss_tpu/ops/fastpath.py
// query_host + host_candidates): the exact small-batch answer path
// that serves point lookups and conflict prechecks without a device
// round trip.  Mirrors the numpy semantics comparison-for-comparison
// (same IEEE float/int compares on the same values — bit-identical
// verdicts); tests/test_native_hostquery.py pins it differentially.
//
// The numpy version costs ~0.2 ms at 1k entities and ~3 ms at 1M
// (dozens of array dispatches); this is one GIL-released call doing
// binary searches + a linear candidate scan (~5-40 us).

#include <cstdint>

namespace {

inline int64_t lower_bound_i32(const int32_t* a, int64_t n, int32_t v) {
  int64_t lo = 0, hi = n;
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (a[mid] < v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

inline int64_t upper_bound_i32(const int32_t* a, int64_t n, int32_t v) {
  int64_t lo = 0, hi = n;
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (a[mid] <= v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

}  // namespace

extern "C" {

// Exact host query over the sorted postings + exact slot columns.
//   qkeys: (B, W) int32, pad -1 (pads find empty ranges and drop out)
//   out_qidx / out_slot: caller buffers with capacity out_cap
// Returns the emitted pair count, or -1 when the candidate total
// exceeds max_candidates (caller takes the device path — the same
// HOST_MAX_CANDIDATES gate as fastpath.host_candidates).
int64_t dss_query_host(
    const int32_t* host_key, const int32_t* host_ent,
    const uint8_t* host_live, int64_t n_post,
    const uint8_t* slot_live, const float* slot_alo,
    const float* slot_ahi, const int64_t* slot_t0,
    const int64_t* slot_t1,
    const int32_t* qkeys, int32_t b, int32_t w,
    const float* q_alo, const float* q_ahi,
    const int64_t* q_t0, const int64_t* q_t1, const int64_t* q_now,
    int64_t max_candidates,
    int64_t* out_qidx, int32_t* out_slot, int64_t out_cap) {
  // pass 1: candidate total (the host/device routing gate)
  int64_t total = 0;
  for (int32_t q = 0; q < b; ++q) {
    for (int32_t j = 0; j < w; ++j) {
      const int32_t k = qkeys[q * w + j];
      const int64_t lo = lower_bound_i32(host_key, n_post, k);
      const int64_t hi = upper_bound_i32(host_key, n_post, k);
      total += hi - lo;
      if (total > max_candidates) return -1;
    }
  }
  // pass 2: exact filter (identical compares to fastpath.query_host)
  int64_t n_out = 0;
  for (int32_t q = 0; q < b; ++q) {
    const float alo = q_alo[q];
    const float ahi = q_ahi[q];
    const int64_t t1min =
        q_t0[q] > q_now[q] ? q_t0[q] : q_now[q];  // max(t_start, now)
    const int64_t te = q_t1[q];
    for (int32_t j = 0; j < w; ++j) {
      const int32_t k = qkeys[q * w + j];
      const int64_t lo = lower_bound_i32(host_key, n_post, k);
      const int64_t hi = upper_bound_i32(host_key, n_post, k);
      for (int64_t off = lo; off < hi; ++off) {
        const int32_t slot = host_ent[off];
        if (!host_live[off]) continue;
        if (!slot_live[slot]) continue;
        if (!(slot_ahi[slot] >= alo)) continue;
        if (!(slot_alo[slot] <= ahi)) continue;
        if (!(slot_t1[slot] >= t1min)) continue;
        if (!(slot_t0[slot] <= te)) continue;
        if (n_out >= out_cap) return -1;  // cap: route to the device
        out_qidx[n_out] = q;
        out_slot[n_out] = slot;
        ++n_out;
      }
    }
  }
  return n_out;
}

}  // extern "C"
