"""Native (C++) kernels for the host-side hot paths.

JAX/XLA owns the device compute path; these cover the host work around
it, each mirroring its numpy reference operation-for-operation so
results are bit-identical (pinned differentially by
tests/test_native_*.py):

- covering.cc — the level-13 covering fast path (request shaping;
  ~5 ms/request of numpy small-op dispatch -> ~0.2 ms)
- hostquery.cc — the exact small-batch serving query over the sorted
  postings + slot columns (no device round trip)
- fastwin.cc — the fused device pipeline's window pack + hit decode,
  plus the shared sampled two-level range lookup both query paths ride

The shared library is built on demand with g++ (make native, or
lazily at first import).  If the toolchain or build is unavailable the
callers fall back to the numpy path — behavior never changes, only
speed.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from dss_tpu.native import _buildlib

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, n) for n in _buildlib.SOURCE_NAMES]
_SRC = _SOURCES[0]  # kept for back-compat references
_SO = os.path.join(_DIR, _buildlib.SO_NAME)

_load_lock = threading.Lock()   # guards _lib / _load_failed + dlopen
_build_lock = threading.Lock()  # serializes g++ runs (never held with
#                                 _load_lock, so available() can't
#                                 block behind a compile)
_lib = None
_load_failed = False


def _build() -> bool:
    """Compile _SOURCES -> libdsscover.so + digest sidecar (see
    _buildlib: atomic renames; content-hash freshness)."""
    return _buildlib.build(_DIR)


def _so_fresh() -> bool:
    """Content-based: the sidecar digest must match the sources on
    disk.  mtimes are untrustworthy here — pip stamps installed files
    with extraction time, so a wheel-shipped stale .so would pass any
    mtime rule."""
    return _buildlib.so_fresh(_DIR)


def _try_load() -> Optional[ctypes.CDLL]:
    """dlopen the .so if fresh on disk.  Fast; never compiles."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _load_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not _so_fresh():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.dss_loop_covering.restype = ctypes.c_int64
            lib.dss_loop_covering.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int64,
            ]
            lib.dss_points_covering.restype = ctypes.c_int64
            lib.dss_points_covering.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int32,
                ctypes.c_double,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int64,
            ]
            i32p = ctypes.POINTER(ctypes.c_int32)
            i64p = ctypes.POINTER(ctypes.c_int64)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            f32p = ctypes.POINTER(ctypes.c_float)
            lib.dss_query_host.restype = ctypes.c_int64
            lib.dss_query_host.argtypes = [
                i32p, i32p, u8p, ctypes.c_int64,          # postings
                u8p, f32p, f32p, i64p, i64p,              # slot columns
                i32p, ctypes.c_int32, ctypes.c_int32,     # qkeys, B, W
                f32p, f32p, i64p, i64p, i64p,             # query bounds
                i32p, ctypes.c_int64, ctypes.c_int64,     # sample index
                i32p, ctypes.c_int64,                     # top-level sample
                i64p, i64p,                               # range scratch
                ctypes.c_int64,                           # max_candidates
                i64p, i32p, ctypes.c_int64,               # out buffers
            ]
            lib.dss_win_ranges.restype = ctypes.c_int64
            lib.dss_win_ranges.argtypes = [
                i32p, ctypes.c_int64,                     # host_key
                i32p, ctypes.c_int64, ctypes.c_int64,     # sample index
                i32p, ctypes.c_int64,                     # top-level sample
                i32p, ctypes.c_int64, ctypes.c_int64,     # qkeys, n, block
                i64p, i64p,                               # lo/hi scratch
            ]
            lib.dss_win_expand.restype = ctypes.c_int64
            lib.dss_win_expand.argtypes = [
                i64p, i64p, ctypes.c_int64,               # lo, hi, n
                ctypes.c_int32, ctypes.c_int64,           # w, block
                i32p, i32p,                               # wins rows
                i32p, i32p, ctypes.c_int64,               # win_q/blk, cap
            ]
            u32p = ctypes.POINTER(ctypes.c_uint32)
            lib.dss_hit_total.restype = ctypes.c_int64
            lib.dss_hit_total.argtypes = [u32p, ctypes.c_int64]
            lib.dss_decode_hits.restype = ctypes.c_int64
            lib.dss_decode_hits.argtypes = [
                i32p, u32p, ctypes.c_int64,               # wordpos, bits
                i32p, i32p,                               # win_q, win_blk
                ctypes.c_int64, ctypes.c_int64,           # shift, block
                i32p, ctypes.c_int64,                     # host_ent, P
                u8p,                                      # slot_live
                i64p, i64p, ctypes.c_int64,               # out, cap
            ]
            _lib = lib
        except (OSError, AttributeError):
            # OSError: dlopen failure.  AttributeError: a stale
            # prebuilt .so missing newer symbols — latch the numpy
            # fallback instead of re-raising on every request.
            _load_failed = True
        return _lib


def ensure_built() -> bool:
    """Build (if needed) and load synchronously.  Call at startup or
    from tests; the request path never compiles."""
    global _load_failed
    if _try_load() is not None:
        return True
    with _build_lock:
        if _try_load() is not None:
            return True
        if not _so_fresh() and not _build():
            # build failure does NOT latch: a later `make native` (or a
            # sibling process's build) producing a fresh .so is picked
            # up by the next _try_load stat.  Only dlopen of a fresh
            # .so latches _load_failed.
            return False
    return _try_load() is not None


def available() -> bool:
    """True if the kernel is loaded (or the .so is fresh on disk and
    loads instantly).  Never triggers a compile: a covering request
    must not stall behind a multi-second g++ run — the background
    build started at import flips this True when done."""
    return _try_load() is not None


# Kick the build off-thread at import: server processes get the kernel
# a few seconds after boot without ever blocking a request on g++.
if not _so_fresh():
    threading.Thread(
        target=ensure_built, name="dsscover-build", daemon=True
    ).start()


class CoveringTooLarge(Exception):
    """Native covering exceeded the max cell count (AreaTooLarge)."""


_OUT_CAP = 100_001
_tls = threading.local()


def _out_buf() -> np.ndarray:
    """Reusable per-thread output buffer: allocating 800 KB per call
    costs more than the kernel itself."""
    buf = getattr(_tls, "buf", None)
    if buf is None:
        buf = _tls.buf = np.empty(_OUT_CAP, dtype=np.uint64)
    return buf


def _ptr(a, ct):
    """ctypes pointer to a contiguous ndarray's buffer."""
    return a.ctypes.data_as(ctypes.POINTER(ct))


def loop_covering(v_xyz: np.ndarray, area_ok: bool) -> Optional[np.ndarray]:
    """Native single-face rect covering of the loop.

    Returns the sorted uint64 cell array, None when the caller must
    take the Python BFS fallback (multi-face / face-edge / oversized
    rect / area gate failed / native unavailable), or raises
    CoveringTooLarge.
    """
    lib = _try_load()
    if lib is None:
        return None
    v = np.ascontiguousarray(v_xyz, dtype=np.float64)
    out = _out_buf()
    rc = lib.dss_loop_covering(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        np.int32(len(v)),
        np.int32(1 if area_ok else 0),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        np.int64(_OUT_CAP),
    )
    if rc == -2:
        raise CoveringTooLarge("covering exceeds maximum cell count")
    if rc < 0:
        return None
    return out[:rc].copy()


class AreaTooLarge(Exception):
    """Loop exceeds the area gate even after the winding retry; .area
    carries the computed km² for the error message."""

    def __init__(self, area: float):
        super().__init__(f"area is too large ({area:f}km²)")
        self.area = area


class Degenerate(Exception):
    """Zero/negative area: the caller takes the polyline path."""


def points_covering(v_xyz: np.ndarray, max_area_km2: float):
    """covering_from_loop_points fast path: winding retry + area gate +
    rect covering in one native call.  The area gate threshold comes
    from the caller (covering.MAX_AREA_KM2 — single source of truth).
    Returns the sorted uint64 cells, or None when the caller must run
    the full Python path; raises AreaTooLarge / Degenerate /
    CoveringTooLarge per the gate results.
    """
    lib = _try_load()
    if lib is None:
        return None
    v = np.ascontiguousarray(v_xyz, dtype=np.float64)
    out = _out_buf()
    area = ctypes.c_double(0.0)
    rc = lib.dss_points_covering(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        np.int32(len(v)),
        ctypes.c_double(max_area_km2),
        ctypes.byref(area),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        np.int64(_OUT_CAP),
    )
    if rc == -1:
        raise Degenerate()
    if rc == -2:
        if area.value > max_area_km2:
            raise AreaTooLarge(area.value)
        raise CoveringTooLarge("covering exceeds maximum cell count")
    if rc < 0:
        return None
    return out[:rc].copy()


def query_host(
    host_key, host_ent, host_live,
    slot_live, slot_alo, slot_ahi, slot_t0, slot_t1,
    qkeys, q_alo, q_ahi, q_t0, q_t1, q_now,
    max_candidates: int,
    *, sample=None, sample0=None, stride: int = 64,
):
    """Native exact host query -> (qidx i64[N], slot i32[N]), or None
    when the lib is unavailable or the candidate total says device
    path.  Inputs must be contiguous arrays of the fastpath dtypes.
    sample / sample0 (optional, see pack_windows) route the range
    lookups through the cached two-level index instead of flat binary
    searches — the serving-path lookups share the fused path's index."""
    lib = _try_load()
    if lib is None:
        return None
    b, w = qkeys.shape
    cap = int(max_candidates)
    # reusable per-thread output + range-scratch buffers (same
    # rationale as _out_buf: a ~768 KB allocation would dwarf the
    # ~15 us kernel)
    bufs = getattr(_tls, "hq", None)
    if bufs is None or len(bufs[0]) < cap:
        bufs = _tls.hq = (
            np.empty(cap, np.int64), np.empty(cap, np.int32)
        )
    out_q, out_s = bufs
    n = b * w
    scratch = getattr(_tls, "hqr", None)
    if scratch is None or len(scratch[0]) < n:
        scratch = _tls.hqr = (np.empty(n, np.int64), np.empty(n, np.int64))
    lo, hi = scratch
    if sample is None:
        sample = np.zeros(0, np.int32)
    if sample0 is None:
        sample0 = np.zeros(0, np.int32)

    rc = lib.dss_query_host(
        _ptr(host_key, ctypes.c_int32), _ptr(host_ent, ctypes.c_int32),
        _ptr(host_live, ctypes.c_uint8), np.int64(len(host_key)),
        _ptr(slot_live, ctypes.c_uint8), _ptr(slot_alo, ctypes.c_float),
        _ptr(slot_ahi, ctypes.c_float), _ptr(slot_t0, ctypes.c_int64),
        _ptr(slot_t1, ctypes.c_int64),
        _ptr(qkeys, ctypes.c_int32), np.int32(b), np.int32(w),
        _ptr(q_alo, ctypes.c_float), _ptr(q_ahi, ctypes.c_float),
        _ptr(q_t0, ctypes.c_int64), _ptr(q_t1, ctypes.c_int64),
        _ptr(q_now, ctypes.c_int64),
        _ptr(sample, ctypes.c_int32), np.int64(len(sample)),
        np.int64(stride),
        _ptr(sample0, ctypes.c_int32), np.int64(len(sample0)),
        _ptr(lo, ctypes.c_int64), _ptr(hi, ctypes.c_int64),
        np.int64(max_candidates),
        _ptr(out_q, ctypes.c_int64), _ptr(out_s, ctypes.c_int32),
        np.int64(cap),
    )
    if rc < 0:
        return None
    return out_q[:rc].copy(), out_s[:rc].copy()


def pack_windows(
    host_key, qk_flat, w: int, block: int, pow2_bucket,
    sample=None, stride: int = 64, sample0=None,
):
    """Native FastTable._pack_windows: postings-range binary searches +
    window expansion + meta packing in two GIL-released calls (~22 ms
    -> ~3 ms per 8k-query batch at 1M postings).  Returns
    (wins, win_q, win_blk, nw) with bit-identical contents to the
    numpy path, or None when the lib is unavailable.  qk_flat must be
    contiguous i32; wins pad rows are zero exactly like the numpy
    path (start == end == 0 -> no lanes match).  sample (optional) is
    the caller-cached host_key[::stride] copy that keeps the search's
    top levels L2-resident; sample0 (optional, requires sample) must
    be sample[::64] — the L1-resident top level (derived on the fly
    when absent)."""
    lib = _try_load()
    if lib is None:
        return None
    n = len(qk_flat)
    scratch = getattr(_tls, "winr", None)
    if scratch is None or len(scratch[0]) < n:
        scratch = _tls.winr = (np.empty(n, np.int64), np.empty(n, np.int64))
    lo, hi = scratch

    if sample is None:
        sample = np.zeros(0, np.int32)
    if sample0 is None:
        sample0 = np.zeros(0, np.int32)
    nw = lib.dss_win_ranges(
        _ptr(host_key, ctypes.c_int32), np.int64(len(host_key)),
        _ptr(sample, ctypes.c_int32), np.int64(len(sample)),
        np.int64(stride),
        _ptr(sample0, ctypes.c_int32), np.int64(len(sample0)),
        _ptr(qk_flat, ctypes.c_int32), np.int64(n), np.int64(block),
        _ptr(lo, ctypes.c_int64), _ptr(hi, ctypes.c_int64),
    )
    if nw == 0:
        empty = np.zeros(0, np.int32)
        return None, empty, empty, 0
    bucket = pow2_bucket(int(nw))
    wins = np.zeros((2, bucket), np.int32)
    win_q = np.empty(nw, np.int32)
    win_blk = np.empty(nw, np.int32)
    rc = lib.dss_win_expand(
        _ptr(lo, ctypes.c_int64), _ptr(hi, ctypes.c_int64), np.int64(n),
        np.int32(w), np.int64(block),
        _ptr(wins[0], ctypes.c_int32), _ptr(wins[1], ctypes.c_int32),
        _ptr(win_q, ctypes.c_int32), _ptr(win_blk, ctypes.c_int32),
        np.int64(nw),
    )
    if rc != nw:  # pragma: no cover — count/expand disagreement
        return None
    return wins, win_q, win_blk, int(nw)


def decode_hits(
    wordpos, bits_u32, win_q, win_blk,
    words_shift: int, block: int,
    host_ent, n_postings: int, slot_live_u8,
):
    """Native hit-word decode for FastTable.collect: popcount total +
    ctz expansion + pad/tombstone filtering in two GIL-released calls
    (~8 ms -> <1 ms per batch).  Output pairs are in the numpy path's
    exact order.  Returns (qidx i64[H], slots i64[H]) or None when the
    lib is unavailable.  All array args must be contiguous."""
    lib = _try_load()
    if lib is None:
        return None
    n_words = len(wordpos)

    total = lib.dss_hit_total(
        _ptr(bits_u32, ctypes.c_uint32), np.int64(n_words)
    )
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    out_q = np.empty(total, np.int64)
    out_s = np.empty(total, np.int64)
    rc = lib.dss_decode_hits(
        _ptr(wordpos, ctypes.c_int32), _ptr(bits_u32, ctypes.c_uint32),
        np.int64(n_words),
        _ptr(win_q, ctypes.c_int32), _ptr(win_blk, ctypes.c_int32),
        np.int64(words_shift), np.int64(block),
        _ptr(host_ent, ctypes.c_int32), np.int64(n_postings),
        _ptr(slot_live_u8, ctypes.c_uint8),
        _ptr(out_q, ctypes.c_int64), _ptr(out_s, ctypes.c_int64),
        np.int64(total),
    )
    if rc < 0:  # pragma: no cover — cap is popcount-exact
        return None
    return out_q[:rc], out_s[:rc]
