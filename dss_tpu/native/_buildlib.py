"""Stdlib-only build + freshness logic for libdsscover.so.

Kept free of numpy/jax imports so the Docker image's build stage (a
bare python:slim with g++) can run it directly:

    python dss_tpu/native/_buildlib.py <dir>

Freshness is CONTENT-based, not mtime-based: a successful build writes
`libdsscover.so.sha` holding the sha256 of the kernel sources, and the
loader accepts the .so only when that digest matches the sources on
disk.  mtimes cannot be trusted here — pip stamps every installed file
with its extraction time, so a wheel-shipped stale .so would look
"fresh" under any mtime rule (and whether it did depended on wheel
entry sort order).  With the digest, a stale shipped .so is detected
and rebuilt where a toolchain exists, or skipped (numpy fallback)
where it doesn't.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

# the single source of truth for what goes into the shared library —
# the Dockerfile build stage and the lazy in-process build both run
# through build() below, so the list cannot desync
SOURCE_NAMES = ["covering.cc", "hostquery.cc", "fastwin.cc"]
SO_NAME = "libdsscover.so"
DIGEST_NAME = SO_NAME + ".sha"


def source_digest(dirpath: str) -> str:
    """sha256 over the kernel sources, in SOURCE_NAMES order."""
    h = hashlib.sha256()
    for name in SOURCE_NAMES:
        with open(os.path.join(dirpath, name), "rb") as f:
            h.update(f.read())
        h.update(b"\x00")  # file boundary
    return h.hexdigest()


_fresh_cache: dict = {}  # dirpath -> (stat signature, verdict)


def _stat_sig(dirpath: str):
    """(name, mtime_ns, size) for the .so, sidecar, and sources — the
    CACHE key for so_fresh.  Correctness stays content-based; the
    stats only decide when the digest must be recomputed, so a stale
    shipped .so on a toolchain-less host costs one hash, not one per
    request."""
    out = []
    for name in [SO_NAME, DIGEST_NAME, *SOURCE_NAMES]:
        try:
            st = os.stat(os.path.join(dirpath, name))
            out.append((name, st.st_mtime_ns, st.st_size))
        except OSError:
            out.append((name, None, None))
    return tuple(out)


def so_fresh(dirpath: str) -> bool:
    """True iff the .so exists and its sidecar digest matches the
    sources on disk.  Never raises: any unreadable/corrupt state reads
    as stale (callers fall back to the numpy paths)."""
    sig = _stat_sig(dirpath)
    cached = _fresh_cache.get(dirpath)
    if cached is not None and cached[0] == sig:
        return cached[1]
    so = os.path.join(dirpath, SO_NAME)
    sha = os.path.join(dirpath, DIGEST_NAME)
    fresh = False
    if os.path.exists(so) and os.path.exists(sha):
        try:
            with open(sha, "r", encoding="ascii") as f:
                recorded = f.read().strip()
            fresh = recorded == source_digest(dirpath)
        except (OSError, UnicodeDecodeError, ValueError):
            fresh = False
    _fresh_cache[dirpath] = (sig, fresh)
    return fresh


def build(dirpath: str, timeout: float = 180) -> bool:
    """Compile the sources -> libdsscover.so + digest sidecar (atomic
    renames so racing processes never load a half-written pair: the
    sidecar lands only after the .so it describes)."""
    tmp = None
    try:
        digest = source_digest(dirpath)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=dirpath)
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp]
            + [os.path.join(dirpath, n) for n in SOURCE_NAMES],
            check=True,
            capture_output=True,
            timeout=timeout,
        )
        os.replace(tmp, os.path.join(dirpath, SO_NAME))
        tmp = None
        fd, tmp = tempfile.mkstemp(suffix=".sha", dir=dirpath)
        with os.fdopen(fd, "w", encoding="ascii") as f:
            f.write(digest + "\n")
        os.replace(tmp, os.path.join(dirpath, DIGEST_NAME))
        tmp = None
        _fresh_cache.pop(dirpath, None)
        return True
    except Exception as e:
        # surface compiler diagnostics (the Docker build stage would
        # otherwise fail with no clue what broke)
        import sys

        err = getattr(e, "stderr", None)
        if err:
            sys.stderr.write(
                err.decode("utf-8", "replace")
                if isinstance(err, bytes) else str(err)
            )
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


if __name__ == "__main__":
    import sys

    d = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
        os.path.abspath(__file__)
    )
    if not build(d):
        sys.exit("native kernel build failed")
    print(f"built {os.path.join(d, SO_NAME)}")
