// Native covering fast path for dss_tpu.geo.covering.
//
// Implements EXACTLY the single-face rectangle covering that
// dss_tpu/geo/covering.py::_loop_covering takes for typical entity
// footprints (reference semantics: /root/reference/pkg/geo/s2.go:16-25,
// coverings at fixed level 13), but in one native call instead of ~80
// small numpy dispatches (~5 ms -> ~20 us per request).  The Python
// path remains the behavioral reference: a differential fuzz test
// (tests/test_native_covering.py) pins this kernel to it cell-for-cell.
//
// Parity notes: every predicate here mirrors the numpy operation order
// (same +,-,*,/ and sqrt sequence in IEEE double), so verdicts are
// bit-identical; the only transcendental (atan2, in the area formula)
// stays in Python and its verdict is passed in via `area_ok`.
//
// Build: make native   (g++ -O2 -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

constexpr int MAX_LEVEL = 30;
constexpr int DAR_LEVEL = 13;
constexpr int LOOKUP_BITS = 4;
constexpr int SWAP_MASK = 1;
constexpr int INVERT_MASK = 2;
constexpr int64_t RECT_MAX_CELLS = 1 << 16;    // covering.py:_RECT_MAX_CELLS
constexpr int64_t MAX_COVERING_CELLS = 100000;  // covering.py:_MAX_COVERING_CELLS

// ---------------------------------------------------------------------------
// Hilbert traversal tables (public S2 scheme; s2cell.py:32-68)
// ---------------------------------------------------------------------------

int64_t lookup_pos[1 << (2 * LOOKUP_BITS + 2)];
int64_t lookup_ij[1 << (2 * LOOKUP_BITS + 2)];
const int pos_to_ij[4][4] = {
    {0, 1, 3, 2}, {0, 2, 3, 1}, {3, 2, 0, 1}, {3, 1, 0, 2}};
const int pos_to_orientation[4] = {SWAP_MASK, 0, 0, INVERT_MASK | SWAP_MASK};

void init_lookup(int level, int i, int j, int orig_orientation, int pos,
                 int orientation) {
  if (level == LOOKUP_BITS) {
    int ij = (i << LOOKUP_BITS) + j;
    lookup_pos[(ij << 2) + orig_orientation] = (pos << 2) + orientation;
    lookup_ij[(pos << 2) + orig_orientation] = (ij << 2) + orientation;
    return;
  }
  level += 1;
  i <<= 1;
  j <<= 1;
  pos <<= 2;
  const int* r = pos_to_ij[orientation];
  for (int idx = 0; idx < 4; ++idx) {
    init_lookup(level, i + (r[idx] >> 1), j + (r[idx] & 1), orig_orientation,
                pos + idx, orientation ^ pos_to_orientation[idx]);
  }
}

struct InitOnce {
  InitOnce() {
    init_lookup(0, 0, 0, 0, 0, 0);
    init_lookup(0, 0, 0, SWAP_MASK, 0, SWAP_MASK);
    init_lookup(0, 0, 0, INVERT_MASK, 0, INVERT_MASK);
    init_lookup(0, 0, 0, SWAP_MASK | INVERT_MASK, 0,
                SWAP_MASK | INVERT_MASK);
  }
} init_once;

// ---------------------------------------------------------------------------
// Projections (s2cell.py:76-166)
// ---------------------------------------------------------------------------

inline double st_to_uv(double s) {
  return s >= 0.5 ? (1.0 / 3.0) * (4.0 * s * s - 1.0)
                  : (1.0 / 3.0) * (1.0 - 4.0 * (1.0 - s) * (1.0 - s));
}

inline double uv_to_st(double u) {
  return u >= 0.0 ? 0.5 * std::sqrt(std::max(1.0 + 3.0 * u, 0.0))
                  : 1.0 - 0.5 * std::sqrt(std::max(1.0 - 3.0 * u, 0.0));
}

inline void xyz_to_face_uv(const double* p, int* face, double* u, double* v) {
  const double x = p[0], y = p[1], z = p[2];
  const double ax = std::fabs(x), ay = std::fabs(y), az = std::fabs(z);
  const int axis = ax >= ay ? (ax >= az ? 0 : 2) : (ay >= az ? 1 : 2);
  const double comp = axis == 0 ? x : (axis == 1 ? y : z);
  const int f = comp >= 0 ? axis : axis + 3;
  switch (f) {
    case 0: *u = y / x;  *v = z / x;  break;
    case 1: *u = -x / y; *v = z / y;  break;
    case 2: *u = -x / z; *v = -y / z; break;
    case 3: *u = z / x;  *v = y / x;  break;
    case 4: *u = z / y;  *v = -x / y; break;
    default: *u = -y / z; *v = -x / z; break;
  }
  *face = f;
}

inline void face_uv_to_xyz(int face, double u, double v, double* out) {
  double x, y, z;
  switch (face) {
    case 0: x = 1;  y = u;  z = v;  break;
    case 1: x = -u; y = 1;  z = v;  break;
    case 2: x = -u; y = -v; z = 1;  break;
    case 3: x = -1; y = -v; z = -u; break;
    case 4: x = v;  y = -1; z = -u; break;
    default: x = v; y = u;  z = -1; break;
  }
  const double n = std::sqrt(x * x + y * y + z * z);
  out[0] = x / n;
  out[1] = y / n;
  out[2] = z / n;
}

uint64_t from_face_ij(uint64_t face, uint64_t i, uint64_t j) {
  uint64_t n = face << 60;
  int64_t bits = static_cast<int64_t>(face & SWAP_MASK);
  const uint64_t mask = (1 << LOOKUP_BITS) - 1;
  for (int k = 7; k >= 0; --k) {
    const int64_t ki =
        static_cast<int64_t>((i >> (k * LOOKUP_BITS)) & mask);
    const int64_t kj =
        static_cast<int64_t>((j >> (k * LOOKUP_BITS)) & mask);
    bits = lookup_pos[bits + (ki << (LOOKUP_BITS + 2)) + (kj << 2)];
    n |= (static_cast<uint64_t>(bits) >> 2) << (k * 2 * LOOKUP_BITS);
    bits &= (SWAP_MASK | INVERT_MASK);
  }
  return n * 2 + 1;
}

inline uint64_t cell_parent(uint64_t cid, int level) {
  const uint64_t lsb = 1ULL << (2 * (MAX_LEVEL - level));
  return (cid & (~lsb + 1)) | lsb;
}

// Leaf (face, i, j) of a unit point (cell_id_from_point, s2cell.py:246-257).
inline void point_to_face_ij(const double* p, int* face, int64_t* i,
                             int64_t* j) {
  double u, v;
  xyz_to_face_uv(p, face, &u, &v);
  const double s = uv_to_st(u);
  const double t = uv_to_st(v);
  const int64_t lim = (1LL << MAX_LEVEL) - 1;
  int64_t ii = static_cast<int64_t>(
      std::floor(s * static_cast<double>(1LL << MAX_LEVEL)));
  int64_t jj = static_cast<int64_t>(
      std::floor(t * static_cast<double>(1LL << MAX_LEVEL)));
  *i = std::min(std::max(ii, static_cast<int64_t>(0)), lim);
  *j = std::min(std::max(jj, static_cast<int64_t>(0)), lim);
}

// ---------------------------------------------------------------------------
// Spherical predicates (covering.py:66-161) — same operation order
// ---------------------------------------------------------------------------

inline void cross3(const double* a, const double* b, double* out) {
  out[0] = a[1] * b[2] - a[2] * b[1];
  out[1] = a[2] * b[0] - a[0] * b[2];
  out[2] = a[0] * b[1] - a[1] * b[0];
}

inline double dot3(const double* a, const double* b) {
  return a[0] * b[0] + a[1] * b[1] + a[2] * b[2];
}

inline int sign3(const double* a, const double* b, const double* c) {
  double x[3];
  cross3(a, b, x);
  const double d = dot3(x, c);
  if (d > 0) return 1;
  if (d < 0) return -1;
  return 0;
}

inline bool ordered_ccw(const double* a, const double* b, const double* c,
                        const double* o) {
  int k = 0;
  if (sign3(b, o, a) >= 0) k += 1;
  if (sign3(c, o, b) >= 0) k += 1;
  if (sign3(a, o, c) > 0) k += 1;
  return k >= 2;
}

inline bool same3(const double* p, const double* q) {
  return p[0] == q[0] && p[1] == q[1] && p[2] == q[2];
}

bool edges_cross(const double* a, const double* b, const double* c,
                 const double* d) {
  double n1[3], n2[3], x[3];
  cross3(a, b, n1);
  cross3(c, d, n2);
  cross3(n1, n2, x);
  const double norm = std::sqrt(dot3(x, x));
  if (norm < 1e-30) return false;  // coplanar / degenerate
  x[0] /= norm;
  x[1] /= norm;
  x[2] /= norm;
  const double dab = dot3(a, b);
  const double dcd = dot3(c, d);
  for (int si = 0; si < 2; ++si) {
    const double s = si == 0 ? 1.0 : -1.0;
    const double p[3] = {s * x[0], s * x[1], s * x[2]};
    if (dot3(p, a) > dab && dot3(p, b) > dab && dot3(p, c) > dcd &&
        dot3(p, d) > dcd) {
      return true;
    }
  }
  return false;
}

inline void ortho(const double* p, double* out) {
  const double ap[3] = {std::fabs(p[0]), std::fabs(p[1]), std::fabs(p[2])};
  int k = 0;  // np.argmin: first minimum
  if (ap[1] < ap[k]) k = 1;
  if (ap[2] < ap[k]) k = 2;
  double axis[3] = {0.0, 0.0, 0.0};
  axis[k] = 1.0;
  double o[3];
  cross3(p, axis, o);
  const double n = std::sqrt(dot3(o, o));
  out[0] = o[0] / n;
  out[1] = o[1] / n;
  out[2] = o[2] / n;
}

bool vertex_crossing(const double* a, const double* b, const double* c,
                     const double* d) {
  if (same3(a, b) || same3(c, d)) return false;
  double ob[3];
  if (same3(a, d)) {
    ortho(a, ob);
    return ordered_ccw(ob, c, b, a);
  }
  if (same3(b, c)) {
    ortho(b, ob);
    return ordered_ccw(ob, d, a, b);
  }
  if (same3(a, c)) {
    ortho(a, ob);
    return ordered_ccw(ob, d, b, a);
  }
  if (same3(b, d)) {
    ortho(b, ob);
    return ordered_ccw(ob, c, a, b);
  }
  return false;
}

inline bool edge_or_vertex_crossing(const double* a, const double* b,
                                    const double* c, const double* d) {
  if (same3(a, c) || same3(a, d) || same3(b, c) || same3(b, d)) {
    return vertex_crossing(a, b, c, d);
  }
  return edges_cross(a, b, c, d);
}

// Loop containment via crossing parity from the fixed origin
// (covering.py Loop, :164-217).
struct NativeLoop {
  const double* v;  // (n, 3)
  int n;
  double origin[3];
  bool origin_inside;

  NativeLoop(const double* vertices, int count) : v(vertices), n(count) {
    const double raw[3] = {-0.0099994664, 0.0025924542, 0.9999466};
    const double nn = std::sqrt(dot3(raw, raw));
    origin[0] = raw[0] / nn;
    origin[1] = raw[1] / nn;
    origin[2] = raw[2] / nn;
    if (n >= 3) {
      double o1[3];
      ortho(v + 3, o1);
      const bool v1_inside = ordered_ccw(o1, v + 0, v + 6, v + 3);
      const bool contains_v1 = crossing_parity(v + 3) == 1;
      origin_inside = v1_inside != contains_v1;
    } else {
      origin_inside = false;
    }
  }

  int crossing_parity(const double* p) const {
    int crossings = 0;
    for (int k = 0; k < n; ++k) {
      const double* a = v + 3 * k;
      const double* b = v + 3 * ((k + 1) % n);
      if (edge_or_vertex_crossing(origin, p, a, b)) crossings ^= 1;
    }
    return crossings;
  }

  bool contains(const double* p) const {
    return origin_inside != (crossing_parity(p) == 1);
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// Entry point
// ---------------------------------------------------------------------------

extern "C" {

// Level-13 covering of the loop via the single-face rect fast path.
//   v_xyz:    n x 3 unit vertices (float64, row-major)
//   area_ok:  1 if loop_area_km2(loop) <= MAX_AREA_KM2 (computed by the
//             caller in Python — keeps the transcendental area formula
//             out of the parity surface)
//   out:      uint64 buffer with capacity out_cap
// Returns: >= 0 cell count (sorted ascending); -2 covering exceeds
// MAX_COVERING_CELLS (AreaTooLarge); -3 caller must take the Python
// BFS fallback (multi-face / face-edge margin / oversized rect /
// area gate failed).
int64_t dss_loop_covering(const double* v_xyz, int32_t n, int32_t area_ok,
                          uint64_t* out, int64_t out_cap) {
  if (n < 1) return -3;

  // vertex leaf ij + level-13 cells (covering.py:503-533)
  std::vector<int64_t> vi(n), vj(n);
  std::vector<uint64_t> vertex_cells(n);
  int face0 = -1;
  const int64_t step = 1LL << (MAX_LEVEL - DAR_LEVEL);
  for (int k = 0; k < n; ++k) {
    int f;
    point_to_face_ij(v_xyz + 3 * k, &f, &vi[k], &vj[k]);
    if (k == 0) {
      face0 = f;
    } else if (f != face0) {
      return -3;  // multi-face: BFS fallback
    }
    vertex_cells[k] =
        cell_parent(from_face_ij(f, vi[k], vj[k]), DAR_LEVEL);
  }
  if (!area_ok) return -3;

  // ij bounding rect at level-13 granularity, +1-cell margin
  const int64_t lim = 1LL << MAX_LEVEL;
  int64_t imin_c = vi[0] & ~(step - 1), imax_c = imin_c;
  int64_t jmin_c = vj[0] & ~(step - 1), jmax_c = jmin_c;
  for (int k = 1; k < n; ++k) {
    const int64_t il = vi[k] & ~(step - 1);
    const int64_t jl = vj[k] & ~(step - 1);
    imin_c = std::min(imin_c, il);
    imax_c = std::max(imax_c, il);
    jmin_c = std::min(jmin_c, jl);
    jmax_c = std::max(jmax_c, jl);
  }
  const int64_t imin = std::max(imin_c - step, static_cast<int64_t>(0));
  const int64_t imax = std::min(imax_c + step, lim - step);
  const int64_t jmin = std::max(jmin_c - step, static_cast<int64_t>(0));
  const int64_t jmax = std::min(jmax_c + step, lim - step);
  const int64_t ni = (imax - imin) / step + 1;
  const int64_t nj = (jmax - jmin) / step + 1;
  if (!(ni * nj <= RECT_MAX_CELLS && imin > 0 && jmin > 0 &&
        imax < lim - step && jmax < lim - step)) {
    return -3;  // face-edge / oversized rect: BFS fallback
  }

  NativeLoop loop(v_xyz, n);

  // loop-vertex (face, u, v) once (predicate (c), covering.py:383-392)
  std::vector<int> pf(n);
  std::vector<double> pu(n), pv(n);
  for (int k = 0; k < n; ++k) {
    xyz_to_face_uv(v_xyz + 3 * k, &pf[k], &pu[k], &pv[k]);
  }

  const double scale = 1.0 / static_cast<double>(1LL << MAX_LEVEL);
  std::vector<uint64_t> hits;
  for (int64_t ii = imin; ii <= imax; ii += step) {
    const double u_lo = st_to_uv(static_cast<double>(ii) * scale);
    const double u_hi = st_to_uv(static_cast<double>(ii + step) * scale);
    for (int64_t jj = jmin; jj <= jmax; jj += step) {
      const uint64_t cid = cell_parent(
          from_face_ij(face0, ii + step / 2, jj + step / 2), DAR_LEVEL);
      const double v_lo = st_to_uv(static_cast<double>(jj) * scale);
      const double v_hi = st_to_uv(static_cast<double>(jj + step) * scale);

      // corners in CCW order (s2cell.py:290-296)
      double corners[4][3];
      const double us[4] = {u_lo, u_hi, u_hi, u_lo};
      const double vs[4] = {v_lo, v_lo, v_hi, v_hi};
      for (int c = 0; c < 4; ++c) {
        face_uv_to_xyz(face0, us[c], vs[c], corners[c]);
      }

      bool hit = false;
      // (a) any corner inside the loop
      for (int c = 0; c < 4 && !hit; ++c) {
        if (loop.contains(corners[c])) hit = true;
      }
      // (b) cell is a loop-vertex cell
      if (!hit) {
        for (int k = 0; k < n; ++k) {
          if (vertex_cells[k] == cid) {
            hit = true;
            break;
          }
        }
      }
      // (c) a loop vertex projects inside the cell's face-uv rect
      if (!hit) {
        for (int k = 0; k < n; ++k) {
          if (pf[k] == face0 && u_lo <= pu[k] && pu[k] <= u_hi &&
              v_lo <= pv[k] && pv[k] <= v_hi) {
            hit = true;
            break;
          }
        }
      }
      // (d) any loop edge crosses any cell edge
      if (!hit) {
        for (int c = 0; c < 4 && !hit; ++c) {
          const double* ca = corners[c];
          const double* cb = corners[(c + 1) % 4];
          for (int k = 0; k < n; ++k) {
            const double* ea = v_xyz + 3 * k;
            const double* eb = v_xyz + 3 * ((k + 1) % n);
            if (edges_cross(ca, cb, ea, eb)) {
              hit = true;
              break;
            }
          }
        }
      }
      if (hit) hits.push_back(cid);
    }
  }

  std::sort(hits.begin(), hits.end());
  const int64_t count = static_cast<int64_t>(hits.size());
  if (count > MAX_COVERING_CELLS) return -2;
  if (count > out_cap) return -3;  // caller buffer too small (shouldn't happen)
  std::copy(hits.begin(), hits.end(), out);
  return count;
}

// Full covering_from_loop_points fast path (covering.py:596-613):
// signed-area + winding-retry + area-gate + rect covering in one call.
// Vertices arrive as unit xyz (Python computes latlng->xyz so numpy's
// SIMD trig stays the parity reference for vertex positions).
//
//   area_out: loop_area_km2 of the (possibly reversed) loop — the
//             reference's quirk formula (area_sr * 510072000) / 4 * pi
// Returns: >= 0 cell count; -1 degenerate (area <= 0: caller takes the
// polyline path); -2 AreaTooLarge (either the area gate after the
// winding retry, or the covering cell cap); -3 caller must run the
// full Python path (multi-face / face-edge / oversized rect / buffer).
int64_t dss_points_covering(const double* v_xyz_in, int32_t n,
                            double max_area_km2, double* area_out,
                            uint64_t* out, int64_t out_cap) {
  if (n < 1) return -3;
  // signed spherical area via the vertex-0 triangle fan
  // (covering.py Loop.signed_area:219-230; same op order)
  std::vector<double> v(v_xyz_in, v_xyz_in + 3 * n);
  auto signed_area = [&](const double* vv) {
    if (n < 3) return 0.0;
    double total = 0.0;
    const double* v0 = vv;
    for (int k = 1; k < n - 1; ++k) {
      const double* b = vv + 3 * k;
      const double* c = vv + 3 * (k + 1);
      double x[3];
      cross3(v0, b, x);
      const double triple = dot3(x, c);
      const double denom =
          1.0 + dot3(v0, b) + dot3(b, c) + dot3(c, v0);
      total += 2.0 * std::atan2(triple, denom);
    }
    return total;
  };
  constexpr double EARTH_AREA_KM2 = 510072000.0;
  const double MAX_AREA_KM2 = max_area_km2;  // single source: covering.py
  const double PI = 3.14159265358979323846;
  auto area_km2 = [&](const double* vv) {
    double s = signed_area(vv);
    const double interior = s >= 0 ? s : 4.0 * PI + s;
    return (interior * EARTH_AREA_KM2) / 4.0 * PI;
  };
  double a = area_km2(v.data());
  if (a > MAX_AREA_KM2) {
    // winding retry: reverse vertex order (covering.py:602-605)
    std::vector<double> rev(3 * n);
    for (int k = 0; k < n; ++k) {
      rev[3 * k] = v[3 * (n - 1 - k)];
      rev[3 * k + 1] = v[3 * (n - 1 - k) + 1];
      rev[3 * k + 2] = v[3 * (n - 1 - k) + 2];
    }
    v.swap(rev);
    a = area_km2(v.data());
  }
  *area_out = a;
  if (a > MAX_AREA_KM2) return -2;
  if (a <= 0) return -1;  // degenerate: polyline fallback
  return dss_loop_covering(v.data(), n, 1, out, out_cap);
}

}  // extern "C"
