"""ShardedDar refresh: tail a durable log into serving multi-chip
read replicas — one per entity class.

SURVEY §7 step 7 (second half): writes land in the single-chip store +
WAL (or the region log in region mode); this replica tails that log and
periodically folds each entity class (SCD operations, RID ISAs, RID
subscriptions, SCD subscriptions) into a fresh `ShardedDar` snapshot on
the device mesh, swapping it in atomically for readers — the same
source-of-truth/read-replica split the reference gets from CRDB ranges
(implementation_details.md:11-42, where range sharding covers EVERY
table).

Consistency: readers grab ONE class snapshot reference per query, so a
query always runs against a complete snapshot — concurrent refreshes
are invisible until their atomic swap.  Staleness is bounded by the
poll interval + rebuild time and exposed via stats.

Refreshes ship TIER DELTAS, not full tables (mirroring the DarTable
tier stack, dss_tpu.dar.tiers): each class keeps a large, rarely
rebuilt BASE ShardedDar plus a small DELTA ShardedDar holding the
records written since the base was built, with a shadow set hiding
base copies superseded or deleted since.  A routine refresh rebuilds
only the delta dar — O(churn), not O(table) — and a major rebuild
(full repack) runs only when the churn ratio crosses the same
DSS_TIER_RATIO policy the DarTable uses.

Sources:
  - `wal_path`: tail a standalone server's WriteAheadLog file
    (incremental: remembers the byte offset, only consumes whole
    lines, tolerates a torn tail write until the next poll);
  - `region_client`: fetch entries from a region log server.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from dss_tpu.dar import codec
from dss_tpu.dar import oracle
from dss_tpu.dar import tiers as tiersmod
from dss_tpu.dar.oracle import Record
from dss_tpu.geo import s2cell
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO
from dss_tpu.parallel.sharded import (
    ShardedDar,
    imbalance_factor,
    shard_of_keys,
    weighted_boundaries,
)

log = logging.getLogger("dss.replica")


def env_rebalance_ratio() -> float:
    """DSS_SHARD_REBALANCE_RATIO: the hysteresis threshold — boundary
    moves happen only when predicted per-shard load imbalance
    (max/mean) exceeds this.  <= 1 disables rebalancing (static
    equal-count placement, the pre-r07 behavior)."""
    try:
        return float(os.environ.get("DSS_SHARD_REBALANCE_RATIO", 1.5))
    except ValueError:
        raise ValueError(
            "DSS_SHARD_REBALANCE_RATIO="
            f"{os.environ['DSS_SHARD_REBALANCE_RATIO']!r} is not a float"
        )


def env_move_interval_s() -> float:
    """DSS_SHARD_MOVE_INTERVAL_S: the move-rate cap — at most one
    boundary move per interval, so rebalance-forced major folds can
    never starve serving."""
    try:
        return float(os.environ.get("DSS_SHARD_MOVE_INTERVAL_S", 5.0))
    except ValueError:
        raise ValueError(
            "DSS_SHARD_MOVE_INTERVAL_S="
            f"{os.environ['DSS_SHARD_MOVE_INTERVAL_S']!r} is not a float"
        )

# entity classes the replica serves (replica class name -> WAL prefix)
CLASSES = ("ops", "isas", "rid_subs", "scd_subs", "constraints")


class _ClsSnap(NamedTuple):
    """One class's published snapshot: base + delta tier dars.  A base
    id in `shadow` is superseded (its current version lives in the
    delta dar) or deleted — queries drop it, so the newest tier wins."""

    base: Optional[ShardedDar]
    base_ids: List[str]
    shadow: frozenset  # base entity_ids hidden by newer state
    delta: Optional[ShardedDar]
    delta_ids: List[str]

    @property
    def live_records(self) -> int:
        return len(self.base_ids) - len(self.shadow) + len(self.delta_ids)


class _WalTail:
    """Incremental reader of a WriteAheadLog file (JSON lines).
    The first record is checked against the supported log format
    (the same boot gate as WriteAheadLog.replay)."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._checked_head = False

    @property
    def position(self) -> int:
        """Consumed byte offset — the multihost refresh-cut currency
        (every process tails the same log; identical offsets mean
        identical record prefixes)."""
        return self._offset

    def at_end(self) -> bool:
        """True when everything durably appended has been consumed —
        the read-your-writes gate for mesh offload (a committed write
        reaches the WAL before its HTTP response)."""
        try:
            return os.path.getsize(self.path) <= self._offset
        except OSError:
            return not os.path.exists(self.path)

    def poll(self, limit: Optional[int] = None) -> List[dict]:
        """`limit` stops consumption at that byte offset (a follower
        tailing to the leader's broadcast cut, never past it)."""
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, "r", encoding="utf-8") as fh:
            fh.seek(self._offset)
            while True:
                pos = fh.tell()
                if limit is not None and pos >= limit:
                    break
                line = fh.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    # torn tail write: re-read from here next poll
                    fh.seek(pos)
                    break
                line = line.strip()
                if not line:
                    self._offset = fh.tell()
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # torn write that still got a newline: stop here
                    # and retry next poll
                    fh.seek(pos)
                    break
                if not self._checked_head and pos == 0:
                    from dss_tpu.dar import wal as _walmod

                    _walmod.check_format_record(rec, self.path)
                    self._checked_head = True
                    # the head is log metadata, not a data record:
                    # validated here, never surfaced to the applier
                    if rec.get("t") == _walmod.FORMAT_RECORD_TYPE:
                        self._offset = fh.tell()
                        continue
                out.append(rec)
                self._offset = fh.tell()
        return out


class _RegionTail:
    """Incremental reader of a region log (batch entries)."""

    def __init__(self, client):
        self.client = client
        self._applied = 0
        self.errors = 0  # consecutive fetch failures (operability)
        self.caught_up = False  # reached head at the last poll

    @property
    def position(self) -> int:
        """Next log entry index to apply — the multihost refresh-cut
        currency in region mode."""
        return self._applied

    def at_end(self) -> bool:
        """Best-effort: head reached at the LAST poll.  Region-mode
        reads are bounded-stale by design (non-writing instances serve
        tail-poll state), so mesh offload matches that contract rather
        than strict read-your-writes."""
        return self.caught_up

    def poll(self, limit: Optional[int] = None) -> List[dict]:
        from dss_tpu.region.client import (
            EpochChanged,
            RegionError,
            SnapshotRequired,
        )

        out = []
        try:
            while True:
                try:
                    entries, head = self.client.fetch(self._applied)
                    self.errors = 0
                except SnapshotRequired:
                    snap = self.client.get_snapshot()
                    if snap is None:
                        return out
                    idx, state = snap
                    # the snapshot carries full docs: replace local
                    # state wholesale, then resume tailing after it
                    out.append({"t": "__replica_reset__", "state": state})
                    self._applied = idx
                    continue
                except EpochChanged:
                    # the log server rebooted and may have regressed
                    # (lost unsynced acked entries, or an older WAL
                    # restored): our incrementally-applied state may
                    # contain entries the reborn log never will —
                    # rebuild wholesale from the log's truth instead
                    # of silently skipping new entries
                    log.warning(
                        "replica: region log epoch changed; rebuilding"
                    )
                    # fetch the rebuild material FIRST: adopting the
                    # epoch before a failed get_snapshot would silence
                    # the regression forever (no dirty flag here — the
                    # next poll must re-raise EpochChanged until the
                    # reset actually happens)
                    snap = self.client.get_snapshot()
                    self.client.adopt_epoch()
                    if snap is not None:
                        idx, state = snap
                        out.append(
                            {"t": "__replica_reset__", "state": state}
                        )
                        self._applied = idx
                    else:
                        out.append(
                            {"t": "__replica_reset__", "state": {}}
                        )
                        self._applied = 0
                    continue
                for idx, recs in entries:
                    if idx >= self._applied and (
                        limit is None or idx < limit
                    ):
                        out.extend(recs)
                        self._applied = idx + 1
                if limit is not None and self._applied >= limit:
                    return out
                if self._applied >= head:
                    self.caught_up = True
                    return out
                self.caught_up = False
        except RegionError as e:
            # transient (next poll retries) — but a replica cut off
            # from the region must be VISIBLY stale, not silently so
            self.errors += 1
            self.caught_up = False
            log.warning(
                "replica region tail failed (%d consecutive): %s",
                self.errors, e,
            )
            return out


def _keys_of(cells) -> np.ndarray:
    return np.unique(
        s2cell.cell_to_dar_key(np.asarray(cells, dtype=np.uint64))
    ).astype(np.int32)


class ShardedReplica:
    """Multi-chip read replica of EVERY entity class on a ("dp", "sp")
    mesh, refreshed from a WAL or region-log tail."""

    def __init__(
        self,
        mesh,
        *,
        wal_path: Optional[str] = None,
        region_client=None,
        max_results: int = 512,
        shard_results: Optional[int] = None,
        warm_batches=(1,),
        tier_ratio: Optional[float] = None,  # None = DSS_TIER_RATIO env
        load: Optional[tiersmod.RangeLoad] = None,
        rebalance_ratio: Optional[float] = None,  # None = env
        move_interval_s: Optional[float] = None,  # None = env
        capacity_weights=None,  # per-sp-shard host capacity vector
        #   (weighted_boundaries member_capacity; assembled from the
        #   member hosts' autotune profiles' capacity_weight scalars);
        #   None = homogeneous members, the historical split
    ):
        if (wal_path is None) == (region_client is None):
            raise ValueError("exactly one of wal_path / region_client")
        self.mesh = mesh
        self.max_results = max_results
        if shard_results is None:
            # autotune-profile seam: DSS_SHARD_RESULTS carries the
            # measured per-shard result capacity base (plan/autotune
            # measure_hit_concentration); unset keeps the legacy
            # max_results-sized default
            raw = os.environ.get("DSS_SHARD_RESULTS", "")
            shard_results = int(raw) if raw else None
        self.shard_results = shard_results
        # boundary-aware autotuned capacity (leader-computed at each
        # boundary move from the post-rebalance predicted per-shard
        # load, broadcast with the move): what builds actually use.
        # None = no move yet, the configured base stands.
        self.shard_results_effective: Optional[int] = None
        if capacity_weights is None:
            self.capacity_weights = None
        else:
            cw = np.asarray(capacity_weights, np.float64).ravel()
            # reject bad vectors HERE, not at some later fold: a zero
            # entry would otherwise surface as inf imbalance + a
            # ValueError from inside the leader's serving sync path
            if not np.all(np.isfinite(cw)) or not np.all(cw > 0):
                raise ValueError(
                    "capacity_weights entries must be finite and > 0"
                )
            self.capacity_weights = cw
        self._tier_ratio = (
            tiersmod.env_policy().ratio
            if tier_ratio is None
            else float(tier_ratio)
        )
        # -- skew-aware placement state ---------------------------------------
        # measured query load per key range; server mode swaps in the
        # store's shared instance (use_load) so coalescer-served
        # traffic drives the same map the splitter consumes
        self.load = load if load is not None else tiersmod.RangeLoad()
        self.rebalance_ratio = (
            env_rebalance_ratio()
            if rebalance_ratio is None
            else float(rebalance_ratio)
        )
        self.move_interval_s = (
            env_move_interval_s()
            if move_interval_s is None
            else float(move_interval_s)
        )
        # the published boundary map (None = equal-count split) and
        # its generation — the currency a multihost leader broadcasts
        # with the fold cut so every process splits identically
        self.boundaries: Optional[np.ndarray] = None
        # boundary_gen is the LOCKSTEP currency (compared against the
        # leader's broadcast bgen; reset to 0 by a reform on every
        # process so joiners and incumbents agree); boundary_moves is
        # the monotonic operator gauge and never resets
        self.boundary_gen = 0
        self.boundary_moves = 0
        self.moved_bytes = 0
        self._imbalance = 1.0  # predicted under current boundaries
        # -inf so the FIRST justified move is never rate-capped (a
        # fresh boot's monotonic clock can be younger than the cap)
        self._last_move = float("-inf")
        self._last_decay = float("-inf")
        self._last_plan = float("-inf")
        self._force_major: Dict[str, bool] = {c: False for c in CLASSES}
        # per-shard measured hits absorbed from retired dars (the live
        # dars' counters reset on every rebuild swap)
        self._shard_hits_total = np.zeros(
            mesh.shape["sp"], np.int64
        )
        # batch sizes to warm per rebuild: each maps to a pow2 jit
        # bucket; mesh-offload consumers add their min_batch so the
        # first oversized batch after a swap doesn't stall on a compile
        self.warm_batches = tuple(warm_batches)
        self._tail = (
            _WalTail(wal_path) if wal_path else _RegionTail(region_client)
        )
        self._records: Dict[str, Dict[str, Record]] = {
            c: {} for c in CLASSES
        }
        # tier bookkeeping per class: ids inside the published base
        # dar (membership only — the records themselves stay in
        # self._records), records newer than it, and base ids to hide
        self._base: Dict[str, set] = {c: set() for c in CLASSES}
        self._delta: Dict[str, Dict[str, Record]] = {c: {} for c in CLASSES}
        self._shadow: Dict[str, set] = {c: set() for c in CLASSES}
        self._owners: Dict[str, int] = {}
        self._dirty = {c: False for c in CLASSES}
        self._gen = {c: 0 for c in CLASSES}  # tail-applied write gen
        self._mu = threading.Lock()  # guards records + tail + rebuild
        # serializes whole refresh() runs: publish order must match
        # build order (the warmup happens outside _mu, so without this
        # a slower older build could overwrite a newer snapshot)
        self._refresh_mu = threading.Lock()
        self._snapshots: Dict[str, Optional[_ClsSnap]] = {
            c: None for c in CLASSES
        }
        self._applied_records = 0
        self._apply_errors = 0
        # host->device bytes materialized by snapshot builds (per-host
        # refresh traffic: on a multi-host mesh this is what each
        # process ships to its addressable shards per refresh)
        self.device_bytes_built = 0
        self._rebuilds = 0
        self._delta_refreshes = 0
        self._major_rebuilds = 0
        self._warm_ms_total = 0.0  # publish-gating warm time (compile
        #                            + layout commit per rebuild)
        self._last_fresh = 0.0  # monotonic time of last caught-up sync
        # -- demand-paced refresh ----------------------------------------------
        # The dar rebuild + publish-gating warm is the expensive half
        # of a sync tick; on a small host it can eat a third of total
        # serving capacity keeping a mesh replica fresh that no query
        # is using.  The background loop therefore always applies the
        # cheap tail (writes keep accumulating), but only rebuilds
        # while a mesh-shaped batch has consulted fresh() within the
        # pace window (or during the boot grace, so the first demanded
        # query finds a warm replica).  An idle replica goes stale by
        # construction, fresh() then steers the planner local, and the
        # SAME fresh() probe is the demand signal that resumes
        # rebuilding — one or two ticks later the mesh route is warm
        # again.  Pace <= 0 restores the historical always-rebuild
        # loop (multihost lockstep never runs this loop and is
        # unaffected).
        raw_pace = os.environ.get("DSS_REPLICA_DEMAND_PACE_S", "")
        self.demand_pace_s = float(raw_pace) if raw_pace else 10.0
        self._demand_last = 0.0
        self._started_at = 0.0
        self._refresh_skips = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingest ---------------------------------------------------------------

    def _intern(self, owner: str) -> int:
        return self._owners.setdefault(owner, len(self._owners))

    def _rec_from_op_doc(self, doc: dict) -> Record:
        op = codec.doc_to_op(doc)
        from dss_tpu.clock import to_nanos

        return Record(
            entity_id=op.id,
            keys=_keys_of(op.cells),
            alt_lo=(
                -np.inf if op.altitude_lower is None else float(op.altitude_lower)
            ),
            alt_hi=(
                np.inf if op.altitude_upper is None else float(op.altitude_upper)
            ),
            t_start=to_nanos(op.start_time),
            t_end=to_nanos(op.end_time),
            owner_id=self._intern(op.owner),
        )

    def _rec_from_entity(self, ent) -> Record:
        """ISA / RID sub / SCD sub share the cells + altitude_lo/hi +
        start/end field shape."""
        from dss_tpu.clock import to_nanos

        return Record(
            entity_id=ent.id,
            keys=_keys_of(ent.cells),
            alt_lo=(
                -np.inf if ent.altitude_lo is None else float(ent.altitude_lo)
            ),
            alt_hi=(
                np.inf if ent.altitude_hi is None else float(ent.altitude_hi)
            ),
            t_start=(
                NO_TIME_LO if ent.start_time is None
                else to_nanos(ent.start_time)
            ),
            t_end=(
                NO_TIME_HI if ent.end_time is None
                else to_nanos(ent.end_time)
            ),
            owner_id=self._intern(ent.owner),
        )

    def _put(self, cls: str, rec: Record) -> None:
        self._records[cls][rec.entity_id] = rec
        if rec.entity_id in self._base[cls]:
            self._shadow[cls].add(rec.entity_id)  # newer than base
        self._delta[cls][rec.entity_id] = rec
        self._dirty[cls] = True
        # per-class write generation: tail application IS the replica's
        # write path, so the freshness surface (/status, stats) can
        # compare replica generations against the primary's cell-clock
        # generations when verifying fence behaviour
        self._gen[cls] += 1

    def _del(self, cls: str, eid: str) -> None:
        if self._records[cls].pop(eid, None) is not None:
            self._delta[cls].pop(eid, None)
            if eid in self._base[cls]:
                self._shadow[cls].add(eid)
            self._dirty[cls] = True
            self._gen[cls] += 1

    def _apply_locked(self, rec: dict) -> None:
        t = rec.get("t", "")
        if t == "__replica_reset__":
            # build the replacement off to the side and swap only once
            # every doc parsed: a corrupt doc mid-snapshot must not
            # leave truncated state serving as complete
            state = rec["state"]
            fresh: Dict[str, Dict[str, Record]] = {c: {} for c in CLASSES}
            for d in state.get("scd", {}).get("ops", []):
                r = self._rec_from_op_doc(d)
                fresh["ops"][r.entity_id] = r
            for d in state.get("scd", {}).get("subs", []):
                r = self._rec_from_entity(codec.doc_to_scd_sub(d))
                fresh["scd_subs"][r.entity_id] = r
            for d in state.get("rid", {}).get("isas", []):
                r = self._rec_from_entity(codec.doc_to_isa(d))
                fresh["isas"][r.entity_id] = r
            for d in state.get("rid", {}).get("subs", []):
                r = self._rec_from_entity(codec.doc_to_rid_sub(d))
                fresh["rid_subs"][r.entity_id] = r
            # absent on pre-constraint snapshots (rolling upgrade)
            for d in state.get("scd", {}).get("constraints", []):
                r = self._rec_from_op_doc(d)
                fresh["constraints"][r.entity_id] = r
            self._records = fresh
            for c in CLASSES:
                # wholesale replacement invalidates the tier split: the
                # next refresh of each class is a major rebuild
                self._base[c] = set()
                self._delta[c] = {}
                self._shadow[c] = set()
                self._dirty[c] = True
                self._gen[c] += 1
        elif t == "scd_op_put":
            self._put("ops", self._rec_from_op_doc(rec["doc"]))
        elif t == "scd_op_del":
            self._del("ops", rec["id"])
        elif t == "isa_put":
            self._put(
                "isas", self._rec_from_entity(codec.doc_to_isa(rec["doc"]))
            )
        elif t == "isa_del":
            self._del("isas", rec["id"])
        elif t == "rid_sub_put":
            self._put(
                "rid_subs",
                self._rec_from_entity(codec.doc_to_rid_sub(rec["doc"])),
            )
        elif t == "rid_sub_del":
            self._del("rid_subs", rec["id"])
        elif t == "scd_sub_put":
            self._put(
                "scd_subs",
                self._rec_from_entity(codec.doc_to_scd_sub(rec["doc"])),
            )
        elif t == "scd_sub_del":
            self._del("scd_subs", rec["id"])
        elif t == "scd_cst_put":
            # constraint docs share the op doc's spatial field shape
            # (altitude_lower/upper, start/end, cells)
            self._put("constraints", self._rec_from_op_doc(rec["doc"]))
        elif t == "scd_cst_del":
            self._del("constraints", rec["id"])
        # rid_sub_bump / scd_sub_bump only touch notification indexes,
        # which the spatial replica does not serve
        self._applied_records += 1

    def tail_position(self) -> int:
        """The tail's consumed position (WAL byte offset / region
        entry index) — the multihost refresh-cut currency."""
        return self._tail.position

    def state_fingerprint(self) -> dict:
        """Cheap per-class divergence detector for lockstep folds:
        processes that consumed the same log prefix MUST agree on
        these counts before issuing the fold's collectives (a
        divergent fold would build different array shapes and wedge or
        corrupt the mesh)."""
        with self._mu:
            return {
                "applied": self._applied_records,
                "apply_errors": self._apply_errors,
                "classes": {
                    c: [
                        len(self._records[c]),
                        len(self._delta[c]),
                        len(self._shadow[c]),
                        len(self._base[c]),
                    ]
                    for c in CLASSES
                },
            }

    def poll_once(self, limit: Optional[int] = None) -> int:
        """Ingest any new log records; -> number applied.  One record
        that fails to apply (version skew, corrupt doc) is skipped and
        counted — it must not drop the rest of its batch (the tail
        cursor has already advanced past it)."""
        with self._mu:
            recs = self._tail.poll(limit=limit)
            for rec in recs:
                try:
                    self._apply_locked(rec)
                except Exception:  # noqa: BLE001 — isolate bad records
                    self._apply_errors += 1
                    log.exception(
                        "replica failed to apply record %r; skipped",
                        rec.get("t"),
                    )
            return len(recs)

    # -- skew-aware placement -------------------------------------------------

    def use_load(self, load: tiersmod.RangeLoad) -> None:
        """Adopt a shared RangeLoad (the store's, in server mode) so
        coalescer-served traffic and replica-served traffic accumulate
        into ONE map."""
        self.load = load

    def note_query_load(self, keys, work: float) -> None:
        self.load.record(keys, work)

    def _all_posting_keys(self) -> np.ndarray:
        """Sorted concatenation of every class's record keys — the
        postings population the splitter plans over (classes share one
        S2 key space and one boundary map)."""
        with self._mu:
            parts = [
                r.keys
                for recs in self._records.values()
                for r in recs.values()
            ]
        if not parts:
            return np.zeros(0, np.int32)
        return np.sort(np.concatenate(parts).astype(np.int32))

    def _predicted_shard_loads(
        self, keys: np.ndarray, w: np.ndarray, boundaries
    ) -> np.ndarray:
        n_sp = self.mesh.shape["sp"]
        loads = np.zeros(n_sp, np.float64)
        if not len(keys):
            return loads
        if boundaries is None:
            # equal-count split: contiguous index ranges
            ps = max((len(keys) + n_sp - 1) // n_sp, 8)
            for i in range(n_sp):
                loads[i] = w[i * ps : (i + 1) * ps].sum()
        else:
            np.add.at(loads, shard_of_keys(keys, boundaries, n_sp), w)
        return loads

    def plan_rebalance(self, now: Optional[float] = None) -> bool:
        """Evaluate the measured load map against the current split
        and move the boundaries when the hot spot justifies it.
        Leader-side only (multihost followers APPLY broadcast
        boundaries, never plan).  -> True when boundaries moved.

        Hysteresis: no move unless predicted imbalance (max/mean
        per-shard load) exceeds `rebalance_ratio`.  Move-rate cap: at
        most one move per `move_interval_s`.  A move forces a major
        rebuild of every class at the NEXT fold — the cost an operator
        trades for spreading the hot range."""
        t = time.monotonic() if now is None else now
        # the whole planning scan (concat+sort of every class's keys)
        # is rate-limited to the move cadence: a 0.5s refresh loop
        # must not pay an O(total postings) sort per tick just to
        # re-learn that the split is still balanced
        if t - max(self._last_plan, self._last_move) < self.move_interval_s:
            return False
        self._last_plan = t
        # decay runs even with rebalancing disabled: the load map (and
        # its gauges) must not grow without bound under a static split
        if t - self._last_decay >= self.move_interval_s:
            self.load.decay()
            self._last_decay = t
        if self.rebalance_ratio <= 1.0:
            return False
        if self.load.total() <= 0:
            self._imbalance = 1.0
            return False
        keys = self._all_posting_keys()
        if not len(keys):
            self._imbalance = 1.0
            return False
        w = self.load.weights_for(keys)
        n_sp = self.mesh.shape["sp"]
        cap = self.capacity_weights
        if cap is not None and len(cap) != n_sp:
            # mesh reshaped under an old capacity vector (reform /
            # degrade): heterogeneity no longer maps — fall back to
            # homogeneous rather than split against the wrong hosts
            cap = None
        cur = self._predicted_shard_loads(keys, w, self.boundaries)
        # hysteresis on CAPACITY-NORMALIZED load: a slow host at its
        # (lighter) target is balanced, not a hot spot
        self._imbalance = imbalance_factor(
            cur if cap is None else cur / cap
        )
        if self._imbalance <= self.rebalance_ratio:
            return False
        new_b = weighted_boundaries(keys, w, n_sp, member_capacity=cap)
        if new_b is None or (
            self.boundaries is not None
            and np.array_equal(new_b, self.boundaries)
        ):
            return False
        # move accounting: postings whose shard assignment changed
        # (key+slot int32 pairs — the per-host re-ship upper bound)
        old_shard = (
            shard_of_keys(keys, self.boundaries, n_sp)
            if self.boundaries is not None
            else self._equal_count_shards(len(keys), n_sp)
        )
        moved = int(
            (old_shard != shard_of_keys(keys, new_b, n_sp)).sum()
        )
        self.moved_bytes += moved * 8
        self.boundaries = new_b
        self.boundary_gen += 1
        self.boundary_moves += 1
        self._last_move = t
        # boundary-aware result-capacity autotune: size the per-shard
        # result slots from the POST-rebalance predicted per-shard
        # load (recomputed only at moves — the value ships with the
        # boundary broadcast, so every lockstep process builds the
        # same shapes)
        self.shard_results_effective = self._auto_shard_results(
            keys, w, new_b
        )
        with self._mu:
            for c in CLASSES:
                self._force_major[c] = True
                self._dirty[c] = True
        log.info(
            "shard rebalance #%d: imbalance %.2f > %.2f, %d postings "
            "move (%d B)",
            self.boundary_moves, self._imbalance, self.rebalance_ratio,
            moved, moved * 8,
        )
        return True

    def _auto_shard_results(
        self, keys: np.ndarray, w: np.ndarray, boundaries
    ) -> Optional[int]:
        """Boundary-aware per-shard result capacity (ROADMAP PR 8
        follow-up): the configured `shard_results` is the
        BALANCED-load budget (e.g. the autotune profile's measured
        hit-concentration base).  When the predicted per-shard load
        share concentrates — exactly what a boundary move produces
        when it isolates a hot range into one narrow shard — a query
        over the hot range draws most of its hits from that one
        shard, and a flat constant re-opens the result-slot
        overflow -> exact-scan fallback the rebalance was meant to
        kill.  Capacity therefore rises toward max_results in
        proportion to the hottest shard's predicted load share (2x
        safety), and never drops below the configured base.  Returns
        None when no raise applies (unset base, or base already at
        max_results)."""
        base = self.shard_results
        if base is None or base >= self.max_results:
            return None
        loads = self._predicted_shard_loads(keys, w, boundaries)
        total = float(loads.sum())
        if total <= 0:
            return None
        share = float(loads.max()) / total
        need = int(np.ceil(self.max_results * min(1.0, 2.0 * share)))
        return int(min(self.max_results, max(base, need)))

    def _build_shard_results(self) -> Optional[int]:
        """What ShardedDar builds actually use: the boundary-aware
        effective capacity when a move computed one, else the
        configured base."""
        return (
            self.shard_results
            if self.shard_results_effective is None
            else self.shard_results_effective
        )

    @staticmethod
    def _equal_count_shards(n: int, n_sp: int) -> np.ndarray:
        ps = max((n + n_sp - 1) // n_sp, 8)
        return np.minimum(
            np.arange(n, dtype=np.int64) // ps, n_sp - 1
        ).astype(np.int32)

    def apply_boundaries(self, boundaries, bgen: int,
                         shard_results: Optional[int] = None) -> None:
        """Adopt a leader-broadcast boundary map (multihost follower
        path): the split — and the boundary-aware result capacity the
        leader sized from the post-rebalance predicted load — is
        applied verbatim, no local planning, so every process builds
        identical shard rows (and identical result-slot shapes) for
        the identical record prefix."""
        if bgen == self.boundary_gen:
            return
        self.boundaries = (
            None if boundaries is None
            else np.asarray(boundaries, np.int32)
        )
        self.shard_results_effective = (
            None if shard_results is None else int(shard_results)
        )
        self.boundary_gen = int(bgen)
        self.boundary_moves += 1
        with self._mu:
            for c in CLASSES:
                self._force_major[c] = True
                self._dirty[c] = True

    def reset_boundaries(self) -> None:
        """Drop to the equal-count cold-start split (mesh shape
        changed: degrade re-home or membership reform — the old n_sp's
        boundary map no longer applies)."""
        self.boundaries = None
        # lockstep currency resets with the map (a reform runs this on
        # EVERY process — incumbents and joiners then agree on bgen 0,
        # so the next broadcast bgen drives identical force-major
        # decisions everywhere); boundary_moves (the gauge) keeps
        # counting.  The boundary-aware result capacity was sized for
        # the dropped map — reset with it.
        self.shard_results_effective = None
        self.boundary_gen = 0
        self._shard_hits_total = np.zeros(
            self.mesh.shape["sp"], np.int64
        )

    def measured_shard_loads(self) -> np.ndarray:
        """Per-shard unique-hit work measured by the sharded kernels:
        retired-dar totals plus the live dars' counters."""
        n_sp = self.mesh.shape["sp"]
        out = np.zeros(n_sp, np.int64)
        tot = self._shard_hits_total
        out[: min(len(tot), n_sp)] += tot[: min(len(tot), n_sp)]
        for snap in self._snapshots.values():
            if snap is None:
                continue
            for dar in (snap.base, snap.delta):
                if dar is not None and dar.n_sp == n_sp:
                    out += dar.shard_hits
        return out

    def refresh(self, *, plan: bool = True) -> bool:
        """Fold ingested records into fresh ShardedDars (one per dirty
        class) and swap them in (atomic per class for readers).
        -> True if any new snapshot was published.

        `plan` runs the rebalance decision first (single-process
        serving); a multihost leader plans and BROADCASTS before
        folding and passes plan=False here, followers always apply
        broadcast boundaries instead."""
        with self._refresh_mu:
            if plan:
                self.plan_rebalance()
            published = False
            for cls in CLASSES:
                published |= self._refresh_class(cls)
            if not self._has_tail_errors():
                self._last_fresh = time.monotonic()
            return published

    def _has_tail_errors(self) -> bool:
        return bool(getattr(self._tail, "errors", 0))

    def _refresh_class(self, cls: str) -> bool:
        with self._mu:
            if not self._dirty[cls] and self._snapshots[cls] is not None:
                return False
            prev = self._snapshots[cls]
            churn = len(self._delta[cls]) + len(self._shadow[cls])
            major = (
                prev is None
                or not self._base[cls]
                or self._force_major[cls]
                or self._tier_ratio <= 0
                or churn > self._tier_ratio * max(len(self._base[cls]), 1)
            )
            bounds = self.boundaries
            if major:
                # full repack: fresh base tier, tombstones GC'd (and,
                # after a boundary move, the rebuild that re-homes
                # every shard row under the new key ranges)
                self._force_major[cls] = False
                recs = list(self._records[cls].values())
                base = (
                    ShardedDar(
                        recs,
                        self.mesh,
                        max_results=self.max_results,
                        shard_results=self._build_shard_results(),
                        boundaries=bounds,
                    )
                    if recs
                    else None
                )
                snap = _ClsSnap(
                    base=base,
                    base_ids=[r.entity_id for r in recs],
                    shadow=frozenset(),
                    delta=None,
                    delta_ids=[],
                )
                self._base[cls] = set(self._records[cls])
                self._delta[cls] = {}
                self._shadow[cls] = set()
            else:
                # ship the tier delta only: rebuild the small delta dar
                # (O(churn)); the base dar and its device residency are
                # untouched
                drecs = list(self._delta[cls].values())
                delta = (
                    ShardedDar(
                        drecs,
                        self.mesh,
                        max_results=self.max_results,
                        shard_results=self._build_shard_results(),
                        boundaries=bounds,
                    )
                    if drecs
                    else None
                )
                snap = _ClsSnap(
                    base=prev.base,
                    base_ids=prev.base_ids,
                    shadow=frozenset(self._shadow[cls]),
                    delta=delta,
                    delta_ids=[r.entity_id for r in drecs],
                )
            built = snap.delta if not major else snap.base
            # records ingested while we build/warm re-mark dirty and
            # are picked up by the next refresh
            self._dirty[cls] = False
        # warm the new dar's query executable BEFORE publishing: the
        # jit cache keys on the snapshot's postings-run capacity, so a
        # rebuild can mean a fresh XLA compile — readers keep hitting
        # the old snapshot until the warmed one swaps in.  The warm
        # also commits the query-input device layouts (put_global with
        # the kernel's in_specs inside query_batch), so the first real
        # offload after a swap pays neither a compile NOR a call-site
        # resharding — the same publish-after-warm rule the resident
        # kernel's fold hook follows (ops/resident.py).  Warm time is
        # accounted (replica_warm_ms_total): it is the rebuild cost an
        # operator trades for a stall-free first query.
        if built is not None:
            t_warm = time.perf_counter()
            for wb in self.warm_batches:
                try:
                    built.query_batch(
                        np.full((wb, 16), -1, np.int32),
                        np.full(wb, -np.inf, np.float32),
                        np.full(wb, np.inf, np.float32),
                        np.full(wb, NO_TIME_LO, np.int64),
                        np.full(wb, NO_TIME_HI, np.int64),
                        now=0,
                    )
                except Exception:  # noqa: BLE001 — warmup best-effort
                    pass
            self._warm_ms_total += (time.perf_counter() - t_warm) * 1000
        with self._mu:
            old = self._snapshots[cls]
            if old is not None:
                # retiring dars take their measured per-shard work
                # with them; absorb it so the load heat map survives
                # rebuild swaps
                retired = (
                    (old.base, old.delta) if major else (old.delta,)
                )
                n_sp = len(self._shard_hits_total)
                for dar in retired:
                    if dar is not None and dar.n_sp == n_sp:
                        self._shard_hits_total += dar.shard_hits
            self._snapshots[cls] = snap
            self._rebuilds += 1
            if built is not None:
                self.device_bytes_built += built.nbytes
            if major:
                self._major_rebuilds += 1
            else:
                self._delta_refreshes += 1
        return True

    def sync(self) -> None:
        """poll + refresh in one call (tests / benchmarks)."""
        self.poll_once()
        self.refresh()

    # -- background tailing ---------------------------------------------------

    def start(self, interval_s: float = 0.5) -> None:
        self._interval_s = interval_s
        self._started_at = time.monotonic()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll_once()
                    if self._refresh_due():
                        self.refresh()
                    else:
                        self._refresh_skips += 1
                        # an idle replica with NOTHING to fold is still
                        # current — the tail is applied and no class is
                        # dirty — so keep the staleness clock honest
                        # instead of letting it climb into the stale
                        # alert at quiescent steady state (deferred-
                        # backlog idleness is excused in the alert via
                        # replica_demand_idle instead)
                        with self._mu:
                            backlog = any(self._dirty.values())
                        if not backlog and not self._has_tail_errors():
                            self._last_fresh = time.monotonic()
                except Exception:  # noqa: BLE001 — keep the tailer alive
                    log.exception("replica refresh failed")

        self._thread = threading.Thread(
            target=loop, name="sharded-replica", daemon=True
        )
        self._thread.start()

    def _refresh_due(self) -> bool:
        """Demand pacing: rebuild only while the mesh route has a
        consumer (fresh() consulted within the pace window) or during
        the boot grace.  The tail is ALWAYS applied by the loop before
        this check, so skipping a rebuild defers work, never loses it
        — the first demanded refresh folds the whole backlog."""
        pace = self.demand_pace_s
        if pace <= 0:
            return True
        now = time.monotonic()
        if now - self._started_at <= pace:
            return True  # boot grace: warm before the first demand
        return now - self._demand_last <= pace

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- serving reads --------------------------------------------------------

    def staleness_s(self) -> float:
        """Seconds since the replica last finished a caught-up sync."""
        if self._last_fresh == 0.0:
            return float("inf")
        return time.monotonic() - self._last_fresh

    def fresh(self, bound_s: Optional[float] = None) -> bool:
        """Mesh-offload gate: the replica must have synced recently,
        have no un-rebuilt class, AND have consumed the whole log.  For
        WAL tails `at_end()` stats the file at call time, so a write
        that committed before this query started is guaranteed visible
        (read-your-writes); region tails give the same bounded
        staleness as any non-writing region instance."""
        if bound_s is None:
            bound_s = 4 * getattr(self, "_interval_s", 0.5)
        # a freshness probe IS the demand signal: a mesh-shaped batch
        # wanted this replica, so the paced background loop resumes
        # rebuilding (a stale answer here steers the caller local and
        # the route re-warms within a tick or two)
        self._demand_last = time.monotonic()
        if self.staleness_s() > bound_s:
            return False
        if any(self._dirty.values()):
            return False
        at_end = getattr(self._tail, "at_end", None)
        return at_end() if at_end is not None else False

    def query(
        self,
        keys: np.ndarray,  # int32 DAR keys
        alt_lo: Optional[float] = None,
        alt_hi: Optional[float] = None,
        t_start: Optional[int] = None,
        t_end: Optional[int] = None,
        *,
        now: int,
        cls: str = "ops",
        owner: Optional[str] = None,
    ) -> List[str]:
        """Entity ids intersecting the query volume, from the current
        snapshot of `cls` (one atomic snapshot grab per query).
        `owner` post-filters to that owner's entities — REQUIRED for
        the subscription classes, whose ids are owner-private (the
        store surfaces scope them the same way)."""
        keys = np.asarray(keys, np.int32).ravel()
        if keys.size == 0:
            return []
        rows = self.query_batch(
            [keys],
            np.asarray([-np.inf if alt_lo is None else alt_lo], np.float32),
            np.asarray([np.inf if alt_hi is None else alt_hi], np.float32),
            np.asarray(
                [NO_TIME_LO if t_start is None else t_start], np.int64
            ),
            np.asarray([NO_TIME_HI if t_end is None else t_end], np.int64),
            now=now,
            cls=cls,
        )
        return self.filter_owner(rows[0], cls, owner)

    def filter_owner(
        self, ids: List[str], cls: str, owner: Optional[str]
    ) -> List[str]:
        """Post-filter ids to one owner's entities (the subscription
        surfaces, whose ids are owner-private)."""
        if owner is None:
            return ids
        oid = self._owners.get(owner)
        recs = self._records[cls]
        return [
            i for i in ids
            if oid is not None and i in recs and recs[i].owner_id == oid
        ]

    def pad_query_batch(
        self,
        keys_list,  # sequence of int32 DAR-key arrays
        alt_lo,
        alt_hi,
        t_start,
        t_end,
        *,
        now,  # scalar or i64[B]
    ):
        """Normalize a batch to the padded arrays the mesh consumes —
        split out so a multihost leader can broadcast EXACTLY what it
        executes (identical shapes => identical collectives on every
        process)."""
        from dss_tpu.dar.pack import pow2_at_least

        b = len(keys_list)
        width = pow2_at_least(
            max((len(k) for k in keys_list), default=1), lo=16
        )
        qkeys = np.full((b, width), -1, np.int32)
        for i, k in enumerate(keys_list):
            u = np.unique(np.asarray(k, np.int32))
            qkeys[i, : len(u)] = u
        now_arr = np.broadcast_to(
            np.asarray(now, np.int64), (b,)
        ).copy()
        return (
            qkeys,
            np.asarray(alt_lo, np.float32),
            np.asarray(alt_hi, np.float32),
            np.asarray(t_start, np.int64),
            np.asarray(t_end, np.int64),
            now_arr,
        )

    def query_batch(
        self,
        keys_list,  # sequence of int32 DAR-key arrays
        alt_lo: np.ndarray,
        alt_hi: np.ndarray,
        t_start: np.ndarray,
        t_end: np.ndarray,
        *,
        now,  # scalar or i64[B]
        cls: str = "ops",
    ) -> List[List[str]]:
        """Batched mesh query -> entity-id lists (sorted).  Hits merge
        across the base and delta tiers; base ids in the shadow set
        (superseded/deleted since the base was built) are dropped, so
        the newest tier wins."""
        qkeys, alo, ahi, ts, te, now_arr = self.pad_query_batch(
            keys_list, alt_lo, alt_hi, t_start, t_end, now=now
        )
        rows = self.query_padded(cls, qkeys, alo, ahi, ts, te, now_arr)
        # serving-entry load accounting: this query's covering stamps
        # its key-range buckets with its measured candidate work (the
        # input the skew-aware splitter plans from)
        for i, row in enumerate(rows):
            self.load.record(keys_list[i], len(row))
        return rows

    def query_padded(
        self,
        cls: str,
        qkeys: np.ndarray,  # [B, width] int32, pad -1
        alt_lo: np.ndarray,
        alt_hi: np.ndarray,
        t_start: np.ndarray,
        t_end: np.ndarray,
        now_arr: np.ndarray,
    ) -> List[List[str]]:
        """The per-tier mesh query over pre-padded arrays (the shape
        every lockstep process replays verbatim)."""
        snap = self._snapshots[cls]
        b = qkeys.shape[0]
        if snap is None or (snap.base is None and snap.delta is None):
            return [[] for _ in range(b)]
        out = [set() for _ in range(b)]
        for dar, ids, drop in (
            (snap.base, snap.base_ids, snap.shadow),
            (snap.delta, snap.delta_ids, None),
        ):
            if dar is None:
                continue
            rows = dar.query_batch(
                qkeys,
                alt_lo,
                alt_hi,
                t_start,
                t_end,
                now=now_arr,
            )
            for i, row in enumerate(rows):
                for s in row:
                    if s < len(ids):
                        eid = ids[s]
                        if drop is None or eid not in drop:
                            out[i].add(eid)
        return [sorted(s) for s in out]

    def query_batch_host(
        self,
        keys_list,
        alt_lo,
        alt_hi,
        t_start,
        t_end,
        *,
        now,
        cls: str = "ops",
    ) -> List[List[str]]:
        """Exact host-side answer straight from the record map — the
        degraded-mode path when no mesh (global or local) is usable.
        Same record state the mesh folds from, so results match."""
        b = len(keys_list)
        now_arr = np.broadcast_to(np.asarray(now, np.int64), (b,))
        with self._mu:
            recs = dict(self._records[cls])
        out = []
        for i in range(b):
            alo = float(np.asarray(alt_lo).ravel()[i])
            ahi = float(np.asarray(alt_hi).ravel()[i])
            ts = int(np.asarray(t_start).ravel()[i])
            te = int(np.asarray(t_end).ravel()[i])
            out.append(
                sorted(
                    oracle.search(
                        recs,
                        np.asarray(keys_list[i], np.int32),
                        None if alo == -np.inf else alo,
                        None if ahi == np.inf else ahi,
                        None if ts == NO_TIME_LO else ts,
                        None if te == NO_TIME_HI else te,
                        int(now_arr[i]),
                    )
                )
            )
        return out

    def shard_stats(self) -> dict:
        """The skew-aware placement gauge family (satellite of the
        load-weighted sharding work; flows into /metrics and the
        Grafana heat panel).  dss_shard_load is a per-shard vector
        (rendered as a labeled gauge); the rest are scalars."""
        loads = self.measured_shard_loads()
        return {
            "dss_shard_load": {
                str(i): float(v) for i, v in enumerate(loads)
            },
            "dss_shard_imbalance_factor": round(self._imbalance, 4),
            "dss_shard_boundary_moves": self.boundary_moves,
            "dss_shard_moved_bytes": self.moved_bytes,
            # per-shard result capacity the builds actually use (the
            # boundary-aware autotune raises it toward max_results
            # when predicted load concentrates; 0 = legacy
            # max_results-sized default)
            "dss_shard_results_cap": int(
                self._build_shard_results() or 0
            ),
            "dss_shard_members": len(
                {d.process_index for d in self.mesh.devices.flat}
            ),
        }

    def stats(self) -> dict:
        out = {
            "replica_applied_records": self._applied_records,
            "replica_apply_errors": self._apply_errors,
            "replica_tail_errors": getattr(self._tail, "errors", 0),
            "replica_rebuilds": self._rebuilds,
            "replica_delta_refreshes": self._delta_refreshes,
            "replica_major_rebuilds": self._major_rebuilds,
            "replica_warm_ms_total": round(self._warm_ms_total, 1),
            "replica_refresh_skips": self._refresh_skips,
            "replica_demand_idle": int(
                self.demand_pace_s > 0
                and self._started_at > 0
                and not self._refresh_due()
            ),
            "replica_staleness_s": (
                -1.0
                if self._last_fresh == 0.0
                else round(self.staleness_s(), 3)
            ),
        }
        out.update(self.shard_stats())
        for cls in CLASSES:
            snap = self._snapshots[cls]
            out[f"replica_{cls}_records"] = len(self._records[cls])
            out[f"replica_{cls}_snapshot_records"] = (
                0 if snap is None else snap.live_records
            )
            fallbacks = 0
            if snap is not None:
                for dar in (snap.base, snap.delta):
                    if dar is not None:
                        fallbacks += dar.overflow_fallbacks
            out[f"replica_{cls}_overflow_fallbacks"] = fallbacks
            out[f"replica_{cls}_delta_records"] = (
                0 if snap is None else len(snap.delta_ids)
            )
            out[f"replica_{cls}_shadowed"] = (
                0 if snap is None else len(snap.shadow)
            )
            out[f"replica_{cls}_dirty"] = int(self._dirty[cls])
            out[f"replica_{cls}_generation"] = self._gen[cls]
        return out


class ShardedOpReplica(ShardedReplica):
    """Back-compat alias: the r3/r4 SCD-operations-only replica surface
    (query defaults to cls='ops')."""
