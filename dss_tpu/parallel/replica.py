"""ShardedDar refresh: tail a durable log into a serving multi-chip
read replica.

SURVEY §7 step 7 (second half): writes land in the single-chip store +
WAL (or the region log in region mode); this replica tails that log and
periodically folds it into a fresh `ShardedDar` snapshot on the device
mesh, swapping it in atomically for readers — the same
source-of-truth/read-replica split the reference gets from CRDB ranges
(implementation_details.md:11-42).

Consistency: readers grab ONE (dar, ids) snapshot reference per query,
so a query always runs against a complete snapshot — concurrent
refreshes are invisible until their atomic swap.  Staleness is bounded
by the poll interval + rebuild time.

Sources:
  - `wal_path`: tail a standalone server's WriteAheadLog file
    (incremental: remembers the byte offset, only consumes whole
    lines, tolerates a torn tail write until the next poll);
  - `region_client`: fetch entries from a region log server.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from dss_tpu.dar import codec
from dss_tpu.dar.oracle import Record
from dss_tpu.geo import s2cell
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO
from dss_tpu.parallel.sharded import ShardedDar

log = logging.getLogger("dss.replica")


class _WalTail:
    """Incremental reader of a WriteAheadLog file (JSON lines)."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0

    def poll(self) -> List[dict]:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, "r", encoding="utf-8") as fh:
            fh.seek(self._offset)
            while True:
                pos = fh.tell()
                line = fh.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    # torn tail write: re-read from here next poll
                    fh.seek(pos)
                    break
                line = line.strip()
                if not line:
                    self._offset = fh.tell()
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    # torn write that still got a newline: stop here
                    # and retry next poll
                    fh.seek(pos)
                    break
                self._offset = fh.tell()
        return out


class _RegionTail:
    """Incremental reader of a region log (batch entries)."""

    def __init__(self, client):
        self.client = client
        self._applied = 0
        self.errors = 0  # consecutive fetch failures (operability)

    def poll(self) -> List[dict]:
        from dss_tpu.region.client import RegionError, SnapshotRequired

        out = []
        try:
            while True:
                try:
                    entries, head = self.client.fetch(self._applied)
                    self.errors = 0
                except SnapshotRequired:
                    snap = self.client.get_snapshot()
                    if snap is None:
                        return out
                    idx, state = snap
                    # the snapshot carries full docs: replace local
                    # state wholesale, then resume tailing after it
                    out.append({"t": "__replica_reset__", "state": state})
                    self._applied = idx
                    continue
                for idx, recs in entries:
                    if idx >= self._applied:
                        out.extend(recs)
                        self._applied = idx + 1
                if self._applied >= head:
                    return out
        except RegionError as e:
            # transient (next poll retries) — but a replica cut off
            # from the region must be VISIBLY stale, not silently so
            self.errors += 1
            log.warning(
                "replica region tail failed (%d consecutive): %s",
                self.errors, e,
            )
            return out


class ShardedOpReplica:
    """SCD-operations read replica on a ("dp", "sp") mesh, refreshed
    from a WAL or region-log tail."""

    def __init__(
        self,
        mesh,
        *,
        wal_path: Optional[str] = None,
        region_client=None,
        max_results: int = 512,
    ):
        if (wal_path is None) == (region_client is None):
            raise ValueError("exactly one of wal_path / region_client")
        self.mesh = mesh
        self.max_results = max_results
        self._tail = (
            _WalTail(wal_path) if wal_path else _RegionTail(region_client)
        )
        self._records: Dict[str, Record] = {}
        self._owners: Dict[str, int] = {}
        self._dirty = False
        self._mu = threading.Lock()  # guards records + tail + rebuild
        # serializes whole refresh() runs: publish order must match
        # build order (the warmup happens outside _mu, so without this
        # a slower older build could overwrite a newer snapshot)
        self._refresh_mu = threading.Lock()
        self._snapshot: Optional[Tuple[ShardedDar, List[str]]] = None
        self._applied_records = 0
        self._apply_errors = 0
        self._rebuilds = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingest ---------------------------------------------------------------

    def _intern(self, owner: str) -> int:
        return self._owners.setdefault(owner, len(self._owners))

    def _rec_from_op_doc(self, doc: dict) -> Record:
        op = codec.doc_to_op(doc)
        keys = np.unique(
            s2cell.cell_to_dar_key(np.asarray(op.cells, dtype=np.uint64))
        )
        from dss_tpu.clock import to_nanos

        return Record(
            entity_id=op.id,
            keys=keys.astype(np.int32),
            alt_lo=(
                -np.inf if op.altitude_lower is None else float(op.altitude_lower)
            ),
            alt_hi=(
                np.inf if op.altitude_upper is None else float(op.altitude_upper)
            ),
            t_start=to_nanos(op.start_time),
            t_end=to_nanos(op.end_time),
            owner_id=self._intern(op.owner),
        )

    def _apply_locked(self, rec: dict) -> None:
        t = rec.get("t", "")
        if t == "__replica_reset__":
            # build the replacement off to the side and swap only once
            # every doc parsed: a corrupt doc mid-snapshot must not
            # leave truncated state serving as complete
            fresh = {}
            for d in rec["state"].get("scd", {}).get("ops", []):
                r = self._rec_from_op_doc(d)
                fresh[r.entity_id] = r
            self._records = fresh
            self._dirty = True
        elif t == "scd_op_put":
            r = self._rec_from_op_doc(rec["doc"])
            self._records[r.entity_id] = r
            self._dirty = True
        elif t == "scd_op_del":
            if self._records.pop(rec["id"], None) is not None:
                self._dirty = True
        self._applied_records += 1

    def poll_once(self) -> int:
        """Ingest any new log records; -> number applied.  One record
        that fails to apply (version skew, corrupt doc) is skipped and
        counted — it must not drop the rest of its batch (the tail
        cursor has already advanced past it)."""
        with self._mu:
            recs = self._tail.poll()
            for rec in recs:
                try:
                    self._apply_locked(rec)
                except Exception:  # noqa: BLE001 — isolate bad records
                    self._apply_errors += 1
                    log.exception(
                        "replica failed to apply record %r; skipped",
                        rec.get("t"),
                    )
            return len(recs)

    def refresh(self) -> bool:
        """Fold ingested records into a fresh ShardedDar and swap it in
        (atomic for readers).  -> True if a new snapshot was published."""
        with self._refresh_mu:
            return self._refresh_serialized()

    def _refresh_serialized(self) -> bool:
        with self._mu:
            if not self._dirty and self._snapshot is not None:
                return False
            recs = list(self._records.values())
            ids = [r.entity_id for r in recs]
            dar = (
                ShardedDar(recs, self.mesh, max_results=self.max_results)
                if recs
                else None
            )
            # records ingested while we build/warm re-mark dirty and
            # are picked up by the next refresh
            self._dirty = False
        # warm the new snapshot's query executable BEFORE publishing:
        # the jit cache keys on the snapshot's postings-run capacity,
        # so a rebuild can mean a fresh XLA compile — readers keep
        # hitting the old snapshot until the warmed one swaps in
        if dar is not None:
            try:
                dar.query_batch(
                    np.full((1, 16), -1, np.int32),
                    np.asarray([-np.inf], np.float32),
                    np.asarray([np.inf], np.float32),
                    np.asarray([NO_TIME_LO], np.int64),
                    np.asarray([NO_TIME_HI], np.int64),
                    now=0,
                )
            except Exception:  # noqa: BLE001 — warmup is best-effort
                pass
        with self._mu:
            self._snapshot = (dar, ids)
            self._rebuilds += 1
        return True

    def sync(self) -> None:
        """poll + refresh in one call (tests / benchmarks)."""
        self.poll_once()
        self.refresh()

    # -- background tailing ---------------------------------------------------

    def start(self, interval_s: float = 0.5) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.sync()
                except Exception:  # noqa: BLE001 — keep the tailer alive
                    log.exception("replica refresh failed")

        self._thread = threading.Thread(
            target=loop, name="sharded-replica", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- serving reads --------------------------------------------------------

    def query(
        self,
        keys: np.ndarray,  # int32 DAR keys
        alt_lo: Optional[float] = None,
        alt_hi: Optional[float] = None,
        t_start: Optional[int] = None,
        t_end: Optional[int] = None,
        *,
        now: int,
    ) -> List[str]:
        """Operation ids intersecting the query volume, from the
        current snapshot (one atomic snapshot grab per query)."""
        snap = self._snapshot
        if snap is None or snap[0] is None:
            return []
        dar, ids = snap
        keys = np.asarray(keys, np.int32).ravel()
        if keys.size == 0:
            return []
        out = dar.query_batch(
            keys[None, :],
            np.asarray(
                [-np.inf if alt_lo is None else alt_lo], np.float32
            ),
            np.asarray([np.inf if alt_hi is None else alt_hi], np.float32),
            np.asarray(
                [NO_TIME_LO if t_start is None else t_start], np.int64
            ),
            np.asarray([NO_TIME_HI if t_end is None else t_end], np.int64),
            now=now,
        )[0]
        return sorted(ids[s] for s in out if s < len(ids))

    def stats(self) -> dict:
        snap = self._snapshot
        return {
            "replica_records": len(self._records),
            "replica_snapshot_records": 0 if snap is None else len(snap[1]),
            "replica_applied_records": self._applied_records,
            "replica_apply_errors": self._apply_errors,
            "replica_tail_errors": getattr(self._tail, "errors", 0),
            "replica_rebuilds": self._rebuilds,
            "replica_dirty": int(self._dirty),
        }
