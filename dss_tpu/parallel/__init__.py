"""Multi-chip DAR sharding.

The reference scales reads via CockroachDB range sharding over the S2
cell keyspace (implementation_details.md:11-42); here the same role is
played by a `jax.sharding.Mesh` with two axes:

    dp — query-batch data parallelism (each chip answers a slice of the
         query batch),
    sp — spatial model parallelism (the sorted postings array is split
         into contiguous cell-key ranges, one per chip; candidate sets
         are merged with an all_gather over ICI).

The EntityTable (attribute columns) is replicated — it is small
relative to postings and every shard needs random access to it.
"""

from dss_tpu.parallel.mesh import (
    MeshPlacement,
    make_global_mesh,
    make_mesh,
    mesh_spans_processes,
)
from dss_tpu.parallel.sharded import (
    ShardedDar,
    imbalance_factor,
    shard_postings,
    sharded_conflict_query_batch,
    weighted_boundaries,
)

__all__ = [
    "MeshPlacement",
    "make_global_mesh",
    "make_mesh",
    "mesh_spans_processes",
    "ShardedDar",
    "imbalance_factor",
    "shard_postings",
    "sharded_conflict_query_batch",
    "weighted_boundaries",
]
