"""Shared-memory serving front: the host ring across process boundaries.

BENCH_r06 put a number on ROADMAP item 1: one Python host process
saturates at ~73 req/s through HTTP while the resident kernel and the
read cache sit mostly idle — and the old `--workers` SO_REUSEPORT mode
could not fix it, because every worker-served search re-scanned a
plain WAL-tail replica and every proxied hop paid a full loopback-HTTP
marshal/unmarshal (exactly the "marshalling step the next stage must
undo" pitfall the pjit guidance in SNIPPETS.md warns about).  This
module is the placement fix: N request workers share ONE device-owner
process over an mmap'd region, and the hot search path crosses the
process boundary as fixed-layout binary slots — no JSON, no pickle,
no sockets, no syscalls beyond the page faults.

One region file, four segments:

  header        geometry + epoch token + owner heartbeat/pid
  worker stats  one 256-byte counter block per worker (single-writer;
                the leader aggregates them into /metrics so ONE scrape
                sees the whole front)
  fence         per entity class: (incarnation, generation, floor,
                high-water) + a hashed-slot int64 stamp array — the
                OWNER mirrors every CellClock bump into it, and each
                worker's local read cache fences on it with the exact
                NO-TTL rules of dar/readcache.py.  Hash collisions can
                only over-invalidate (a fence sees a too-new stamp and
                the worker re-asks the owner) — a hit-rate tax, never
                a staleness bug, the same argument as CellClock itself.
  rings         per worker: `depth` fixed-size slots.  Each slot is a
                little seqlock-style state machine

                    FREE -> REQ (worker publishes a request)
                         -> BUSY (owner claimed it)
                         -> RESP (owner published the answer)
                         -> FREE (worker consumed it)

                Workers only perform FREE->REQ and RESP->FREE; the
                owner only performs REQ->BUSY and BUSY->RESP, so each
                slot is single-producer/single-consumer in both
                directions.  Payload is written before the state word
                and the state word is one aligned 8-byte store —
                x86-64 total-store-order makes the publish safe
                without locks (the only ISA this repo's build hosts
                run; an acquire/release port is a TODO for ARM).

Request payload: canonical covering cells as a raw uint64 run +
time/altitude window + class/owner scope + deadline.  Response: the
(id, t_end) hit pairs, the WAL sequence at answer time (the worker's
replica-catchup bound for record assembly), the class write generation
(freshness header), and an admission verdict — 429 + Retry-After ride
the slot exactly like the in-process admission path, so the shm route
keeps the coalescer's admission/deadline semantics end to end.

Fault sites (chaos/faults.py): `shm.ring.enqueue` (worker side — an
injected fault falls back to the loopback proxy, never a 5xx) and
`shm.fence.broadcast` (owner side — an injected fault POISONS the
class fence by raising its floor, so worker caches over-invalidate
rather than ever serving across a missed bump).
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dss_tpu import chaos
from dss_tpu.dar.readcache import _env_int
from dss_tpu.obs.metrics import (
    ROUTE_CLASSES,
    STAGE_BUCKETS,
    STAGE_NAMES,
    route_class,
    stage_name,
)

__all__ = [
    "SHM_CLASSES",
    "RingFull",
    "RingTimeout",
    "RingOversize",
    "ShmRegion",
    "ShmRequest",
    "ShmResponse",
    "FenceMirror",
    "WorkerFenceView",
    "ShmOwner",
    "ShmWorkerClient",
    "StageHistWriter",
    "shm_stage_hist",
    "env_knobs",
    "front_stats",
]

# the five entity classes, in wire order (the slot's cls field is an
# index into this tuple; both sides import the same constant)
SHM_CLASSES = ("isa", "rid_sub", "op", "scd_sub", "constraint")

MAGIC = 0x4453_5353_484D_5231  # "DSSSHMR1"
VERSION = 2  # v2: trace words in the slot header + the per-process
#              stage-histogram segment (distributed tracing PR)

HEADER_BYTES = 4096
WSTAT_BYTES = 256  # 32 i64 counters per worker
FENCE_HDR_BYTES = 64

# slot states
FREE, REQ, BUSY, RESP = 0, 1, 2, 3

# response statuses (HTTP-ish so the worker's mapping is obvious)
ST_OK = 0
ST_OVERLOADED = 429
ST_DEADLINE = 504
ST_ERROR = 500
ST_OVERFLOW = 507  # answer larger than the slot: re-ask over loopback

# response flag bits
RESP_F_MESH_SERVED = 1  # bounded-stale mesh answer: worker must NOT
#                         populate its cache from it (the leader's
#                         _cached_ids refuses for the same reason)

# request flags
F_ALLOW_STALE = 1
F_HAS_ALT_LO = 2
F_HAS_T0 = 4
F_HAS_T1 = 8
F_HAS_OWNER = 16
F_HAS_ALT_HI = 32

# worker stat block indices (single-writer per block; the leader's
# /metrics aggregation reads them as dss_shm_worker_* families)
WS_HEARTBEAT_NS = 0
WS_ENQUEUED = 1
WS_SERVED = 2
WS_CACHE_HITS = 3
WS_CACHE_MISSES = 4
WS_RING_FULL = 5
WS_TIMEOUTS = 6
WS_OVERSIZE = 7
WS_PROXY_FALLBACKS = 8
WS_ASSEMBLY_MISSES = 9
WS_WAIT_NS = 10
WS_ERRORS = 11
WS_PLAN_SHM = 12
WS_PLAN_PROXY = 13
WSTAT_NAMES = {
    WS_ENQUEUED: "enqueued",
    WS_SERVED: "served",
    WS_CACHE_HITS: "cache_hits",
    WS_CACHE_MISSES: "cache_misses",
    WS_RING_FULL: "ring_full",
    WS_TIMEOUTS: "timeouts",
    WS_OVERSIZE: "oversize",
    WS_PROXY_FALLBACKS: "proxy_fallbacks",
    WS_ASSEMBLY_MISSES: "assembly_misses",
    WS_ERRORS: "errors",
    WS_PLAN_SHM: "plan_shm",
    WS_PLAN_PROXY: "plan_proxy",
}

_OWNER_MAX = 120  # bytes of utf-8 owner scope a slot can carry

# owner counter block: 16 i64s at header offset 64, single-writer
# (the owner process).  Published so ANY process mapping the region —
# every request worker included — can render the whole front's
# dss_shm_* families from its own /metrics endpoint: with the owner
# off the public port, scrapes only ever land on workers.
_OHDR_OFF = 64
OH_SERVED = 0
OH_ERRORS = 1
OH_DEADLINE_DROPS = 2
OH_OVERLOADED = 3
OH_RECLAIMED = 4
OH_SERVE_NS = 5
OH_DEAD_WORKERS = 6

# struct layouts (little-endian, 8-aligned).  state + req_id live at
# offsets 0/8; the TRACE block at 16 carries the W3C trace id +
# sampled bit INTO the owner (words 0-2) and the owner's span-slot
# durations (obs/trace.OWNER_SLOTS, ns each, words 3-10) back OUT —
# how one request becomes ONE stitched trace across the process
# boundary without a byte of JSON on the hot path.  Request and
# response payloads share the area past the trace block (a slot is
# request OR response, never both).
_TRACE_OFF = 16
_TRACE_REQ = struct.Struct("<QQQ")  # tid_hi, tid_lo, flags
_TRACE_RESP_WORDS = 8  # one i64 duration (ns) per OWNER_SLOTS entry
_TRACE_RESP = struct.Struct("<" + "q" * _TRACE_RESP_WORDS)
_TRACE_RESP_OFF = _TRACE_OFF + _TRACE_REQ.size
_TRACE_BYTES = 96  # 3 + 8 words, padded to 8-word alignment
TRACE_F_SAMPLED = 1
TRACE_F_PRESENT = 2

_REQ_HDR = struct.Struct("<iiddqqqqii")  # cls, flags, alt_lo, alt_hi,
#                                          t0, t1, now, deadline_ns,
#                                          owner_len, n_cells
_RESP_HDR = struct.Struct("<iiqqdi")  # status, n_hits, wal_seq, gen,
#                                       retry_after_s, flags
_PAYLOAD_OFF = _TRACE_OFF + _TRACE_BYTES
_REQ_FIXED = _PAYLOAD_OFF + _REQ_HDR.size
_RESP_FIXED = _PAYLOAD_OFF + _RESP_HDR.size


def tid_split(trace_id: str) -> Tuple[int, int]:
    """32-hex W3C trace id -> (hi, lo) uint64 pair for the slot."""
    v = int(trace_id, 16)
    return (v >> 64) & ((1 << 64) - 1), v & ((1 << 64) - 1)


def tid_join(hi: int, lo: int) -> str:
    return format((int(hi) << 64) | int(lo), "032x")


# -- per-process stage-histogram blocks --------------------------------------
#
# dss_stage_duration_seconds{stage,route} aggregated across the front:
# each process (worker i -> block i, the leader/owner -> block
# nworkers) scatters its stage observations into its own fixed-layout
# block — (route class x stage x [bucket counts..., sum_ns, count])
# int64s, single-writer like the worker stats blocks — and ANY
# process's /metrics renders the merged family (shm_stage_hist), so
# one scrape shows the whole front's per-stage tails no matter which
# worker SO_REUSEPORT hands the connection to.

_SHIST_ROW = len(STAGE_BUCKETS) + 2  # buckets + sum_ns + count
_SHIST_WORDS = len(ROUTE_CLASSES) * len(STAGE_NAMES) * _SHIST_ROW
SHIST_BLOCK_BYTES = ((_SHIST_WORDS * 8 + 4095) // 4096) * 4096
_ROUTE_IDX = {r: i for i, r in enumerate(ROUTE_CLASSES)}
_STAGE_IDX = {s: i for i, s in enumerate(STAGE_NAMES)}


class RingFull(RuntimeError):
    """No free slot in this worker's ring: the caller falls back to
    the loopback proxy (never blocks, never 5xxs)."""


class RingTimeout(RuntimeError):
    """The owner did not answer within the wait bound."""


class RingOversize(RuntimeError):
    """Request (covering) or response (hits) exceeds the slot."""


def env_knobs() -> dict:
    """ShmRegion geometry from DSS_SHM_* env vars (docs/OPERATIONS.md;
    DSS_SHM_DEPTH / DSS_SHM_SLOT_BYTES are autotune-swept knobs)."""
    return {
        "depth": _env_int("DSS_SHM_DEPTH", 64),
        "slot_bytes": _env_int("DSS_SHM_SLOT_BYTES", 32768),
        "fence_slots": _env_int("DSS_SHM_FENCE_SLOTS", 1 << 16),
    }


def _pad8(n: int) -> int:
    return (n + 7) & ~7


def empty_stats() -> dict:
    """The stable dss_shm_* gauge key set for deployments with no
    shared-memory front attached — dashboards and the observability
    tier never miss a series (same pattern as federation.empty_stats)."""
    out = {
        "dss_shm_ring_depth": 0,
        "dss_shm_workers": 0,
        "dss_shm_dead_workers": 0,
        "dss_shm_slots_in_flight": 0,
        "dss_shm_served_total": 0,
        "dss_shm_errors_total": 0,
        "dss_shm_deadline_drops_total": 0,
        "dss_shm_overloaded_total": 0,
        "dss_shm_reclaimed_total": 0,
        "dss_shm_serve_ms_total": 0.0,
        "dss_shm_saturation": 0.0,
        "dss_shm_ring_full_total": 0,
    }
    for name in WSTAT_NAMES.values():
        out[f"dss_shm_worker_{name}"] = {}
    return out


def front_stats(region: "ShmRegion") -> dict:
    """The whole front's dss_shm_* families, assembled from the shared
    region alone: slot states, the per-worker stats blocks, and the
    owner counter block it publishes into the header.  Owner and
    workers call the SAME function, so a scrape landing on ANY process
    of the front reports one coherent view (the fix for multi-process
    /metrics incoherence under SO_REUSEPORT)."""
    r = region
    oh = r._ohdr
    in_flight = int(np.count_nonzero(r._states != FREE))
    out = {
        "dss_shm_ring_depth": r.depth,
        "dss_shm_workers": r.nworkers,
        "dss_shm_dead_workers": int(oh[OH_DEAD_WORKERS]),
        "dss_shm_slots_in_flight": in_flight,
        "dss_shm_served_total": int(oh[OH_SERVED]),
        "dss_shm_errors_total": int(oh[OH_ERRORS]),
        "dss_shm_deadline_drops_total": int(oh[OH_DEADLINE_DROPS]),
        "dss_shm_overloaded_total": int(oh[OH_OVERLOADED]),
        "dss_shm_reclaimed_total": int(oh[OH_RECLAIMED]),
        "dss_shm_serve_ms_total": round(int(oh[OH_SERVE_NS]) / 1e6, 3),
        # fraction of the whole front's slots in flight — the
        # DssShmRingSaturated alert input
        "dss_shm_saturation": round(
            in_flight / max(1, r.depth * r.nworkers), 4
        ),
    }
    fams: Dict[str, Dict[str, float]] = {
        f"dss_shm_worker_{name}": {} for name in WSTAT_NAMES.values()
    }
    ring_full_total = 0
    for w in range(r.nworkers):
        ws = r.worker_stats(w)
        label = f"worker-{w}"
        for name in WSTAT_NAMES.values():
            fams[f"dss_shm_worker_{name}"][label] = ws[name]
        ring_full_total += ws["ring_full"]
    out.update(fams)
    out["dss_shm_ring_full_total"] = ring_full_total
    return out


class ShmRequest:
    """A decoded request slot (owner side)."""

    __slots__ = ("cls", "cells", "alt_lo", "alt_hi", "t0_ns", "t1_ns",
                 "now_ns", "deadline_ns", "owner", "allow_stale",
                 "worker", "slot", "req_id", "trace_id",
                 "trace_sampled")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class ShmResponse:
    """A decoded response slot (worker side)."""

    __slots__ = ("status", "ids", "t1s", "wal_seq", "gen",
                 "retry_after_s", "flags", "trace_ns")

    def __init__(self, status, ids, t1s, wal_seq, gen, retry_after_s,
                 flags=0, trace_ns=None):
        self.status = status
        self.ids = ids
        self.t1s = t1s
        self.wal_seq = wal_seq
        self.gen = gen
        self.retry_after_s = retry_after_s
        self.flags = flags
        # the owner's span-slot durations (ns per obs/trace.OWNER_SLOTS
        # entry) — only meaningful when the request carried a sampled
        # trace; the worker stitches them into its own trace as child
        # spans of the ring round trip
        self.trace_ns = trace_ns

    @property
    def mesh_served(self) -> bool:
        return bool(self.flags & RESP_F_MESH_SERVED)


class ShmRegion:
    """The mmap'd region: geometry, views, and slot codecs shared by
    the owner and worker endpoints.  One process calls `create`
    (truncates + initializes), everyone else `open_existing`."""

    def __init__(self, path: str, mm: mmap.mmap, *, nworkers: int,
                 depth: int, slot_bytes: int, fence_slots: int,
                 nclasses: int):
        self.path = path
        self._mm = mm
        self.nworkers = nworkers
        self.depth = depth
        self.slot_bytes = slot_bytes
        self.fence_slots = fence_slots
        self.nclasses = nclasses
        self._buf = memoryview(mm)
        self.wstats_off = HEADER_BYTES
        # stage-histogram blocks: one per worker + one for the owner
        self.shist_off = self.wstats_off + nworkers * WSTAT_BYTES
        shist_bytes = (nworkers + 1) * SHIST_BLOCK_BYTES
        self.fence_off = self.shist_off + shist_bytes
        fence_bytes = nclasses * (FENCE_HDR_BYTES + fence_slots * 8)
        self.rings_off = _pad8(self.fence_off + fence_bytes)
        # numpy views over the region (shared pages, not copies)
        self._wstats = np.ndarray(
            (nworkers, WSTAT_BYTES // 8), dtype=np.int64, buffer=mm,
            offset=self.wstats_off,
        )
        self._shist = np.ndarray(
            (nworkers + 1, _SHIST_WORDS), dtype=np.int64, buffer=mm,
            offset=self.shist_off,
            strides=(SHIST_BLOCK_BYTES, 8),
        )
        self._fence_hdrs = []
        self._fence_stamps = []
        for c in range(nclasses):
            off = self.fence_off + c * (FENCE_HDR_BYTES + fence_slots * 8)
            self._fence_hdrs.append(np.ndarray(
                (FENCE_HDR_BYTES // 8,), dtype=np.int64, buffer=mm,
                offset=off,
            ))
            self._fence_stamps.append(np.ndarray(
                (fence_slots,), dtype=np.int64, buffer=mm,
                offset=off + FENCE_HDR_BYTES,
            ))
        # strided state view: one i64 per slot, across all rings
        self._states = np.ndarray(
            (nworkers * depth,), dtype=np.int64, buffer=mm,
            offset=self.rings_off, strides=(slot_bytes,),
        )
        self._fence_mask = np.int64(fence_slots - 1)
        # owner counter block (header): single-writer, any reader
        self._ohdr = np.ndarray(
            (16,), dtype=np.int64, buffer=mm, offset=_OHDR_OFF,
        )

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, path: str, *, nworkers: int, depth: int = 64,
               slot_bytes: int = 32768, fence_slots: int = 1 << 16,
               nclasses: int = len(SHM_CLASSES)) -> "ShmRegion":
        if fence_slots & (fence_slots - 1):
            raise ValueError("fence_slots must be a power of two")
        if slot_bytes < 4096 or slot_bytes % 8:
            raise ValueError("slot_bytes must be >= 4096 and 8-aligned")
        fence_bytes = nclasses * (FENCE_HDR_BYTES + fence_slots * 8)
        total = (
            _pad8(
                HEADER_BYTES + nworkers * WSTAT_BYTES
                + (nworkers + 1) * SHIST_BLOCK_BYTES + fence_bytes
            )
            + nworkers * depth * slot_bytes
        )
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        struct.pack_into(
            "<QIIIIII", mm, 0, MAGIC, VERSION, nworkers, depth,
            slot_bytes, fence_slots, nclasses,
        )
        region = cls(
            path, mm, nworkers=nworkers, depth=depth,
            slot_bytes=slot_bytes, fence_slots=fence_slots,
            nclasses=nclasses,
        )
        region.set_owner_heartbeat()
        struct.pack_into("<q", mm, 48, os.getpid())
        return region

    @classmethod
    def open_existing(cls, path: str) -> "ShmRegion":
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, ver, nworkers, depth, slot_bytes, fence_slots, ncls = (
            struct.unpack_from("<QIIIIII", mm, 0)
        )
        if magic != MAGIC:
            raise ValueError(f"{path}: not a DSS shm region")
        if ver != VERSION:
            raise ValueError(
                f"{path}: region format {ver} != binary {VERSION}"
            )
        return cls(
            path, mm, nworkers=nworkers, depth=depth,
            slot_bytes=slot_bytes, fence_slots=fence_slots,
            nclasses=ncls,
        )

    def close(self) -> None:
        # drop numpy views before closing the map (BufferError otherwise)
        self._wstats = None
        self._shist = None
        self._fence_hdrs = []
        self._fence_stamps = []
        self._states = None
        self._ohdr = None
        self._buf.release()
        self._mm.close()

    # -- header --------------------------------------------------------------

    @property
    def epoch_token(self) -> int:
        return struct.unpack_from("<q", self._mm, 32)[0]

    def bump_epoch_token(self) -> None:
        struct.pack_into(
            "<q", self._mm, 32, self.epoch_token + 1
        )

    def set_owner_heartbeat(self) -> None:
        struct.pack_into("<q", self._mm, 40, time.time_ns())

    def owner_heartbeat_age_s(self) -> float:
        hb = struct.unpack_from("<q", self._mm, 40)[0]
        return max(0.0, (time.time_ns() - hb) / 1e9)

    # -- worker stats --------------------------------------------------------

    def stat_add(self, worker: int, idx: int, n: int = 1) -> None:
        # single-writer per block: the worker process owns its row
        self._wstats[worker, idx] += n

    def stat_set(self, worker: int, idx: int, v: int) -> None:
        self._wstats[worker, idx] = v

    def worker_stats(self, worker: int) -> Dict[str, int]:
        row = self._wstats[worker]
        out = {name: int(row[i]) for i, name in WSTAT_NAMES.items()}
        out["heartbeat_age_s"] = round(
            max(0.0, (time.time_ns() - int(row[WS_HEARTBEAT_NS])) / 1e9), 3
        ) if row[WS_HEARTBEAT_NS] else -1
        return out

    # -- fence segment -------------------------------------------------------

    def fence_write_meta(self, cls_idx: int, *, inc: int = None,
                         gen: int = None, floor: int = None,
                         high: int = None) -> None:
        hdr = self._fence_hdrs[cls_idx]
        if inc is not None:
            hdr[0] = inc
        if gen is not None:
            hdr[1] = gen
        if floor is not None:
            hdr[2] = floor
        if high is not None:
            hdr[3] = high

    def fence_stamp(self, cls_idx: int, dar_keys, gen: int) -> None:
        """Owner side: mirror one write's bump — scatter `gen` onto
        the hashed slots of the affected DAR keys, then publish the
        generation (stamps first, so a racing worker fence can only
        see too-new, never too-old)."""
        stamps = self._fence_stamps[cls_idx]
        slots = np.asarray(dar_keys, np.int64).ravel() & self._fence_mask
        if len(slots):
            stamps[slots] = gen
        self._fence_hdrs[cls_idx][1] = gen
        self._fence_hdrs[cls_idx][3] = gen

    def fence_poison(self, cls_idx: int) -> None:
        """Raise the class floor to its generation: every worker cache
        entry stamped so far fails its next fence check.  The fail-safe
        arm of a dropped/faulted broadcast."""
        hdr = self._fence_hdrs[cls_idx]
        g = int(hdr[1]) + 1
        hdr[1] = g
        hdr[2] = g

    def fence_read(self, cls_idx: int,
                   dar_keys) -> Tuple[int, int, int, int]:
        """Worker side: (incarnation, max stamp over the covering,
        generation, floor) — the same shape CellClock.fence returns,
        so the worker's ReadCache applies the identical rules."""
        hdr = self._fence_hdrs[cls_idx]
        floor = int(hdr[2])
        m = floor
        slots = np.asarray(dar_keys, np.int64).ravel() & self._fence_mask
        if len(slots):
            m = max(m, int(self._fence_stamps[cls_idx][slots].max()))
        return (int(hdr[0]), m, int(hdr[1]), floor)

    # -- slots ---------------------------------------------------------------

    def _slot_off(self, worker: int, slot: int) -> int:
        return self.rings_off + (worker * self.depth + slot) * self.slot_bytes

    def slot_state(self, worker: int, slot: int) -> int:
        return int(self._states[worker * self.depth + slot])

    def set_slot_state(self, worker: int, slot: int, state: int) -> None:
        self._states[worker * self.depth + slot] = state

    def req_capacity_cells(self, owner_len: int) -> int:
        return (
            self.slot_bytes - _REQ_FIXED - _pad8(owner_len)
        ) // 8

    def write_request(self, worker: int, slot: int, req_id: int, *,
                      cls_idx: int, cells: np.ndarray,
                      alt_lo, alt_hi, t0_ns, t1_ns, now_ns: int,
                      deadline_ns: int, owner: str,
                      allow_stale: bool,
                      trace_id: Optional[str] = None,
                      trace_sampled: bool = False) -> None:
        """Encode the request payload, then publish state=REQ.  Raises
        RingOversize when the covering (or owner scope) cannot fit."""
        off = self._slot_off(worker, slot)
        owner_b = owner.encode("utf-8") if owner else b""
        if len(owner_b) > _OWNER_MAX:
            raise RingOversize("owner scope too long for slot")
        cells = np.ascontiguousarray(cells, dtype=np.uint64)
        n = len(cells)
        if n > self.req_capacity_cells(len(owner_b)):
            raise RingOversize(f"covering of {n} cells exceeds slot")
        flags = 0
        if allow_stale:
            flags |= F_ALLOW_STALE
        if alt_lo is not None:
            flags |= F_HAS_ALT_LO
        if alt_hi is not None:
            flags |= F_HAS_ALT_HI
        if t0_ns is not None:
            flags |= F_HAS_T0
        if t1_ns is not None:
            flags |= F_HAS_T1
        if owner_b:
            flags |= F_HAS_OWNER
        mm = self._mm
        # trace words: id + sampled bit in, owner span slots zeroed
        # (the response fills them) — fixed words, never serialized
        if trace_id:
            hi, lo = tid_split(trace_id)
            tflags = TRACE_F_PRESENT | (
                TRACE_F_SAMPLED if trace_sampled else 0
            )
        else:
            hi = lo = tflags = 0
        _TRACE_REQ.pack_into(mm, off + _TRACE_OFF, hi, lo, tflags)
        _TRACE_RESP.pack_into(
            mm, off + _TRACE_RESP_OFF, *([0] * _TRACE_RESP_WORDS)
        )
        _REQ_HDR.pack_into(
            mm, off + _PAYLOAD_OFF, cls_idx, flags,
            0.0 if alt_lo is None else float(alt_lo),
            0.0 if alt_hi is None else float(alt_hi),
            0 if t0_ns is None else int(t0_ns),
            0 if t1_ns is None else int(t1_ns),
            int(now_ns), int(deadline_ns), len(owner_b), n,
        )
        p = off + _REQ_FIXED
        if owner_b:
            mm[p:p + len(owner_b)] = owner_b
        p += _pad8(len(owner_b))
        if n:
            mm[p:p + 8 * n] = cells.tobytes()
        struct.pack_into("<q", mm, off + 8, req_id)
        # publish LAST: one aligned 8-byte store
        self._states[worker * self.depth + slot] = REQ

    def read_request(self, worker: int, slot: int) -> ShmRequest:
        off = self._slot_off(worker, slot)
        mm = self._mm
        req_id = struct.unpack_from("<q", mm, off + 8)[0]
        thi, tlo, tflags = _TRACE_REQ.unpack_from(mm, off + _TRACE_OFF)
        (cls_idx, flags, alt_lo, alt_hi, t0, t1, now_ns, deadline_ns,
         owner_len, n) = _REQ_HDR.unpack_from(mm, off + _PAYLOAD_OFF)
        p = off + _REQ_FIXED
        owner = (
            bytes(mm[p:p + owner_len]).decode("utf-8")
            if flags & F_HAS_OWNER else None
        )
        p += _pad8(owner_len)
        # copy out: the serve path outlives the slot (it gets reused
        # for the response)
        cells = np.frombuffer(
            bytes(mm[p:p + 8 * n]), dtype=np.uint64
        ) if n else np.zeros(0, np.uint64)
        return ShmRequest(
            cls=SHM_CLASSES[cls_idx],
            cells=cells,
            alt_lo=alt_lo if flags & F_HAS_ALT_LO else None,
            alt_hi=alt_hi if flags & F_HAS_ALT_HI else None,
            t0_ns=t0 if flags & F_HAS_T0 else None,
            t1_ns=t1 if flags & F_HAS_T1 else None,
            now_ns=now_ns,
            deadline_ns=deadline_ns,
            owner=owner,
            allow_stale=bool(flags & F_ALLOW_STALE),
            worker=worker, slot=slot, req_id=req_id,
            trace_id=(
                tid_join(thi, tlo)
                if tflags & TRACE_F_PRESENT else None
            ),
            trace_sampled=bool(tflags & TRACE_F_SAMPLED),
        )

    def write_response(self, worker: int, slot: int, *, status: int,
                       ids: Sequence[str] = (), t1s: Sequence[int] = (),
                       wal_seq: int = 0, gen: int = 0,
                       retry_after_s: float = 0.0,
                       flags: int = 0,
                       trace_ns: Optional[Sequence[int]] = None) -> None:
        """Encode the response over the request payload, then publish
        state=RESP.  An answer that cannot fit publishes ST_OVERFLOW
        instead (the worker re-asks over the loopback proxy).
        `trace_ns` carries the owner's span-slot durations (one int64
        ns per obs/trace.OWNER_SLOTS entry) for sampled requests."""
        off = self._slot_off(worker, slot)
        mm = self._mm
        if trace_ns is not None:
            vec = list(trace_ns)[:_TRACE_RESP_WORDS]
            vec += [0] * (_TRACE_RESP_WORDS - len(vec))
            _TRACE_RESP.pack_into(mm, off + _TRACE_RESP_OFF, *vec)
        n = len(ids)
        id_blob = b""
        if n:
            parts = []
            for i in ids:
                b = i.encode("utf-8")
                parts.append(struct.pack("<H", len(b)))
                parts.append(b)
            id_blob = b"".join(parts)
        need = _RESP_FIXED + 8 * n + len(id_blob)
        if need > self.slot_bytes:
            status, n, t1s, id_blob = ST_OVERFLOW, 0, (), b""
        _RESP_HDR.pack_into(
            mm, off + _PAYLOAD_OFF, status, n, int(wal_seq), int(gen),
            float(retry_after_s), int(flags),
        )
        p = off + _RESP_FIXED
        if n:
            t1arr = np.ascontiguousarray(t1s, dtype=np.int64)
            mm[p:p + 8 * n] = t1arr.tobytes()
            p += 8 * n
            mm[p:p + len(id_blob)] = id_blob
        self._states[worker * self.depth + slot] = RESP

    def read_response(self, worker: int, slot: int) -> ShmResponse:
        off = self._slot_off(worker, slot)
        mm = self._mm
        status, n, wal_seq, gen, retry_after_s, flags = (
            _RESP_HDR.unpack_from(mm, off + _PAYLOAD_OFF)
        )
        p = off + _RESP_FIXED
        t1s = np.frombuffer(
            bytes(mm[p:p + 8 * n]), dtype=np.int64
        ) if n else np.zeros(0, np.int64)
        p += 8 * n
        ids: List[str] = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<H", mm, p)
            p += 2
            ids.append(bytes(mm[p:p + ln]).decode("utf-8"))
            p += ln
        return ShmResponse(
            status, ids, t1s, wal_seq, gen, retry_after_s, flags,
            trace_ns=_TRACE_RESP.unpack_from(mm, off + _TRACE_RESP_OFF),
        )


class FenceMirror:
    """Owner-side per-class broadcast hook, attached to that class's
    CellClock (tiers.CellClock.attach_mirror).  Every bump scatters
    into the shm fence segment; a faulted broadcast poisons the class
    floor instead of silently dropping the bump — worker caches then
    over-invalidate, which is the safe direction."""

    __slots__ = ("_region", "_cls_idx", "_cls")

    def __init__(self, region: ShmRegion, cls_idx: int):
        self._region = region
        self._cls_idx = cls_idx
        self._cls = SHM_CLASSES[cls_idx]

    def sync(self, clock) -> None:
        """Initial publish of the clock's fence metadata (attach time,
        before any worker serves)."""
        self._region.fence_write_meta(
            self._cls_idx, inc=clock.incarnation, gen=clock.generation,
            floor=clock.floor, high=clock.high_water,
        )

    def on_bump(self, key_arrays, gen: int) -> None:
        try:
            chaos.fault_point("shm.fence.broadcast", detail=self._cls)
        except chaos.FaultError:
            self._region.fence_poison(self._cls_idx)
            return
        keys = [
            np.asarray(k, np.int64).ravel()
            for k in key_arrays if k is not None
        ]
        merged = (
            np.concatenate(keys) if len(keys) > 1
            else (keys[0] if keys else np.zeros(0, np.int64))
        )
        self._region.fence_stamp(self._cls_idx, merged, gen)

    def on_bump_all(self, gen: int) -> None:
        # wholesale invalidation: floor jumps with the generation
        self._region.fence_write_meta(
            self._cls_idx, gen=gen, floor=gen
        )


class WorkerFenceView:
    """Worker-side read view of the fence segment: returns fences in
    CellClock.fence's exact shape so dar/readcache.ReadCache applies
    identical NO-TTL rules to worker-local entries."""

    __slots__ = ("_region",)

    def __init__(self, region: ShmRegion):
        self._region = region

    def fence(self, cls: str, dar_keys) -> Tuple[int, int, int, int]:
        return self._region.fence_read(SHM_CLASSES.index(cls), dar_keys)

    def epoch(self) -> str:
        # standalone --workers mode has no region epoch; the token
        # still rotates on owner-side wholesale events so workers can
        # fence on it exactly like an epoch string
        return str(self._region.epoch_token)


class StageHistWriter:
    """One process's handle on its shared stage-histogram block
    (worker i -> block i, the leader/owner -> block nworkers).
    Single-writer per block; attached to the process's MetricsRegistry
    (obs/metrics.attach_stage_writer) so every access-log stage
    observation also lands in the shared segment."""

    __slots__ = ("_row",)

    def __init__(self, region: ShmRegion, proc_index: int):
        if not 0 <= proc_index <= region.nworkers:
            raise ValueError(
                f"proc index {proc_index} outside region "
                f"({region.nworkers} workers + owner)"
            )
        self._row = region._shist[proc_index]

    def observe(self, route: str, stage: str, duration_s: float) -> None:
        base = (
            _ROUTE_IDX[route_class(route)] * len(STAGE_NAMES)
            + _STAGE_IDX[stage_name(stage)]
        ) * _SHIST_ROW
        row = self._row
        for i, b in enumerate(STAGE_BUCKETS):
            if duration_s <= b:
                row[base + i] += 1
        row[base + _SHIST_ROW - 2] += int(duration_s * 1e9)
        row[base + _SHIST_ROW - 1] += 1


def shm_stage_hist(region: ShmRegion) -> dict:
    """The whole front's dss_stage_duration_seconds data, merged
    across every process block: {(route_class, stage): (bucket counts,
    sum_s, count)}.  Zero-count rows are omitted so the exposition
    stays compact."""
    merged = np.asarray(region._shist).sum(axis=0)
    out = {}
    for r, rc in enumerate(ROUTE_CLASSES):
        for s, st in enumerate(STAGE_NAMES):
            base = (r * len(STAGE_NAMES) + s) * _SHIST_ROW
            cnt = int(merged[base + _SHIST_ROW - 1])
            if cnt == 0:
                continue
            out[(rc, st)] = (
                tuple(
                    int(x)
                    for x in merged[base:base + len(STAGE_BUCKETS)]
                ),
                merged[base + _SHIST_ROW - 2] / 1e9,
                cnt,
            )
    return out


class ShmOwner:
    """The device-owner endpoint: one scanner thread claims REQ slots
    across every worker ring and a small pool serves them through the
    store's normal search path (admission, deadline routing, planner,
    read cache — the whole pipeline), then publishes responses back
    into the same slots.  Also reclaims rings of dead workers."""

    def __init__(self, region: ShmRegion, serve_fn: Callable,
                 *, threads: int = None, wal_seq_fn: Callable = None,
                 worker_ttl_s: float = 5.0):
        """serve_fn(ShmRequest) -> (ids, t1s, gen); raises
        errors.StatusError subclasses for admission/deadline verdicts.
        wal_seq_fn() -> the WAL sequence already durable when the
        answer was computed (the worker's catchup bound)."""
        self._region = region
        self._serve_fn = serve_fn
        self._wal_seq_fn = wal_seq_fn or (lambda: 0)
        self._threads = threads or min(
            4, max(2, (os.cpu_count() or 2))
        )
        self._worker_ttl_s = worker_ttl_s
        self._stop = threading.Event()
        self._queue: "list" = []
        self._qlock = threading.Lock()
        self._qcond = threading.Condition(self._qlock)
        self._pool: List[threading.Thread] = []
        self._scanner: Optional[threading.Thread] = None
        self._dead_workers: set = set()
        # wall-clock ns when each dead worker was declared dead: only
        # a heartbeat written AFTER this (a respawned process, or a
        # stalled one that resumed) proves the worker is back
        self._dead_since: Dict[int, int] = {}
        # counters live in the region header (single-writer: this
        # process; the lock serializes the owner's own threads) so
        # every worker can render whole-front stats — see front_stats
        self._lock = threading.Lock()

    def _count(self, idx: int, n: int = 1) -> None:
        with self._lock:
            self._region._ohdr[idx] += n

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for i in range(self._threads):
            t = threading.Thread(
                target=self._serve_loop, name=f"shm-serve-{i}",
                daemon=True,
            )
            t.start()
            self._pool.append(t)
        self._scanner = threading.Thread(
            target=self._scan_loop, name="shm-scan", daemon=True
        )
        self._scanner.start()

    def close(self) -> None:
        self._stop.set()
        with self._qcond:
            self._qcond.notify_all()
        if self._scanner is not None:
            self._scanner.join(timeout=5)
        for t in self._pool:
            t.join(timeout=5)

    # -- reclaim -------------------------------------------------------------

    def reclaim_worker(self, worker: int) -> int:
        """Free a dead worker's in-flight slots: REQ slots are dropped
        unserved (the requester is gone), RESP slots are consumed on
        its behalf.  BUSY slots flip to RESP when their serve thread
        finishes and are swept on the next scan.  -> slots freed."""
        r = self._region
        freed = 0
        self._dead_workers.add(worker)
        self._dead_since[worker] = time.time_ns()
        for s in range(r.depth):
            st = r.slot_state(worker, s)
            if st in (REQ, RESP):
                r.set_slot_state(worker, s, FREE)
                freed += 1
        self._count(OH_RECLAIMED, freed)
        with self._lock:
            r._ohdr[OH_DEAD_WORKERS] = len(self._dead_workers)
        return freed

    def revive_worker(self, worker: int) -> None:
        self._dead_workers.discard(worker)
        self._dead_since.pop(worker, None)
        with self._lock:
            self._region._ohdr[OH_DEAD_WORKERS] = len(self._dead_workers)

    # -- serving -------------------------------------------------------------

    def _scan_loop(self) -> None:
        r = self._region
        idle_sleep = 0.0002
        last_ttl_check = 0.0
        while not self._stop.is_set():
            r.set_owner_heartbeat()
            states = r._states
            req_idx = np.nonzero(states == REQ)[0]
            if len(req_idx):
                claimed = []
                t_claim = time.perf_counter_ns()
                for flat in req_idx.tolist():
                    w, s = divmod(flat, r.depth)
                    if w in self._dead_workers:
                        r.set_slot_state(w, s, FREE)
                        self._count(OH_RECLAIMED)
                        continue
                    r.set_slot_state(w, s, BUSY)
                    claimed.append((w, s, t_claim))
                if claimed:
                    with self._qcond:
                        self._queue.extend(claimed)
                        self._qcond.notify_all()
                idle_sleep = 0.0002
            else:
                time.sleep(idle_sleep)
                idle_sleep = min(idle_sleep * 2, 0.002)
            # sweep RESP slots of dead workers + heartbeat-based TTL
            now = time.monotonic()
            if now - last_ttl_check > 1.0:
                last_ttl_check = now
                for w in list(self._dead_workers):
                    # a heartbeat stamped AFTER the worker was declared
                    # dead means a respawned (or resumed) process owns
                    # the row again — revive it so its requests serve
                    hb = int(r._wstats[w][WS_HEARTBEAT_NS])
                    if hb > self._dead_since.get(w, 0):
                        self.revive_worker(w)
                        continue
                    for s in range(r.depth):
                        if r.slot_state(w, s) == RESP:
                            r.set_slot_state(w, s, FREE)
                            self._count(OH_RECLAIMED)
                if self._worker_ttl_s > 0:
                    for w in range(r.nworkers):
                        if w in self._dead_workers:
                            continue
                        row = r._wstats[w]
                        hb = int(row[WS_HEARTBEAT_NS])
                        if hb and (time.time_ns() - hb) / 1e9 > self._worker_ttl_s:
                            self.reclaim_worker(w)

    def _serve_loop(self) -> None:
        r = self._region
        while True:
            with self._qcond:
                while not self._queue and not self._stop.is_set():
                    self._qcond.wait(0.1)
                if self._stop.is_set() and not self._queue:
                    return
                w, s, t_claim = self._queue.pop(0)
            t0 = time.perf_counter_ns()
            status = ST_ERROR
            try:
                req = r.read_request(w, s)
                status = self._serve_one(req, queue_wait_ns=t0 - t_claim)
            except Exception:  # noqa: BLE001 — a bad slot must not kill the pool
                self._count(OH_ERRORS)
                try:
                    r.write_response(w, s, status=ST_ERROR)
                except Exception:  # noqa: BLE001
                    r.set_slot_state(w, s, FREE)
            finally:
                with self._lock:
                    # served counts SUCCESSFUL serves only — an
                    # operator reading the drain rate during overload
                    # must not see sheds/errors inflating it (they
                    # have their own counters); serve_ns keeps total
                    # owner busy time across all outcomes
                    if status == ST_OK:
                        r._ohdr[OH_SERVED] += 1
                    r._ohdr[OH_SERVE_NS] += time.perf_counter_ns() - t0

    def _serve_one(self, req: ShmRequest, queue_wait_ns: int = 0) -> int:
        from dss_tpu import errors as _errors
        from dss_tpu.dar import deadline as _deadline
        from dss_tpu.obs import trace as _trace

        r = self._region
        if req.deadline_ns and time.monotonic_ns() >= req.deadline_ns:
            self._count(OH_DEADLINE_DROPS)
            r.write_response(
                req.worker, req.slot, status=ST_DEADLINE,
            )
            return ST_DEADLINE
        route_dl = (
            req.deadline_ns / 1e9 if req.deadline_ns else None
        )
        if route_dl is not None:
            _deadline.set_route_deadline(route_dl)
        # sampled request: collect the serve path's spans (cache
        # lookup, admission, plan, dispatch, collect — emitted by the
        # store/coalescer seams on THIS thread) and ship them back as
        # the fixed OWNER_SLOTS duration words, so the worker stitches
        # one trace spanning both processes
        tok = None
        trace_vec = None
        t_serve0 = time.perf_counter_ns()
        if req.trace_id and req.trace_sampled:
            tok = _trace.begin_collect(req.trace_id)
        try:
            out = self._serve_fn(req)
            # (ids, t1s, gen) or (ids, t1s, gen, flags): the store
            # adds flags (RESP_F_MESH_SERVED); simple serve fns don't
            ids, t1s, gen = out[0], out[1], out[2]
            flags = out[3] if len(out) > 3 else 0
        except _errors.OverloadedError as e:
            self._count(OH_OVERLOADED)
            r.write_response(
                req.worker, req.slot, status=ST_OVERLOADED,
                retry_after_s=e.retry_after_s,
            )
            return ST_OVERLOADED
        except _errors.StatusError as e:
            status = (
                ST_DEADLINE
                if e.code == _errors.Code.DEADLINE_EXCEEDED
                else ST_ERROR
            )
            r.write_response(req.worker, req.slot, status=status)
            return status
        finally:
            if route_dl is not None:
                _deadline.set_route_deadline(None)
            if tok is not None:
                trace_vec = _trace.owner_slot_vector(
                    _trace.end_collect(tok),
                    extra={
                        "owner.queue_wait": queue_wait_ns / 1e6,
                        "owner.serve": (
                            (time.perf_counter_ns() - t_serve0) / 1e6
                        ),
                    },
                )
        r.write_response(
            req.worker, req.slot, status=ST_OK, ids=ids, t1s=t1s,
            wal_seq=self._wal_seq_fn(), gen=gen, flags=flags,
            trace_ns=trace_vec,
        )
        return ST_OK

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        return front_stats(self._region)


class ShmWorkerClient:
    """One worker process's endpoint: slot allocation (in-process lock
    — multiple request threads share the ring), request/response round
    trips, heartbeats, and the worker-owned stats block."""

    def __init__(self, region: ShmRegion, worker_index: int, *,
                 wait_s: float = None, heartbeat_s: float = 0.5):
        if not 0 <= worker_index < region.nworkers:
            raise ValueError(
                f"worker index {worker_index} outside region "
                f"({region.nworkers} workers)"
            )
        self._region = region
        self.worker = worker_index
        self._wait_s = (
            wait_s if wait_s is not None
            else float(os.environ.get("DSS_SHM_WAIT_S", 2.0))
        )
        self._alloc_lock = threading.Lock()
        self._free = list(range(region.depth))
        # slots abandoned by a timed-out waiter: reclaimed once the
        # owner has published RESP (the allocator sweeps them)
        self._abandoned: set = set()
        self._req_seq = 0
        self._stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, args=(heartbeat_s,),
            name="shm-heartbeat", daemon=True,
        )
        self._region.stat_set(
            self.worker, WS_HEARTBEAT_NS, time.time_ns()
        )
        self._hb_thread.start()

    def close(self) -> None:
        self._stop.set()

    def _hb_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self._region.stat_set(
                self.worker, WS_HEARTBEAT_NS, time.time_ns()
            )

    def stat_add(self, idx: int, n: int = 1) -> None:
        with self._alloc_lock:
            self._region.stat_add(self.worker, idx, n)

    def in_flight(self) -> int:
        with self._alloc_lock:
            return self._region.depth - len(self._free)

    def _alloc(self) -> int:
        with self._alloc_lock:
            # sweep abandoned slots the owner has finished with: RESP
            # (the answer landed after we gave up — consume it) or
            # FREE (the owner reclaimed the slot, e.g. after TTL-
            # declaring this worker dead during a stall; REQ/BUSY
            # slots stay the owner's until it publishes one of those)
            for s in list(self._abandoned):
                st = self._region.slot_state(self.worker, s)
                if st == RESP:
                    self._region.set_slot_state(self.worker, s, FREE)
                elif st != FREE:
                    continue
                self._abandoned.discard(s)
                self._free.append(s)
            # only hand out a slot the SHARED state agrees is FREE: a
            # respawned incarnation starts with a full local free list,
            # but the previous incarnation's in-flight slots may still
            # be BUSY in the owner — writing a new request over one
            # would let the old serve's response answer the new query
            # (bit-identity violation).  Non-FREE slots park in
            # _abandoned until the owner returns them.
            while self._free:
                s = self._free.pop()
                if self._region.slot_state(self.worker, s) == FREE:
                    return s
                self._abandoned.add(s)
            self._region.stat_add(self.worker, WS_RING_FULL)
            raise RingFull("no free slot")

    def _release(self, slot: int) -> None:
        with self._alloc_lock:
            self._free.append(slot)

    def call(self, *, cls: str, cells, alt_lo=None, alt_hi=None,
             t0_ns=None, t1_ns=None, now_ns: int, owner: str = None,
             allow_stale: bool = False,
             deadline_s: float = None,
             trace_id: str = None,
             trace_sampled: bool = False) -> ShmResponse:
        """One round trip.  Raises RingFull / RingOversize /
        RingTimeout — all of which the caller maps to the loopback
        proxy fallback.  The chaos seam `shm.ring.enqueue` fires
        before the slot is touched, so an injected fault costs
        nothing but the fallback.  `trace_id`/`trace_sampled` ride the
        slot's reserved trace words; a sampled request's response
        carries the owner's span-slot durations back (trace_ns)."""
        chaos.fault_point("shm.ring.enqueue", detail=cls)
        r = self._region
        slot = self._alloc()
        wrote = False
        try:
            self._req_seq += 1
            req_id = self._req_seq
            wait_s = self._wait_s
            if deadline_s is not None:
                wait_s = min(wait_s, max(0.001, deadline_s))
            deadline_ns = time.monotonic_ns() + int(wait_s * 1e9)
            r.write_request(
                self.worker, slot, req_id,
                cls_idx=SHM_CLASSES.index(cls), cells=cells,
                alt_lo=alt_lo, alt_hi=alt_hi, t0_ns=t0_ns, t1_ns=t1_ns,
                now_ns=now_ns, deadline_ns=deadline_ns,
                owner=owner or "", allow_stale=allow_stale,
                trace_id=trace_id, trace_sampled=trace_sampled,
            )
            wrote = True
            self._region.stat_add(self.worker, WS_ENQUEUED)
            # spin-then-sleep wait: first ~200us busy (the common
            # owner turnaround), then short sleeps up to the bound
            t_end = time.monotonic_ns() + int(wait_s * 1e9)
            spin_until = time.monotonic_ns() + 200_000
            sleep_s = 0.0
            while True:
                st = r.slot_state(self.worker, slot)
                if st == RESP:
                    break
                if st == FREE:
                    # the owner reclaimed this slot unserved (it
                    # declared this worker dead — a stall or a prior
                    # incarnation's death): no response is coming, so
                    # take the slot back and fall back NOW instead of
                    # burning the whole wait bound
                    self._release(slot)
                    slot = None
                    self._region.stat_add(self.worker, WS_TIMEOUTS)
                    raise RingTimeout(
                        "owner reclaimed the slot (worker marked dead)"
                    )
                now = time.monotonic_ns()
                if now >= t_end:
                    with self._alloc_lock:
                        self._abandoned.add(slot)
                    self._region.stat_add(self.worker, WS_TIMEOUTS)
                    raise RingTimeout(
                        f"owner did not answer within {wait_s:g}s"
                    )
                if now < spin_until:
                    continue
                sleep_s = min(sleep_s + 0.00005, 0.001)
                time.sleep(sleep_s)
            resp = r.read_response(self.worker, slot)
            r.set_slot_state(self.worker, slot, FREE)
            self._release(slot)
            slot = None
            return resp
        except RingOversize:
            self._region.stat_add(self.worker, WS_OVERSIZE)
            raise
        finally:
            if slot is not None and not wrote:
                self._release(slot)
            # wrote-but-failed slots stay abandoned (owner owns them)

    def stats(self) -> Dict[str, int]:
        return self._region.worker_stats(self.worker)
