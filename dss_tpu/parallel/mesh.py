"""Mesh construction for the DAR query fabric.

Axes: ("dp", "sp") — query-batch data parallelism x spatial postings
sharding.  On a v5e-8 the default factoring is dp=2 x sp=4: postings
ranges ride the fast ICI ring inside each sp group, and two independent
query streams run in parallel.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _factor(n: int) -> Tuple[int, int]:
    """Default (dp, sp) factoring: spatial sharding is the scaling
    dimension, but keep a dp=2 query-stream axis once there are >=4
    chips (v5e-8 default: dp=2 x sp=4)."""
    dp = 2 if (n >= 4 and n % 2 == 0) else 1
    return dp, n // dp


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: Optional[int] = None,
    sp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("dp", "sp") mesh over the first n_devices devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if dp is None and sp is None:
        dp, sp = _factor(n_devices)
    elif dp is None:
        dp = n_devices // sp
    elif sp is None:
        sp = n_devices // dp
    if dp * sp != n_devices:
        raise ValueError(f"dp*sp = {dp}*{sp} != n_devices = {n_devices}")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, ("dp", "sp"))


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of more than one OS process
    (a multi-host mesh: the "sp" all_gather crosses DCN, and host
    arrays can only be materialized shard-by-addressable-shard)."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


class MeshPlacement(NamedTuple):
    """A ("dp", "sp") mesh over GLOBAL devices plus the explicit
    host<->shard placement bookkeeping multi-host serving needs:
    which process owns which mesh coordinates, and which "sp" postings
    ranges this process can address (and therefore must fold/hold)."""

    mesh: Mesh
    dp: int
    sp: int
    # [dp, sp] process index owning each mesh coordinate
    owner: np.ndarray
    # process index -> sorted tuple of sp columns it owns >=1 coord of
    sp_by_process: Dict[int, Tuple[int, ...]]
    process_index: int
    num_processes: int

    @property
    def addressable_sp(self) -> Tuple[int, ...]:
        """The postings-shard columns THIS process folds and holds."""
        return self.sp_by_process.get(self.process_index, ())

    def describe(self) -> str:
        return " ".join(
            f"p{p}:sp{list(cols)}"
            for p, cols in sorted(self.sp_by_process.items())
        )


def make_global_mesh(
    *,
    dp: Optional[int] = None,
    sp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    processes: Optional[Sequence[int]] = None,
) -> MeshPlacement:
    """Build the ("dp", "sp") mesh over the GLOBAL device list (every
    process's devices, jax.distributed-joined) with explicit placement.

    Devices are ordered (process_index, id) and laid out COLUMN-blocked
    per process: a process with k local devices owns k/dp whole,
    contiguous "sp" columns, filled down the dp axis.  That makes the
    contiguity the per-host fold accounting and tier-delta shipping
    assume an enforced invariant, not a hope: a host owns contiguous
    postings ranges, per-host folds touch one contiguous block, and
    the "sp" all_gather's inter-host hops are the DCN seam.  A dp that
    does not divide some process's local device count would scatter
    that host's devices across columns other hosts also own (the old
    row-major reshape did exactly this silently) — now it FAILS
    LOUDLY instead of producing a placement whose owner map lies.

    `processes` restricts the mesh to those processes' devices — the
    elastic-membership surface: the jax.distributed world is the
    provisioned slot pool, the mesh is the serving membership, and a
    join/leave is a new mesh over a different process subset (no
    runtime re-initialization).

    Defaults to dp=1 for a process-spanning mesh: the query batch is
    replicated to every process anyway (SPMD), so the scaling
    dimension across hosts is the postings axis.
    """
    if devices is None:
        devices = jax.devices()
    if processes is not None:
        allowed = {int(p) for p in processes}
        devices = [d for d in devices if d.process_index in allowed]
        if not devices:
            raise ValueError(
                f"no devices belong to member processes {sorted(allowed)}"
            )
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    n = len(devices)
    if dp is None and sp is None:
        dp, sp = (1, n) if _spans(devices) else _factor(n)
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != n_devices = {n}")
    # column-blocked placement: walk processes in order, each filling
    # its local-count/dp whole columns top to bottom
    local_counts: Dict[int, int] = {}
    for d in devices:
        local_counts[d.process_index] = (
            local_counts.get(d.process_index, 0) + 1
        )
    if len(local_counts) > 1:
        bad = {
            p: k for p, k in local_counts.items() if k % dp != 0
        }
        if bad:
            raise ValueError(
                f"dp={dp} does not divide local device counts {bad}: "
                "per-host sp columns would be non-contiguous/shared "
                "(choose dp=1 or a dp dividing every host's devices)"
            )
    arr = np.empty((dp, sp), dtype=object)
    col = 0
    for p in sorted(local_counts):
        pdevs = [d for d in devices if d.process_index == p]
        k = len(pdevs) // dp if len(local_counts) > 1 else None
        if k is None:
            # single process: plain row-major (any layout is local)
            arr = np.asarray(devices, dtype=object).reshape(dp, sp)
            col = sp
            break
        block = np.asarray(pdevs, dtype=object).reshape(dp, k)
        arr[:, col : col + k] = block
        col += k
    assert col == sp
    mesh = Mesh(arr, ("dp", "sp"))
    owner = np.asarray(
        [[d.process_index for d in row] for row in arr], dtype=np.int64
    )
    sp_by_process: Dict[int, Tuple[int, ...]] = {}
    for p in sorted({int(x) for x in owner.flat}):
        cols = sorted(
            {j for j in range(sp) if (owner[:, j] == p).any()}
        )
        sp_by_process[p] = tuple(cols)
    # the invariant the docstring promises: every column has ONE owner
    # and every process's columns form one contiguous run
    for p, cols in sp_by_process.items():
        if list(cols) != list(range(cols[0], cols[-1] + 1)):
            raise AssertionError(
                f"process {p} sp columns non-contiguous: {cols}"
            )
    if len(local_counts) > 1:
        for j in range(sp):
            if len({int(x) for x in owner[:, j]}) != 1:
                raise AssertionError(
                    f"sp column {j} spans processes: {owner[:, j]}"
                )
    try:
        proc_idx = jax.process_index()
    except Exception:  # pragma: no cover — pre-distributed-init
        proc_idx = 0
    return MeshPlacement(
        mesh=mesh,
        dp=dp,
        sp=sp,
        owner=owner,
        sp_by_process=sp_by_process,
        process_index=proc_idx,
        num_processes=len(sp_by_process),
    )


def _spans(devices) -> bool:
    return len({d.process_index for d in devices}) > 1
