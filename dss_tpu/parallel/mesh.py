"""Mesh construction for the DAR query fabric.

Axes: ("dp", "sp") — query-batch data parallelism x spatial postings
sharding.  On a v5e-8 the default factoring is dp=2 x sp=4: postings
ranges ride the fast ICI ring inside each sp group, and two independent
query streams run in parallel.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _factor(n: int) -> Tuple[int, int]:
    """Default (dp, sp) factoring: spatial sharding is the scaling
    dimension, but keep a dp=2 query-stream axis once there are >=4
    chips (v5e-8 default: dp=2 x sp=4)."""
    dp = 2 if (n >= 4 and n % 2 == 0) else 1
    return dp, n // dp


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: Optional[int] = None,
    sp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("dp", "sp") mesh over the first n_devices devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if dp is None and sp is None:
        dp, sp = _factor(n_devices)
    elif dp is None:
        dp = n_devices // sp
    elif sp is None:
        sp = n_devices // dp
    if dp * sp != n_devices:
        raise ValueError(f"dp*sp = {dp}*{sp} != n_devices = {n_devices}")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, ("dp", "sp"))
