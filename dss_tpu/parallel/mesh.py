"""Mesh construction for the DAR query fabric.

Axes: ("dp", "sp") — query-batch data parallelism x spatial postings
sharding.  On a v5e-8 the default factoring is dp=2 x sp=4: postings
ranges ride the fast ICI ring inside each sp group, and two independent
query streams run in parallel.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _factor(n: int) -> Tuple[int, int]:
    """Default (dp, sp) factoring: spatial sharding is the scaling
    dimension, but keep a dp=2 query-stream axis once there are >=4
    chips (v5e-8 default: dp=2 x sp=4)."""
    dp = 2 if (n >= 4 and n % 2 == 0) else 1
    return dp, n // dp


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: Optional[int] = None,
    sp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("dp", "sp") mesh over the first n_devices devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if dp is None and sp is None:
        dp, sp = _factor(n_devices)
    elif dp is None:
        dp = n_devices // sp
    elif sp is None:
        sp = n_devices // dp
    if dp * sp != n_devices:
        raise ValueError(f"dp*sp = {dp}*{sp} != n_devices = {n_devices}")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, ("dp", "sp"))


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of more than one OS process
    (a multi-host mesh: the "sp" all_gather crosses DCN, and host
    arrays can only be materialized shard-by-addressable-shard)."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


class MeshPlacement(NamedTuple):
    """A ("dp", "sp") mesh over GLOBAL devices plus the explicit
    host<->shard placement bookkeeping multi-host serving needs:
    which process owns which mesh coordinates, and which "sp" postings
    ranges this process can address (and therefore must fold/hold)."""

    mesh: Mesh
    dp: int
    sp: int
    # [dp, sp] process index owning each mesh coordinate
    owner: np.ndarray
    # process index -> sorted tuple of sp columns it owns >=1 coord of
    sp_by_process: Dict[int, Tuple[int, ...]]
    process_index: int
    num_processes: int

    @property
    def addressable_sp(self) -> Tuple[int, ...]:
        """The postings-shard columns THIS process folds and holds."""
        return self.sp_by_process.get(self.process_index, ())

    def describe(self) -> str:
        return " ".join(
            f"p{p}:sp{list(cols)}"
            for p, cols in sorted(self.sp_by_process.items())
        )


def make_global_mesh(
    *,
    dp: Optional[int] = None,
    sp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> MeshPlacement:
    """Build the ("dp", "sp") mesh over the GLOBAL device list (every
    process's devices, jax.distributed-joined) with explicit placement.

    Devices are ordered (process_index, id) and reshaped row-major, so
    each process's devices land on CONTIGUOUS "sp" columns whenever
    local device counts divide sp: a host then owns contiguous
    postings ranges, per-host folds touch one contiguous block, and
    the "sp" all_gather's inter-host hops are the DCN seam.

    Defaults to dp=1 for a process-spanning mesh: the query batch is
    replicated to every process anyway (SPMD), so the scaling
    dimension across hosts is the postings axis.
    """
    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    n = len(devices)
    if dp is None and sp is None:
        dp, sp = (1, n) if _spans(devices) else _factor(n)
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != n_devices = {n}")
    arr = np.asarray(devices, dtype=object).reshape(dp, sp)
    mesh = Mesh(arr, ("dp", "sp"))
    owner = np.asarray(
        [[d.process_index for d in row] for row in arr], dtype=np.int64
    )
    sp_by_process: Dict[int, Tuple[int, ...]] = {}
    for p in sorted({int(x) for x in owner.flat}):
        cols = sorted(
            {j for j in range(sp) if (owner[:, j] == p).any()}
        )
        sp_by_process[p] = tuple(cols)
    try:
        proc_idx = jax.process_index()
    except Exception:  # pragma: no cover — pre-distributed-init
        proc_idx = 0
    return MeshPlacement(
        mesh=mesh,
        dp=dp,
        sp=sp,
        owner=owner,
        sp_by_process=sp_by_process,
        process_index=proc_idx,
        num_processes=len(sp_by_process),
    )


def _spans(devices) -> bool:
    return len({d.process_index for d in devices}) > 1
