"""Sharded DAR conflict queries: shard_map over a ("dp", "sp") mesh.

Replaces the reference's CRDB range layer for the read path
(implementation_details.md:11-42 — ranges shard the cell keyspace, any
node proxies to the right range).  Here:

  - the globally-sorted postings array is split into `sp` contiguous
    cell-key ranges (equal postings counts, so load is balanced even
    when cell occupancy is skewed);
  - each device runs the single-chip candidate gather + 4D attribute
    test (dss_tpu.ops.conflict) against its local range and compacts
    its hits to a fixed width;
  - per-shard results are merged with an all_gather over the "sp" axis
    (ICI) and dedup-compacted — the SQL DISTINCT across ranges;
  - the query batch itself is sharded over "dp": independent query
    streams never communicate.

The EntityTable is replicated: attribute columns are ~29 B/entity
(vs ~8 B/posting x ~dozens of postings/entity), and every shard needs
random access to attributes of slots its postings name.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental location
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the "skip the replication check" kwarg was renamed check_rep ->
# check_vma across jax versions; resolve the supported name once
import inspect as _inspect

_SHMAP_NOCHECK = {
    (
        "check_vma"
        if "check_vma" in _inspect.signature(shard_map).parameters
        else "check_rep"
    ): False
}

from dss_tpu.dar import oracle
from dss_tpu.dar.oracle import Record
from dss_tpu.dar.pack import pack_records
from dss_tpu.parallel.mesh import mesh_spans_processes
from dss_tpu.ops.conflict import (
    INT32_MAX,
    NO_TIME_HI,
    NO_TIME_LO,
    EntityTable,
    Postings,
    QuerySpec,
    _attr_test,
    _candidates,
    _compact_unique,
)


def shard_postings(
    post_key: np.ndarray,
    post_ent: np.ndarray,
    n_sp: int,
    sentinel_slot: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Split sorted postings into n_sp equal contiguous ranges.

    Returns ([n_sp, Ps] keys, [n_sp, Ps] slots), each row sorted, padded
    with INT32_MAX / sentinel.  Splitting by postings *count* (not key
    range) balances load under skewed cell occupancy; contiguity keeps
    each row sorted so per-shard searchsorted still works.
    """
    live = post_key != INT32_MAX
    pk = np.asarray(post_key)[live]
    pe = np.asarray(post_ent)[live]
    n = len(pk)
    ps = max((n + n_sp - 1) // n_sp, 8)
    keys = np.full((n_sp, ps), INT32_MAX, np.int32)
    ents = np.full((n_sp, ps), sentinel_slot, np.int32)
    for i in range(n_sp):
        lo, hi = i * ps, min((i + 1) * ps, n)
        if lo < n:
            keys[i, : hi - lo] = pk[lo:hi]
            ents[i, : hi - lo] = pe[lo:hi]
    return keys, ents


def put_global(mesh: Mesh, spec: P, arr: np.ndarray):
    """Materialize a host array onto the mesh under `spec`.

    Single-process meshes keep the plain device_put fast path.  A
    process-spanning mesh cannot device_put host data onto devices it
    does not address; make_array_from_callback instead asks each
    process for ONLY its addressable shards — every host materializes
    (and for sharded specs, folds device-side state for) just the
    shard rows it owns, which is the multi-host memory story.
    """
    sharding = NamedSharding(mesh, spec)
    if not mesh_spans_processes(mesh):
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def _local_query(
    post: Postings,
    ents: EntityTable,
    q: QuerySpec,
    now,  # [Q] int64 per-query visibility time
    owner,
    *,
    cap: int,
    shard_results: int,
    with_owner: bool,
):
    """Per-device: candidates from the local postings range, 4D test,
    compact to shard_results.  Returns (slots [Q, sr], n_unique [Q])."""

    def one(qq, nw, ow):
        ent, valid = _candidates(post, ents, qq.keys, cap)
        hit = valid & _attr_test(
            ents, ent, qq, nw, ow if with_owner else None
        )
        return _compact_unique(ent, hit, shard_results)

    if with_owner:
        return jax.vmap(one)(q, now, owner)
    return jax.vmap(one, in_axes=(0, 0, None))(q, now, jnp.int32(0))


@partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "cap",
        "shard_results",
        "max_results",
        "with_owner",
        "replicate_out",
    ),
)
def sharded_conflict_query_batch(
    post_key,  # [n_sp, Ps] int32, rows sorted, pad INT32_MAX
    post_ent,  # [n_sp, Ps] int32
    ents: EntityTable,  # replicated
    q: QuerySpec,  # leading batch axis Q, Q % dp == 0
    now,  # [Q] int64 per-query visibility time
    owner=None,  # [Q] int32 when with_owner
    *,
    mesh: Mesh,
    cap: int,
    shard_results: int,
    max_results: int,
    with_owner: bool = False,
    replicate_out: bool = False,
):
    """Batched sharded query.  Returns (slots [Q, max_results] padded
    with INT32_MAX, overflowed [Q] bool).

    replicate_out=True all_gathers the merged results over "dp" as
    well, so EVERY device (and therefore every process of a multi-host
    mesh) ends up holding the full [Q, max_results] answer — required
    when the caller cannot address all of the mesh's devices.  The
    merged values are bit-identical to the sharded-output path: the
    extra gather only changes placement, never the merge."""
    owner_arr = owner if with_owner else jnp.zeros(q.keys.shape[0], jnp.int32)

    def step(pk, pe, ents, keys, alo, ahi, ts, te, now, ow):
        post = Postings(post_key=pk[0], post_ent=pe[0])
        qq = QuerySpec(keys=keys, alt_lo=alo, alt_hi=ahi, t_start=ts, t_end=te)
        slots_s, n_uni = _local_query(
            post,
            ents,
            qq,
            now,
            ow,
            cap=cap,
            shard_results=shard_results,
            with_owner=with_owner,
        )
        shard_ovf = n_uni > shard_results  # [Qloc]
        gathered = jax.lax.all_gather(slots_s, "sp")  # [n_sp, Qloc, sr]
        merged = jnp.moveaxis(gathered, 0, 1).reshape(slots_s.shape[0], -1)

        def compact(m):
            return _compact_unique(m, m != INT32_MAX, max_results)

        out, n_unique = jax.vmap(compact)(merged)
        ovf = (
            jax.lax.psum(shard_ovf.astype(jnp.int32), "sp") > 0
        ) | (n_unique > max_results)
        if replicate_out:
            # [dp, Qloc, mr] -> [Q, mr] (dp-major, matching the P("dp")
            # input split) on every device
            out = jax.lax.all_gather(out, "dp").reshape(
                -1, out.shape[-1]
            )
            ovf = jax.lax.all_gather(ovf, "dp").reshape(-1)
        return out, ovf

    qspec = P("dp")
    out_specs = (
        (P(), P()) if replicate_out else (P("dp", None), P("dp"))
    )
    return shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P("sp", None),  # post_key
            P("sp", None),  # post_ent
            P(),  # ents (replicated)
            P("dp", None),  # q.keys
            qspec,
            qspec,
            qspec,
            qspec,  # q scalars-per-query
            qspec,  # now (per-query)
            qspec,  # owner
        ),
        out_specs=out_specs,
        **_SHMAP_NOCHECK,
    )(
        post_key,
        post_ent,
        ents,
        q.keys,
        q.alt_lo,
        q.alt_hi,
        q.t_start,
        q.t_end,
        now,
        owner_arr,
    )


class ShardedDar:
    """A read-only sharded snapshot of a DAR entity class.

    Built from host Records (e.g. a DarTable's authoritative state or a
    WAL replay); holds device arrays laid out for the mesh.  This is
    the multi-chip read replica — writes go through the single-chip
    DarTable / WAL and periodically refresh this snapshot, mirroring
    the reference's CRDB-as-source-of-truth split (SURVEY.md §7).
    """

    def __init__(
        self,
        records: List[Record],
        mesh: Mesh,
        *,
        max_results: int = 512,
        shard_results: Optional[int] = None,
    ):
        self.mesh = mesh
        self.n_sp = mesh.shape["sp"]
        self.dp = mesh.shape["dp"]
        # process-spanning mesh: arrays materialize addressable-shard-
        # by-shard and query outputs must replicate to every process
        self.multihost = mesh_spans_processes(mesh)
        self.max_results = max_results
        self.shard_results = shard_results or max_results
        self.records = {slot: r for slot, r in enumerate(records)}
        self.overflow_fallbacks = 0  # host-scan fallbacks (observability)

        packed = pack_records(records, pad_postings=False)
        self.cap = packed.base_cap
        skey, sent = shard_postings(
            packed.post_key, packed.post_ent, self.n_sp, packed.capacity
        )

        # host->device bytes this snapshot materializes (refresh
        # traffic accounting; on a multi-host mesh each process ships
        # only its addressable slice of the sharded arrays)
        self.nbytes = int(
            skey.nbytes
            + sent.nbytes
            + sum(
                np.asarray(a).nbytes
                for a in (
                    packed.alt_lo, packed.alt_hi, packed.t_start,
                    packed.t_end, packed.active, packed.owner,
                )
            )
        )
        self.post_key = put_global(mesh, P("sp", None), skey)
        self.post_ent = put_global(mesh, P("sp", None), sent)
        self.ents = EntityTable(
            alt_lo=put_global(mesh, P(), packed.alt_lo),
            alt_hi=put_global(mesh, P(), packed.alt_hi),
            t_start=put_global(mesh, P(), packed.t_start),
            t_end=put_global(mesh, P(), packed.t_end),
            active=put_global(mesh, P(), packed.active),
            owner=put_global(mesh, P(), packed.owner),
        )

    def query_batch(
        self,
        keys_batch: np.ndarray,  # [Q, K] int32 DAR keys, pad -1
        alt_lo: np.ndarray,  # [Q] f32
        alt_hi: np.ndarray,
        t_start: np.ndarray,  # [Q] i64
        t_end: np.ndarray,
        *,
        now,  # int scalar or [Q] i64 per-query visibility time
    ):
        """Run a batch of queries; returns list-of-lists of entity slots."""
        qn = keys_batch.shape[0]
        now_arr = np.broadcast_to(
            np.asarray(now, np.int64), (qn,)
        ).copy()
        # pad the key width to a pow2 bucket: K is data-dependent (area
        # covering size) and an unpadded shape would compile a fresh
        # executable per distinct K
        kw = 16
        while kw < keys_batch.shape[1]:
            kw *= 2
        if kw != keys_batch.shape[1]:
            keys_batch = np.concatenate(
                [
                    keys_batch,
                    np.full(
                        (qn, kw - keys_batch.shape[1]), -1, np.int32
                    ),
                ],
                axis=1,
            )
        # bucket the batch axis (pow2, dp-aligned): Q is traffic-
        # dependent and an unbucketed shape would compile a fresh
        # multi-chip executable per distinct batch size — stalling
        # every coalesced caller behind a ~30s jit for each new size
        bucket = 16
        while bucket < qn:
            bucket *= 2
        if bucket % self.dp:
            bucket = ((bucket + self.dp - 1) // self.dp) * self.dp
        pad = bucket - qn
        if pad:
            keys_batch = np.concatenate(
                [keys_batch, np.full((pad, keys_batch.shape[1]), -1, np.int32)]
            )
            alt_lo = np.concatenate([alt_lo, np.full(pad, -np.inf, np.float32)])
            alt_hi = np.concatenate([alt_hi, np.full(pad, np.inf, np.float32)])
            t_start = np.concatenate([t_start, np.full(pad, NO_TIME_LO)])
            t_end = np.concatenate([t_end, np.full(pad, NO_TIME_HI)])
            now_arr = np.concatenate(
                [now_arr, np.zeros(pad, np.int64)]
            )
        if self.multihost:
            # every process runs this same call in lockstep (SPMD);
            # inputs shard onto the global mesh addressable-first and
            # the replicated output lands whole on every process
            mk = partial(put_global, self.mesh)
            spec = QuerySpec(
                keys=mk(P("dp", None), np.asarray(keys_batch, np.int32)),
                alt_lo=mk(P("dp"), np.asarray(alt_lo, np.float32)),
                alt_hi=mk(P("dp"), np.asarray(alt_hi, np.float32)),
                t_start=mk(P("dp"), np.asarray(t_start, np.int64)),
                t_end=mk(P("dp"), np.asarray(t_end, np.int64)),
            )
            now_dev = mk(P("dp"), np.asarray(now_arr, np.int64))
        else:
            # pre-partition the query inputs to the EXACT layout the
            # compiled kernel consumes (the shard_map in_specs) — the
            # pjit pitfall: an uncommitted jnp.asarray lands on the
            # default device and XLA inserts a call-site resharding
            # into every query, exactly what the resident-kernel work
            # removes from the single-chip path (ops/resident.py).
            # The postings/entity arrays were already put_global'd to
            # their specs at build time; this closes the gap for the
            # per-call side.
            mk = partial(put_global, self.mesh)
            spec = QuerySpec(
                keys=mk(P("dp", None), np.asarray(keys_batch, np.int32)),
                alt_lo=mk(P("dp"), np.asarray(alt_lo, np.float32)),
                alt_hi=mk(P("dp"), np.asarray(alt_hi, np.float32)),
                t_start=mk(P("dp"), np.asarray(t_start, np.int64)),
                t_end=mk(P("dp"), np.asarray(t_end, np.int64)),
            )
            now_dev = mk(P("dp"), np.asarray(now_arr, np.int64))
        slots, ovf = sharded_conflict_query_batch(
            self.post_key,
            self.post_ent,
            self.ents,
            spec,
            now_dev,
            mesh=self.mesh,
            cap=self.cap,
            shard_results=self.shard_results,
            max_results=self.max_results,
            replicate_out=self.multihost,
        )
        slots = np.asarray(slots)[:qn]
        ovf = np.asarray(ovf)[:qn]
        out = []
        for i in range(qn):
            if ovf[i]:
                # result wider than max_results: exact host fallback
                # for this query (counted — a hot cell silently
                # degrading to the slow path must be observable)
                self.overflow_fallbacks += 1
                out.append(
                    oracle.search(
                        self.records,
                        keys_batch[i][keys_batch[i] >= 0],
                        None
                        if alt_lo[i] == -np.inf
                        else float(alt_lo[i]),
                        None if alt_hi[i] == np.inf else float(alt_hi[i]),
                        None if t_start[i] == NO_TIME_LO else int(t_start[i]),
                        None if t_end[i] == NO_TIME_HI else int(t_end[i]),
                        int(now_arr[i]),
                    )
                )
            else:
                row = slots[i]
                out.append([int(s) for s in row[row != INT32_MAX]])
        return out
