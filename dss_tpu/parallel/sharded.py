"""Sharded DAR conflict queries: shard_map over a ("dp", "sp") mesh.

Replaces the reference's CRDB range layer for the read path
(implementation_details.md:11-42 — ranges shard the cell keyspace, any
node proxies to the right range).  Here:

  - the globally-sorted postings array is split into `sp` contiguous
    cell-key ranges (equal postings counts, so load is balanced even
    when cell occupancy is skewed);
  - each device runs the single-chip candidate gather + 4D attribute
    test (dss_tpu.ops.conflict) against its local range and compacts
    its hits to a fixed width;
  - per-shard results are merged with an all_gather over the "sp" axis
    (ICI) and dedup-compacted — the SQL DISTINCT across ranges;
  - the query batch itself is sharded over "dp": independent query
    streams never communicate.

The EntityTable is replicated: attribute columns are ~29 B/entity
(vs ~8 B/posting x ~dozens of postings/entity), and every shard needs
random access to attributes of slots its postings name.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental location
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the "skip the replication check" kwarg was renamed check_rep ->
# check_vma across jax versions; resolve the supported name once
import inspect as _inspect

_SHMAP_NOCHECK = {
    (
        "check_vma"
        if "check_vma" in _inspect.signature(shard_map).parameters
        else "check_rep"
    ): False
}

from dss_tpu.dar import oracle
from dss_tpu.dar.oracle import Record
from dss_tpu.dar.pack import pack_records
from dss_tpu.parallel.mesh import mesh_spans_processes
from dss_tpu.ops.conflict import (
    INT32_MAX,
    NO_TIME_HI,
    NO_TIME_LO,
    EntityTable,
    Postings,
    QuerySpec,
    _attr_test,
    _candidates,
    _compact_unique,
)


def shard_postings(
    post_key: np.ndarray,
    post_ent: np.ndarray,
    n_sp: int,
    sentinel_slot: int,
    boundaries: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Split sorted postings into n_sp contiguous ranges.

    Returns ([n_sp, Ps] keys, [n_sp, Ps] slots), each row sorted, padded
    with INT32_MAX / sentinel.  Without `boundaries` the split is by
    equal postings *count* — balanced by storage, the cold-start
    fallback.  With `boundaries` (n_sp-1 sorted int32 DAR-key split
    points, usually from `weighted_boundaries`) shard i takes the key
    range [boundaries[i-1], boundaries[i]) — the load-weighted
    placement the rebalancer broadcasts, applicable to ANY postings
    array over the same key space (base and delta tiers share one
    boundary map).  Contiguity keeps each row sorted so per-shard
    searchsorted still works.
    """
    live = post_key != INT32_MAX
    pk = np.asarray(post_key)[live]
    pe = np.asarray(post_ent)[live]
    n = len(pk)
    if boundaries is None:
        ps = max((n + n_sp - 1) // n_sp, 8)
        lohi = [
            (i * ps, min((i + 1) * ps, n)) if i * ps < n else (n, n)
            for i in range(n_sp)
        ]
    else:
        b = np.asarray(boundaries, np.int32)
        if len(b) != n_sp - 1:
            raise ValueError(
                f"boundaries has {len(b)} split points for {n_sp} shards"
            )
        cuts = [0] + [int(c) for c in np.searchsorted(pk, b)] + [n]
        lohi = [(cuts[i], cuts[i + 1]) for i in range(n_sp)]
        ps = max(max((hi - lo) for lo, hi in lohi), 8)
    keys = np.full((n_sp, ps), INT32_MAX, np.int32)
    ents = np.full((n_sp, ps), sentinel_slot, np.int32)
    for i, (lo, hi) in enumerate(lohi):
        if hi > lo:
            keys[i, : hi - lo] = pk[lo:hi]
            ents[i, : hi - lo] = pe[lo:hi]
    return keys, ents


def weighted_boundaries(
    post_key: np.ndarray,
    weights: Optional[np.ndarray],
    n_sp: int,
    member_capacity: Optional[np.ndarray] = None,
) -> Optional[np.ndarray]:
    """Key-space split points equalizing predicted *query work* per
    shard (the searched-mapping step: placement driven by measured
    cost, not storage count).

    `weights` is per-posting measured load (RangeLoad.weights_for);
    every posting additionally carries one unit of count baseline, so
    zero measured load (cold start) reproduces the equal-count split
    and cold ranges still spread by storage.  Returns n_sp-1 sorted
    int32 DAR keys, or None when there is nothing to split.  Split
    points snap to key values (a single key's postings never straddle
    shards), so a single cell hotter than a whole shard ends up alone
    in its shard — the best key-range placement can do.  Per-shard
    posting counts are capped at 4x the equal-count mean (the device
    postings array is rectangular, padded to the LARGEST shard — the
    cap bounds that memory/refresh-traffic blowup at 4x; indivisible
    single-key runs excepted).

    `member_capacity` (optional, length n_sp) weighs each shard's
    TARGET work by its host's measured serving capacity (the
    `capacity_weight` scalar from per-host autotune profiles —
    dss_tpu/plan/autotune.py): a slow host gets a proportionally
    lighter key run.  None or a uniform vector reproduces the
    equal-target split bit-identically.
    """
    pk = np.asarray(post_key, np.int32).ravel()
    pk = pk[pk != INT32_MAX]
    n = len(pk)
    if n == 0 or n_sp <= 1:
        return None
    if member_capacity is None:
        cap = np.ones(n_sp, np.float64)
    else:
        cap = np.asarray(member_capacity, np.float64).ravel()
        if len(cap) != n_sp:
            raise ValueError(
                f"member_capacity has {len(cap)} entries for "
                f"{n_sp} shards"
            )
        if not np.all(cap > 0):
            raise ValueError("member_capacity entries must be > 0")
    w = np.ones(n, np.float64)
    if weights is not None:
        lw = np.asarray(weights, np.float64).ravel()
        tot = lw.sum()
        if tot > 0:
            # normalize measured load to the same mass as the count
            # baseline, then let it dominate: a shard's predicted work
            # is mostly its query load, tempered by storage so empty-
            # load ranges still split by count
            w += lw * (n / tot) * 8.0
    # greedy fill at KEY-RUN granularity (a key's postings never
    # straddle shards), re-targeting the remaining weight over the
    # remaining shards after each cut — a single run heavier than a
    # whole shard then gets (nearly) its own shard instead of
    # collapsing every later boundary onto the same key, and the mass
    # on either side of it still splits evenly
    uk, starts = np.unique(pk, return_index=True)
    run_w = np.add.reduceat(w, starts)
    run_n = np.diff(np.append(starts, n))
    # the device postings array is rectangular ([n_sp, max shard
    # postings]): cap any one shard's posting COUNT at 4x the mean so
    # a load-weighted split that packs cold mass densely can cost at
    # most 4x the equal-count layout's device bytes, never unbounded
    # (a single key run larger than the cap is indivisible and allowed
    # through)
    count_cap = max(4 * ((n + n_sp - 1) // n_sp), 8)
    bounds: list = []
    rem_w = float(run_w.sum())
    rem_sh = n_sp
    acc = 0.0
    acc_n = 0
    consumed = 0  # postings in already-closed shards

    def fits_after_cut(extra: int) -> bool:
        # a cut is only legal when the postings left over still fit in
        # the remaining shards under the cap — otherwise an early cut
        # would force some LATER shard (often the last) over it
        return (n - (consumed + extra)) <= (rem_sh - 1) * count_cap

    def next_target() -> float:
        # the shard being filled is bounds-index len(bounds); its
        # target is its capacity's share of the remaining weight
        # (uniform capacity: exactly rem_w / rem_sh, the historical
        # equal-target split)
        s = len(bounds)
        return rem_w * float(cap[s]) / float(cap[s:].sum())

    for i in range(len(uk)):
        if len(bounds) == n_sp - 1:
            break
        target = next_target()
        if (
            acc > 0
            and (
                (run_w[i] >= target and acc + run_w[i] > 1.5 * target)
                or acc_n + int(run_n[i]) > count_cap
            )
            and fits_after_cut(acc_n)
        ):
            # the next run would overfill the shard (by weight, or by
            # the rectangular-padding count cap): cut BEFORE it so the
            # accumulated cold mass isn't welded to the hot run
            bounds.append(int(uk[i]))
            consumed += acc_n
            rem_w -= acc
            rem_sh -= 1
            acc = 0.0
            acc_n = 0
            if len(bounds) == n_sp - 1:
                break
            target = next_target()
        acc += float(run_w[i])
        acc_n += int(run_n[i])
        if acc >= target and i + 1 < len(uk) and fits_after_cut(acc_n):
            bounds.append(int(uk[i + 1]))
            consumed += acc_n
            rem_w -= acc
            rem_sh -= 1
            acc = 0.0
            acc_n = 0
    while len(bounds) < n_sp - 1:
        # out of keys: remaining shards are empty (legal — duplicate
        # boundaries yield zero-width ranges)
        bounds.append(bounds[-1] if bounds else int(uk[-1]))
    return np.asarray(bounds, np.int32)


def shard_of_keys(
    keys: np.ndarray, boundaries: Optional[np.ndarray], n_sp: int
) -> np.ndarray:
    """Shard index for each key under a boundary map (None = cannot be
    answered without the postings array; used for move accounting and
    predicted-load-per-shard summaries)."""
    k = np.asarray(keys, np.int32).ravel()
    if boundaries is None or not len(k):
        return np.zeros(len(k), np.int32)
    return np.searchsorted(
        np.asarray(boundaries, np.int32), k, side="right"
    ).astype(np.int32)


def imbalance_factor(loads) -> float:
    """max/mean over per-shard loads — 1.0 is perfectly balanced; the
    rebalance trigger compares this against DSS_SHARD_REBALANCE_RATIO."""
    arr = np.asarray(loads, np.float64).ravel()
    if not len(arr) or arr.sum() <= 0:
        return 1.0
    return float(arr.max() / arr.mean())


def put_global(mesh: Mesh, spec: P, arr: np.ndarray):
    """Materialize a host array onto the mesh under `spec`.

    Single-process meshes keep the plain device_put fast path.  A
    process-spanning mesh cannot device_put host data onto devices it
    does not address; make_array_from_callback instead asks each
    process for ONLY its addressable shards — every host materializes
    (and for sharded specs, folds device-side state for) just the
    shard rows it owns, which is the multi-host memory story.
    """
    sharding = NamedSharding(mesh, spec)
    if not mesh_spans_processes(mesh):
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def _local_query(
    post: Postings,
    ents: EntityTable,
    q: QuerySpec,
    now,  # [Q] int64 per-query visibility time
    owner,
    *,
    cap: int,
    shard_results: int,
    with_owner: bool,
):
    """Per-device: candidates from the local postings range, 4D test,
    compact to shard_results.  Returns (slots [Q, sr], n_unique [Q])."""

    def one(qq, nw, ow):
        ent, valid = _candidates(post, ents, qq.keys, cap)
        hit = valid & _attr_test(
            ents, ent, qq, nw, ow if with_owner else None
        )
        return _compact_unique(ent, hit, shard_results)

    if with_owner:
        return jax.vmap(one)(q, now, owner)
    return jax.vmap(one, in_axes=(0, 0, None))(q, now, jnp.int32(0))


@partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "cap",
        "shard_results",
        "max_results",
        "with_owner",
        "replicate_out",
    ),
)
def sharded_conflict_query_batch(
    post_key,  # [n_sp, Ps] int32, rows sorted, pad INT32_MAX
    post_ent,  # [n_sp, Ps] int32
    ents: EntityTable,  # replicated
    q: QuerySpec,  # leading batch axis Q, Q % dp == 0
    now,  # [Q] int64 per-query visibility time
    owner=None,  # [Q] int32 when with_owner
    *,
    mesh: Mesh,
    cap: int,
    shard_results: int,
    max_results: int,
    with_owner: bool = False,
    replicate_out: bool = False,
):
    """Batched sharded query.  Returns (slots [Q, max_results] padded
    with INT32_MAX, overflowed [Q] bool, shard_hits [n_sp] int32 —
    per-shard unique candidate hits summed over the batch, the
    measured per-shard work the skew-aware rebalancer consumes).

    replicate_out=True all_gathers the merged results over "dp" as
    well, so EVERY device (and therefore every process of a multi-host
    mesh) ends up holding the full [Q, max_results] answer — required
    when the caller cannot address all of the mesh's devices.  The
    merged values are bit-identical to the sharded-output path: the
    extra gather only changes placement, never the merge."""
    owner_arr = owner if with_owner else jnp.zeros(q.keys.shape[0], jnp.int32)

    def step(pk, pe, ents, keys, alo, ahi, ts, te, now, ow):
        post = Postings(post_key=pk[0], post_ent=pe[0])
        qq = QuerySpec(keys=keys, alt_lo=alo, alt_hi=ahi, t_start=ts, t_end=te)
        slots_s, n_uni = _local_query(
            post,
            ents,
            qq,
            now,
            ow,
            cap=cap,
            shard_results=shard_results,
            with_owner=with_owner,
        )
        shard_ovf = n_uni > shard_results  # [Qloc]
        # per-shard measured work: unique hits this shard contributed
        # across its local query slice, summed over "dp" so every
        # device (and host) holds the identical [n_sp] load vector
        hits = jax.lax.psum(
            jax.lax.all_gather(jnp.sum(n_uni).astype(jnp.int32), "sp"),
            "dp",
        )
        gathered = jax.lax.all_gather(slots_s, "sp")  # [n_sp, Qloc, sr]
        merged = jnp.moveaxis(gathered, 0, 1).reshape(slots_s.shape[0], -1)

        def compact(m):
            return _compact_unique(m, m != INT32_MAX, max_results)

        out, n_unique = jax.vmap(compact)(merged)
        ovf = (
            jax.lax.psum(shard_ovf.astype(jnp.int32), "sp") > 0
        ) | (n_unique > max_results)
        if replicate_out:
            # [dp, Qloc, mr] -> [Q, mr] (dp-major, matching the P("dp")
            # input split) on every device
            out = jax.lax.all_gather(out, "dp").reshape(
                -1, out.shape[-1]
            )
            ovf = jax.lax.all_gather(ovf, "dp").reshape(-1)
        return out, ovf, hits

    qspec = P("dp")
    out_specs = (
        (P(), P(), P())
        if replicate_out
        else (P("dp", None), P("dp"), P())
    )
    return shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P("sp", None),  # post_key
            P("sp", None),  # post_ent
            P(),  # ents (replicated)
            P("dp", None),  # q.keys
            qspec,
            qspec,
            qspec,
            qspec,  # q scalars-per-query
            qspec,  # now (per-query)
            qspec,  # owner
        ),
        out_specs=out_specs,
        **_SHMAP_NOCHECK,
    )(
        post_key,
        post_ent,
        ents,
        q.keys,
        q.alt_lo,
        q.alt_hi,
        q.t_start,
        q.t_end,
        now,
        owner_arr,
    )


class ShardedDar:
    """A read-only sharded snapshot of a DAR entity class.

    Built from host Records (e.g. a DarTable's authoritative state or a
    WAL replay); holds device arrays laid out for the mesh.  This is
    the multi-chip read replica — writes go through the single-chip
    DarTable / WAL and periodically refresh this snapshot, mirroring
    the reference's CRDB-as-source-of-truth split (SURVEY.md §7).
    """

    def __init__(
        self,
        records: List[Record],
        mesh: Mesh,
        *,
        max_results: int = 512,
        shard_results: Optional[int] = None,
        boundaries: Optional[np.ndarray] = None,
    ):
        self.mesh = mesh
        self.n_sp = mesh.shape["sp"]
        self.dp = mesh.shape["dp"]
        # process-spanning mesh: arrays materialize addressable-shard-
        # by-shard and query outputs must replicate to every process
        self.multihost = mesh_spans_processes(mesh)
        self.max_results = max_results
        self.shard_results = shard_results or max_results
        self.records = {slot: r for slot, r in enumerate(records)}
        self.overflow_fallbacks = 0  # host-scan fallbacks (observability)
        # key-space split map this dar was built under (None = legacy
        # equal-count); kept for move accounting across rebuilds
        self.boundaries = (
            None if boundaries is None
            else np.asarray(boundaries, np.int32)
        )
        # measured per-shard unique-hit work, accumulated across
        # query batches (the rebalancer's measured-imbalance input);
        # locked — concurrent snapshot readers must not lose updates
        self.shard_hits = np.zeros(self.n_sp, np.int64)
        self._hits_mu = threading.Lock()

        packed = pack_records(records, pad_postings=False)
        self.cap = packed.base_cap
        skey, sent = shard_postings(
            packed.post_key,
            packed.post_ent,
            self.n_sp,
            packed.capacity,
            boundaries=self.boundaries,
        )

        # host->device bytes this snapshot materializes (refresh
        # traffic accounting; on a multi-host mesh each process ships
        # only its addressable slice of the sharded arrays)
        self.nbytes = int(
            skey.nbytes
            + sent.nbytes
            + sum(
                np.asarray(a).nbytes
                for a in (
                    packed.alt_lo, packed.alt_hi, packed.t_start,
                    packed.t_end, packed.active, packed.owner,
                )
            )
        )
        self.post_key = put_global(mesh, P("sp", None), skey)
        self.post_ent = put_global(mesh, P("sp", None), sent)
        self.ents = EntityTable(
            alt_lo=put_global(mesh, P(), packed.alt_lo),
            alt_hi=put_global(mesh, P(), packed.alt_hi),
            t_start=put_global(mesh, P(), packed.t_start),
            t_end=put_global(mesh, P(), packed.t_end),
            active=put_global(mesh, P(), packed.active),
            owner=put_global(mesh, P(), packed.owner),
        )

    def query_batch(
        self,
        keys_batch: np.ndarray,  # [Q, K] int32 DAR keys, pad -1
        alt_lo: np.ndarray,  # [Q] f32
        alt_hi: np.ndarray,
        t_start: np.ndarray,  # [Q] i64
        t_end: np.ndarray,
        *,
        now,  # int scalar or [Q] i64 per-query visibility time
    ):
        """Run a batch of queries; returns list-of-lists of entity slots."""
        qn = keys_batch.shape[0]
        now_arr = np.broadcast_to(
            np.asarray(now, np.int64), (qn,)
        ).copy()
        # pad the key width to a pow2 bucket: K is data-dependent (area
        # covering size) and an unpadded shape would compile a fresh
        # executable per distinct K
        kw = 16
        while kw < keys_batch.shape[1]:
            kw *= 2
        if kw != keys_batch.shape[1]:
            keys_batch = np.concatenate(
                [
                    keys_batch,
                    np.full(
                        (qn, kw - keys_batch.shape[1]), -1, np.int32
                    ),
                ],
                axis=1,
            )
        # bucket the batch axis (pow2, dp-aligned): Q is traffic-
        # dependent and an unbucketed shape would compile a fresh
        # multi-chip executable per distinct batch size — stalling
        # every coalesced caller behind a ~30s jit for each new size
        bucket = 16
        while bucket < qn:
            bucket *= 2
        if bucket % self.dp:
            bucket = ((bucket + self.dp - 1) // self.dp) * self.dp
        pad = bucket - qn
        if pad:
            keys_batch = np.concatenate(
                [keys_batch, np.full((pad, keys_batch.shape[1]), -1, np.int32)]
            )
            alt_lo = np.concatenate([alt_lo, np.full(pad, -np.inf, np.float32)])
            alt_hi = np.concatenate([alt_hi, np.full(pad, np.inf, np.float32)])
            t_start = np.concatenate([t_start, np.full(pad, NO_TIME_LO)])
            t_end = np.concatenate([t_end, np.full(pad, NO_TIME_HI)])
            now_arr = np.concatenate(
                [now_arr, np.zeros(pad, np.int64)]
            )
        if self.multihost:
            # every process runs this same call in lockstep (SPMD);
            # inputs shard onto the global mesh addressable-first and
            # the replicated output lands whole on every process
            mk = partial(put_global, self.mesh)
            spec = QuerySpec(
                keys=mk(P("dp", None), np.asarray(keys_batch, np.int32)),
                alt_lo=mk(P("dp"), np.asarray(alt_lo, np.float32)),
                alt_hi=mk(P("dp"), np.asarray(alt_hi, np.float32)),
                t_start=mk(P("dp"), np.asarray(t_start, np.int64)),
                t_end=mk(P("dp"), np.asarray(t_end, np.int64)),
            )
            now_dev = mk(P("dp"), np.asarray(now_arr, np.int64))
        else:
            # pre-partition the query inputs to the EXACT layout the
            # compiled kernel consumes (the shard_map in_specs) — the
            # pjit pitfall: an uncommitted jnp.asarray lands on the
            # default device and XLA inserts a call-site resharding
            # into every query, exactly what the resident-kernel work
            # removes from the single-chip path (ops/resident.py).
            # The postings/entity arrays were already put_global'd to
            # their specs at build time; this closes the gap for the
            # per-call side.
            mk = partial(put_global, self.mesh)
            spec = QuerySpec(
                keys=mk(P("dp", None), np.asarray(keys_batch, np.int32)),
                alt_lo=mk(P("dp"), np.asarray(alt_lo, np.float32)),
                alt_hi=mk(P("dp"), np.asarray(alt_hi, np.float32)),
                t_start=mk(P("dp"), np.asarray(t_start, np.int64)),
                t_end=mk(P("dp"), np.asarray(t_end, np.int64)),
            )
            now_dev = mk(P("dp"), np.asarray(now_arr, np.int64))
        slots, ovf, shard_hits = sharded_conflict_query_batch(
            self.post_key,
            self.post_ent,
            self.ents,
            spec,
            now_dev,
            mesh=self.mesh,
            cap=self.cap,
            shard_results=self.shard_results,
            max_results=self.max_results,
            replicate_out=self.multihost,
        )
        slots = np.asarray(slots)[:qn]
        ovf = np.asarray(ovf)[:qn]
        with self._hits_mu:
            self.shard_hits += np.asarray(shard_hits, np.int64)
        out = []
        for i in range(qn):
            if ovf[i]:
                # result wider than max_results: exact host fallback
                # for this query (counted — a hot cell silently
                # degrading to the slow path must be observable)
                self.overflow_fallbacks += 1
                out.append(
                    oracle.search(
                        self.records,
                        keys_batch[i][keys_batch[i] >= 0],
                        None
                        if alt_lo[i] == -np.inf
                        else float(alt_lo[i]),
                        None if alt_hi[i] == np.inf else float(alt_hi[i]),
                        None if t_start[i] == NO_TIME_LO else int(t_start[i]),
                        None if t_end[i] == NO_TIME_HI else int(t_end[i]),
                        int(now_arr[i]),
                    )
                )
            else:
                row = slots[i]
                out.append([int(s) for s in row[row != INT32_MAX]])
        return out
