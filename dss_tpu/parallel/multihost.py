"""Multi-host mesh: the DCN seam for the sharded DAR.

The reference scales one DSS Region across NODES by pointing every
instance at one CockroachDB cluster whose ranges span machines
(implementation_details.md:11-42).  Every multi-chip path here used to
assume ONE OS process owning all local devices; this module is the
process-spanning analog: N server processes (one per host) join a
single ("dp", "sp") mesh via `jax.distributed`, each host folds and
holds only its addressable postings shards, and the query path's
"sp" all_gather runs over DCN instead of ICI.

Pieces:

  initialize(cfg) -> MultihostRuntime
      Wires `jax.distributed` BEFORE backend init with serving-grade
      failure semantics: the stock initializer terminates every
      process when any peer dies (training semantics); here the
      runtime client is built with heartbeat kill-switches disabled
      and liveness is owned by the barrier watchdog below, so peer
      loss DEGRADES serving instead of ending it.  A CPU dryrun
      override (`cfg.dryrun_devices`) forces an N-virtual-device CPU
      backend per process with gloo cross-process collectives — the
      whole DCN program validated without TPUs.

  MultihostRuntime
      The coordination surface: KV pub/sub for the leader->follower
      command stream, named barriers, the peer-loss watchdog, and the
      `dss_multihost_*` gauge family.

  MultihostReplica(ShardedReplica)
      The serving integration.  Process 0 (leader) serves traffic and
      paces the mesh; followers run `run_follower()` — a pump that
      replays the leader's command stream so every process issues the
      SAME collectives in the SAME order (the SPMD contract).  Two
      command kinds:

        refresh: the leader polls its log tail, then broadcasts the
            exact CUT (byte offset / entry index) it folded at;
            followers tail their own copy of the log TO THAT CUT and
            fold the identical record prefix.  The fold reuses the
            tier protocol unchanged: a routine refresh rebuilds only
            the per-class DELTA dar (O(churn) host fold + shard
            materialization per host), a major compaction repacks the
            base.  What crosses DCN per refresh is each host's
            addressable slice of the (usually tiny) delta tier.

        query: the leader broadcasts the padded query batch, then
            both sides run the same per-tier mesh queries; the "sp"
            all_gather merges per-shard hits across hosts and a final
            "dp" gather replicates the merged answer to every
            process.

      Degraded mode: a watchdog barrier timeout (or a collective
      failing mid-query) flips the survivor to LOCAL-ONLY serving —
      queries answer from the exact host-side record map immediately,
      and the next refresh rebuilds every class on a local-devices
      mesh.  Results stay correct (every host tails the full log);
      only the memory scale-out is lost until the mesh re-forms.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
from typing import Callable, List, NamedTuple, Optional

import numpy as np

log = logging.getLogger("dss.multihost")

# env-var fallbacks for the server flags (k8s downward-API friendly)
ENV_COORDINATOR = "DSS_JAX_COORDINATOR"
ENV_PROCESS_ID = "DSS_PROCESS_ID"
ENV_NUM_PROCESSES = "DSS_NUM_PROCESSES"
ENV_DRYRUN = "DSS_MULTIHOST_DRYRUN"

# exported gauge family (test_deploy_observability imports this)
MULTIHOST_METRICS = (
    "dss_multihost_processes",
    "dss_multihost_process_id",
    "dss_multihost_degraded",
    "dss_multihost_last_barrier_age_s",
    "dss_multihost_barrier_failures",
    "dss_multihost_refresh_bytes",
    "dss_multihost_commands",
    "dss_multihost_local_only",
    "dss_multihost_members",
    "dss_multihost_is_member",
)


class MultihostDegradedError(RuntimeError):
    """The process-spanning mesh lost a peer (barrier timeout or a
    cross-process collective failed); the caller must drop to
    local-only serving."""


class MultihostConfig(NamedTuple):
    coordinator: str  # host:port of process 0's coordination service
    process_id: int
    num_processes: int
    # CPU dryrun: force an N-virtual-device CPU backend + gloo
    # cross-process collectives (0 = real accelerator backend)
    dryrun_devices: int = 0
    init_timeout_s: float = 60.0
    # watchdog cadence: a barrier every interval; a peer missing one
    # for timeout_s flips serving to degraded local-only
    watchdog_interval_s: float = 1.0
    watchdog_timeout_s: float = 5.0

    @classmethod
    def from_flags(
        cls,
        coordinator: str = "",
        process_id: Optional[int] = None,
        num_processes: Optional[int] = None,
        dryrun_devices: int = 0,
        **kw,
    ) -> Optional["MultihostConfig"]:
        """Flags first, env fallbacks second; None when neither names
        a coordinator (single-process mode)."""
        coordinator = coordinator or os.environ.get(ENV_COORDINATOR, "")
        if process_id is None and os.environ.get(ENV_PROCESS_ID):
            process_id = int(os.environ[ENV_PROCESS_ID])
        if num_processes is None and os.environ.get(ENV_NUM_PROCESSES):
            num_processes = int(os.environ[ENV_NUM_PROCESSES])
        if not dryrun_devices and os.environ.get(ENV_DRYRUN):
            dryrun_devices = int(os.environ[ENV_DRYRUN])
        if not coordinator:
            return None
        if process_id is None or num_processes is None:
            raise ValueError(
                "multi-host mode needs process_id + num_processes "
                f"(flags or {ENV_PROCESS_ID}/{ENV_NUM_PROCESSES})"
            )
        return cls(
            coordinator=coordinator,
            process_id=int(process_id),
            num_processes=int(num_processes),
            dryrun_devices=int(dryrun_devices),
            **kw,
        )


class MultihostRuntime:
    """Handle on the joined multi-process runtime: coordination KV,
    barriers, the peer-loss watchdog, and the gauge family."""

    def __init__(self, cfg: MultihostConfig, client, service):
        self.cfg = cfg
        self.process_id = cfg.process_id
        self.num_processes = cfg.num_processes
        self._client = client
        self._service = service
        self.closing = False
        self.degraded = False
        self.degraded_reason = ""
        self.refresh_bytes = 0  # tier bytes materialized via refreshes
        self.commands = 0  # command-stream length (leader==followers)
        self._barrier_failures = 0
        self._last_barrier_ok = time.monotonic()
        self._on_degraded: List[Callable[[], None]] = []
        self._watchdog: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # -- coordination primitives ---------------------------------------------

    def kv_set(self, key: str, value: bytes) -> None:
        # chaos seam: the leader's refresh/command broadcast rides
        # this KV — an injected failure here is a DCN refresh loss
        from dss_tpu.chaos import fault_point

        fault_point("multihost.refresh", detail=key)
        self._client.key_value_set_bytes(f"dssmh/{key}", value)

    def kv_get(self, key: str, timeout_s: float) -> bytes:
        """Blocks until some process sets the key (the pub/sub the
        command stream rides); raises on timeout."""
        return self._client.blocking_key_value_get_bytes(
            f"dssmh/{key}", int(timeout_s * 1000)
        )

    def kv_delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(f"dssmh/{key}")
        except Exception:  # noqa: BLE001 — GC is best-effort
            pass

    def barrier(self, name: str, timeout_s: float) -> None:
        # chaos seam: an injected barrier failure is a peer loss (the
        # watchdog's exception path -> mark_degraded, exactly as a
        # real missing process); a delay is a slow DCN hop
        from dss_tpu.chaos import fault_point

        fault_point("multihost.barrier", detail=name)
        self._client.wait_at_barrier(
            f"dssmh-{name}", int(timeout_s * 1000)
        )

    # -- degradation ----------------------------------------------------------

    def on_degraded(self, fn: Callable[[], None]) -> None:
        self._on_degraded.append(fn)

    def mark_degraded(self, reason: str) -> None:
        if self.degraded or self.closing:
            return
        self.degraded = True
        self.degraded_reason = reason
        log.error(
            "multihost mesh degraded (%s): dropping to local-only "
            "serving", reason,
        )
        for fn in list(self._on_degraded):
            try:
                fn()
            except Exception:  # noqa: BLE001 — degrade must not cascade
                log.exception("degradation callback failed")

    def ensure_healthy(self) -> None:
        if self.degraded:
            raise MultihostDegradedError(self.degraded_reason)

    # -- peer-loss watchdog ---------------------------------------------------

    def start_watchdog(self) -> None:
        """Heartbeat barrier on every process at the same cadence; a
        peer missing for watchdog_timeout_s flips degraded mode.  The
        watchdog owns liveness (initialize() disables the stock
        kill-the-world heartbeats), so peer loss degrades exactly one
        layer: the mesh."""
        if self.num_processes < 2 or self._watchdog is not None:
            return
        stop = threading.Event()

        def loop():
            k = 0
            while not stop.is_set() and not self.closing:
                try:
                    self.barrier(f"hb-{k}", self.cfg.watchdog_timeout_s)
                    self._last_barrier_ok = time.monotonic()
                except Exception as e:  # noqa: BLE001 — any failure = peer loss
                    if self.closing:
                        return
                    self._barrier_failures += 1
                    self.mark_degraded(
                        f"watchdog barrier hb-{k} failed: "
                        f"{type(e).__name__}"
                    )
                    return  # no peers left to heartbeat with
                k += 1
                stop.wait(self.cfg.watchdog_interval_s)

        self._watchdog_stop = stop
        self._watchdog = threading.Thread(
            target=loop, name="dss-multihost-watchdog", daemon=True
        )
        self._watchdog.start()

    # -- lifecycle / stats ----------------------------------------------------

    def close(self) -> None:
        self.closing = True
        if self._watchdog is not None:
            self._watchdog_stop.set()
            self._watchdog.join(
                timeout=self.cfg.watchdog_timeout_s + 1.0
            )
        try:
            self._client.shutdown()
        except Exception:  # noqa: BLE001 — peers may already be gone
            pass
        if self._service is not None:
            try:
                self._service.shutdown()
            except Exception:  # noqa: BLE001
                pass

    def stats(self) -> dict:
        return {
            "dss_multihost_processes": self.num_processes,
            "dss_multihost_process_id": self.process_id,
            "dss_multihost_degraded": int(self.degraded),
            "dss_multihost_last_barrier_age_s": (
                round(time.monotonic() - self._last_barrier_ok, 3)
                if self._watchdog is not None
                else 0.0
            ),
            "dss_multihost_barrier_failures": self._barrier_failures,
            "dss_multihost_refresh_bytes": self.refresh_bytes,
            "dss_multihost_commands": self.commands,
        }


def initialize(cfg: MultihostConfig) -> MultihostRuntime:
    """Join the process-spanning runtime.  MUST run before the first
    jax backend touch (jax.devices(), any computation).

    Differences from stock `jax.distributed.initialize`, all in
    service of serving availability:
      - heartbeat intervals are effectively disabled: the stock
        missed-heartbeat path TERMINATES the surviving processes
        (training semantics — and jaxlib's custom-callback override
        crashes with a nanobind cast bug), while a serving mesh must
        outlive a peer.  Liveness belongs to the watchdog barrier.
      - shutdown_on_destruction=False: a degraded survivor must not
        block on dead peers at exit.
      - dryrun_devices forces the virtual-CPU backend + gloo
        cross-process collectives (the DCN program without TPUs).
    """
    import jax

    if cfg.dryrun_devices:
        import re

        want = (
            f"--xla_force_host_platform_device_count="
            f"{cfg.dryrun_devices}"
        )
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # an inherited count (e.g. the test harness's virtual-8
            # mesh) must not override the per-process dryrun shape
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                want,
                flags,
            )
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from jax._src import distributed
    from jax._src.lib import xla_extension

    state = distributed.global_state
    if state.client is not None:
        raise RuntimeError("multihost runtime already initialized")
    service = None
    if cfg.process_id == 0:
        bind = "[::]:" + cfg.coordinator.rsplit(":", 1)[1]
        service = xla_extension.get_distributed_runtime_service(
            bind,
            cfg.num_processes,
            # the watchdog owns liveness — see the docstring
            heartbeat_interval=3600,
            max_missing_heartbeats=1_000_000,
        )
        state.service = service
    client = xla_extension.get_distributed_runtime_client(
        cfg.coordinator,
        cfg.process_id,
        init_timeout=int(cfg.init_timeout_s),
        heartbeat_interval=3600,
        max_missing_heartbeats=1_000_000,
        shutdown_on_destruction=False,
    )
    client.connect()
    state.client = client
    state.process_id = cfg.process_id
    state.num_processes = cfg.num_processes
    state.coordinator_address = cfg.coordinator
    log.info(
        "multihost runtime up: process %d/%d via %s%s",
        cfg.process_id,
        cfg.num_processes,
        cfg.coordinator,
        f" (CPU dryrun x{cfg.dryrun_devices})" if cfg.dryrun_devices else "",
    )
    return MultihostRuntime(cfg, client, service)


# -- command-stream encoding (leader -> followers over the KV store) ----------


def _encode_cmd(kind: str, arrays: Optional[dict] = None, **scalars) -> bytes:
    head = json.dumps({"kind": kind, **scalars}).encode()
    buf = io.BytesIO()
    np.savez(buf, **(arrays or {}))
    return len(head).to_bytes(4, "big") + head + buf.getvalue()


def _decode_cmd(raw: bytes):
    n = int.from_bytes(raw[:4], "big")
    head = json.loads(raw[4 : 4 + n].decode())
    arrays = {}
    if len(raw) > 4 + n:
        with np.load(io.BytesIO(raw[4 + n :]), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    return head, arrays


class MultihostReplica:
    """Process-spanning `ShardedReplica`: one replica per process over
    ONE global mesh, held in lockstep by the leader's command stream.

    Built as a wrapper (not a subclass) so the lockstep discipline has
    a single choke point: every mesh-touching entry (refresh, query)
    goes through `_mesh_op`, which serializes collectives process-wide
    and broadcasts the command before executing it locally.
    """

    def __init__(
        self,
        runtime: MultihostRuntime,
        placement,
        *,
        wal_path: Optional[str] = None,
        region_client=None,
        max_results: int = 512,
        warm_batches=(1,),
        tier_ratio: Optional[float] = None,
        cut_timeout_s: float = 30.0,
        members: Optional[tuple] = None,
    ):
        from dss_tpu.parallel.replica import ShardedReplica

        self.runtime = runtime
        self.placement = placement
        self._cut_timeout_s = cut_timeout_s
        # elastic membership: the jax.distributed world is the
        # provisioned slot pool; `members` is the subset of processes
        # whose devices form the SERVING mesh.  A standby process
        # (world member, not mesh member) tails the log in lockstep —
        # that IS its snapshot+tail catch-up — and the next fold after
        # a reform cuts it into the boundary map.
        self._members = (
            tuple(sorted(set(members)))
            if members
            else tuple(range(runtime.num_processes))
        )
        if 0 not in self._members:
            raise ValueError("process 0 (the leader) must be a member")
        self._pending_members: Optional[tuple] = None
        self._dp = placement.dp
        self._inner = ShardedReplica(
            placement.mesh,
            wal_path=wal_path,
            region_client=region_client,
            max_results=max_results,
            warm_batches=warm_batches,
            tier_ratio=tier_ratio,
        )
        # one mesh op at a time, process-wide: the command stream IS
        # the global collective order, so local execution must follow
        # it strictly
        self._op_mu = threading.RLock()
        self._seq = 0  # leader: next command seq to publish
        # extension point: out-of-band command kinds a harness can
        # register (the dryrun's peer-kill rides this)
        self.extra_commands = {}
        self._local_only = False  # degraded: serve from local state
        self._local_rebuilt = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        runtime.on_degraded(self._on_peer_loss)
        runtime.start_watchdog()

    # -- shared helpers -------------------------------------------------------

    @property
    def mesh(self):
        return self._inner.mesh

    @property
    def members(self) -> tuple:
        return self._members

    @property
    def is_member(self) -> bool:
        """Is THIS process part of the serving mesh (vs a standby
        slot tailing the log awaiting a join)?"""
        return self.runtime.process_id in self._members

    def _account_refresh_bytes(self) -> None:
        self.runtime.refresh_bytes = self._inner.device_bytes_built

    def _on_peer_loss(self) -> None:
        """Watchdog callback: flip to host-only serving NOW (correct —
        every process tails the full log), and let the refresh loop
        rebuild the dars on a local-devices mesh."""
        self._local_only = True

    def _degrade_rebuild_locked(self) -> None:
        """Re-home the replica on a local-only mesh and force a full
        rebuild of every class (the global mesh's arrays are useless —
        their collectives would block on dead peers)."""
        import jax

        from dss_tpu.parallel.mesh import make_mesh

        inner = self._inner
        local = jax.local_devices()
        inner.mesh = make_mesh(len(local), devices=local)
        # the old mesh's sp count is gone with the peers: the boundary
        # map (n_sp-1 split points) no longer applies
        inner.reset_boundaries()
        with inner._mu:
            for c in inner._records:
                inner._base[c] = set()
                inner._delta[c] = {}
                inner._shadow[c] = set()
                inner._dirty[c] = True
            inner._snapshots = {c: None for c in inner._snapshots}
        inner.refresh()
        self._local_rebuilt = True
        log.warning(
            "multihost replica re-homed on a local %s mesh "
            "(degraded local-only serving)", dict(inner.mesh.shape),
        )

    # -- leader side ----------------------------------------------------------

    def _broadcast(self, kind: str, arrays=None, **scalars) -> None:
        if self.runtime.num_processes < 2:
            return  # single-process mesh: nobody to pace
        payload = _encode_cmd(kind, arrays, **scalars)
        self.runtime.kv_set(f"cmd/{self._seq}", payload)
        self._seq += 1
        self.runtime.commands = self._seq
        # bound the coordinator's KV footprint: followers are at most
        # a few commands behind (each blocks on seq order), so a long
        # window is already generous
        if self._seq > 4096:
            self.runtime.kv_delete(f"cmd/{self._seq - 4096}")

    def broadcast_control(self, kind: str, **scalars) -> None:
        """Publish an out-of-band command (must be registered in the
        followers' `extra_commands`)."""
        with self._op_mu:
            self._broadcast(kind, **scalars)

    def set_members(self, members) -> None:
        """Request a membership change (join and/or leave): the NEXT
        leader sync broadcasts a reform with the fold cut, every
        member re-homes on a mesh over the new member set, and the
        incoming process's lockstep log tail becomes its serving
        state.  Leader-side API."""
        m = tuple(sorted(set(int(p) for p in members)))
        if 0 not in m:
            raise ValueError("process 0 (the leader) must be a member")
        bad = [p for p in m if p >= self.runtime.num_processes]
        if bad:
            raise ValueError(
                f"members {bad} outside the provisioned world "
                f"(num_processes={self.runtime.num_processes})"
            )
        self._pending_members = m

    def _apply_reform(self, members: tuple) -> None:
        """Re-home the replica on a mesh over `members` (runs on every
        process, leader and follower alike, at the broadcast cut).
        Members rebuild every class major on the new mesh (each host
        materializes only its addressable shard rows); a process that
        left drops its device state and keeps tailing as standby."""
        from dss_tpu.parallel.mesh import make_global_mesh

        inner = self._inner
        self._members = tuple(members)
        if self.is_member:
            placement = make_global_mesh(
                dp=self._dp, processes=self._members
            )
            self.placement = placement
            inner.mesh = placement.mesh
        inner.reset_boundaries()
        with inner._mu:
            for c in inner._records:
                inner._base[c] = set()
                inner._delta[c] = {}
                inner._shadow[c] = set()
                inner._dirty[c] = True
            inner._snapshots = {c: None for c in inner._snapshots}
        if self.is_member:
            inner.refresh(plan=False)
            self._account_refresh_bytes()
            log.info(
                "mesh reformed: members %s, placement %s",
                self._members, self.placement.describe(),
            )
        else:
            log.info(
                "left the serving mesh (members now %s); tailing as "
                "standby", self._members,
            )

    def _boundary_payload(self) -> dict:
        inner = self._inner
        return {
            "boundaries": (
                None
                if inner.boundaries is None
                else [int(x) for x in inner.boundaries]
            ),
            "bgen": inner.boundary_gen,
            # boundary-aware result capacity sized by the leader from
            # the post-rebalance predicted load: ships with the map so
            # every process builds identical result-slot shapes
            "sres": inner.shard_results_effective,
        }

    def sync(self) -> None:
        """Leader pacing: poll the tail to its current end, broadcast
        the exact cut (+ the rebalanced boundary map), fold in
        lockstep.  A pending membership change reforms the mesh at
        this fold boundary first.  Degraded: plain local sync."""
        with self._op_mu:
            inner = self._inner
            if self._local_only:
                if not self._local_rebuilt:
                    self._degrade_rebuild_locked()
                inner.sync()
                self._account_refresh_bytes()
                return
            if not self.runtime.is_leader:
                raise RuntimeError(
                    "followers are paced by run_follower(), not sync()"
                )
            inner.poll_once()
            if self._pending_members is not None:
                m, self._pending_members = self._pending_members, None
                if m != self._members:
                    cut = inner.tail_position()
                    try:
                        self._broadcast(
                            "reform",
                            cut=cut,
                            fp=inner.state_fingerprint(),
                            members=list(m),
                        )
                        self._apply_reform(m)
                    except MultihostDegradedError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        if self._maybe_degrade_on(e):
                            return
                        raise
                    return
            # the rebalance decision is leader-only (followers apply
            # the broadcast boundaries verbatim); a boundary move
            # marks every class dirty, so the fold below ships it
            inner.plan_rebalance()
            with inner._mu:
                dirty = any(inner._dirty.values()) or any(
                    s is None for s in inner._snapshots.values()
                )
            if not dirty:
                return  # nothing to fold: no collectives, no command
            cut = inner.tail_position()
            try:
                self._broadcast(
                    "refresh",
                    cut=cut,
                    fp=inner.state_fingerprint(),
                    **self._boundary_payload(),
                )
                inner.refresh(plan=False)
            except MultihostDegradedError:
                raise
            except Exception as e:  # noqa: BLE001 — collective failure
                if self._maybe_degrade_on(e):
                    return
                raise
            self._account_refresh_bytes()

    def query_batch(
        self,
        keys_list,
        alt_lo,
        alt_hi,
        t_start,
        t_end,
        *,
        now,
        cls: str = "ops",
    ):
        inner = self._inner
        # paths that never touch the global mesh answer WITHOUT the
        # mesh-op lock: a follower's (or degraded survivor's) reads
        # must not queue behind an in-flight lockstep fold's XLA
        # compile they take no part in
        if not self.runtime.is_leader:
            # followers cannot initiate mesh collectives (only replay
            # them): their own read traffic answers exactly from the
            # host record map
            return inner.query_batch_host(
                keys_list, alt_lo, alt_hi, t_start, t_end,
                now=now, cls=cls,
            )
        if self._local_only:
            if not self._local_rebuilt:
                # mesh gone, local dars not rebuilt yet: answer
                # exactly from the host record map (no collectives)
                return inner.query_batch_host(
                    keys_list, alt_lo, alt_hi, t_start, t_end,
                    now=now, cls=cls,
                )
            # re-homed on a local-devices mesh: ordinary single-
            # process replica queries, concurrency-safe by snapshot
            return inner.query_batch(
                keys_list, alt_lo, alt_hi, t_start, t_end,
                now=now, cls=cls,
            )
        with self._op_mu:
            if self._local_only:
                # degradation flipped while we waited for the lock
                return inner.query_batch_host(
                    keys_list, alt_lo, alt_hi, t_start, t_end,
                    now=now, cls=cls,
                )
            qkeys, alo, ahi, ts, te, now_arr = inner.pad_query_batch(
                keys_list, alt_lo, alt_hi, t_start, t_end, now=now
            )
            try:
                self._broadcast(
                    "query",
                    arrays={
                        "qkeys": qkeys, "alt_lo": alo, "alt_hi": ahi,
                        "t_start": ts, "t_end": te, "now": now_arr,
                    },
                    cls=cls,
                )
                rows = inner.query_padded(
                    cls, qkeys, alo, ahi, ts, te, now_arr
                )
                # leader-side load accounting (the planning input):
                # followers never record — the leader's map is the one
                # the broadcast boundaries come from
                for i, row in enumerate(rows):
                    inner.load.record(keys_list[i], len(row))
                return rows
            except Exception as e:  # noqa: BLE001 — collective failure
                if self._maybe_degrade_on(e):
                    return inner.query_batch_host(
                        keys_list, alt_lo, alt_hi, t_start, t_end,
                        now=now, cls=cls,
                    )
                raise

    def _maybe_degrade_on(self, e: Exception) -> bool:
        """A cross-process collective died under us (peer loss beat
        the watchdog to it): degrade instead of failing the caller."""
        if self.runtime.closing or self._local_only:
            return True
        log.error(
            "multihost mesh op failed (%s: %s); degrading",
            type(e).__name__, e,
        )
        self.runtime.mark_degraded(f"mesh op failed: {type(e).__name__}")
        return self._local_only  # set by the callback

    def query(self, *args, **kw):
        """Single-query surface (the /aux replica routes)."""
        return self._query_via_batch(*args, **kw)

    def _query_via_batch(
        self,
        keys,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now,
        cls="ops",
        owner=None,
    ):
        from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO

        keys = np.asarray(keys, np.int32).ravel()
        if keys.size == 0:
            return []
        rows = self.query_batch(
            [keys],
            np.asarray(
                [-np.inf if alt_lo is None else alt_lo], np.float32
            ),
            np.asarray(
                [np.inf if alt_hi is None else alt_hi], np.float32
            ),
            np.asarray(
                [NO_TIME_LO if t_start is None else t_start], np.int64
            ),
            np.asarray(
                [NO_TIME_HI if t_end is None else t_end], np.int64
            ),
            now=now,
            cls=cls,
        )
        return self._inner.filter_owner(rows[0], cls, owner)

    # -- follower side --------------------------------------------------------

    def run_follower(self, poll_timeout_s: float = 1.0) -> None:
        """Replay the leader's command stream until stopped.  Returns
        normally on a stop command; raises MultihostDegradedError when
        the mesh degrades (the caller decides whether to keep serving
        local-only or exit)."""
        if self.runtime.is_leader:
            raise RuntimeError("run_follower() is for processes > 0")
        seq = 0
        inner = self._inner
        while not self._stop.is_set():
            try:
                raw = self.runtime.kv_get(f"cmd/{seq}", poll_timeout_s)
            except Exception:  # noqa: BLE001 — timeout or leader gone
                if self._stop.is_set():
                    return
                if self._local_only or self.runtime.degraded:
                    self._local_only = True
                    raise MultihostDegradedError(
                        self.runtime.degraded_reason or "leader lost"
                    )
                continue
            head, arrays = _decode_cmd(raw)
            seq += 1
            self.runtime.commands = seq
            kind = head["kind"]
            try:
                with self._op_mu:
                    if kind == "stop":
                        return
                    if kind == "refresh":
                        self._follower_refresh(
                            head["cut"],
                            head.get("fp"),
                            boundaries=head.get("boundaries"),
                            bgen=head.get("bgen", 0),
                            shard_results=head.get("sres"),
                        )
                    elif kind == "reform":
                        # membership change at the broadcast cut: tail
                        # there first (the joiner's snapshot+tail
                        # catch-up ends exactly at the cut), verify
                        # state, then re-home on the new member mesh
                        self._follower_tail_to(
                            head["cut"],
                            head.get("fp"),
                            # a reform rebuilds major from records on
                            # every process: tier bookkeeping (which a
                            # joining standby never accumulated) does
                            # not participate in the new shapes
                            content_only=True,
                        )
                        self._apply_reform(tuple(head["members"]))
                    elif kind == "query":
                        if self.is_member:
                            inner.query_padded(
                                head["cls"],
                                arrays["qkeys"],
                                arrays["alt_lo"],
                                arrays["alt_hi"],
                                arrays["t_start"],
                                arrays["t_end"],
                                arrays["now"],
                            )
                    elif kind in self.extra_commands:
                        self.extra_commands[kind](head)
            except MultihostDegradedError as e:
                self.runtime.mark_degraded(str(e))
                raise
            except Exception as e:  # noqa: BLE001 — collective failure
                self.runtime.mark_degraded(
                    f"follower replay failed: {type(e).__name__}"
                )
                raise MultihostDegradedError(str(e)) from e

    @staticmethod
    def _fp_content(fp: Optional[dict]) -> Optional[dict]:
        """The log-content half of a state fingerprint: applied counts
        and per-class record counts, WITHOUT the tier bookkeeping.  A
        standby process tails the log but never folds, so its
        delta/base/shadow split legitimately differs from the members'
        — yet its RECORDS must match exactly, and a reform rebuilds
        every class major from records alone."""
        if fp is None:
            return None
        return {
            "applied": fp.get("applied"),
            "apply_errors": fp.get("apply_errors"),
            "classes": {
                c: v[0] for c, v in fp.get("classes", {}).items()
            },
        }

    def _follower_tail_to(
        self, cut, leader_fp, content_only: bool = False
    ) -> None:
        """Tail to EXACTLY the leader's cut and verify state: both
        processes then hold the identical record prefix, so tier
        decisions, array shapes, and the resulting collective sequence
        all match.  The leader's state fingerprint is checked BEFORE
        any collective is issued — a divergent fold (e.g. a region
        snapshot-reset that jumped past the cut on one side) must
        degrade, never wedge the mesh with mismatched shapes.
        `content_only` compares records, not tier bookkeeping (standby
        catch-up checks and reforms, where every class rebuilds major
        from the record map)."""
        inner = self._inner
        deadline = time.monotonic() + self._cut_timeout_s
        while inner.tail_position() < cut:
            inner.poll_once(limit=cut)
            if inner.tail_position() >= cut:
                break
            if time.monotonic() > deadline:
                raise MultihostDegradedError(
                    f"refresh cut {cut} unreachable (tail at "
                    f"{inner.tail_position()})"
                )
            time.sleep(0.01)
        if inner.tail_position() != cut:
            raise MultihostDegradedError(
                f"tail overshot the refresh cut ({cut} -> "
                f"{inner.tail_position()}): lockstep broken"
            )
        fp = inner.state_fingerprint()
        if content_only:
            fp, leader_fp = (
                self._fp_content(fp), self._fp_content(leader_fp)
            )
        if leader_fp is not None and fp != leader_fp:
            raise MultihostDegradedError(
                f"replica state diverged from leader at cut {cut}: "
                f"{fp} != {leader_fp}"
            )

    def _follower_refresh(
        self, cut, leader_fp, boundaries=None, bgen: int = 0,
        shard_results=None,
    ) -> None:
        """Tail to the cut, adopt the leader's boundary map verbatim
        (the load measurement lives on the leader — followers must
        never plan their own split or the mesh would build mismatched
        shard rows), then fold.  A standby (non-member) process stops
        after the tail: staying caught up IS its snapshot+tail
        readiness for a future join — its record map must match the
        leader's, but its never-folded tier bookkeeping legitimately
        differs, so only log content is compared."""
        self._follower_tail_to(
            cut, leader_fp, content_only=not self.is_member
        )
        if not self.is_member:
            return
        inner = self._inner
        inner.apply_boundaries(boundaries, bgen,
                               shard_results=shard_results)
        inner.refresh(plan=False)
        self._account_refresh_bytes()

    # -- lifecycle / passthrough ----------------------------------------------

    def start(self, interval_s: float = 0.5) -> None:
        """Leader: background pacing loop (poll + broadcast + fold)."""
        self._interval_s = interval_s
        self._inner._interval_s = interval_s

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.sync()
                except Exception:  # noqa: BLE001 — keep pacing alive
                    log.exception("multihost refresh failed")

        self._thread = threading.Thread(
            target=loop, name="multihost-replica", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self.runtime.closing = True
        self._stop.set()
        if (
            self.runtime.is_leader
            and not self._local_only
            and self.runtime.num_processes > 1
        ):
            try:
                with self._op_mu:
                    self._broadcast("stop")
            except Exception:  # noqa: BLE001 — peers may be gone
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._inner.close()

    def fresh(self, bound_s: Optional[float] = None) -> bool:
        if self._local_only:
            return False  # degraded: bounded-staleness contract broken
        if not self.is_member:
            return False  # standby slot: no mesh state to serve from
        return self._inner.fresh(bound_s)

    def staleness_s(self) -> float:
        return self._inner.staleness_s()

    def poll_once(self, limit=None) -> int:
        return self._inner.poll_once(limit=limit)

    def use_load(self, load) -> None:
        """Adopt the store's shared RangeLoad (leader serving path);
        see ShardedReplica.use_load."""
        self._inner.use_load(load)

    def stats(self) -> dict:
        out = self._inner.stats()
        out.update(self.runtime.stats())
        out["dss_multihost_local_only"] = int(self._local_only)
        out["dss_multihost_members"] = len(self._members)
        out["dss_multihost_is_member"] = int(self.is_member)
        return out
