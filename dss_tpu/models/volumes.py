"""4D volumes: the universal spatial-temporal extent type.

Mirrors /root/reference/pkg/models/geo.go: Volume4D/Volume3D with a
Geometry footprint (polygon / circle / precomputed cell set), and
UnionVolumes4D which takes the envelope in time and altitude and the
union of coverings in space (geo.go:124-190).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import List, Optional

import numpy as np

from dss_tpu.geo import covering as geo_covering


@dataclass
class LatLngPoint:
    lat: float
    lng: float


class Geometry:
    """A footprint that can compute its level-13 cell covering."""

    def calculate_covering(self) -> np.ndarray:  # uint64 cell ids
        raise NotImplementedError


@dataclass
class GeoPolygon(Geometry):
    vertices: List[LatLngPoint]

    def calculate_covering(self) -> np.ndarray:
        return geo_covering.covering_polygon(
            [(v.lat, v.lng) for v in self.vertices]
        )


@dataclass
class GeoCircle(Geometry):
    center: LatLngPoint
    radius_meter: float

    def calculate_covering(self) -> np.ndarray:
        return geo_covering.covering_circle(
            self.center.lat, self.center.lng, self.radius_meter
        )


@dataclass
class GeoCellUnion(Geometry):
    """A precomputed covering (reference precomputedCellGeometry)."""

    cells: np.ndarray  # uint64

    def calculate_covering(self) -> np.ndarray:
        return np.asarray(self.cells, dtype=np.uint64)


@dataclass
class Volume3D:
    footprint: Optional[Geometry] = None
    altitude_lo: Optional[float] = None
    altitude_hi: Optional[float] = None

    def calculate_covering(self) -> np.ndarray:
        if self.footprint is None:
            raise ValueError("missing footprint")
        # canonical (sorted, deduped) at ingress — one covering form
        # shared by read-cache keying and the DAR pack path
        return geo_covering.canonical_cells(
            self.footprint.calculate_covering()
        )


@dataclass
class Volume4D:
    spatial_volume: Optional[Volume3D] = None
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None

    def calculate_spatial_covering(self) -> np.ndarray:
        if self.spatial_volume is None:
            raise ValueError("missing spatial volume")
        return self.spatial_volume.calculate_covering()


def union_volumes_4d(volumes: List[Volume4D]) -> Volume4D:
    """Envelope union: earliest start, latest end, min alt-lo, max alt-hi,
    union of coverings (reference pkg/models/geo.go:124-190)."""
    result = Volume4D()
    merged_cells: set[int] = set()
    have_footprint = False
    for volume in volumes:
        if volume.end_time is not None:
            if result.end_time is None or volume.end_time > result.end_time:
                result.end_time = volume.end_time
        if volume.start_time is not None:
            if result.start_time is None or volume.start_time < result.start_time:
                result.start_time = volume.start_time
        sv = volume.spatial_volume
        if sv is not None:
            if result.spatial_volume is None:
                result.spatial_volume = Volume3D()
            rsv = result.spatial_volume
            if sv.altitude_lo is not None:
                if rsv.altitude_lo is None or sv.altitude_lo < rsv.altitude_lo:
                    rsv.altitude_lo = sv.altitude_lo
            if sv.altitude_hi is not None:
                if rsv.altitude_hi is None or sv.altitude_hi > rsv.altitude_hi:
                    rsv.altitude_hi = sv.altitude_hi
            if sv.footprint is not None:
                cells = sv.footprint.calculate_covering()
                merged_cells.update(int(c) for c in cells)
                have_footprint = True
    if have_footprint:
        result.spatial_volume.footprint = GeoCellUnion(
            np.array(sorted(merged_cells), dtype=np.uint64)
        )
    return result
