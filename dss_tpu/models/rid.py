"""RID entity models: IdentificationServiceArea + Subscription.

Mirrors /root/reference/pkg/rid/models/identification_service_area.go
and subscriptions.go: 4D extents with level-13 cell coverings, base-32
commit-timestamp versions, and the time-range adjustment rules
(5-minute clock skew for starts, 24h max subscription duration).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import List, Optional

import numpy as np

from dss_tpu import errors
from dss_tpu.models.core import Owner, Version
from dss_tpu.models.volumes import Volume4D

MAX_SUBSCRIPTION_DURATION = timedelta(hours=24)
MAX_CLOCK_SKEW = timedelta(minutes=5)


@dataclass
class IdentificationServiceArea:
    id: str
    owner: Owner
    url: str = ""
    cells: np.ndarray = field(default_factory=lambda: np.array([], np.uint64))
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    version: Optional[Version] = None
    altitude_hi: Optional[float] = None
    altitude_lo: Optional[float] = None

    def set_extents(self, extents: Volume4D) -> None:
        """Validation + covering, per identification_service_area.go:71-104."""
        if extents is None:
            return
        self.start_time = extents.start_time
        self.end_time = extents.end_time
        if extents.spatial_volume is None:
            raise errors.bad_request("missing required spatial_volume")
        sv = extents.spatial_volume
        self.altitude_hi = sv.altitude_hi
        self.altitude_lo = sv.altitude_lo
        if sv.footprint is None:
            raise errors.bad_request("spatial_volume missing required footprint")
        self.cells = sv.footprint.calculate_covering()

    def adjust_time_range(
        self, now: datetime, old: "IdentificationServiceArea | None"
    ) -> None:
        """identification_service_area.go:108-140."""
        if self.start_time is None:
            self.start_time = now if old is None else old.start_time
        else:
            if now - self.start_time > MAX_CLOCK_SKEW:
                raise errors.bad_request(
                    "IdentificationServiceArea time_start must not be in the past"
                )
        if self.end_time is None and old is not None:
            self.end_time = old.end_time
        if self.end_time is None:
            raise errors.bad_request(
                "IdentificationServiceArea must have an time_end"
            )
        if self.end_time < self.start_time:
            raise errors.bad_request(
                "IdentificationServiceArea time_end must be after time_start"
            )


@dataclass
class Subscription:
    id: str
    owner: Owner
    url: str = ""
    notification_index: int = 0
    cells: np.ndarray = field(default_factory=lambda: np.array([], np.uint64))
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    version: Optional[Version] = None
    altitude_hi: Optional[float] = None
    altitude_lo: Optional[float] = None

    def set_extents(self, extents: Volume4D) -> None:
        """subscriptions.go:98-131."""
        if extents is None:
            return
        self.start_time = extents.start_time
        self.end_time = extents.end_time
        if extents.spatial_volume is None:
            raise errors.bad_request("missing required spatial_volume")
        sv = extents.spatial_volume
        self.altitude_hi = sv.altitude_hi
        self.altitude_lo = sv.altitude_lo
        if sv.footprint is None:
            raise errors.bad_request("spatial_volume missing required footprint")
        self.cells = sv.footprint.calculate_covering()

    def adjust_time_range(self, now: datetime, old: "Subscription | None") -> None:
        """subscriptions.go:135-173: clock-skew gate, defaulting rules and
        the 24h cap."""
        if self.start_time is None:
            self.start_time = now if old is None else old.start_time
        else:
            if now - self.start_time > MAX_CLOCK_SKEW:
                raise errors.bad_request(
                    "subscription time_start must not be in the past"
                )
        if self.end_time is None and old is not None:
            self.end_time = old.end_time
        if self.end_time is None:
            self.end_time = self.start_time + MAX_SUBSCRIPTION_DURATION
        if self.end_time < self.start_time:
            raise errors.bad_request(
                "subscription time_end must be after time_start"
            )
        if self.end_time - self.start_time > MAX_SUBSCRIPTION_DURATION:
            raise errors.bad_request("subscription window exceeds 24 hours")
