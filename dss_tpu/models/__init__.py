"""Shared value types: IDs, owners, versions, OVNs, 4D volumes."""

from dss_tpu.models.core import (  # noqa: F401
    ID,
    Owner,
    Version,
    OVN,
    new_ovn_from_time,
    validate_uss_base_url,
    validate_uuid,
)
from dss_tpu.models.volumes import (  # noqa: F401
    LatLngPoint,
    GeoPolygon,
    GeoCircle,
    GeoCellUnion,
    Volume3D,
    Volume4D,
    union_volumes_4d,
)
