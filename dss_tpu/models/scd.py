"""SCD entity models: Operation references + Subscriptions + Constraints.

Mirrors /root/reference/pkg/scd/models/operations.go and
subscriptions.go: int32 fencing versions, OVNs, operation states, and
the subscription time-range rules (shared with RID).  Constraint
references go BEYOND the reference (constraints_handler.go:12-30 stubs
them): same int32 fencing version + OVN discipline as operations, no
state machine (a constraint is authoritative airspace data, not a
negotiated intent).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import List, Optional

import numpy as np

from dss_tpu import errors
from dss_tpu.models.core import OVN, Owner

MAX_SUBSCRIPTION_DURATION = timedelta(hours=24)
MAX_CLOCK_SKEW = timedelta(minutes=5)


class OperationState:
    UNKNOWN = ""
    ACCEPTED = "Accepted"
    ACTIVATED = "Activated"
    NON_CONFORMING = "NonConforming"
    CONTINGENT = "Contingent"
    ENDED = "Ended"

    ALL = (ACCEPTED, ACTIVATED, NON_CONFORMING, CONTINGENT, ENDED)
    # States whose upserts require the full OVN key
    # (pkg/scd/store/cockroach/operations.go:335-347).
    REQUIRES_KEY = (ACCEPTED, ACTIVATED)


@dataclass
class Operation:
    id: str
    owner: Owner
    version: int = 0  # int32 fencing token (scd/models/models.go:17-22)
    ovn: OVN = ""
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    altitude_lower: Optional[float] = None
    altitude_upper: Optional[float] = None
    uss_base_url: str = ""
    state: str = OperationState.UNKNOWN
    cells: np.ndarray = field(default_factory=lambda: np.array([], np.uint64))
    subscription_id: str = ""
    # The op's USS consumes constraint updates (its subscription has
    # notify_for_constraints) and therefore participates in
    # constraint-aware deconfliction: upserts in REQUIRES_KEY states
    # must present the OVN of every intersecting constraint, and the
    # AirspaceConflict payload lists missing constraints alongside
    # missing operations.  Ops that never declared awareness keep the
    # reference's op-only key check.
    constraint_aware: bool = False

    def validate_time_range(self) -> None:
        """operations.go:78-94."""
        if self.start_time is None:
            raise errors.bad_request("Operation must have an time_start")
        if self.end_time is None:
            raise errors.bad_request("Operation must have an time_end")
        if self.end_time < self.start_time:
            raise errors.bad_request(
                "Operation time_end must be after time_start"
            )


@dataclass
class Constraint:
    """Constraint reference: an authority-published airspace restriction
    (mass-event closure, emergency corridor, geofence).  Carries the
    same int32 fencing version + OVN pair as Operation; unlike
    operations there is no state machine and upserts never require an
    OVN key — constraints deconflict operations, nothing deconflicts a
    constraint."""

    id: str
    owner: Owner
    version: int = 0  # int32 fencing token, same rules as Operation
    ovn: OVN = ""
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    altitude_lower: Optional[float] = None
    altitude_upper: Optional[float] = None
    uss_base_url: str = ""
    cells: np.ndarray = field(default_factory=lambda: np.array([], np.uint64))

    def validate_time_range(self) -> None:
        if self.start_time is None:
            raise errors.bad_request("Constraint must have a time_start")
        if self.end_time is None:
            raise errors.bad_request("Constraint must have a time_end")
        if self.end_time < self.start_time:
            raise errors.bad_request(
                "Constraint time_end must be after time_start"
            )


@dataclass
class Subscription:
    id: str
    owner: Owner
    version: int = 0
    notification_index: int = 0
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    altitude_hi: Optional[float] = None
    altitude_lo: Optional[float] = None
    base_url: str = ""
    notify_for_operations: bool = False
    notify_for_constraints: bool = False
    implicit_subscription: bool = False
    dependent_operations: List[str] = field(default_factory=list)
    cells: np.ndarray = field(default_factory=lambda: np.array([], np.uint64))

    def adjust_time_range(self, now: datetime, old: "Subscription | None") -> None:
        """scd/models/subscriptions.go:90-128 (same rules as RID)."""
        if self.start_time is None:
            self.start_time = now if old is None else old.start_time
        else:
            if now - self.start_time > MAX_CLOCK_SKEW:
                raise errors.bad_request(
                    "subscription time_start must not be in the past"
                )
        if self.end_time is None and old is not None:
            self.end_time = old.end_time
        if self.end_time is None:
            self.end_time = self.start_time + MAX_SUBSCRIPTION_DURATION
        if self.end_time < self.start_time:
            raise errors.bad_request(
                "subscription time_end must be after time_start"
            )
        if self.end_time - self.start_time > MAX_SUBSCRIPTION_DURATION:
            raise errors.bad_request("subscription window exceeds 24 hours")
