"""Core value types.

Mirrors /root/reference/pkg/models/models.go (ID, Owner, the base-32
commit-timestamp Version used as an RMW fencing token) and
pkg/scd/models/models.go (the opaque OVN and https-only USS base URL
validation).
"""

from __future__ import annotations

import base64
import hashlib
import re
from datetime import datetime, timezone

from dss_tpu import errors
from dss_tpu.clock import from_nanos, to_nanos

# Go strconv base-32 digit set (FormatUint/ParseUint with base=32).
_BASE32_DIGITS = "0123456789abcdefghijklmnopqrstuv"
_BASE32_INDEX = {c: i for i, c in enumerate(_BASE32_DIGITS)}

_UUID_RE = re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$"
)

ID = str
Owner = str


def validate_uuid(id_str: str) -> None:
    """Request-level UUID validation (reference pkg/validations)."""
    if not _UUID_RE.match(id_str or ""):
        raise errors.bad_request(f"invalid uuid: {id_str!r}")


def _format_base32(n: int) -> str:
    if n == 0:
        return "0"
    out = []
    while n:
        out.append(_BASE32_DIGITS[n & 31])
        n >>= 5
    return "".join(reversed(out))


def _parse_base32(s: str) -> int:
    n = 0
    for c in s:
        try:
            n = (n << 5) | _BASE32_INDEX[c]
        except KeyError:
            raise ValueError(f"invalid base-32 digit {c!r}")
    if n >= 1 << 64:
        raise ValueError("value out of uint64 range")
    return n


class Version:
    """RID version: a base-32-encoded commit timestamp (nanoseconds),
    used as an RMW fencing token (reference pkg/models/models.go:40-61)."""

    __slots__ = ("_nanos", "_s")

    def __init__(self, nanos: int, s: str):
        self._nanos = nanos
        self._s = s

    @classmethod
    def from_string(cls, s: str) -> "Version":
        if not s:
            raise ValueError("requires version string")
        return cls(_parse_base32(s), s)

    @classmethod
    def from_time(cls, t: datetime) -> "Version":
        nanos = to_nanos(t)
        return cls(nanos, _format_base32(nanos))

    @property
    def empty(self) -> bool:
        return self._nanos == 0

    def matches(self, other: "Version | None") -> bool:
        if other is None:
            return False
        return self._s == other._s

    def to_timestamp(self) -> datetime:
        return from_nanos(self._nanos)

    def __str__(self) -> str:
        return self._s

    def __repr__(self) -> str:
        return f"Version({self._s})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Version) and self._s == other._s

    def __hash__(self):
        return hash(self._s)


def version_matches(v: Version | None, w: Version | None) -> bool:
    if v is None or w is None:
        return False
    return v.matches(w)


OVN = str


def new_ovn_from_time(t: datetime, salt: str) -> OVN:
    """OVN = base64(sha256(salt + RFC3339(t))) — reference
    pkg/scd/models/models.go:35-40.  RFC3339 here matches Go's format:
    seconds precision, 'Z' for UTC."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    t_utc = t.astimezone(timezone.utc)
    stamp = t_utc.strftime("%Y-%m-%dT%H:%M:%SZ")
    digest = hashlib.sha256((salt + stamp).encode()).digest()
    return base64.b64encode(digest).decode()


def ovn_valid(ovn: str) -> bool:
    return 16 <= len(ovn) <= 128


def validate_uss_base_url(url: str) -> None:
    """https-only (reference pkg/scd/models/models.go:67-83)."""
    m = re.match(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://", url or "")
    scheme = m.group(1).lower() if m else ""
    if scheme == "https":
        return
    if scheme == "http":
        raise ValueError("uss_base_url in new_subscription must use TLS")
    raise ValueError("uss_base_url must support https scheme")
