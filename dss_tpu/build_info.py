"""Build information (the pkg/build analog, info.go:4-25).

The reference injects commit/time/host via `-ldflags -X`; the Python
analog reads DSS_BUILD_* env vars (set by the Dockerfile / CI at image
build) and falls back to asking git at runtime.  Logged at server
startup and exported as an info gauge on /metrics."""

from __future__ import annotations

import os
import socket
import subprocess
import time
from functools import lru_cache


@lru_cache(maxsize=1)
def build_info() -> dict:
    commit = os.environ.get("DSS_BUILD_COMMIT", "")
    built_at = os.environ.get("DSS_BUILD_TIME", "")
    if not commit:
        # dev-checkout fallback only: the .git must sit right next to
        # the package, or `git rev-parse` would walk up and report
        # whatever unrelated repo encloses a pip-installed venv
        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(pkg_dir)
        if os.path.exists(os.path.join(repo_root, ".git")):  # dir or worktree file
            try:
                commit = subprocess.run(
                    ["git", "-C", repo_root, "rev-parse", "--short", "HEAD"],
                    capture_output=True,
                    text=True,
                    timeout=5,
                ).stdout.strip() or "unknown"
            except (OSError, subprocess.SubprocessError):
                commit = "unknown"
        else:
            commit = "unknown"
    return {
        "commit": commit,
        "build_time": built_at or "unknown",
        "host": socket.gethostname(),
        "started_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
    }
