"""Shared Record -> packed-array layout for DAR snapshots.

Single source of truth for how host Records become the device
EntityTable columns + sorted postings, used by both the single-chip
DarTable rebuild (dss_tpu.dar.snapshot) and the multi-chip read
replica (dss_tpu.parallel.sharded.ShardedDar), so the two can never
disagree on sentinel conventions or candidate-run capacity.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from dss_tpu.dar.oracle import Record
from dss_tpu.ops.conflict import INT32_MAX, NO_TIME_HI, NO_TIME_LO


def pow2_at_least(n: int, lo: int = 8) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


class PackedRecords(NamedTuple):
    """Numpy (host) form of the device layout.  Row `capacity` of the
    entity columns is the inactive sentinel all padded gathers hit."""

    alt_lo: np.ndarray  # f32[capacity+1]
    alt_hi: np.ndarray  # f32[capacity+1]
    t_start: np.ndarray  # i64[capacity+1]
    t_end: np.ndarray  # i64[capacity+1]
    active: np.ndarray  # bool[capacity+1]
    owner: np.ndarray  # i32[capacity+1]
    post_key: np.ndarray  # i32[P] sorted, pad INT32_MAX
    post_ent: np.ndarray  # i32[P], pad = capacity (sentinel)
    capacity: int  # entity slots (sentinel excluded)
    base_cap: int  # max postings run per key, rounded up to pow2
    n_postings: int  # live postings before padding


def pack_records(
    records: List[Record],
    *,
    capacity: int = None,
    pad_postings: bool = True,
) -> PackedRecords:
    """Pack Records slot-by-index into entity columns + sorted postings."""
    n = len(records)
    if capacity is None:
        capacity = max(n, 1)
    if capacity < n:
        raise ValueError(f"capacity {capacity} < {n} records")

    alt_lo = np.full(capacity + 1, np.inf, np.float32)
    alt_hi = np.full(capacity + 1, -np.inf, np.float32)
    t_start = np.full(capacity + 1, NO_TIME_HI, np.int64)
    t_end = np.full(capacity + 1, NO_TIME_LO, np.int64)
    active = np.zeros(capacity + 1, np.bool_)
    owner = np.full(capacity + 1, -1, np.int32)

    total = sum(len(r.keys) for r in records)
    pk = np.empty(total, np.int32)
    pe = np.empty(total, np.int32)
    ofs = 0
    for slot, rec in enumerate(records):
        alt_lo[slot] = rec.alt_lo
        alt_hi[slot] = rec.alt_hi
        t_start[slot] = rec.t_start
        t_end[slot] = rec.t_end
        active[slot] = True
        owner[slot] = rec.owner_id
        pk[ofs : ofs + len(rec.keys)] = rec.keys
        pe[ofs : ofs + len(rec.keys)] = slot
        ofs += len(rec.keys)
    order = np.argsort(pk, kind="stable")
    pk, pe = pk[order], pe[order]
    if total:
        _, counts = np.unique(pk, return_counts=True)
        base_cap = pow2_at_least(int(counts.max()), lo=8)
    else:
        base_cap = 8
    if pad_postings:
        pad = pow2_at_least(max(total, 8), lo=8)
        post_key = np.full(pad, INT32_MAX, np.int32)
        post_ent = np.full(pad, capacity, np.int32)
        post_key[:total] = pk
        post_ent[:total] = pe
    else:
        post_key, post_ent = pk, pe
    return PackedRecords(
        alt_lo=alt_lo,
        alt_hi=alt_hi,
        t_start=t_start,
        t_end=t_end,
        active=active,
        owner=owner,
        post_key=post_key,
        post_ent=post_ent,
        capacity=capacity,
        base_cap=base_cap,
        n_postings=total,
    )
