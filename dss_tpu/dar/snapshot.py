"""DarTable: an HBM-resident spatial index for one entity class.

The device-side replacement for the reference's CockroachDB cell index
(GIN array index for RID, pkg/rid/cockroach/store.go:121-152; join
tables for SCD, pkg/scd/store/cockroach/store.go:92-151).  One DarTable
holds one entity class (ISAs, RID subscriptions, SCD operations, SCD
subscriptions).

Host side keeps the authoritative Record per slot; the device holds the
packed EntityTable + sorted base Postings + a small sorted delta
overlay.  Writes are synchronous: a new slot is allocated per entity
version (append-only; the old slot is tombstoned), its postings go to
the delta, and the delta is merged into the base when full.  Queries
run the batched JAX kernel; a result-width overflow falls back to the
exact numpy oracle.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dss_tpu.dar import oracle
from dss_tpu.dar.oracle import Record
from dss_tpu.dar.pack import pack_records, pow2_at_least
from dss_tpu.ops.fastpath import FastTable
from dss_tpu.ops.conflict import (
    INT32_MAX,
    NO_TIME_HI,
    NO_TIME_LO,
    EntityTable,
    Postings,
    QuerySpec,
    conflict_query_batch,
    max_count_per_cell as _kernel_max_count,
)

_QUERY_BUCKETS = (64, 256, 1024, 4096)
_DELTA_PER_KEY_CAP = 64


def _bucket(n: int, buckets=_QUERY_BUCKETS) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"query too wide: {n} cells (max {buckets[-1]})")


@jax.jit
def _set_entity_row(ents: EntityTable, slot, alt_lo, alt_hi, t_start, t_end, active, owner):
    return EntityTable(
        alt_lo=ents.alt_lo.at[slot].set(alt_lo),
        alt_hi=ents.alt_hi.at[slot].set(alt_hi),
        t_start=ents.t_start.at[slot].set(t_start),
        t_end=ents.t_end.at[slot].set(t_end),
        active=ents.active.at[slot].set(active),
        owner=ents.owner.at[slot].set(owner),
    )


@jax.jit
def _tombstone_row(ents: EntityTable, slot):
    return EntityTable(
        alt_lo=ents.alt_lo,
        alt_hi=ents.alt_hi,
        t_start=ents.t_start,
        t_end=ents.t_end,
        active=ents.active.at[slot].set(False),
        owner=ents.owner,
    )


class DarTable:
    """Thread-safe HBM spatial index for one entity class."""

    def __init__(
        self,
        *,
        max_results: int = 512,
        delta_capacity: int = 8192,
        entity_capacity: int = 1024,
    ):
        self._lock = threading.RLock()
        self.max_results = max_results
        self.delta_capacity = delta_capacity

        # host authoritative state
        self.records: Dict[int, Record] = {}  # slot -> live record
        self.slot_of: Dict[str, int] = {}  # entity_id -> live slot
        self._next_slot = 0
        self._entity_capacity = entity_capacity

        # host mirrors of postings
        self._base_key = np.full(0, INT32_MAX, np.int32)
        self._base_ent = np.full(0, 0, np.int32)
        self.base_cap = 8
        self._delta_key = np.full(delta_capacity, INT32_MAX, np.int32)
        self._delta_ent = np.zeros(delta_capacity, np.int32)
        self._delta_count = 0

        # batch fast path (built lazily from the last rebuild)
        self._host_cols = None
        self._fast = None

        # device state
        self._ents = self._empty_entity_table(entity_capacity)
        self._base = Postings(
            post_key=jnp.full((8,), INT32_MAX, jnp.int32),
            post_ent=jnp.full((8,), entity_capacity, jnp.int32),
        )
        self._push_delta()

    # -- construction helpers ------------------------------------------------

    def _empty_entity_table(self, capacity: int) -> EntityTable:
        return EntityTable(
            alt_lo=jnp.full((capacity + 1,), np.inf, jnp.float32),
            alt_hi=jnp.full((capacity + 1,), -np.inf, jnp.float32),
            t_start=jnp.full((capacity + 1,), NO_TIME_HI, jnp.int64),
            t_end=jnp.full((capacity + 1,), NO_TIME_LO, jnp.int64),
            active=jnp.zeros((capacity + 1,), jnp.bool_),
            owner=jnp.full((capacity + 1,), -1, jnp.int32),
        )

    def _push_delta(self):
        self._delta = Postings(
            post_key=jnp.asarray(self._delta_key),
            post_ent=jnp.asarray(
                np.where(
                    self._delta_key == INT32_MAX,
                    self._entity_capacity,
                    self._delta_ent,
                ).astype(np.int32)
            ),
        )

    # -- write path ----------------------------------------------------------

    def upsert(
        self,
        entity_id: str,
        keys: np.ndarray,
        alt_lo: Optional[float],
        alt_hi: Optional[float],
        t_start: int,
        t_end: int,
        owner_id: int,
    ) -> None:
        """Insert or replace an entity. keys are int32 DAR keys."""
        keys = np.unique(np.asarray(keys, dtype=np.int32))
        with self._lock:
            self._fast = None
            old_slot = self.slot_of.pop(entity_id, None)
            if old_slot is not None:
                del self.records[old_slot]
                self._ents = _tombstone_row(self._ents, old_slot)
            if (
                self._next_slot >= self._entity_capacity
                or self._delta_count + len(keys) > self.delta_capacity
            ):
                self._rebuild_locked(
                    pending=Record(
                        entity_id=entity_id,
                        keys=keys,
                        alt_lo=-np.inf if alt_lo is None else float(alt_lo),
                        alt_hi=np.inf if alt_hi is None else float(alt_hi),
                        t_start=int(t_start),
                        t_end=int(t_end),
                        owner_id=int(owner_id),
                    )
                )
                return
            slot = self._next_slot
            self._next_slot += 1
            rec = Record(
                entity_id=entity_id,
                keys=keys,
                alt_lo=-np.inf if alt_lo is None else float(alt_lo),
                alt_hi=np.inf if alt_hi is None else float(alt_hi),
                t_start=int(t_start),
                t_end=int(t_end),
                owner_id=int(owner_id),
            )
            self.records[slot] = rec
            self.slot_of[entity_id] = slot
            self._ents = _set_entity_row(
                self._ents,
                slot,
                jnp.float32(rec.alt_lo),
                jnp.float32(rec.alt_hi),
                jnp.int64(rec.t_start),
                jnp.int64(rec.t_end),
                True,
                jnp.int32(rec.owner_id),
            )
            # append postings into the sorted delta
            n = self._delta_count
            self._delta_key[n : n + len(keys)] = keys
            self._delta_ent[n : n + len(keys)] = slot
            self._delta_count = n + len(keys)
            order = np.argsort(self._delta_key[: self._delta_count], kind="stable")
            self._delta_key[: self._delta_count] = self._delta_key[order]
            self._delta_ent[: self._delta_count] = self._delta_ent[order]
            # per-key run cap: if exceeded, fold delta into base
            if self._delta_count:
                dk = self._delta_key[: self._delta_count]
                _, counts = np.unique(dk, return_counts=True)
                if counts.max(initial=0) > _DELTA_PER_KEY_CAP:
                    self._rebuild_locked()
                    return
            self._push_delta()

    def remove(self, entity_id: str) -> bool:
        with self._lock:
            slot = self.slot_of.pop(entity_id, None)
            if slot is None:
                return False
            del self.records[slot]
            self._ents = _tombstone_row(self._ents, slot)
            if self._fast is not None:
                # no rebuild needed: flip the FastTable's host live bit;
                # collect() drops the slot during result assembly (the
                # device columns are untouched until the next rebuild)
                self._fast[0].mark_dead(slot)
            return True

    def _rebuild_locked(self, pending: Optional[Record] = None):
        """Compact slots and rebuild base postings from live records."""
        live = list(self.records.values())
        if pending is not None:
            live.append(pending)
        capacity = pow2_at_least(max(len(live), 1) * 2, lo=1024)
        self._entity_capacity = capacity

        self.records = dict(enumerate(live))
        self.slot_of = {rec.entity_id: slot for slot, rec in self.records.items()}
        self._next_slot = len(live)

        packed = pack_records(live, capacity=capacity)
        self.base_cap = packed.base_cap
        self._base_key = packed.post_key
        self._base_ent = packed.post_ent
        self._host_cols = packed
        self._fast = None

        self._ents = EntityTable(
            alt_lo=jnp.asarray(packed.alt_lo),
            alt_hi=jnp.asarray(packed.alt_hi),
            t_start=jnp.asarray(packed.t_start),
            t_end=jnp.asarray(packed.t_end),
            active=jnp.asarray(packed.active),
            owner=jnp.asarray(packed.owner),
        )
        self._base = Postings(
            post_key=jnp.asarray(packed.post_key),
            post_ent=jnp.asarray(packed.post_ent),
        )
        self._delta_key[:] = INT32_MAX
        self._delta_ent[:] = 0
        self._delta_count = 0
        self._push_delta()

    def rebuild(self):
        with self._lock:
            self._rebuild_locked()

    def bulk_load(self, records) -> None:
        """Replace the table contents with `records` (list of Record) in
        one rebuild — the snapshot-refresh path (WAL replay / bench
        population) that skips per-entity delta churn.  Duplicate
        entity_ids keep the last occurrence (WAL replay order)."""
        with self._lock:
            by_id = {r.entity_id: r for r in records}
            self.records = dict(enumerate(by_id.values()))
            self._rebuild_locked()

    # -- read path -----------------------------------------------------------

    def _pad_keys(self, keys: np.ndarray) -> np.ndarray:
        keys = np.unique(np.asarray(keys, dtype=np.int32))
        q = _bucket(max(len(keys), 1))
        out = np.full(q, -1, np.int32)
        out[: len(keys)] = keys
        return out

    def query(
        self,
        keys: np.ndarray,
        alt_lo: Optional[float] = None,
        alt_hi: Optional[float] = None,
        t_start: Optional[int] = None,
        t_end: Optional[int] = None,
        *,
        now: int,
        owner_id: Optional[int] = None,
    ) -> List[str]:
        """Entity ids intersecting the query volume (live at/after now)."""
        with self._lock:
            if len(np.asarray(keys).ravel()) == 0:
                return []
            padded = self._pad_keys(keys)[None, :]
            spec = QuerySpec(
                keys=jnp.asarray(padded),
                alt_lo=jnp.asarray(
                    [np.float32(-np.inf) if alt_lo is None else np.float32(alt_lo)]
                ),
                alt_hi=jnp.asarray(
                    [np.float32(np.inf) if alt_hi is None else np.float32(alt_hi)]
                ),
                t_start=jnp.asarray(
                    [NO_TIME_LO if t_start is None else np.int64(t_start)]
                ),
                t_end=jnp.asarray(
                    [NO_TIME_HI if t_end is None else np.int64(t_end)]
                ),
            )
            owner_arr = (
                jnp.asarray([np.int32(owner_id)]) if owner_id is not None else None
            )
            slots, overflow = conflict_query_batch(
                self._base,
                self._delta,
                self._ents,
                spec,
                jnp.int64(now),
                owner_arr,
                base_cap=self.base_cap,
                delta_cap=_DELTA_PER_KEY_CAP,
                max_results=self.max_results,
                with_owner=owner_id is not None,
            )
            if bool(overflow[0]):
                # exact fallback on the host
                slot_list = oracle.search(
                    self.records,
                    np.asarray(keys),
                    alt_lo,
                    alt_hi,
                    t_start,
                    t_end,
                    now,
                    owner_id,
                )
            else:
                arr = np.asarray(slots[0])
                slot_list = [int(s) for s in arr[arr != INT32_MAX]]
            out = []
            for s in slot_list:
                rec = self.records.get(s)
                if rec is not None:
                    out.append(rec.entity_id)
            return out

    def _ensure_fast_locked(self):
        """Build (or reuse) the batch fast path from the current base.
        Folds any pending delta with a rebuild first.  Returns
        (FastTable, snapshot dict) where the snapshot carries immutable
        per-slot arrays + the slot->entity_id list, so queries can
        assemble results without holding the lock (a concurrent upsert
        mutates self.records in place)."""
        if self._fast is None or self._delta_count:
            self._rebuild_locked()
            cols = self._host_cols
            n = cols.n_postings
            pe = self._base_ent[:n]
            ids = [None] * (cols.capacity + 1)
            for slot, rec in self.records.items():
                ids[slot] = rec.entity_id
            ft = FastTable(
                self._base_key[:n],
                pe,
                cols.alt_lo[pe],
                cols.alt_hi[pe],
                cols.t_start[pe],
                cols.t_end[pe],
                cols.active[pe],
                slot_exact={
                    "alt_lo": cols.alt_lo,
                    "alt_hi": cols.alt_hi,
                    "t0": cols.t_start,
                    "t1": cols.t_end,
                    "live": cols.active.copy(),
                },
            )
            # owner + ids are the only per-slot columns the read path
            # still needs host-side; exact filtering happens on device
            # (FastTable.slot_exact carries the fallback copies)
            self._fast = (ft, {"owner": cols.owner, "ids": ids})
        return self._fast

    def query_many(
        self,
        keys_list,  # sequence of int32 arrays (DAR keys per query)
        alt_lo: np.ndarray,  # f32[B], -inf unbounded
        alt_hi: np.ndarray,
        t_start: np.ndarray,  # i64[B] ns, NO_TIME_LO unbounded
        t_end: np.ndarray,
        *,
        now: int,
        owner_ids: Optional[np.ndarray] = None,  # i32[B], -1 = no filter
    ) -> List[List[str]]:
        """Batched search via the fast path (host range lookup + dense
        device filter + exact host re-check).  Exact same result sets
        as query(); built for high-QPS read service and the bench."""
        with self._lock:
            ft, snap = self._ensure_fast_locked()
        b = len(keys_list)
        if b == 0:
            return []
        width = max(16, pow2_at_least(max(len(k) for k in keys_list), lo=16))
        qkeys = np.full((b, width), -1, np.int32)
        for i, k in enumerate(keys_list):
            u = np.unique(np.asarray(k, np.int32))
            qkeys[i, : len(u)] = u
        qidx, slots = ft.query_fused(
            qkeys, alt_lo, alt_hi, t_start, t_end, now=now
        )
        if owner_ids is not None:
            keep = (owner_ids[qidx] < 0) | (
                snap["owner"][slots] == owner_ids[qidx]
            )
            qidx, slots = qidx[keep], slots[keep]
        # dedup (an entity can hit via several cells) and assemble ids
        pairs = np.unique(qidx * np.int64(2**32) + slots)
        ids = snap["ids"]
        out = [[] for _ in range(b)]
        for p in pairs:
            i, s = int(p >> 32), int(p & 0xFFFFFFFF)
            eid = ids[s] if s < len(ids) else None
            if eid is not None:
                out[i].append(eid)
        return out

    def max_owner_count(self, keys: np.ndarray, owner_id: int, *, now: int) -> int:
        """DSS0030 quota metric: max per-cell count of live entities owned
        by owner_id over the query cells."""
        with self._lock:
            if len(np.asarray(keys).ravel()) == 0:
                return 0
            padded = self._pad_keys(keys)
            val = _kernel_max_count(
                self._base,
                self._delta,
                self._ents,
                jnp.asarray(padded),
                jnp.int64(now),
                jnp.int32(owner_id),
                base_cap=self.base_cap,
                delta_cap=_DELTA_PER_KEY_CAP,
            )
            return int(val)

    # -- introspection (bench / graft entry) ----------------------------------

    @property
    def device_state(self):
        with self._lock:
            return self._base, self._delta, self._ents

    def stats(self) -> dict:
        with self._lock:
            return {
                "live_records": len(self.records),
                "entity_capacity": self._entity_capacity,
                "base_postings": int((self._base_key != INT32_MAX).sum()),
                "delta_postings": self._delta_count,
                "base_cap": self.base_cap,
            }
