"""DarTable: an HBM-resident spatial index for one entity class.

The device-side replacement for the reference's CockroachDB cell index
(GIN array index for RID, pkg/rid/cockroach/store.go:121-152; join
tables for SCD, pkg/scd/store/cockroach/store.go:92-151).  One DarTable
holds one entity class (ISAs, RID subscriptions, SCD operations, SCD
subscriptions).

LSM-shaped for lock-free reads (the MVCC-concurrency analog of CRDB
snapshot reads).  ALL state a reader touches is published as ONE
immutable `_State` object, swapped atomically by reference assignment:

  - `snap`: the device snapshot — a FastTable (resident packed postings
    + exact attribute columns, dss_tpu.ops.fastpath) plus host-side
    slot->id/owner maps.  Device/host arrays inside a snapshot are
    never mutated after publication.
  - `overlay`: records written since the snapshot build, packed into
    small sorted numpy postings for a vectorized host scan.  Updated
    O(Δ) per write: the new record's postings are spliced into copies
    of the packed arrays (contiguous memcpy), never re-packed from the
    record dicts (which cost O(overlay) python per write).
  - `dead`: snapshot slots superseded or removed since the build;
    readers drop them after the fused query.  (The FastTable's own
    mark_dead is NOT used here — mutating the shared live column would
    race in-flight readers that captured an older overlay.)

A reader therefore sees a consistent (snapshot, overlay, dead) triple:
an entity live at the time the reader grabbed the state is visible via
exactly the snapshot or the overlay; an entity updated by a concurrent
writer is visible as exactly one of its versions.

SNAPSHOTS ARE TIERED (dss_tpu.dar.tiers): the published state holds a
stack of immutable snapshots — a large, rarely-rewritten L0 base plus
a small L1 delta tier.  A minor FOLD (overlay -> L1) runs OFF the
write lock: a folder thread copies the writer-tracked delta record set
under the lock (records newer than L0 — O(delta) pointer copy), builds
a fresh L1 aside (pack + HBM upload of the DELTA ONLY), then swaps
under the lock, reconciling the writes that landed mid-fold by object
identity (they simply stay in the overlay of the new state).  A MAJOR
compaction (L1 + tombstones -> fresh L0) is the only O(table) rebuild
and triggers on the churn ratio (tiers.TierPolicy).  Shadowing is
enforced at write time: updating/removing an entity marks its slot
dead in every tier holding it live, so the newest tier always wins and
queries just merge per-tier hits.  Folds trigger on overlay overflow
(`delta_capacity` postings) and opportunistically when the table has
been write-idle, so read-heavy phases serve from the snapshot path.

Queries run the batched fused kernel; many concurrent requests are
micro-batched by dss_tpu.dar.coalesce.QueryCoalescer.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from dss_tpu.dar import budget
from dss_tpu.dar import tiers as tiersmod
from dss_tpu.dar.oracle import Record
from dss_tpu.dar.pack import pow2_at_least
from dss_tpu.dar.tiers import EMPTY_SNAPSHOT, Tier, TierSnapshot
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO
from dss_tpu.ops import fastpath

# back-compat aliases: the single-snapshot type moved to dar.tiers when
# it became the per-tier building block
_Snapshot = TierSnapshot
_EMPTY_SNAPSHOT = EMPTY_SNAPSHOT


class _Overlay(NamedTuple):
    """Records since the snapshot build, packed for a vectorized scan
    (the host-side analog of the device postings layout).  Arrays are
    immutable once published; writers splice copies."""

    ids: List[str]  # local index -> entity_id
    key: np.ndarray  # i32[P] sorted
    ent: np.ndarray  # i32[P] local index per posting
    alt_lo: np.ndarray  # f32[n]
    alt_hi: np.ndarray  # f32[n]
    t0: np.ndarray  # i64[n]
    t1: np.ndarray  # i64[n]
    owner: np.ndarray  # i32[n]


class _State(NamedTuple):
    tiers: "tuple[Tier, ...]"  # oldest (L0) first; () before any fold
    pending: Dict[str, Record]  # overlay source records (immutable)
    overlay: Optional[_Overlay]  # packed form of pending (None if empty)

    # back-compat views (bench.py / __graft_entry__ grab the base
    # FastTable through these)
    @property
    def snap(self) -> TierSnapshot:
        return self.tiers[0].snap if self.tiers else EMPTY_SNAPSHOT

    @property
    def dead(self) -> frozenset:
        return self.tiers[0].dead if self.tiers else frozenset()


_EMPTY_STATE = _State((), {}, None)


def _pack_overlay(pending: Dict[str, Record]) -> Optional[_Overlay]:
    if not pending:
        return None
    recs = list(pending.values())
    ids = [r.entity_id for r in recs]
    key = np.concatenate([r.keys for r in recs]).astype(np.int32)
    ent = np.repeat(
        np.arange(len(recs), dtype=np.int32),
        [len(r.keys) for r in recs],
    )
    order = np.argsort(key, kind="stable")
    return _Overlay(
        ids=ids,
        key=key[order],
        ent=ent[order],
        alt_lo=np.asarray([r.alt_lo for r in recs], np.float32),
        alt_hi=np.asarray([r.alt_hi for r in recs], np.float32),
        t0=np.asarray([r.t_start for r in recs], np.int64),
        t1=np.asarray([r.t_end for r in recs], np.int64),
        owner=np.asarray([r.owner_id for r in recs], np.int32),
    )


def _overlay_upsert(
    ov: Optional[_Overlay], rec: Record, idx: Optional[int]
) -> "tuple[_Overlay, int]":
    """O(Δ) overlay update: splice the record's postings into copies of
    the packed arrays (contiguous memcpy, not a python repack).
    `idx` is the record's existing local index (update) or None (new).
    Returns (new_overlay, local_index)."""
    k = np.asarray(rec.keys, np.int32)
    if ov is None:
        return (
            _Overlay(
                ids=[rec.entity_id],
                key=k.copy(),
                ent=np.zeros(len(k), np.int32),
                alt_lo=np.asarray([rec.alt_lo], np.float32),
                alt_hi=np.asarray([rec.alt_hi], np.float32),
                t0=np.asarray([rec.t_start], np.int64),
                t1=np.asarray([rec.t_end], np.int64),
                owner=np.asarray([rec.owner_id], np.int32),
            ),
            0,
        )
    if idx is None:
        idx = len(ov.ids)
        ids = ov.ids + [rec.entity_id]
        alt_lo = np.append(ov.alt_lo, np.float32(rec.alt_lo))
        alt_hi = np.append(ov.alt_hi, np.float32(rec.alt_hi))
        t0 = np.append(ov.t0, np.int64(rec.t_start))
        t1 = np.append(ov.t1, np.int64(rec.t_end))
        owner = np.append(ov.owner, np.int32(rec.owner_id))
        key, ent = ov.key, ov.ent
    else:
        ids = ov.ids
        alt_lo = ov.alt_lo.copy()
        alt_lo[idx] = rec.alt_lo
        alt_hi = ov.alt_hi.copy()
        alt_hi[idx] = rec.alt_hi
        t0 = ov.t0.copy()
        t0[idx] = rec.t_start
        t1 = ov.t1.copy()
        t1[idx] = rec.t_end
        owner = ov.owner.copy()
        owner[idx] = rec.owner_id
        keep = ov.ent != idx
        key, ent = ov.key[keep], ov.ent[keep]
    pos = np.searchsorted(key, k)
    key = np.insert(key, pos, k)
    ent = np.insert(ent, pos, np.full(len(k), idx, np.int32))
    return (
        _Overlay(ids, key, ent, alt_lo, alt_hi, t0, t1, owner),
        idx,
    )


def _overlay_drop(ov: _Overlay, idx: int) -> Optional[_Overlay]:
    """Remove a record's postings (its attr slot stays, orphaned —
    bounded by the fold threshold)."""
    keep = ov.ent != idx
    if not keep.any() and len(ov.ids) == 1:
        return None
    return ov._replace(key=ov.key[keep], ent=ov.ent[keep])


def _scatter_hits(out_sets, qidx, slots, ids) -> None:
    """Distribute deduped (query, slot) hits into out_sets[q] as
    entity ids.  One vectorized dedup + grouped set.update per query —
    the per-hit int()/add loop it replaces was ~a third of
    query_many's host cost at serving batch sizes.  Slots beyond
    len(ids) (pad lanes) are dropped."""
    if len(qidx) == 0:
        return
    pairs = np.unique(qidx * np.int64(2**32) + slots)
    qi = (pairs >> np.int64(32)).astype(np.int64)
    sl = pairs & np.int64(0xFFFFFFFF)
    ok = sl < len(ids)
    if not ok.all():
        qi, sl = qi[ok], sl[ok]
    # pairs are sorted, so each query's hits are one contiguous run
    bounds = np.searchsorted(qi, np.arange(len(out_sets) + 1))
    sl_list = sl.tolist()
    getter = ids.__getitem__
    for i in range(len(out_sets)):
        lo, hi = bounds[i], bounds[i + 1]
        if hi > lo:
            out_sets[i].update(map(getter, sl_list[lo:hi]))


def _overlay_search(
    ov: _Overlay,
    qkeys: np.ndarray,  # i32[B, W] pad -1
    alt_lo, alt_hi, t_start, t_end,  # per-query arrays
    now_arr: np.ndarray,
    owner_ids: Optional[np.ndarray],
):
    """Vectorized host scan of the overlay -> (qidx, local_ent) pairs."""
    B, W = qkeys.shape
    flat = qkeys.ravel()
    lo = np.searchsorted(ov.key, flat, side="left")
    hi = np.searchsorted(ov.key, flat, side="right")
    n = hi - lo
    nonempty = n > 0
    lo, n = lo[nonempty], n[nonempty]
    flat_q = np.repeat(np.arange(B), W)[nonempty]
    total = int(n.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    cand = ov.ent[np.repeat(lo, n) + fastpath.segmented_arange(n)]
    cq = np.repeat(flat_q, n)
    keep = (
        (ov.alt_hi[cand] >= alt_lo[cq])
        & (ov.alt_lo[cand] <= alt_hi[cq])
        & (ov.t1[cand] >= np.maximum(t_start[cq], now_arr[cq]))
        & (ov.t0[cand] <= t_end[cq])
    )
    if owner_ids is not None:
        keep &= (owner_ids[cq] < 0) | (ov.owner[cand] == owner_ids[cq])
    return cq[keep].astype(np.int64), cand[keep].astype(np.int64)


class _PendingQuery:
    """One in-flight query_many batch: the immutable state it runs
    against plus either ready host-path hits or a device PendingBatch.
    Produced by DarTable.query_many_submit, resolved by
    DarTable.query_many_collect — the two halves the pipelined
    QueryCoalescer overlaps (pack batch N+1 while batch N is on the
    device)."""

    __slots__ = (
        "st", "b", "qkeys", "alt_lo", "alt_hi", "t_start", "t_end",
        "now_arr", "owner_ids", "tier_host", "tier_pending",
    )

    def __init__(self, st, b, qkeys, alt_lo, alt_hi, t_start, t_end,
                 now_arr, owner_ids, tier_host, tier_pending):
        self.st = st
        self.b = b
        self.qkeys = qkeys
        self.alt_lo = alt_lo
        self.alt_hi = alt_hi
        self.t_start = t_start
        self.t_end = t_end
        self.now_arr = now_arr
        self.owner_ids = owner_ids
        # per-tier (aligned with st.tiers): exact host-path hits, or a
        # fastpath.PendingBatch when that tier went to the device
        self.tier_host = tier_host  # list of (qidx, slots) | None
        self.tier_pending = tier_pending  # list of PendingBatch | None

    def wait_device(self) -> None:
        """Block until the device results are ready (no data fetch, no
        decode) — lets the pipelined caller time the pure device wait
        separately from the host decode in collect."""
        for p in self.tier_pending:
            if p is not None:
                p.ready()

    def used_device(self) -> bool:
        """Did this batch touch the device?  (Any tier that could not
        answer from its host postings copy submitted a kernel.)  The
        ONE predicate the coalescer's pressure accounting and the
        resident loop's cost attribution both consume — keep it here
        so tier-accounting changes can't desync them."""
        return any(p is not None for p in self.tier_pending)


class DarTable:
    """HBM spatial index for one entity class: lock-free reads against
    the published immutable state; copy-on-write writes; background
    folds."""

    def __init__(
        self,
        *,
        max_results: int = 512,  # kept for API compat; fused path has
        #                          no fixed result width
        delta_capacity: int = 8192,
        entity_capacity: int = 1024,  # kept for API compat; slots are
        #                               assigned per snapshot build
        idle_fold_s: float = 0.5,  # fold the overlay after this long
        #                            without writes (0 disables)
        tier_ratio: Optional[float] = None,  # major-compaction churn
        #                            ratio; None = DSS_TIER_RATIO env
        #                            (0 disables tiering: every fold is
        #                            a full rebuild)
        tier_min_l0: Optional[int] = None,  # L0 sizes below this always
        #                            compact major; None = env default
    ):
        del max_results, entity_capacity
        policy = tiersmod.env_policy()
        self._tier_ratio = (
            policy.ratio if tier_ratio is None else float(tier_ratio)
        )
        self._tier_min_l0 = (
            policy.min_l0 if tier_min_l0 is None else int(tier_min_l0)
        )
        self._write_lock = threading.RLock()
        self._rebuild_postings = delta_capacity
        # per-cell write clock (tiers.CellClock): every upsert/remove
        # stamps the affected DAR keys AFTER the state publish, so a
        # version-fenced cache entry stamped before a write can never
        # survive it (dar/readcache.py).  Lives on the table, not in
        # the published state — minor folds and major compactions swap
        # snapshots without ever touching the stamps.
        self.cell_clock = tiersmod.CellClock()
        self.records: Dict[str, Record] = {}  # authoritative, writer-owned
        self._state: _State = _EMPTY_STATE
        # writer-owned overlay index (id -> local idx in the overlay);
        # reset on every fold/rebuild.  Readers never touch it.
        self._overlay_idx: Dict[str, int] = {}
        # writer-owned delta set: records newer than the L0 base (the
        # minor-fold source; cleared by major compactions/rebuilds).
        # Readers never touch it — they see its packed forms (L1 tier +
        # overlay) through the published state.
        self._delta: Dict[str, Record] = {}
        # background folding
        self._idle_fold_s = idle_fold_s
        self._gen = 0  # bumped by synchronous rebuilds: abandons folds
        self._folding = False
        self._fold_removed: List[str] = []  # ids removed mid-fold
        self._fold_event = threading.Event()
        self._fold_thread: Optional[threading.Thread] = None
        self._last_write = 0.0
        self._closed = False
        # resident-kernel warm hook (ops/resident.py): called with a
        # freshly built snapshot's FastTable BEFORE it is swapped in,
        # so a rebuild's new block count has its AOT bucket grid
        # scheduled (async — compiles land on a background thread and
        # must never stall the fold) as early as possible
        self._resident_warm = None
        self._stats_folds = 0
        self._stats_fold_ms = 0.0
        self._stats_swap_ms = 0.0
        self._stats_minor_folds = 0
        self._stats_minor_ms = 0.0
        self._stats_compactions = 0
        self._stats_compact_ms = 0.0

    # -- write path ----------------------------------------------------------

    def upsert(
        self,
        entity_id: str,
        keys: np.ndarray,
        alt_lo: Optional[float],
        alt_hi: Optional[float],
        t_start: int,
        t_end: int,
        owner_id: int,
    ) -> None:
        """Insert or replace an entity. keys are int32 DAR keys."""
        keys = np.unique(np.asarray(keys, dtype=np.int32))
        rec = Record(
            entity_id=entity_id,
            keys=keys,
            alt_lo=-np.inf if alt_lo is None else float(alt_lo),
            alt_hi=np.inf if alt_hi is None else float(alt_hi),
            t_start=int(t_start),
            t_end=int(t_end),
            owner_id=int(owner_id),
        )
        with self._write_lock:
            old = self.records.get(entity_id)
            self.records[entity_id] = rec
            self._delta[entity_id] = rec
            st = self._state
            pending = dict(st.pending)
            pending[entity_id] = rec
            # shadow every older tier copy (newest tier wins)
            tiers = tiersmod.mark_dead(st.tiers, entity_id)
            overlay, idx = _overlay_upsert(
                st.overlay, rec, self._overlay_idx.get(entity_id)
            )
            self._overlay_idx[entity_id] = idx
            # one atomic publish: tiers + overlay + dead sets together
            self._state = _State(tiers, pending, overlay)
            # clock bump LAST (after the publish): a concurrent
            # lock-free cache miss that read its fence before this
            # write can only produce an entry stamped too OLD, which
            # the next fence check discards — never one stamped fresh
            # over pre-write data.  Old + new coverings both bump: a
            # record leaving cell X changes X's answers too.
            self.cell_clock.bump(
                None if old is None else old.keys, keys
            )
            self._last_write = time.monotonic()
            if len(overlay.key) > self._rebuild_postings:
                self._request_fold()
            elif self._idle_fold_s > 0:
                self._ensure_folder()  # idle compaction needs the thread

    def remove(self, entity_id: str) -> bool:
        with self._write_lock:
            rec = self.records.pop(entity_id, None)
            if rec is None:
                return False
            self._delta.pop(entity_id, None)
            st = self._state
            pending = st.pending
            overlay = st.overlay
            if entity_id in pending:
                pending = dict(pending)
                del pending[entity_id]
                idx = self._overlay_idx.pop(entity_id, None)
                if overlay is not None and idx is not None:
                    overlay = _overlay_drop(overlay, idx)
            tiers = tiersmod.mark_dead(st.tiers, entity_id)
            if self._folding:
                self._fold_removed.append(entity_id)
            self._state = _State(tiers, pending, overlay)
            self.cell_clock.bump(rec.keys)  # after publish, like upsert
            self._last_write = time.monotonic()
            return True

    # -- folding (overlay -> snapshot), off the write lock -------------------

    def _ensure_folder(self):
        if self._fold_thread is None or not self._fold_thread.is_alive():
            self._fold_thread = threading.Thread(
                target=self._fold_loop, name="dar-folder", daemon=True
            )
            self._fold_thread.start()

    def _request_fold(self):
        self._ensure_folder()
        self._fold_event.set()

    def close(self):
        """Stop the folder thread (tables created in tests/benchmarks
        must not leak a wake-every-idle_fold_s daemon each)."""
        self._closed = True
        self._fold_event.set()
        th = self._fold_thread
        if th is not None and th is not threading.current_thread():
            th.join(timeout=5)

    def _fold_loop(self):
        while not self._closed:
            triggered = self._fold_event.wait(
                self._idle_fold_s if self._idle_fold_s > 0 else None
            )
            self._fold_event.clear()
            if self._closed:
                return
            try:
                if triggered:
                    self.fold()
                else:
                    # idle compaction: fold a quiet non-empty overlay
                    # (or a tier stack whose churn crossed the major
                    # threshold) so read-heavy phases serve from the
                    # snapshot path.  has_churn gates the major check:
                    # without it an empty/small table would wake into a
                    # guaranteed-no-op fold every idle tick forever
                    st = self._state
                    has_churn = bool(
                        self._delta
                        or len(st.tiers) > 1
                        or any(t.dead_count for t in st.tiers)
                    )
                    if (
                        st.pending
                        or (has_churn and self._want_major())
                    ) and (
                        time.monotonic() - self._last_write
                        >= self._idle_fold_s
                    ):
                        self.fold()
            except Exception:  # noqa: BLE001 — folder must survive
                import logging

                logging.getLogger("dss.dar").exception("fold failed")

    def _want_major(self) -> bool:
        """Major-compaction trigger: the tier stack's churn (delta
        records + shadowed rows) crossed the size-ratio threshold, or
        there is no L0 yet.  Advisory — safe to read without the lock
        (the fold re-decides under it)."""
        st = self._state
        if not st.tiers:
            return True  # first fold builds the base
        if self._tier_ratio <= 0:
            return True  # tiering disabled: every fold is a rebuild
        l0_n = len(st.tiers[0].snap.ids)
        if l0_n < self._tier_min_l0:
            return True  # small tables repack in microseconds
        churn = len(self._delta) + sum(t.dead_count for t in st.tiers)
        return churn > self._tier_ratio * max(l0_n, 1)

    def compact(self) -> bool:
        """Force a major compaction: L1 + tombstones merged into a
        fresh L0 (off the write lock, like any fold).  -> True if a new
        snapshot was published."""
        return self.fold(major=True)

    def fold(self, *, major: Optional[bool] = None) -> bool:
        """Fold the overlay into the tier stack OFF the write lock and
        swap atomically, keeping mid-fold writes in the new overlay.

        Minor (the common case): rebuild ONLY the small L1 tier from
        the writer-tracked delta set — O(overlay + L1), never O(table);
        the L0 base (and its HBM residency) is untouched.  Major
        (`major=True`, or the churn-ratio policy): rebuild L0 from all
        records, clearing the delta set and garbage-collecting every
        tombstone.  -> True if a new snapshot was published."""
        t_all = time.perf_counter()
        with self._write_lock:
            if self._folding:
                return False  # a fold is already running
            st = self._state
            if major is None:
                major = self._want_major()
            if not st.tiers:
                major = True  # no base to tier onto yet
            if major:
                if (
                    not st.pending
                    and not self._delta
                    and len(st.tiers) <= 1
                    and not any(t.dead_count for t in st.tiers)
                ):
                    return False  # nothing to compact
                recs = list(self.records.values())  # O(n) pointer copy
            else:
                if not st.pending:
                    return False  # overlay empty; L1 already == delta
                recs = list(self._delta.values())  # O(delta) copy
            self._folding = True
            self._fold_removed = []
            gen0 = self._gen
        try:
            snap = self._build_snapshot(recs)  # pack + HBM upload, unlocked
            if self._resident_warm is not None and snap.fast is not None:
                try:
                    # schedule the new snapshot's AOT shape buckets
                    # (the hook is async — a grid compile must never
                    # stall the fold; until a bucket lands, submits
                    # fall back to the shared jit).  No-op when the
                    # block count is unchanged — the process cache
                    # already holds the grid, the minor-fold common
                    # case.
                    self._resident_warm(snap.fast)
                except Exception:  # noqa: BLE001 — warm is best-effort
                    import logging

                    logging.getLogger("dss.dar").exception(
                        "resident warm failed"
                    )
            t_swap = time.perf_counter()
            with self._write_lock:
                if self._gen != gen0:
                    return False  # a synchronous rebuild superseded us
                built = snap.recs
                cur = self._state
                # writes that landed mid-fold: record object differs
                # from what we built (or is brand new)
                new_pending = {
                    i: r
                    for i, r in cur.pending.items()
                    if built.get(i) is not r
                }
                dead = set()
                for i in new_pending:
                    s = snap.slot_of.get(i)
                    if s is not None:
                        dead.add(s)
                for i in self._fold_removed:
                    s = snap.slot_of.get(i)
                    if s is not None:
                        dead.add(s)
                new_tier = tiersmod.make_tier(snap, dead)
                if major:
                    # fresh base: delta keeps only mid-compaction writes
                    self._delta = {
                        i: r
                        for i, r in self._delta.items()
                        if built.get(i) is not r
                    }
                    tiers = (new_tier,) if snap.ids else ()
                else:
                    # L0 carries over untouched (mid-fold writes already
                    # grew its dead set in cur); the fresh L1 — built
                    # from the FULL delta set — replaces the old one
                    tiers = (
                        (cur.tiers[0], new_tier)
                        if snap.ids
                        else (cur.tiers[0],)
                    )
                overlay = _pack_overlay(new_pending)
                self._overlay_idx = {
                    i: k for k, i in enumerate(new_pending)
                }
                self._state = _State(tiers, new_pending, overlay)
                self._stats_swap_ms += (
                    time.perf_counter() - t_swap
                ) * 1000
            dur_ms = (time.perf_counter() - t_all) * 1000
            self._stats_folds += 1
            self._stats_fold_ms += dur_ms
            if major:
                self._stats_compactions += 1
                self._stats_compact_ms += dur_ms
            else:
                self._stats_minor_folds += 1
                self._stats_minor_ms += dur_ms
            return True
        finally:
            with self._write_lock:
                self._folding = False
                self._fold_removed = []

    @staticmethod
    def _build_snapshot(live: List[Record]) -> _Snapshot:
        return tiersmod.build_snapshot(live)

    def _rebuild_locked(self):
        """Synchronous in-lock rebuild (bulk loads / explicit calls).
        Bumps the generation so any in-flight background fold abandons
        its (now stale) snapshot instead of swapping it in."""
        self._gen += 1
        snap = self._build_snapshot(list(self.records.values()))
        self._state = _State(
            (tiersmod.make_tier(snap),) if snap.ids else (),
            {},
            None,
        )
        self._overlay_idx = {}
        self._delta = {}

    def rebuild(self):
        with self._write_lock:
            self._rebuild_locked()

    def bulk_load(self, records) -> None:
        """Replace the table contents with `records` (list of Record) in
        one rebuild — the snapshot-refresh path (WAL replay / bench
        population) that skips per-entity overlay churn.  Duplicate
        entity_ids keep the last occurrence (WAL replay order)."""
        with self._write_lock:
            self.records = {r.entity_id: r for r in records}
            self._rebuild_locked()
            # wholesale replacement: raise the clock floor (O(1))
            # instead of stamping every record's covering
            self.cell_clock.bump_all()

    def set_resident_warm(self, fn) -> None:
        """Install the fold-time resident warm hook: fn(fast_table) is
        called with each freshly built snapshot's FastTable before the
        swap (the QueryCoalescer installs this when its resident loop
        is enabled)."""
        self._resident_warm = fn

    def warm_resident(self, kernel, batch_buckets=None,
                      window_buckets=None) -> int:
        """AOT-compile the resident bucket grid for every CURRENT tier
        (server-boot warm; fold-time warm of future tiers goes through
        set_resident_warm).  Returns fresh executables built."""
        n = 0
        for tier in self._state.tiers:
            if tier.snap.fast is not None:
                n += kernel.warm(
                    tier.snap.fast, batch_buckets, window_buckets
                )
        return n

    # -- read path (lock-free) -----------------------------------------------

    def query(
        self,
        keys: np.ndarray,
        alt_lo: Optional[float] = None,
        alt_hi: Optional[float] = None,
        t_start: Optional[int] = None,
        t_end: Optional[int] = None,
        *,
        now: int,
        owner_id: Optional[int] = None,
    ) -> List[str]:
        """Entity ids intersecting the query volume (live at/after now)."""
        if len(np.asarray(keys).ravel()) == 0:
            return []
        return self.query_many(
            [np.asarray(keys, np.int32).ravel()],
            np.asarray([-np.inf if alt_lo is None else alt_lo], np.float32),
            np.asarray([np.inf if alt_hi is None else alt_hi], np.float32),
            np.asarray(
                [NO_TIME_LO if t_start is None else t_start], np.int64
            ),
            np.asarray([NO_TIME_HI if t_end is None else t_end], np.int64),
            now=now,
            owner_ids=None
            if owner_id is None
            else np.asarray([owner_id], np.int32),
        )[0]

    def query_many_submit(
        self,
        keys_list,  # sequence of int32 arrays (DAR keys per query)
        alt_lo: np.ndarray,  # f32[B], -inf unbounded
        alt_hi: np.ndarray,
        t_start: np.ndarray,  # i64[B] ns, NO_TIME_LO unbounded
        t_end: np.ndarray,
        *,
        now,  # int scalar or i64[B] per-query
        owner_ids: Optional[np.ndarray] = None,  # i32[B], -1 = no filter
        state: Optional[_State] = None,  # pre-grabbed state (internal)
        host_route: bool = False,  # force chunked exact host scans
        kernel=None,  # resident AOT selector (ops/resident.py): device
        #               tiers run the pre-compiled donated executable
        #               for their shape bucket instead of the shared jit
    ) -> Optional[_PendingQuery]:
        """The host/pack half of query_many: grab ONE immutable state,
        pack the query batch, and either answer small batches from the
        exact host postings copy or enqueue the fused device kernel
        (async — nothing here blocks on the device).  Returns a handle
        for query_many_collect; None for an empty batch.  Pipelined
        callers overlap this with a previous batch's collect.

        host_route=True is the deadline router's forced path (the
        QueryCoalescer under deadline pressure): every tier is served
        as chunked exact host scans (FastTable.query_host_chunked, the
        warmed HOST_MAX_BATCH bucket per chunk) instead of the fused
        device kernel — bit-identical results, no device round trip.
        A tier whose chunks exceed the raised host-candidate cap falls
        back to the device submit for that tier only (correctness over
        routing intent)."""
        st = state if state is not None else self._state
        b = len(keys_list)
        if b == 0:
            return None
        now_arr = np.broadcast_to(np.asarray(now, np.int64), (b,))
        width = max(16, pow2_at_least(max(len(k) for k in keys_list), lo=16))
        qkeys = np.full((b, width), -1, np.int32)
        for i, k in enumerate(keys_list):
            k = np.asarray(k, np.int32)
            qkeys[i, : len(k)] = k
        # row-dedup in one vectorized pass instead of per-item
        # np.unique (a third of this function's host cost at batch 32):
        # sort each row, then blank repeats to the -1 pad key.  Key
        # order within a row is irrelevant (set semantics) and pads
        # find empty postings ranges wherever they sit.
        qkeys.sort(axis=1)
        dup = qkeys[:, 1:] == qkeys[:, :-1]
        if dup.any():
            qkeys[:, 1:][dup] = -1

        # per-tier answers, host path first: small batches answer from
        # each tier's host postings copy (exact, native C++ when built)
        # instead of paying a device round trip — the tiny L1 tier
        # almost always stays on the host even when L0 needs the device
        tier_host: List = []
        need_device: List[int] = []
        for ti, tier in enumerate(st.tiers):
            if tier.snap.fast is None:
                tier_host.append(None)
                continue
            if host_route:
                host = tier.snap.fast.query_host_chunked(
                    qkeys, alt_lo, alt_hi, t_start, t_end, now=now_arr
                )
            else:
                host = tier.snap.fast.query_host_auto(
                    qkeys, alt_lo, alt_hi, t_start, t_end, now=now_arr
                )
            tier_host.append(host)
            if host is None:
                need_device.append(ti)
        if need_device and budget.is_host_only():
            # caller is on the event loop: re-run via executor
            raise budget.NeedsDevice()
        tier_pending: List = [None] * len(st.tiers)
        for ti in need_device:
            tier_pending[ti] = st.tiers[ti].snap.fast.submit(
                qkeys, alt_lo, alt_hi, t_start, t_end, now=now_arr,
                kernel=kernel,
            )
        return _PendingQuery(
            st, b, qkeys, alt_lo, alt_hi, t_start, t_end, now_arr,
            owner_ids, tier_host, tier_pending,
        )

    def query_many_collect(self, pq: Optional[_PendingQuery]) -> List[List[str]]:
        """The collect/decode half of query_many: resolve the device
        batch (the one host sync), then dead-slot/owner filtering, the
        overlay scan, and id assembly — all against the state grabbed
        at submit time, so the (snapshot, overlay, dead) triple stays
        consistent across the pipeline gap."""
        if pq is None:
            return []
        st = pq.st
        out_sets = [set() for _ in range(pq.b)]
        for tier, host, pending in zip(
            st.tiers, pq.tier_host, pq.tier_pending
        ):
            if tier.snap.fast is None:
                continue
            if host is not None:
                qidx, slots = host
            else:
                qidx, slots = tier.snap.fast.collect(pending)
            if len(qidx):
                # per-tier shadowing: slots superseded by a newer tier
                # (or the overlay) were marked dead at write/fold time,
                # so dropping them here makes the newest tier win
                qidx, slots = tiersmod.filter_dead(tier, qidx, slots)
                if pq.owner_ids is not None and len(qidx):
                    keep = (pq.owner_ids[qidx] < 0) | (
                        tier.snap.owner[slots] == pq.owner_ids[qidx]
                    )
                    qidx, slots = qidx[keep], slots[keep]
            _scatter_hits(out_sets, qidx, slots, tier.snap.ids)

        if st.overlay is not None:
            oq, oent = _overlay_search(
                st.overlay, pq.qkeys, pq.alt_lo, pq.alt_hi, pq.t_start,
                pq.t_end, pq.now_arr, pq.owner_ids,
            )
            _scatter_hits(out_sets, oq, oent, st.overlay.ids)

        # an entity updated since a tier was built appears via a newer
        # tier or the overlay only (its old slot is in that tier's dead
        # set); sets dedup any transient double-sighting.  Sorted for
        # deterministic responses.
        return [sorted(s) for s in out_sets]

    def query_many(
        self,
        keys_list,  # sequence of int32 arrays (DAR keys per query)
        alt_lo: np.ndarray,  # f32[B], -inf unbounded
        alt_hi: np.ndarray,
        t_start: np.ndarray,  # i64[B] ns, NO_TIME_LO unbounded
        t_end: np.ndarray,
        *,
        now,  # int scalar or i64[B] per-query
        owner_ids: Optional[np.ndarray] = None,  # i32[B], -1 = no filter
        state: Optional[_State] = None,  # pre-grabbed state (internal)
        host_route: bool = False,  # force chunked exact host scans
        kernel=None,  # resident AOT selector (ops/resident.py)
    ) -> List[List[str]]:
        """Batched search via the fused fast path + overlay scan.
        Lock-free: runs against ONE atomically-grabbed immutable state.
        submit+collect in one call; the pipelined QueryCoalescer calls
        the halves separately to overlap host pack with device work."""
        return self.query_many_collect(
            self.query_many_submit(
                keys_list, alt_lo, alt_hi, t_start, t_end,
                now=now, owner_ids=owner_ids, state=state,
                host_route=host_route, kernel=kernel,
            )
        )

    def max_owner_count(self, keys: np.ndarray, owner_id: int, *, now: int) -> int:
        """DSS0030 quota metric: max per-cell count of live entities owned
        by owner_id over the query cells
        (pkg/rid/cockroach/subscriptions.go:86-116).

        The whole computation runs against ONE grabbed immutable state
        (query + per-cell counts), so the counts can never disagree with
        the snapshot the query matched — writer-owned `self.records` is
        never touched."""
        qk = np.unique(np.asarray(keys, np.int32).ravel())
        if len(qk) == 0:
            return 0
        st = self._state
        ids = self.query_many(
            [qk],
            np.asarray([-np.inf], np.float32),
            np.asarray([np.inf], np.float32),
            np.asarray([NO_TIME_LO], np.int64),
            np.asarray([NO_TIME_HI], np.int64),
            now=now,
            owner_ids=np.asarray([owner_id], np.int32),
            state=st,
        )[0]
        counts = {int(k): 0 for k in qk}
        for eid in ids:
            rec = st.pending.get(eid) or tiersmod.resolve_record(
                st.tiers, eid
            )
            if rec is None:
                continue
            for k in np.intersect1d(rec.keys, qk):
                counts[int(k)] += 1
        return max(counts.values(), default=0)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        st = self._state
        tier = tiersmod.stats(st.tiers)
        out = {
            "live_records": len(self.records),
            # total snapshot rows across tiers (dead rows included,
            # matching the pre-tier meaning of this gauge)
            "snapshot_records": (
                tier["tier_l0_records"] + tier["tier_l1_records"]
            ),
            "pending_records": len(st.pending),
            "dead_slots": tier["tier_shadowed_rows"],
            "folds": self._stats_folds,
            "fold_ms_total": round(self._stats_fold_ms, 1),
            "fold_swap_ms_total": round(self._stats_swap_ms, 3),
            # tiered-compaction gauges (dss_dar_<class>_tier_* in
            # /metrics): tier sizes, shadowed rows, and the minor-fold
            # vs major-compaction duration split
            "tier_delta_records": len(self._delta),
            "tier_minor_folds": self._stats_minor_folds,
            "tier_minor_fold_ms_total": round(self._stats_minor_ms, 1),
            "tier_compactions": self._stats_compactions,
            "tier_compact_ms_total": round(self._stats_compact_ms, 1),
            "tier_ratio": self._tier_ratio,
            # version-fence introspection (/status + /metrics): the
            # write generation and the cell-clock high-water mark the
            # read cache fences against
            "write_generation": self.cell_clock.generation,
            "cell_clock_high_water": self.cell_clock.high_water,
        }
        out.update(tier)
        return out
