"""Pure-numpy oracle for DAR queries.

Mirrors the reference's SQL, literally:

  - conflict/search: DISTINCT entities sharing a cell with the query,
    then COALESCE'd altitude + time filters and ends_at >= now
    (pkg/scd/store/cockroach/operations.go:374-435,
     pkg/rid/cockroach/identification_service_area.go:166-197)
  - per-owner-per-cell counts (pkg/rid/cockroach/subscriptions.go:86-116)

Used as the golden reference for the JAX kernels and as the exact
fallback when a device query overflows its fixed result width.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np


@dataclass
class Record:
    """Host-side row: one live entity version."""

    entity_id: str
    keys: np.ndarray  # int32 DAR keys, sorted unique
    alt_lo: float  # -inf if unbounded
    alt_hi: float  # +inf if unbounded
    t_start: int  # unix ns
    t_end: int  # unix ns
    owner_id: int


def search(
    records: Dict[int, Record],
    keys: np.ndarray,
    alt_lo: Optional[float],
    alt_hi: Optional[float],
    t_start: Optional[int],
    t_end: Optional[int],
    now: int,
    owner_id: Optional[int] = None,
):
    """Slots of records intersecting the query, SQL-COALESCE semantics."""
    qk = set(int(k) for k in np.asarray(keys).ravel())
    out = []
    for slot, r in records.items():
        if not qk.intersection(int(k) for k in r.keys):
            continue
        if alt_lo is not None and not (r.alt_hi >= alt_lo):
            continue
        if alt_hi is not None and not (r.alt_lo <= alt_hi):
            continue
        if t_start is not None and not (r.t_end >= t_start):
            continue
        if t_end is not None and not (r.t_start <= t_end):
            continue
        if not (r.t_end >= now):
            continue
        if owner_id is not None and r.owner_id != owner_id:
            continue
        out.append(slot)
    return sorted(out)


def max_count_per_cell(
    records: Dict[int, Record],
    keys: np.ndarray,
    owner_id: int,
    now: int,
) -> int:
    """Max over query cells of live same-owner entities in that cell."""
    live = [
        set(int(k) for k in r.keys)
        for r in records.values()
        if r.owner_id == owner_id and r.t_end >= now
    ]
    best = 0
    for k in np.asarray(keys).ravel():
        ki = int(k)
        best = max(best, sum(1 for s in live if ki in s))
    return best
