"""Ambient per-request deadline propagation (route -> serving stack).

The HTTP timeout middleware (api/app.py) knows each request's absolute
deadline; the QueryCoalescer — four call layers down, reached through
service and store code that has no deadline parameter — needs it to
route the request's micro-batch (chunked exact host scans when the
device round trip would blow the tightest queued headroom) and to
fast-shed work whose deadline already expired in queue.

Rather than threading a `deadline` kwarg through every service/store
signature, the deadline rides a thread-local — the same pattern the
per-stage tracer (obs/stages.set_sink) and the host-only read budget
(dar/budget.set_host_only) already use for request-scoped context that
crosses the handler -> executor -> store boundary.  api/app.py installs
it on the worker thread (or the event loop, for inline reads) around
each service call; dar/coalesce.QueryCoalescer reads it at admission
and caps the item's SLO-derived deadline with it.

Deadlines are absolute `time.monotonic()` instants (never wall clock:
NTP steps must not expire queued work)."""

from __future__ import annotations

import threading
from typing import Optional

_tls = threading.local()


def set_route_deadline(deadline: Optional[float]) -> None:
    """Install (or clear, with None) the current request's absolute
    monotonic deadline on this thread."""
    _tls.deadline = deadline


def get_route_deadline() -> Optional[float]:
    """The absolute monotonic deadline of the request being served on
    this thread, or None outside a deadline-scoped request."""
    return getattr(_tls, "deadline", None)
