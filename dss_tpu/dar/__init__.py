"""The DAR (DSS Airspace Representation) storage layer.

  snapshot   — DarTable: HBM-resident packed entity/postings arrays with
               a delta overlay; the device-side replacement for the
               reference's CockroachDB inverted cell index.
  oracle     — pure-numpy mirror of the reference's SQL semantics; used
               for golden tests and as the exact overflow fallback.
  store      — repository interfaces (the seam from pkg/rid/repos and
               pkg/scd/store) + the in-memory and DAR-backed stores.
  wal        — append-only write-ahead log (the CRDB source-of-truth
               stand-in) with replay.
"""
