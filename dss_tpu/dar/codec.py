"""Entity <-> plain-dict codecs, used by the WAL and checkpoints.

Documents are JSON-serializable: datetimes as unix nanoseconds, cells
as lists of ints (Python json handles uint64 exactly).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dss_tpu.clock import from_nanos, to_nanos
from dss_tpu.models import rid as ridm
from dss_tpu.models import scd as scdm
from dss_tpu.models.core import Version


def _t(dt) -> Optional[int]:
    return None if dt is None else to_nanos(dt)


def _dt(ns) -> Optional[object]:
    return None if ns is None else from_nanos(ns)


def _cells(cells) -> list:
    return [int(c) for c in np.asarray(cells, dtype=np.uint64)]


def _uncells(lst) -> np.ndarray:
    return np.array([int(c) for c in (lst or [])], dtype=np.uint64)


def isa_to_doc(isa: ridm.IdentificationServiceArea) -> dict:
    return {
        "id": isa.id,
        "owner": isa.owner,
        "url": isa.url,
        "cells": _cells(isa.cells),
        "start_time": _t(isa.start_time),
        "end_time": _t(isa.end_time),
        "version": str(isa.version) if isa.version else None,
        "altitude_hi": isa.altitude_hi,
        "altitude_lo": isa.altitude_lo,
    }


def doc_to_isa(d: dict) -> ridm.IdentificationServiceArea:
    return ridm.IdentificationServiceArea(
        id=d["id"],
        owner=d["owner"],
        url=d.get("url", ""),
        cells=_uncells(d.get("cells")),
        start_time=_dt(d.get("start_time")),
        end_time=_dt(d.get("end_time")),
        version=Version.from_string(d["version"]) if d.get("version") else None,
        altitude_hi=d.get("altitude_hi"),
        altitude_lo=d.get("altitude_lo"),
    )


def rid_sub_to_doc(s: ridm.Subscription) -> dict:
    return {
        "id": s.id,
        "owner": s.owner,
        "url": s.url,
        "notification_index": s.notification_index,
        "cells": _cells(s.cells),
        "start_time": _t(s.start_time),
        "end_time": _t(s.end_time),
        "version": str(s.version) if s.version else None,
        "altitude_hi": s.altitude_hi,
        "altitude_lo": s.altitude_lo,
    }


def doc_to_rid_sub(d: dict) -> ridm.Subscription:
    return ridm.Subscription(
        id=d["id"],
        owner=d["owner"],
        url=d.get("url", ""),
        notification_index=d.get("notification_index", 0),
        cells=_uncells(d.get("cells")),
        start_time=_dt(d.get("start_time")),
        end_time=_dt(d.get("end_time")),
        version=Version.from_string(d["version"]) if d.get("version") else None,
        altitude_hi=d.get("altitude_hi"),
        altitude_lo=d.get("altitude_lo"),
    )


def op_to_doc(o: scdm.Operation) -> dict:
    return {
        "id": o.id,
        "owner": o.owner,
        "version": o.version,
        "ovn": o.ovn,
        "start_time": _t(o.start_time),
        "end_time": _t(o.end_time),
        "altitude_lower": o.altitude_lower,
        "altitude_upper": o.altitude_upper,
        "uss_base_url": o.uss_base_url,
        "state": o.state,
        "cells": _cells(o.cells),
        "subscription_id": o.subscription_id,
        "constraint_aware": o.constraint_aware,
    }


def doc_to_op(d: dict) -> scdm.Operation:
    return scdm.Operation(
        id=d["id"],
        owner=d["owner"],
        version=d.get("version", 0),
        ovn=d.get("ovn", ""),
        start_time=_dt(d.get("start_time")),
        end_time=_dt(d.get("end_time")),
        altitude_lower=d.get("altitude_lower"),
        altitude_upper=d.get("altitude_upper"),
        uss_base_url=d.get("uss_base_url", ""),
        state=d.get("state", ""),
        cells=_uncells(d.get("cells")),
        subscription_id=d.get("subscription_id", ""),
        constraint_aware=d.get("constraint_aware", False),
    )


def constraint_to_doc(c: scdm.Constraint) -> dict:
    return {
        "id": c.id,
        "owner": c.owner,
        "version": c.version,
        "ovn": c.ovn,
        "start_time": _t(c.start_time),
        "end_time": _t(c.end_time),
        "altitude_lower": c.altitude_lower,
        "altitude_upper": c.altitude_upper,
        "uss_base_url": c.uss_base_url,
        "cells": _cells(c.cells),
    }


def doc_to_constraint(d: dict) -> scdm.Constraint:
    return scdm.Constraint(
        id=d["id"],
        owner=d["owner"],
        version=d.get("version", 0),
        ovn=d.get("ovn", ""),
        start_time=_dt(d.get("start_time")),
        end_time=_dt(d.get("end_time")),
        altitude_lower=d.get("altitude_lower"),
        altitude_upper=d.get("altitude_upper"),
        uss_base_url=d.get("uss_base_url", ""),
        cells=_uncells(d.get("cells")),
    )


def scd_sub_to_doc(s: scdm.Subscription) -> dict:
    return {
        "id": s.id,
        "owner": s.owner,
        "version": s.version,
        "notification_index": s.notification_index,
        "start_time": _t(s.start_time),
        "end_time": _t(s.end_time),
        "altitude_hi": s.altitude_hi,
        "altitude_lo": s.altitude_lo,
        "base_url": s.base_url,
        "notify_for_operations": s.notify_for_operations,
        "notify_for_constraints": s.notify_for_constraints,
        "implicit_subscription": s.implicit_subscription,
        "dependent_operations": list(s.dependent_operations),
        "cells": _cells(s.cells),
    }


def doc_to_scd_sub(d: dict) -> scdm.Subscription:
    return scdm.Subscription(
        id=d["id"],
        owner=d["owner"],
        version=d.get("version", 0),
        notification_index=d.get("notification_index", 0),
        start_time=_dt(d.get("start_time")),
        end_time=_dt(d.get("end_time")),
        altitude_hi=d.get("altitude_hi"),
        altitude_lo=d.get("altitude_lo"),
        base_url=d.get("base_url", ""),
        notify_for_operations=d.get("notify_for_operations", False),
        notify_for_constraints=d.get("notify_for_constraints", False),
        implicit_subscription=d.get("implicit_subscription", False),
        dependent_operations=list(d.get("dependent_operations", [])),
        cells=_uncells(d.get("cells")),
    )
