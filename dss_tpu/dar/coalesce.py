"""Pipelined micro-batching query coalescer for DarTable.

The serving-stack glue between request-per-thread handlers and the
batched fused kernel: concurrent callers enqueue single queries; the
coalescer drains whatever is queued and runs it as ONE
DarTable.query_many batch.  Continuous batching — no timing window:

  - a lone caller runs immediately as a batch of 1 (no added latency),
  - while a batch is in flight, new arrivals queue up and form the
    next batch, so concurrency N collapses to ~1 kernel per round trip
    instead of N round trips.

Three upgrades over the single-worker coalescer this replaces (the
Orca-style iteration-level scheduling shape from LLM serving):

  PIPELINE — the worker is split into a *pack* stage (host: key sort,
  searchsorted, window packing, async device submit via
  DarTable.query_many_submit) and a *collect* stage (device wait + D2H
  decode + overlay merge via DarTable.query_many_collect), each on its
  own thread with a bounded double-buffer queue between them.  A batch
  is always executing on the device while the next one is being packed
  — the overlap bench.py's pipelined leg measures (70 ms pipelined vs
  183 ms serial per 8192 queries), now on the production path.

  ADAPTIVE BATCHING — the drain size is a controller output, not a
  constant: observed per-batch latency above `target_batch_ms` halves
  the next drain, a saturated fast batch doubles it (AIMD-shaped,
  bounded [min_batch, max_batch]).  Small drains keep single-query
  latency near the exact host path; big drains ride the device's
  throughput ceiling under load.

  BACKPRESSURE — the queue is bounded (queue_depth x max_batch).  A
  full queue blocks admission briefly (admission_wait_s) and then
  sheds the request with a typed errors.OverloadedError carrying a
  queue-drain Retry-After estimate; api/app.py maps it to HTTP 429.
  Overload therefore degrades to bounded latency for admitted
  requests + explicit rejections, not an unbounded backlog.

This replaces the reference's per-request SQL round trip to CRDB
(goroutine-per-RPC, pkg/rid/cockroach/identification_service_area.go
:166-197) with the TPU-idiomatic shape: request parallelism becomes
data parallelism over the query batch axis.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import List, Optional

import numpy as np

from dss_tpu import errors
from dss_tpu.dar import budget
from dss_tpu.obs import stages as _stages
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO


class _Item:
    __slots__ = ("keys", "alt_lo", "alt_hi", "t_start", "t_end", "now",
                 "owner_id", "allow_stale", "event", "result", "error")

    def __init__(self, keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
                 allow_stale=False):
        self.keys = keys
        self.alt_lo = -np.inf if alt_lo is None else float(alt_lo)
        self.alt_hi = np.inf if alt_hi is None else float(alt_hi)
        self.t_start = NO_TIME_LO if t_start is None else int(t_start)
        self.t_end = NO_TIME_HI if t_end is None else int(t_end)
        self.now = int(now)
        self.owner_id = -1 if owner_id is None else int(owner_id)
        self.allow_stale = bool(allow_stale)
        self.event = threading.Event()
        self.result: Optional[List[str]] = None
        self.error: Optional[BaseException] = None


class _BatchController:
    """AIMD-shaped drain-size controller.

    Tracks one number: the next batch's max drain size (`cur`).  A
    batch whose end-to-end pipeline time (pack + device + collect)
    exceeds `target_ms` halves it — long batches are what push queue
    wait (and thus p50) past the latency budget.  A SATURATED batch
    (drained the full `cur` — demand exceeds the batch size) finishing
    under target_ms / 2 doubles it — there is headroom to amortize the
    dispatch round trip over more queries.  Unsaturated batches leave
    `cur` alone: demand, not the controller, is the binding constraint.
    """

    __slots__ = ("min_batch", "max_batch", "target_ms", "cur",
                 "grows", "shrinks")

    def __init__(self, min_batch: int = 64, max_batch: int = 4096,
                 target_ms: float = 25.0, start: Optional[int] = None):
        self.min_batch = int(min_batch)
        self.max_batch = int(max_batch)
        self.target_ms = float(target_ms)
        cur = 8 * self.min_batch if start is None else int(start)
        self.cur = max(self.min_batch, min(self.max_batch, cur))
        self.grows = 0
        self.shrinks = 0

    def observe(self, n_items: int, total_ms: float) -> None:
        if total_ms > self.target_ms and self.cur > self.min_batch:
            self.cur = max(self.min_batch, self.cur // 2)
            self.shrinks += 1
        elif (
            n_items >= self.cur
            and total_ms < self.target_ms / 2
            and self.cur < self.max_batch
        ):
            self.cur = min(self.max_batch, self.cur * 2)
            self.grows += 1


def _env_bool(v: str) -> bool:
    s = v.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off", ""):
        return False
    raise ValueError(s)


def env_knobs() -> dict:
    """QueryCoalescer constructor kwargs from DSS_CO_* environment
    variables (the deployment-level serving config; docs/SERVING.md).
    Unset variables are omitted so the constructor defaults hold."""
    out = {}
    for env, key, conv in (
        ("DSS_CO_MIN_BATCH", "min_batch", int),
        ("DSS_CO_MAX_BATCH", "max_batch", int),
        ("DSS_CO_TARGET_BATCH_MS", "target_batch_ms", float),
        ("DSS_CO_QUEUE_DEPTH", "queue_depth", int),
        ("DSS_CO_ADMISSION_WAIT_S", "admission_wait_s", float),
        ("DSS_CO_PIPELINE_DEPTH", "pipeline_depth", int),
        ("DSS_CO_INLINE", "inline", _env_bool),
    ):
        raw = os.environ.get(env)
        if raw is not None:
            try:
                out[key] = conv(raw)
            except ValueError:
                raise ValueError(f"{env}={raw!r} is not a valid {key}")
    return out


# inflight-queue sentinel: tells the collect stage to exit
_DONE = object()


class QueryCoalescer:
    """Pipelined two-stage coalescer: pack thread + collect thread per
    DarTable, bounded admission, adaptive drain size."""

    def __init__(
        self,
        table,
        *,
        min_batch: int = 64,
        max_batch: int = 4096,
        target_batch_ms: float = 25.0,
        queue_depth: int = 4,
        admission_wait_s: float = 0.25,
        pipeline_depth: int = 2,
        inline: bool = True,
    ):
        self._table = table
        self._cond = threading.Condition()
        self._queue: List[_Item] = []
        self._closed = False
        self._busy = False  # an inline batch is executing on a caller
        self._packing = False  # the pack stage is mid-drain
        self._inflight = 0  # packed batches not yet collected
        self._ctl = _BatchController(
            min_batch=min_batch, max_batch=max_batch,
            target_ms=target_batch_ms,
        )
        self._queue_depth = int(queue_depth)
        self._max_queue = self._queue_depth * self._ctl.max_batch
        self._admission_wait_s = float(admission_wait_s)
        self._inline = bool(inline)
        self._inflight_q: _queue.Queue = _queue.Queue(
            maxsize=max(1, int(pipeline_depth))
        )
        self._pack_thread: Optional[threading.Thread] = None
        self._collect_thread: Optional[threading.Thread] = None
        # stage-time + shed accounting (stats() -> /metrics gauges)
        self._slock = threading.Lock()
        self._stat_batches = 0
        self._stat_items = 0
        self._stat_inline = 0
        self._stat_shed = 0
        self._stat_pack_ms = 0.0
        self._stat_device_ms = 0.0
        self._stat_collect_ms = 0.0
        self._stat_last_batch = 0
        self._ema_qps = 0.0  # recent drain throughput, for Retry-After
        # optional multi-chip offload: big read-only batches can run on
        # a fresh ShardedReplica mesh instead of the local device
        self._mesh_fn = None
        self._mesh_fresh = None
        self._mesh_min = 64
        self._mesh_max = 256  # beyond this, ONE local fused dispatch
        #                       beats serialized mesh chunk round trips
        self.mesh_offloads = 0

    def set_mesh_delegate(self, fn, fresh_fn, min_batch: int = 64):
        """Route batches of >= min_batch bounded-staleness queries
        (every item flagged allow_stale, no owner filters) to `fn`
        (the ShardedReplica mesh) when fresh_fn() says the replica is
        caught up.  Conflict prechecks never set allow_stale, so
        correctness-critical reads always hit the local table."""
        self._mesh_fn = fn
        self._mesh_fresh = fresh_fn
        self._mesh_min = min_batch

    def configure(
        self,
        *,
        min_batch: Optional[int] = None,
        max_batch: Optional[int] = None,
        target_batch_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        admission_wait_s: Optional[float] = None,
        inline: Optional[bool] = None,
    ) -> None:
        """Adjust serving knobs at runtime (ops endpoint / tests).
        Pipeline depth is fixed at construction (the double buffer)."""
        with self._cond:
            if min_batch is not None:
                self._ctl.min_batch = int(min_batch)
            if max_batch is not None:
                self._ctl.max_batch = int(max_batch)
            if target_batch_ms is not None:
                self._ctl.target_ms = float(target_batch_ms)
            self._ctl.cur = max(
                self._ctl.min_batch, min(self._ctl.max_batch, self._ctl.cur)
            )
            if queue_depth is not None:
                self._queue_depth = int(queue_depth)
            self._max_queue = self._queue_depth * self._ctl.max_batch
            if admission_wait_s is not None:
                self._admission_wait_s = float(admission_wait_s)
            if inline is not None:
                self._inline = bool(inline)
            self._cond.notify_all()

    def _ensure_threads(self):
        if self._pack_thread is None or not self._pack_thread.is_alive():
            self._pack_thread = threading.Thread(
                target=self._pack_loop, name="dar-coalescer-pack",
                daemon=True,
            )
            self._pack_thread.start()
        if (
            self._collect_thread is None
            or not self._collect_thread.is_alive()
        ):
            self._collect_thread = threading.Thread(
                target=self._collect_loop, name="dar-coalescer-collect",
                daemon=True,
            )
            self._collect_thread.start()

    def _retry_after_locked(self) -> float:
        """Queue-drain horizon estimate for the 429 Retry-After."""
        backlog = len(self._queue) + self._inflight * self._ctl.cur
        if self._ema_qps > 1.0:
            est = backlog / self._ema_qps
        else:
            est = 1.0
        return min(5.0, max(0.05, est))

    def query(
        self,
        keys: np.ndarray,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now: int,
        owner_id=None,
        allow_stale: bool = False,
    ) -> List[str]:
        """Blocking single query, executed as part of a micro-batch.
        Raises errors.OverloadedError when the bounded queue stays full
        past the admission wait (the caller should back off)."""
        keys = np.asarray(keys, np.int32).ravel()
        if len(keys) == 0:
            return []
        item = _Item(
            keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
            allow_stale,
        )
        inline = False
        deadline = None
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("coalescer is closed")
                if (
                    self._inline
                    and not self._busy
                    and not self._packing
                    and self._inflight == 0
                    and not self._queue
                ):
                    # lone caller: run inline as a batch of 1 — skips
                    # two thread handoffs (~0.15 ms on a loaded host).
                    # Reads are lock-free (immutable state grab), so
                    # executing on the caller's thread is safe; `_busy`
                    # makes arrivals during execution queue up and
                    # batch as before.
                    self._busy = True
                    inline = True
                    break
                if budget.is_host_only():
                    # event-loop caller would block in event.wait()
                    # behind another thread's (possibly compiling)
                    # batch: bounce to the executor path instead
                    raise budget.NeedsDevice()
                if len(self._queue) < self._max_queue:
                    self._queue.append(item)
                    self._ensure_threads()
                    self._cond.notify_all()
                    break
                # admission control: the queue is at capacity.  Wait a
                # bounded moment for the pipeline to drain, then shed —
                # bounded latency for admitted work beats a backlog
                # whose p50 grows without limit.
                t_mono = time.monotonic()
                if deadline is None:
                    deadline = t_mono + max(0.0, self._admission_wait_s)
                if t_mono >= deadline:
                    with self._slock:
                        self._stat_shed += 1
                    raise errors.OverloadedError(
                        f"query queue full ({self._max_queue} deep); "
                        "request shed",
                        retry_after_s=self._retry_after_locked(),
                    )
                self._cond.wait(deadline - t_mono)
        if inline:
            try:
                self._execute([item])
                with self._slock:
                    self._stat_inline += 1
            finally:
                with self._cond:
                    self._busy = False
                    if self._queue and not self._closed:
                        self._ensure_threads()
                    self._cond.notify_all()
        else:
            t_wait = time.perf_counter()
            item.event.wait()
            _stages.mark(
                "coalesce_wait_ms",
                (time.perf_counter() - t_wait) * 1000,
            )
        if item.error is not None:
            raise item.error
        return item.result

    def close(self, join: bool = True, timeout: float = 30.0):
        """Stop accepting queries and (by default) wait for BOTH stages
        to drain — queued and in-flight batches complete, and joining
        prevents the interpreter tearing down the device runtime while
        a stage is mid-dispatch."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            pack_th = self._pack_thread
            coll_th = self._collect_thread
        if not join:
            return
        me = threading.current_thread()
        for th in (pack_th, coll_th):
            if th is not None and th is not me:
                th.join(timeout)

    # -- pipeline stages ------------------------------------------------------

    def _mesh_eligible(self, batch: List[_Item]) -> bool:
        return (
            self._mesh_fn is not None
            and self._mesh_min <= len(batch) <= self._mesh_max
            and all(it.allow_stale and it.owner_id < 0 for it in batch)
        )

    def _pack_loop(self):
        """Stage 1: drain the queue, pack windows on the host, start
        the device kernel asynchronously.  Hands (batch, pending) to
        the collect stage through a bounded double buffer, so pack of
        batch N+1 overlaps device execution + decode of batch N."""
        while True:
            with self._cond:
                # also wait while an inline batch is executing: its
                # arrivals should form ONE next batch, not race it
                while (not self._queue or self._busy) and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    break
                n = min(len(self._queue), self._ctl.cur)
                batch = self._queue[:n]
                del self._queue[:n]
                self._packing = True
                self._inflight += 1
                # queue space just opened: wake admission waiters
                self._cond.notify_all()
            t0 = time.perf_counter()
            pq = None
            kind = "exec"
            try:
                if not self._mesh_eligible(batch):
                    submit = getattr(self._table, "query_many_submit", None)
                    if submit is not None:
                        keys, lo, hi, t0s, t1s, now, owners = (
                            self._pack_args(batch)
                        )
                        pq = submit(
                            keys, lo, hi, t0s, t1s,
                            now=now, owner_ids=owners,
                        )
                        kind = "table"
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                self._deliver_error(batch, e)
                with self._cond:
                    self._packing = False
                    self._inflight -= 1
                    self._cond.notify_all()
                continue
            pack_ms = (time.perf_counter() - t0) * 1000
            # bounded handoff: blocks when the collect stage is
            # pipeline_depth batches behind (the double buffer)
            self._inflight_q.put((batch, kind, pq, pack_ms))
            with self._cond:
                self._packing = False
        # shutdown sentinel — put OUTSIDE the condition lock: the
        # handoff queue may be full, and blocking on put() while
        # holding _cond deadlocks against the collect stage's
        # end-of-batch `with self._cond` accounting (collect could
        # then never drain the queue to unblock this put)
        self._inflight_q.put(_DONE)

    def _collect_loop(self):
        """Stage 2: wait for the device, decode, deliver results, and
        feed the batch-size controller."""
        while True:
            handoff = self._inflight_q.get()
            if handoff is _DONE:
                return
            batch, kind, pq, pack_ms = handoff
            t0 = time.perf_counter()
            t1 = t0
            device_ms = 0.0
            try:
                if kind == "table":
                    pq.wait_device()
                    t1 = time.perf_counter()
                    device_ms = (t1 - t0) * 1000
                    results = self._table.query_many_collect(pq)
                    for it, res in zip(batch, results):
                        it.result = res
                        it.event.set()
                else:
                    # mesh-eligible (or submit-less table): the full
                    # synchronous path, mesh-first with local fallback
                    self._execute(batch)
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                self._deliver_error(batch, e)
            collect_ms = (time.perf_counter() - t1) * 1000
            total_ms = pack_ms + device_ms + collect_ms
            with self._slock:
                self._stat_batches += 1
                self._stat_items += len(batch)
                self._stat_pack_ms += pack_ms
                self._stat_device_ms += device_ms
                self._stat_collect_ms += collect_ms
                self._stat_last_batch = len(batch)
                if total_ms > 0:
                    inst = len(batch) / (total_ms / 1000.0)
                    self._ema_qps = (
                        inst if self._ema_qps == 0.0
                        else 0.8 * self._ema_qps + 0.2 * inst
                    )
            with self._cond:
                self._ctl.observe(len(batch), total_ms)
                self._inflight -= 1
                self._cond.notify_all()

    @staticmethod
    def _deliver_error(batch: List[_Item], e: BaseException) -> None:
        for it in batch:
            if not it.event.is_set():
                it.error = e
                it.event.set()

    @staticmethod
    def _pack_args(batch: List[_Item]):
        """Marshal a batch into the array arguments shared by
        query_many / query_many_submit / the mesh fn."""
        return (
            [it.keys for it in batch],
            np.asarray([it.alt_lo for it in batch], np.float32),
            np.asarray([it.alt_hi for it in batch], np.float32),
            np.asarray([it.t_start for it in batch], np.int64),
            np.asarray([it.t_end for it in batch], np.int64),
            np.asarray([it.now for it in batch], np.int64),
            np.asarray([it.owner_id for it in batch], np.int32),
        )

    # -- synchronous execution (inline path + mesh batches) -------------------

    def _execute(self, batch: List[_Item]):
        try:
            b = len(batch)
            if (
                self._mesh_fn is not None
                and self._mesh_min <= b <= self._mesh_max
                and all(
                    it.allow_stale and it.owner_id < 0 for it in batch
                )
                and self._mesh_fresh()
            ):
                try:
                    # chunk to the warmed jit bucket (the replica warms
                    # batch=min_batch per rebuild): a 65..4096 batch
                    # must not stall every caller on a fresh multi-chip
                    # compile for an unwarmed pow2 bucket
                    for start in range(0, b, self._mesh_min):
                        part = batch[start : start + self._mesh_min]
                        keys, lo, hi, t0s, t1s, now, _ = (
                            self._pack_args(part)
                        )
                        results = self._mesh_fn(
                            keys, lo, hi, t0s, t1s, now
                        )
                        for it, res in zip(part, results):
                            it.result = res
                            it.event.set()
                    self.mesh_offloads += 1
                    return
                except Exception:  # noqa: BLE001 — fall back local
                    import logging

                    logging.getLogger("dss.dar").exception(
                        "mesh offload failed; serving batch locally"
                    )
            keys, lo, hi, t0s, t1s, now, owners = self._pack_args(batch)
            results = self._table.query_many(
                keys, lo, hi, t0s, t1s, now=now, owner_ids=owners,
            )
            for it, res in zip(batch, results):
                it.result = res
                it.event.set()
        except BaseException as e:  # noqa: BLE001 — deliver to callers
            self._deliver_error(batch, e)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        """Serving-pipeline gauges (flow into /metrics via the index's
        stats): queue depth, adaptive batch size, per-stage time
        totals, shed count."""
        with self._cond:
            out = {
                "co_queue_depth": len(self._queue),
                "co_queue_cap": self._max_queue,
                "co_inflight": self._inflight,
                "co_batch_size": self._ctl.cur,
                "co_batch_grows": self._ctl.grows,
                "co_batch_shrinks": self._ctl.shrinks,
            }
        with self._slock:
            out.update(
                co_batches=self._stat_batches,
                co_items=self._stat_items,
                co_inline=self._stat_inline,
                co_shed=self._stat_shed,
                co_pack_ms_total=round(self._stat_pack_ms, 3),
                co_device_ms_total=round(self._stat_device_ms, 3),
                co_collect_ms_total=round(self._stat_collect_ms, 3),
                co_last_batch=self._stat_last_batch,
                co_ema_qps=round(self._ema_qps, 1),
            )
        out["mesh_offloads"] = self.mesh_offloads
        return out
