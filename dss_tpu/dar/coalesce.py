"""Pipelined micro-batching query coalescer for DarTable.

The serving-stack glue between request-per-thread handlers and the
batched fused kernel: concurrent callers enqueue single queries; the
coalescer drains whatever is queued and runs it as ONE
DarTable.query_many batch.  Continuous batching — no timing window:

  - a lone caller runs immediately as a batch of 1 (no added latency),
  - while a batch is in flight, new arrivals queue up and form the
    next batch, so concurrency N collapses to ~1 kernel per round trip
    instead of N round trips.

Four upgrades over the single-worker coalescer this replaces (the
Orca-style iteration-level scheduling shape from LLM serving, plus
Clockwork-style predictable-latency admission):

  PIPELINE — the worker is split into a *pack* stage (host: key sort,
  searchsorted, window packing, async device submit via
  DarTable.query_many_submit) and a *collect* stage (device wait + D2H
  decode + overlay merge via DarTable.query_many_collect), each on its
  own thread with a bounded double-buffer queue between them.  A batch
  is always executing on the device while the next one is being packed
  — the overlap bench.py's pipelined leg measures (70 ms pipelined vs
  183 ms serial per 8192 queries), now on the production path.

  ADAPTIVE BATCHING — the drain size is a controller output, not a
  constant: observed per-batch latency above `target_batch_ms` halves
  the next drain, a saturated fast batch doubles it (AIMD-shaped,
  bounded [min_batch, max_batch]).  Small drains keep single-query
  latency near the exact host path; big drains ride the device's
  throughput ceiling under load.

  BACKPRESSURE — the queue is bounded (queue_depth x max_batch).  A
  full queue blocks admission briefly (admission_wait_s) and then
  sheds the request with a typed errors.OverloadedError carrying a
  queue-drain Retry-After estimate from the live drain-rate EWMA;
  api/app.py maps it to HTTP 429.  Overload therefore degrades to
  bounded latency for admitted requests + explicit rejections, not an
  unbounded backlog.

  DEADLINE-AWARE ROUTING — every item carries an absolute deadline
  (admission time + the DSS_CO_SLO_MS serving SLO, capped by the HTTP
  route deadline that dar/deadline.py propagates from the timeout
  middleware).  The coalescer keeps online EWMA cost models
  (_CostModel: device dispatch floor, per-item device batch cost,
  per-chunk host-scan cost — seeded at boot, updated from every
  completed batch, exported as co_est_* gauges) and routes each
  drained batch by PREDICTED cost against the tightest queued
  headroom: when the fused device path (floor + batch cost + queued
  device work) would blow that headroom, the batch is served as
  chunked exact host scans (FastTable.query_host_chunked — the ~100 us
  exact path, chunked to the warmed bucket) and the device kernel is
  reserved for bulk, stale-ok, and headroom-rich batches.  The drain
  size itself is deadline-capped (never drain more than the predicted
  route cost fits into the minimum queued headroom), and items whose
  deadline already expired in queue are fast-shed with a typed
  DEADLINE_EXCEEDED error (HTTP 504) instead of occupying a kernel
  slot.  A static size threshold put the p50<5 ms serving knee at the
  batch-size cliff (any drain > 64 paid the ~110 ms tunneled dispatch
  floor); measured-cost routing is what moves the knee to the host's
  actual scan throughput.

  RESIDENT ROUTE — when the resident serving kernel (ops/resident.py)
  is attached, the device class splits in two: the cold fused dispatch
  (one round trip per pack-stage submit) and the resident loop's
  persistent device stream (AOT shape buckets, donated I/O, a feeder
  that keeps several batches in flight so dispatch cost amortizes).
  The router treats resident as a third candidate with ITS OWN
  cost-model key (est_res_floor_ms, seeded by DSS_CO_EST_RES_FLOOR_MS)
  fed only by resident observations — so the floor it learns is the
  amortized resident floor, never polluted by (or polluting) the
  cold-dispatch estimate.  A full resident ring falls back to the cold
  path; the pack stage never blocks on the device stream.

  PLANNED ROUTING — as of the plan layer (dss_tpu/plan), every route
  decision here is a Plan produced by one Planner that owns ALL cost
  models: the pack stage, the inline lone-caller path, the drain cap,
  and the Retry-After estimate consume plans instead of re-deriving
  costs, so the drain sizing and the route choice can never disagree,
  and a decision is a pure function of (batch shape, model state,
  clock) — unit-testable with no live coalescer, no device, no
  threads (tests/test_planner.py pins decision-identity against the
  pre-planner router).  Adding a route touches dss_tpu/plan/planner.py
  only.

This replaces the reference's per-request SQL round trip to CRDB
(goroutine-per-RPC, pkg/rid/cockroach/identification_service_area.go
:166-197) with the TPU-idiomatic shape: request parallelism becomes
data parallelism over the query batch axis.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import List, Optional

import numpy as np

from dss_tpu import chaos, errors
from dss_tpu.dar import budget
from dss_tpu.dar import deadline as _deadline
from dss_tpu.obs import stages as _stages
from dss_tpu.obs import trace as _trace
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO
from dss_tpu.plan import (
    HEADROOM_SAFETY as _PLAN_HEADROOM_SAFETY,
)
from dss_tpu.plan import (
    BatchShape,
    CostModel,
    Planner,
    plan_drain_cap,
)
from dss_tpu.plan.planner import state_of as _plan_state_of


class _Item:
    __slots__ = ("keys", "alt_lo", "alt_hi", "t_start", "t_end", "now",
                 "owner_id", "allow_stale", "deadline", "event", "result",
                 "error", "via_mesh", "tctx", "tspans", "enq_ns")

    def __init__(self, keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
                 allow_stale=False, deadline=None):
        self.keys = keys
        self.alt_lo = -np.inf if alt_lo is None else float(alt_lo)
        self.alt_hi = np.inf if alt_hi is None else float(alt_hi)
        self.t_start = NO_TIME_LO if t_start is None else int(t_start)
        self.t_end = NO_TIME_HI if t_end is None else int(t_end)
        self.now = int(now)
        self.owner_id = -1 if owner_id is None else int(owner_id)
        self.allow_stale = bool(allow_stale)
        # absolute monotonic instant by which this query must complete
        # (None = no deadline); set at admission from the SLO + the
        # propagated route deadline, consumed by the batch router
        self.deadline: Optional[float] = deadline
        self.event = threading.Event()
        self.result: Optional[List[str]] = None
        self.error: Optional[BaseException] = None
        # answered by the sharded mesh replica (bounded-stale): the
        # read cache must not stamp this result as fresh
        self.via_mesh = False
        # cross-thread span handoff (obs/trace.py): the caller's trace
        # handle captured at admission; the pipeline threads STAMP
        # measured (name, start_ns, dur_ms, attrs) tuples here and the
        # caller's own thread records them after the event resolves —
        # queue-wait, plan, dispatch, collect become parented spans
        # without the pipeline ever touching the recorder.  All None/0
        # when tracing is off: one branch per item.
        self.tctx = None
        self.tspans = None
        self.enq_ns = 0

    def expired(self, now_monotonic: float) -> bool:
        return self.deadline is not None and self.deadline <= now_monotonic


# The cost model moved to dss_tpu/plan/costs.py (the planner owns it
# now); the name is re-exported here because the serving tests and
# docs grew up calling it _CostModel.
_CostModel = CostModel


class _BatchController:
    """AIMD-shaped drain-size controller.

    Tracks one number: the next batch's max drain size (`cur`).  A
    batch whose end-to-end pipeline time (pack + device + collect)
    exceeds `target_ms` halves it — long batches are what push queue
    wait (and thus p50) past the latency budget.  A SATURATED batch
    (drained the full `cur` — demand exceeds the batch size) finishing
    under target_ms / 2 doubles it — there is headroom to amortize the
    dispatch round trip over more queries.  Unsaturated batches leave
    `cur` alone: demand, not the controller, is the binding constraint.
    """

    __slots__ = ("min_batch", "max_batch", "target_ms", "cur",
                 "grows", "shrinks")

    def __init__(self, min_batch: int = 64, max_batch: int = 4096,
                 target_ms: float = 25.0, start: Optional[int] = None):
        self.min_batch = int(min_batch)
        self.max_batch = int(max_batch)
        self.target_ms = float(target_ms)
        cur = 8 * self.min_batch if start is None else int(start)
        self.cur = max(self.min_batch, min(self.max_batch, cur))
        self.grows = 0
        self.shrinks = 0

    def observe(self, n_items: int, total_ms: float) -> None:
        if total_ms > self.target_ms and self.cur > self.min_batch:
            self.cur = max(self.min_batch, self.cur // 2)
            self.shrinks += 1
        elif (
            n_items >= self.cur
            and total_ms < self.target_ms / 2
            and self.cur < self.max_batch
        ):
            self.cur = min(self.max_batch, self.cur * 2)
            self.grows += 1

    def drain_cap(
        self, headroom_ms: Optional[float], cost: _CostModel,
        inflight: int, inflight_host_chunks: int = 0,
        resident_ready: bool = False, inflight_resident: int = 0,
    ) -> int:
        """Deadline-aware drain bound — the logic lives in
        plan.plan_drain_cap (one HEADROOM_SAFETY budget shared with
        the route choice, so the drain sizing and the plan can never
        disagree); this shim keeps the controller's historical call
        shape for callers that hold a bare cost model (the coalescer
        itself goes through its planner in _drain_locked)."""
        state = _plan_state_of(
            cost,
            inflight_device=int(inflight),
            inflight_host_chunks=int(inflight_host_chunks),
            inflight_resident=int(inflight_resident),
            resident_ready=bool(resident_ready),
        )
        return plan_drain_cap(self.cur, headroom_ms, state)


def _env_bool(v: str) -> bool:
    s = v.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off", ""):
        return False
    raise ValueError(s)


def env_knobs() -> dict:
    """QueryCoalescer constructor kwargs from DSS_CO_* environment
    variables (the deployment-level serving config; docs/SERVING.md).
    Unset variables are omitted so the constructor defaults hold."""
    out = {}
    for env, key, conv in (
        ("DSS_CO_MIN_BATCH", "min_batch", int),
        ("DSS_CO_MAX_BATCH", "max_batch", int),
        ("DSS_CO_TARGET_BATCH_MS", "target_batch_ms", float),
        ("DSS_CO_QUEUE_DEPTH", "queue_depth", int),
        ("DSS_CO_ADMISSION_WAIT_S", "admission_wait_s", float),
        ("DSS_CO_PIPELINE_DEPTH", "pipeline_depth", int),
        ("DSS_CO_INLINE", "inline", _env_bool),
        # deadline-aware routing: the per-query serving SLO (0 disables
        # SLO-derived deadlines; route deadlines still apply) and the
        # boot seeds of the EWMA cost models
        ("DSS_CO_SLO_MS", "slo_ms", float),
        ("DSS_CO_EST_FLOOR_MS", "est_floor_ms", float),
        ("DSS_CO_EST_ITEM_MS", "est_item_ms", float),
        ("DSS_CO_EST_CHUNK_MS", "est_chunk_ms", float),
        # resident serving kernel (ops/resident.py): enable the
        # persistent device-feeder loop, seed ITS OWN floor estimate
        # (never shared with the cold-device floor), and size the
        # host ring / device stream depth
        ("DSS_CO_RESIDENT", "resident", _env_bool),
        ("DSS_CO_EST_RES_FLOOR_MS", "est_res_floor_ms", float),
        ("DSS_CO_EST_RES_LAT_MS", "est_res_lat_ms", float),
        ("DSS_CO_RES_RING", "res_ring", int),
        ("DSS_CO_RES_INFLIGHT", "res_inflight", int),
    ):
        raw = os.environ.get(env)
        if raw is not None:
            try:
                out[key] = conv(raw)
            except ValueError:
                raise ValueError(f"{env}={raw!r} is not a valid {key}")
    return out


# inflight-queue sentinel: tells the collect stage to exit
_DONE = object()

# fraction of a batch's tightest headroom the planner budgets for the
# serving route itself (the rest covers decode + caller wake) — the
# value now lives in dss_tpu/plan/planner.py, shared by the route
# choice and plan_drain_cap so they can never disagree.
_HEADROOM_SAFETY = _PLAN_HEADROOM_SAFETY


class QueryCoalescer:
    """Pipelined two-stage coalescer: pack thread + collect thread per
    DarTable, bounded admission, adaptive drain size."""

    def __init__(
        self,
        table,
        *,
        min_batch: int = 64,
        max_batch: int = 4096,
        target_batch_ms: float = 25.0,
        queue_depth: int = 4,
        admission_wait_s: float = 0.25,
        pipeline_depth: int = 2,
        inline: bool = True,
        slo_ms: float = 0.0,  # 0 = no SLO-derived deadlines: items
        #   carry only the propagated route deadline.  Deployments
        #   chasing a joint qps+latency target set DSS_CO_SLO_MS (the
        #   bench legs run with 50 ms) — the router only ever forces
        #   the host route under REAL deadline pressure, so the
        #   conservative default cannot regress bulk throughput.
        est_floor_ms: float = 20.0,
        est_item_ms: float = 0.02,
        est_chunk_ms: float = 0.3,
        resident: bool = False,  # enable the resident serving kernel
        #   (ops/resident.py): a persistent device-feeder loop with
        #   AOT shape buckets + donated I/O becomes a third route
        #   candidate with its own cost-model key.  Servers on the tpu
        #   backend enable it (cmds/server.py --no_resident opts out);
        #   default off so host-only callers and tests keep the
        #   two-route behavior unless they ask.
        est_res_floor_ms: Optional[float] = None,  # resident floor
        #   seed (DSS_CO_EST_RES_FLOOR_MS); None = est_floor_ms / 4
        est_res_lat_ms: Optional[float] = None,  # resident stream
        #   full-latency seed (DSS_CO_EST_RES_LAT_MS); None =
        #   est_floor_ms — one round trip, the honest prior
        res_ring: int = 32,  # resident host ring capacity (batches)
        res_inflight: int = 4,  # resident device stream depth
        clock=time.monotonic,  # injectable for fake-clock routing tests
    ):
        self._table = table
        self._cond = threading.Condition()
        self._queue: List[_Item] = []
        self._closed = False
        self._busy = False  # an inline batch is executing on a caller
        self._packing = False  # the pack stage is mid-drain
        self._inflight = 0  # packed batches not yet collected
        self._inflight_items = 0  # queries inside those batches
        self._inflight_device = 0  # of those batches: on the device
        self._inflight_host_chunks = 0  # forced-host chunks queued at
        #                                 the collect thread
        self._ctl = _BatchController(
            min_batch=min_batch, max_batch=max_batch,
            target_ms=target_batch_ms,
        )
        self._queue_depth = int(queue_depth)
        self._max_queue = self._queue_depth * self._ctl.max_batch
        self._admission_wait_s = float(admission_wait_s)
        self._inline = bool(inline)
        self._clock = clock
        # per-query serving SLO: each admitted item must complete
        # within slo_ms (capped by the propagated route deadline);
        # 0 disables SLO-derived deadlines
        self._slo_ms = float(slo_ms)
        # the host-chunk bucket mirrors the warmed host-path width
        # every table serves chunks at (FastTable.HOST_MAX_BATCH)
        try:
            from dss_tpu.ops.fastpath import FastTable as _FT

            chunk = _FT.HOST_MAX_BATCH
        except Exception:  # pragma: no cover
            chunk = 64
        # the planner owns ALL cost models (dss_tpu/plan): every route
        # decision, the drain sizing, and the Retry-After throughput
        # read the same estimates through it.  self._cost stays as the
        # live CostModel alias — observation call sites and the
        # routing tests address it directly.
        self._planner = Planner(
            floor_ms=est_floor_ms, item_ms=est_item_ms,
            chunk_ms=est_chunk_ms, chunk=chunk,
            res_floor_ms=est_res_floor_ms, res_lat_ms=est_res_lat_ms,
        )
        self._cost = self._planner.cost
        # resident loop (created on demand — needs a table with the
        # submit/collect split)
        self._res_loop = None
        self._res_ring = int(res_ring)
        self._res_inflight = int(res_inflight)
        self._inflight_resident = 0  # batches queued at the res loop
        if resident:
            self._make_resident_loop()
        self._inflight_q: _queue.Queue = _queue.Queue(
            maxsize=max(1, int(pipeline_depth))
        )
        self._pack_thread: Optional[threading.Thread] = None
        self._collect_thread: Optional[threading.Thread] = None
        # stage-time + shed accounting (stats() -> /metrics gauges)
        self._slock = threading.Lock()
        self._stat_batches = 0
        self._stat_items = 0
        self._stat_inline = 0
        self._stat_shed = 0
        self._stat_deadline_shed = 0
        self._stat_route_host = 0  # batches fully served on the host
        self._stat_route_hostchunk = 0  # of those: forced chunked route
        self._stat_route_device = 0  # batches that touched the device
        self._stat_route_resident = 0  # batches via the resident loop
        self._stat_device_loss_absorbed = 0  # device-loss batches
        #   re-served on the host instead of erroring callers
        self._stat_pack_ms = 0.0
        self._stat_device_ms = 0.0
        self._stat_collect_ms = 0.0
        self._stat_last_batch = 0
        self._ema_qps = 0.0  # recent drain throughput, for Retry-After
        # optional read-cache counter view (set_cache_view): per-class
        # co_cache_* gauges merged into stats()
        self._cache_view = None
        # optional per-key-range load accounting (set_load_view): every
        # locally-served query stamps its covering's buckets, feeding
        # the skew-aware shard rebalancer
        self._load_view = None
        # optional multi-chip offload: big read-only batches can run on
        # a fresh ShardedReplica mesh instead of the local device
        self._mesh_fn = None
        self._mesh_fresh = None
        self._mesh_min = 64
        self._mesh_max = 256  # beyond this, ONE local fused dispatch
        #                       beats serialized mesh chunk round trips
        self._mesh_bgen = None  # replica boundary-generation getter:
        #   plans record WHICH shard placement they were made against
        self.mesh_offloads = 0
        # optional degradation ladder (chaos.DegradationLadder): when
        # attached, device-loss failures flip DEVICE_LOST (the planner
        # stops admitting device-class routes) and the failed batch is
        # re-served on the host — no caller ever sees the loss
        self._health = None

    def _make_resident_loop(self):
        """Create (once) the resident device-feeder loop and install
        the fold-time AOT warm hook on the table.  Requires the
        submit/collect split; silently stays off for plain tables."""
        if self._res_loop is not None:
            return
        if getattr(self._table, "query_many_submit", None) is None:
            return
        from dss_tpu.ops.resident import ResidentLoop

        self._res_loop = ResidentLoop(
            self._table,
            ring_capacity=self._res_ring,
            max_inflight=self._res_inflight,
        )
        set_warm = getattr(self._table, "set_resident_warm", None)
        if set_warm is not None:
            kern = self._res_loop.kernel

            def warm_hook(ft, _kern=kern):
                # fold-time warm: only tables big enough to route to
                # the device are worth AOT grid compiles — the tiny L1
                # tiers a minor fold rebuilds serve from the host path
                # anyway, and their block count changes every fold.
                # ASYNC on purpose: a synchronous grid compile inside
                # the fold would re-introduce the O(table) stall the
                # tiered snapshots removed; until a bucket lands,
                # submits ride the shared jit exactly as before.
                if ft.n_postings >= 1 << 14:
                    _kern.warm_async(ft)

            set_warm(warm_hook)

    def resident_loop(self):
        """The attached ResidentLoop, or None (boot warm + tests)."""
        return self._res_loop

    def set_health(self, ladder) -> None:
        """Attach the store's degradation ladder (dss_store wiring):
        the planner reads device_ok from it and device-loss failures
        report into it."""
        self._health = ladder

    def _device_ok(self) -> bool:
        h = self._health
        return True if h is None else h.device_ok()

    def _absorb_device_loss(self, e: BaseException) -> bool:
        """Is `e` a device loss this pipeline should absorb (report
        DEVICE_LOST to the ladder, re-serve the batch on the host)
        instead of delivering to callers?"""
        if not chaos.is_device_loss(e):
            return False
        with self._slock:
            self._stat_device_loss_absorbed += 1
        if self._health is not None:
            self._health.enter("device_lost", reason=str(e))
        return True

    def _host_rerun(self, batch: List[_Item]) -> None:
        """Serve a device-failed batch via forced host chunks — the
        pure-host path (FastTable.query_host_chunked), so a lost
        device costs latency, never correctness or a caller 5xx."""
        try:
            keys, lo, hi, t0s, t1s, now, owners = self._pack_args(batch)
            submit = getattr(self._table, "query_many_submit", None)
            if submit is not None:
                pq = submit(
                    keys, lo, hi, t0s, t1s, now=now, owner_ids=owners,
                    host_route=True,
                )
                self._deliver_results(
                    batch, self._table.query_many_collect(pq)
                )
            else:
                self._deliver_results(
                    batch,
                    self._table.query_many(
                        keys, lo, hi, t0s, t1s, now=now,
                        owner_ids=owners, host_route=True,
                    ),
                )
        except BaseException as e:  # noqa: BLE001 — deliver to callers
            self._deliver_error(batch, e)

    def set_cache_view(self, fn) -> None:
        """Attach the read cache's per-class counter view (readcache
        .ReadCache.class_stats): co_cache_{hits,misses,invalidations}
        then ride this coalescer's stats into /metrics as
        dss_dar_<class>_co_cache_* — hits ARE part of the serving
        story (they bypass this pipeline entirely: no admission, no
        deadline stamp, no Retry-After backlog contribution)."""
        self._cache_view = fn

    def set_load_view(self, load) -> None:
        """Attach a tiers.RangeLoad: every query THIS pipeline serves
        records its covering + measured result work into the per-key-
        range load EWMA the skew-aware shard splitter plans from.
        Only coalescer-served traffic counts by construction — read-
        cache hits bypass the pipeline entirely and never reach a
        shard, and mesh-offloaded batches are recorded by the replica
        itself (its own serving entry), never double-counted here."""
        self._load_view = load

    def set_mesh_delegate(self, fn, fresh_fn, min_batch: int = 64,
                          bgen_fn=None):
        """Route batches of >= min_batch bounded-staleness queries
        (every item flagged allow_stale, no owner filters) to `fn`
        (the ShardedReplica mesh) when fresh_fn() says the replica is
        caught up.  Conflict prechecks never set allow_stale, so
        correctness-critical reads always hit the local table.
        `bgen_fn` (optional) reports the replica's shard-boundary
        generation so every Plan records which placement it was
        decided against."""
        self._mesh_fn = fn
        self._mesh_fresh = fresh_fn
        self._mesh_min = min_batch
        self._mesh_bgen = bgen_fn

    def configure(
        self,
        *,
        min_batch: Optional[int] = None,
        max_batch: Optional[int] = None,
        target_batch_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        admission_wait_s: Optional[float] = None,
        inline: Optional[bool] = None,
        slo_ms: Optional[float] = None,
        resident: Optional[bool] = None,
        est_floor_ms: Optional[float] = None,
        est_item_ms: Optional[float] = None,
        est_chunk_ms: Optional[float] = None,
        est_res_floor_ms: Optional[float] = None,
        est_res_lat_ms: Optional[float] = None,
        res_ring: Optional[int] = None,
        res_inflight: Optional[int] = None,
    ) -> None:
        """Adjust serving knobs at runtime (ops endpoint / tests / the
        tune actuator).  Pipeline depth is fixed at construction (the
        double buffer).  resident=True attaches the resident loop
        (idempotent); resident=False detaches it for NEW batches (the
        loop drains what it holds — in-flight callers still resolve).
        The est_* knobs reseed the live CostModel (CostModel.reseed —
        the tuner's hot-swap path; winsorization would otherwise make
        a post-flip correction crawl); res_ring/res_inflight resize
        the resident loop by detach+reattach when one is running
        (in-flight batches drain first, same contract as resident
        toggling)."""
        if (est_floor_ms is not None or est_item_ms is not None
                or est_chunk_ms is not None
                or est_res_floor_ms is not None
                or est_res_lat_ms is not None):
            self._cost.reseed(
                floor_ms=est_floor_ms, item_ms=est_item_ms,
                chunk_ms=est_chunk_ms,
                res_floor_ms=est_res_floor_ms,
                res_lat_ms=est_res_lat_ms,
            )
        if res_ring is not None or res_inflight is not None:
            if res_ring is not None:
                self._res_ring = max(1, int(res_ring))
            if res_inflight is not None:
                self._res_inflight = max(1, int(res_inflight))
            if self._res_loop is not None:
                loop, self._res_loop = self._res_loop, None
                loop.close(join=True)
                self._make_resident_loop()
        if resident is not None:
            if resident:
                self._make_resident_loop()
            elif self._res_loop is not None:
                loop, self._res_loop = self._res_loop, None
                loop.close(join=True)
        with self._cond:
            if slo_ms is not None:
                self._slo_ms = float(slo_ms)
            if min_batch is not None:
                self._ctl.min_batch = int(min_batch)
            if max_batch is not None:
                self._ctl.max_batch = int(max_batch)
            if target_batch_ms is not None:
                self._ctl.target_ms = float(target_batch_ms)
            self._ctl.cur = max(
                self._ctl.min_batch, min(self._ctl.max_batch, self._ctl.cur)
            )
            if queue_depth is not None:
                self._queue_depth = int(queue_depth)
            self._max_queue = self._queue_depth * self._ctl.max_batch
            if admission_wait_s is not None:
                self._admission_wait_s = float(admission_wait_s)
            if inline is not None:
                self._inline = bool(inline)
            self._cond.notify_all()

    def _ensure_threads(self):
        if self._pack_thread is None or not self._pack_thread.is_alive():
            self._pack_thread = threading.Thread(
                target=self._pack_loop, name="dar-coalescer-pack",
                daemon=True,
            )
            self._pack_thread.start()
        if (
            self._collect_thread is None
            or not self._collect_thread.is_alive()
        ):
            self._collect_thread = threading.Thread(
                target=self._collect_loop, name="dar-coalescer-collect",
                daemon=True,
            )
            self._collect_thread.start()

    def _retry_after_locked(self) -> float:
        """Queue-drain horizon estimate for the 429 Retry-After: live
        backlog (queued + actually in-flight items, not a batch-size
        guess) over the measured drain-rate EWMA.  Before any drain
        has been observed, the PLANNER's best-plan throughput for the
        queued shape class stands in — the throughput of the route it
        would actually choose for what is queued, not an unconditional
        min(host, device).  The old fallback quoted `min_route_qps`
        even when the planner would never pick that route for the
        queued traffic: an all-stale bulk overload the resident
        stream absorbs was told to wait at cold-dispatch-floor rates
        (5 s horizons inviting synchronized retry storms), and a
        fresh-SLO overload draining hostward was quoted device
        throughput it will never see."""
        backlog = len(self._queue) + self._inflight_items
        qps = self._ema_qps
        if qps <= 1.0:
            # plan for what is ACTUALLY queued: the drained shape the
            # pack stage will see next (same headroom scan as
            # _drain_locked, same shape derivation as _shape_of)
            look = self._queue[: self._ctl.cur]
            now_m = self._clock()
            headroom_ms = None
            for it in look:
                if (
                    it.deadline is not None
                    and not it.allow_stale
                    and not it.expired(now_m)
                ):
                    h = (it.deadline - now_m) * 1000.0
                    if headroom_ms is None or h < headroom_ms:
                        headroom_ms = h
            all_stale = bool(look) and all(
                it.allow_stale for it in look
            )
            qps = max(
                1.0,
                self._planner.backlog_qps(
                    self._ctl.cur, self._capture_state(), headroom_ms,
                    all_stale=all_stale,
                ),
            )
        return min(5.0, max(0.05, backlog / qps))

    def query(
        self,
        keys: np.ndarray,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now: int,
        owner_id=None,
        allow_stale: bool = False,
    ) -> List[str]:
        """Blocking single query, executed as part of a micro-batch.
        Raises errors.OverloadedError when the bounded queue stays full
        past the admission wait (the caller should back off)."""
        keys = np.asarray(keys, np.int32).ravel()
        if len(keys) == 0:
            return []
        # deadline at admission: the serving SLO from "now" (queue wait
        # counts against it), capped by the route deadline the HTTP
        # timeout middleware propagated.  Bounded-staleness queries
        # carry only the route deadline — they are explicitly latency-
        # tolerant, so they never drag a batch onto the host route.
        route_dl = _deadline.get_route_deadline()
        if allow_stale or self._slo_ms <= 0:
            dl = route_dl
        else:
            dl = self._clock() + self._slo_ms / 1000.0
            if route_dl is not None:
                dl = min(dl, route_dl)
        if dl is not None and dl <= self._clock():
            # the route deadline was consumed before the query reached
            # the store (slow auth/parse/covering): shed NOW — the
            # inline path would otherwise run a scan whose response
            # the timeout middleware has already replaced with a 504
            with self._slock:
                self._stat_deadline_shed += 1
            raise errors.deadline_exceeded(
                "request deadline expired before query admission"
            )
        item = _Item(
            keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
            allow_stale, deadline=dl,
        )
        # trace handle captured on the caller's thread: the pipeline
        # stamps span timings onto the item and THIS thread records
        # them after the event resolves (cross-thread span handoff)
        th = _trace.current()
        t_adm_w = 0
        if th is not None:
            item.tctx = th
            t_adm_w = time.time_ns()
        inline = False
        deadline = None
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("coalescer is closed")
                if (
                    self._inline
                    and not self._busy
                    and not self._packing
                    and self._inflight == 0
                    and not self._queue
                ):
                    # lone caller: run inline as a batch of 1 — skips
                    # two thread handoffs (~0.15 ms on a loaded host).
                    # Reads are lock-free (immutable state grab), so
                    # executing on the caller's thread is safe; `_busy`
                    # makes arrivals during execution queue up and
                    # batch as before.
                    self._busy = True
                    inline = True
                    break
                if budget.is_host_only():
                    # event-loop caller would block in event.wait()
                    # behind another thread's (possibly compiling)
                    # batch: bounce to the executor path instead
                    raise budget.NeedsDevice()
                if len(self._queue) < self._max_queue:
                    if th is not None:
                        item.enq_ns = time.time_ns()
                    self._queue.append(item)
                    self._ensure_threads()
                    self._cond.notify_all()
                    break
                # admission control: the queue is at capacity.  Wait a
                # bounded moment for the pipeline to drain, then shed —
                # bounded latency for admitted work beats a backlog
                # whose p50 grows without limit.
                t_mono = time.monotonic()
                if deadline is None:
                    deadline = t_mono + max(0.0, self._admission_wait_s)
                if t_mono >= deadline:
                    with self._slock:
                        self._stat_shed += 1
                    raise errors.OverloadedError(
                        f"query queue full ({self._max_queue} deep); "
                        "request shed",
                        retry_after_s=self._retry_after_locked(),
                    )
                self._cond.wait(deadline - t_mono)
        if th is not None:
            # the admission gate: usually microseconds, the full
            # admission_wait under backpressure
            _trace.add_span(
                th, "admission", t_adm_w,
                (time.time_ns() - t_adm_w) / 1e6,
            )
        if inline:
            # the lone-caller shortcut must not bypass the router: an
            # idle-server fresh query whose candidates overflow the
            # auto host cap would otherwise ride the device dispatch
            # floor and blow the very SLO the router protects
            hr = None
            if item.deadline is not None and not item.allow_stale:
                hr = max(0.0, (item.deadline - self._clock()) * 1000.0)
            try:
                self._execute([item], headroom_ms=hr)
                with self._slock:
                    self._stat_inline += 1
            finally:
                with self._cond:
                    self._busy = False
                    if self._queue and not self._closed:
                        self._ensure_threads()
                    self._cond.notify_all()
        else:
            t_wait = time.perf_counter()
            item.event.wait()
            _stages.mark(
                "coalesce_wait_ms",
                (time.perf_counter() - t_wait) * 1000,
            )
        if th is not None:
            self._record_item_spans(item, th)
        if item.error is not None:
            raise item.error
        if item.via_mesh:
            # tell the store's cache layer (same thread) this answer
            # is bounded-stale mesh output, not fresh-path output
            from dss_tpu.dar import readcache as _readcache

            _readcache.note_mesh_served()
        return item.result

    def close(self, join: bool = True, timeout: float = 30.0):
        """Stop accepting queries and (by default) wait for BOTH stages
        to drain — queued and in-flight batches complete, and joining
        prevents the interpreter tearing down the device runtime while
        a stage is mid-dispatch.  The resident loop is closed LAST
        (after the pack stage can no longer enqueue into its ring):
        it drains the ring, so batches still queued there at shutdown
        are submitted, collected, and delivered like any other."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            pack_th = self._pack_thread
            coll_th = self._collect_thread
        if not join:
            return
        me = threading.current_thread()
        for th in (pack_th, coll_th):
            if th is not None and th is not me:
                th.join(timeout)
        if self._res_loop is not None:
            self._res_loop.close(join=True, timeout=timeout)

    # -- pipeline stages ------------------------------------------------------

    def _shape_of(self, batch: List[_Item],
                  inline: bool = False) -> BatchShape:
        """The planner's view of a drained batch."""
        # getattr defaults: the routing tests drive this with bare
        # placeholder items (the pre-planner router read only len())
        return BatchShape(
            n=len(batch),
            all_stale=all(
                getattr(it, "allow_stale", False) for it in batch
            ),
            owner_scoped=any(
                getattr(it, "owner_id", -1) >= 0 for it in batch
            ),
            inline=inline,
        )

    def _capture_state(self, host_only: bool = False):
        """Freeze the planner's full decision input: live cost
        estimates + this pipeline's pressure counters + which routes
        are attached right now.  Racy unlocked reads of the pressure
        counters are deliberate and unchanged from the pre-planner
        router — a decision made one batch stale is still safe (the
        counters only pad predictions)."""
        bgen = 0
        if self._mesh_bgen is not None:
            try:
                bgen = int(self._mesh_bgen())
            except Exception:  # noqa: BLE001 — introspection only
                bgen = 0
        return self._planner.capture(
            inflight_device=self._inflight_device,
            inflight_host_chunks=self._inflight_host_chunks,
            inflight_resident=self._inflight_resident,
            resident_ready=self._resident_ready(),
            mesh_ready=self._mesh_fn is not None,
            mesh_min=self._mesh_min,
            mesh_max=self._mesh_max,
            host_only=host_only,
            boundary_gen=bgen,
            device_ok=self._device_ok(),
        )

    def _mesh_eligible(self, batch: List[_Item]) -> bool:
        from dss_tpu.plan.planner import mesh_admissible

        return mesh_admissible(
            self._shape_of(batch), self._capture_state()
        )

    def _drain_locked(self):
        """Pop the next drain off the queue (caller holds _cond):
        -> (batch, expired, headroom_ms).  Items whose deadline already
        passed are split out for fast-shedding; headroom_ms is the
        tightest remaining deadline among the drainable fresh items
        (None when none carries a deadline — e.g. an all-stale-ok
        drain); the drain size is the AIMD controller output bounded
        by what the predicted route cost fits into that headroom."""
        now_m = self._clock()
        look = self._queue[: self._ctl.cur]
        headroom_ms = None
        for it in look:
            if (
                it.deadline is not None
                and not it.allow_stale
                and not it.expired(now_m)
            ):
                h = (it.deadline - now_m) * 1000.0
                if headroom_ms is None or h < headroom_ms:
                    headroom_ms = h
        cap = self._planner.drain_cap(
            self._ctl.cur, headroom_ms, self._capture_state()
        )
        batch: List[_Item] = []
        expired: List[_Item] = []
        taken = 0
        for it in look:
            if it.expired(now_m):
                expired.append(it)
                taken += 1
                continue
            if len(batch) >= cap:
                break
            batch.append(it)
            taken += 1
        del self._queue[:taken]
        return batch, expired, headroom_ms

    def _resident_ready(self) -> bool:
        """Resident route admissible right now: loop attached and its
        host ring has space (a full ring means the device stream is
        already saturated — routing more at it would just queue)."""
        return self._res_loop is not None and self._res_loop.has_space()

    def _plan_batch(self, batch, headroom_ms):
        """Plan a pack-stage drain: ONE planner decision over all
        attached routes (mesh / resident / cold device / forced host
        chunks), recorded in the co_plan_* counters.  The policy
        itself lives in dss_tpu/plan/planner.decide — a pure function
        pinned decision-identical to the pre-planner router."""
        return self._planner.plan(
            self._shape_of(batch), self._capture_state(), headroom_ms,
        )

    def _choose_route(self, batch, headroom_ms,
                      allow_resident: bool = True) -> str:
        """Route-string view of the planner decision (the pre-planner
        router's contract, kept for the routing tests): never returns
        "mesh" — the mesh candidate was historically decided before
        this comparison and still is (_plan_batch handles it)."""
        return self._planner.plan(
            self._shape_of(batch), self._capture_state(), headroom_ms,
            allow_resident=allow_resident, allow_mesh=False,
            record=False,
        ).route

    def _choose_host_route(self, batch, headroom_ms) -> bool:
        """Boolean view of _choose_route for consumers that CANNOT
        ride the resident loop (the inline lone-caller path and the
        mesh fallback run synchronously on the caller's thread).  The
        resident candidate is excluded from the comparison: a batch
        cleared only because the stream's latency fits would otherwise
        be run as a COLD dispatch here and blow the very deadline the
        clearance assumed."""
        return (
            self._choose_route(batch, headroom_ms, allow_resident=False)
            == "hostchunk"
        )

    def _pack_loop(self):
        """Stage 1: drain the queue (deadline-capped), fast-shed
        expired items, route the batch (host chunks vs fused device
        kernel) by predicted cost vs headroom, pack windows on the
        host, start any device kernel asynchronously.  Hands
        (batch, pending) to the collect stage through a bounded double
        buffer, so pack of batch N+1 overlaps device execution +
        decode of batch N."""
        while True:
            with self._cond:
                # also wait while an inline batch is executing: its
                # arrivals should form ONE next batch, not race it
                while (not self._queue or self._busy) and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    break
                batch, expired, headroom_ms = self._drain_locked()
                self._packing = True
                self._inflight += 1
                self._inflight_items += len(batch)
                # queue space just opened: wake admission waiters
                self._cond.notify_all()
            if expired:
                # deadline expired while queued: typed 504 now, not a
                # wasted kernel slot later
                self._deliver_error(
                    expired,
                    errors.deadline_exceeded(
                        "request deadline expired in the serving queue"
                    ),
                )
                with self._slock:
                    self._stat_deadline_shed += len(expired)
            if not batch:
                with self._cond:
                    self._packing = False
                    self._inflight -= 1
                    self._cond.notify_all()
                continue
            # cross-thread tracing: when any drained item carries a
            # trace handle, the pipeline measures its stages as
            # (name, start, dur) tuples and stamps them onto the
            # items at delivery — one `traced` check per batch when
            # tracing is off
            traced = any(it.tctx is not None for it in batch)
            tr_spans = [] if traced else None
            t0 = time.perf_counter()
            t0_w = time.time_ns() if traced else 0
            pq = None
            kind = "exec"
            host_route = False
            used_device = False
            try:
                submit = getattr(self._table, "query_many_submit", None)
                if submit is not None:
                    # ONE planner decision covers every attached route;
                    # a "mesh" plan rides the synchronous exec path
                    # exactly as the pre-planner mesh-eligibility
                    # check did (freshness re-verified at execution,
                    # local fallback re-plans inline)
                    if traced:
                        tp_w, tp0 = time.time_ns(), time.perf_counter()
                    route = self._plan_batch(batch, headroom_ms).route
                    if traced:
                        tr_spans.append((
                            "plan", tp_w,
                            (time.perf_counter() - tp0) * 1000,
                            {"route": route},
                        ))
                    if route == "resident":
                        if self._enqueue_resident(batch, tr_spans):
                            # the resident loop owns this batch now:
                            # its feeder submits into the device
                            # stream, its collector delivers + feeds
                            # the resident cost key.  Nothing goes
                            # through the collect stage.
                            with self._cond:
                                self._packing = False
                                self._cond.notify_all()
                            continue
                        # ring filled between the plan and the
                        # enqueue: demote to a cold dispatch (the
                        # pack stage never blocks on the stream)
                        self._planner.note_fallback()
                        route = "device"
                    if route != "mesh":
                        host_route = route == "hostchunk"
                        if host_route:
                            # forced chunked host scans execute on the
                            # COLLECT stage: running them here would
                            # serialize the two-stage pipeline exactly
                            # when deadline pressure needs it most
                            # (pack keeps draining while collect scans)
                            kind = "hostchunk"
                        else:
                            keys, lo, hi, t0s, t1s, now, owners = (
                                self._pack_args(batch)
                            )
                            if traced:
                                td_w = time.time_ns()
                                td0 = time.perf_counter()
                            try:
                                # chaos seam: the cold fused dispatch
                                chaos.fault_point("device.dispatch")
                                pq = submit(
                                    keys, lo, hi, t0s, t1s,
                                    now=now, owner_ids=owners,
                                    host_route=False,
                                )
                            except BaseException as e:
                                if not self._absorb_device_loss(e):
                                    raise
                                # device lost at submit: demote THIS
                                # batch to forced host chunks (the
                                # collect stage runs them) — the
                                # planner stops admitting the device
                                # class from the next state capture
                                host_route = True
                                kind = "hostchunk"
                                pq = None
                            else:
                                kind = "table"
                                used_device = self._pq_used_device(pq)
                                if traced:
                                    tr_spans.append((
                                        "device.dispatch", td_w,
                                        (time.perf_counter() - td0)
                                        * 1000,
                                        {"used_device": used_device},
                                    ))
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                self._deliver_error(batch, e)
                with self._cond:
                    self._packing = False
                    self._inflight -= 1
                    self._inflight_items -= len(batch)
                    self._cond.notify_all()
                continue
            pack_ms = (time.perf_counter() - t0) * 1000
            if traced:
                tr_spans.append(("coalesce.pack", t0_w, pack_ms, None))
            if used_device or kind == "hostchunk":
                # count the pressure BEFORE the handoff: the collect
                # thread decrements after processing, so incrementing
                # after put() could briefly hide in-flight work from
                # the router's predictions
                with self._cond:
                    if used_device:
                        self._inflight_device += 1
                    else:
                        self._inflight_host_chunks += (
                            self._cost._chunks(len(batch))
                        )
            # bounded handoff: blocks when the collect stage is
            # pipeline_depth batches behind (the double buffer)
            self._inflight_q.put(
                (batch, kind, pq, pack_ms, host_route, used_device,
                 tr_spans)
            )
            with self._cond:
                self._packing = False
        # shutdown sentinel — put OUTSIDE the condition lock: the
        # handoff queue may be full, and blocking on put() while
        # holding _cond deadlocks against the collect stage's
        # end-of-batch `with self._cond` accounting (collect could
        # then never drain the queue to unblock this put)
        self._inflight_q.put(_DONE)

    def _collect_loop(self):
        """Stage 2: wait for the device, decode, deliver results, and
        feed the batch-size controller + the route cost models."""
        while True:
            handoff = self._inflight_q.get()
            if handoff is _DONE:
                return
            (batch, kind, pq, pack_ms, host_route, used_device,
             tr_spans) = handoff
            t0 = time.perf_counter()
            t1 = t0
            device_ms = 0.0
            # what the batch ACTUALLY rode (a forced host batch can
            # fall back to the device per tier); used_device keeps the
            # pack-time accounting for the pressure-counter decrement
            observed_device = used_device
            try:
                if kind == "table":
                    pq.wait_device()
                    t1 = time.perf_counter()
                    device_ms = (t1 - t0) * 1000
                    results = self._table.query_many_collect(pq)
                    if tr_spans is not None:
                        coll_ms = (time.perf_counter() - t1) * 1000
                        now_w = time.time_ns()
                        self._stamp_spans(batch, tr_spans + [
                            ("device.wait",
                             now_w - int((device_ms + coll_ms) * 1e6),
                             device_ms, None),
                            ("collect", now_w - int(coll_ms * 1e6),
                             coll_ms, None),
                        ])
                    self._deliver_results(batch, results)
                elif kind == "hostchunk":
                    # the deadline router's forced route, deferred here
                    # so it overlaps the pack of the next drain.  Run
                    # the split halves: a tier whose chunks overflow
                    # the raised candidate cap silently rides the
                    # device, and that outcome must be OBSERVED (fed to
                    # the device model, counted as a device batch) or
                    # one fallback would poison est_chunk_ms with a
                    # dispatch floor and mislabel the route mix
                    keys, lo, hi, t0s, t1s, now, owners = (
                        self._pack_args(batch)
                    )
                    if tr_spans is not None:
                        th_w = time.time_ns()
                        th0 = time.perf_counter()
                    pq = self._table.query_many_submit(
                        keys, lo, hi, t0s, t1s,
                        now=now, owner_ids=owners, host_route=True,
                    )
                    observed_device = self._pq_used_device(pq)
                    results = self._table.query_many_collect(pq)
                    if tr_spans is not None:
                        self._stamp_spans(batch, tr_spans + [
                            ("host.scan", th_w,
                             (time.perf_counter() - th0) * 1000,
                             {"fallback_device": observed_device}),
                        ])
                    self._deliver_results(batch, results)
                else:
                    # mesh-planned (or submit-less table): the full
                    # synchronous path, mesh-first with local fallback
                    # (plan already recorded at pack time)
                    self._execute(batch, record_plan=False)
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                if self._absorb_device_loss(e):
                    # device lost while this batch was in flight:
                    # re-serve it on the pure host path — callers pay
                    # latency, never a 5xx (the ladder's DEVICE_LOST
                    # contract)
                    self._host_rerun(batch)
                else:
                    self._deliver_error(batch, e)
            collect_ms = (time.perf_counter() - t1) * 1000
            total_ms = pack_ms + device_ms + collect_ms
            with self._slock:
                self._stat_batches += 1
                self._stat_items += len(batch)
                self._stat_pack_ms += pack_ms
                self._stat_device_ms += device_ms
                self._stat_collect_ms += collect_ms
                self._stat_last_batch = len(batch)
                if kind in ("table", "hostchunk"):
                    # feed the EWMA cost models with the measured
                    # end-to-end batch cost (what a queued caller pays)
                    if observed_device:
                        self._stat_route_device += 1
                        self._cost.observe_device(len(batch), total_ms)
                    else:
                        self._stat_route_host += 1
                        if host_route:
                            self._stat_route_hostchunk += 1
                        if host_route or len(batch) >= self._cost.chunk:
                            # tiny auto-host batches cost one SCAN, not
                            # one warmed 64-wide CHUNK — feeding them
                            # in would train est_chunk_ms to ~a point
                            # lookup and make the first pressure burst
                            # over-drain its headroom
                            self._cost.observe_host(len(batch), total_ms)
                if total_ms > 0:
                    inst = len(batch) / (total_ms / 1000.0)
                    self._ema_qps = (
                        inst if self._ema_qps == 0.0
                        else 0.8 * self._ema_qps + 0.2 * inst
                    )
            with self._cond:
                self._ctl.observe(len(batch), total_ms)
                self._inflight -= 1
                self._inflight_items -= len(batch)
                if used_device:
                    self._inflight_device -= 1
                elif kind == "hostchunk":
                    self._inflight_host_chunks -= self._cost._chunks(
                        len(batch)
                    )
                self._cond.notify_all()

    @staticmethod
    def _record_item_spans(item: _Item, th) -> None:
        """Record the pipeline-stamped spans through the caller's own
        trace handle (runs on the caller's thread, after the event) —
        plus the queue-wait span derived from enqueue -> first stamped
        span."""
        spans = item.tspans or ()
        if item.enq_ns and spans:
            first = min(s[1] for s in spans)
            if first > item.enq_ns:
                _trace.add_span(
                    th, "queue_wait", item.enq_ns,
                    (first - item.enq_ns) / 1e6,
                )
        for rec in spans:
            name, start_ns, dur_ms = rec[0], rec[1], rec[2]
            attrs = rec[3] if len(rec) > 3 else None
            _trace.add_span(th, name, start_ns, dur_ms, attrs=attrs)

    @staticmethod
    def _stamp_spans(batch: List[_Item], spans) -> None:
        """Attach the batch's measured span tuples to every traced
        item (the caller threads record them — see _record_item_spans).
        Must run BEFORE results are delivered: event.set releases the
        caller."""
        for it in batch:
            if it.tctx is not None:
                it.tspans = spans

    @staticmethod
    def _deliver_error(batch: List[_Item], e: BaseException) -> None:
        for it in batch:
            if not it.event.is_set():
                it.error = e
                it.event.set()

    def _deliver_results(self, batch: List[_Item], results) -> None:
        load = self._load_view
        for it, res in zip(batch, results):
            it.result = res
            it.event.set()
            if load is not None and not it.via_mesh:
                # after event.set() on purpose: load accounting must
                # never add latency in front of a waiting caller
                try:
                    load.record(it.keys, len(res))
                except Exception:  # noqa: BLE001 — metrics-only path
                    pass

    def _enqueue_resident(self, batch: List[_Item],
                          pre_spans=None) -> bool:
        """Hand a drained batch to the resident loop's host ring.
        Non-blocking: False (ring full / loop closed) leaves the batch
        with the caller, which falls back to the cold device path —
        the pack stage never stalls behind the device stream.  The
        loop's collector delivers results AND feeds the resident cost
        key with the measured marginal (inter-completion) cost; the
        cold-device floor is never touched by these observations.
        `pre_spans` carries pack-stage trace spans (plan) stamped onto
        traced items together with the stream span at delivery."""
        loop = self._res_loop
        if loop is None:
            return False
        payload = self._pack_args(batch)

        def done(results, err, gap_ms, lat_ms, used_device,
                 _batch=batch, _pre=pre_spans):
            if err is not None:
                if self._absorb_device_loss(err):
                    # the stream died mid-flight: re-serve on the host
                    # (runs on the loop's collector thread — the
                    # stream is dead anyway, nothing to serialize on)
                    self._host_rerun(_batch)
                else:
                    self._deliver_error(_batch, err)
            else:
                if _pre is not None:
                    self._stamp_spans(_batch, _pre + [(
                        "resident.stream",
                        time.time_ns() - int(lat_ms * 1e6), lat_ms,
                        {"gap_ms": round(gap_ms, 3),
                         "used_device": bool(used_device)},
                    )])
                self._deliver_results(_batch, results)
            with self._slock:
                self._stat_batches += 1
                self._stat_items += len(_batch)
                self._stat_last_batch = len(_batch)
                self._stat_route_resident += 1
                if err is None:
                    if used_device:
                        # only batches that actually rode the device
                        # stream feed the resident keys — a batch whose
                        # tiers all answered host-side completes in
                        # sub-ms and would train the stream estimates
                        # toward host-scan cost, sending later
                        # deadline traffic into a stream that cannot
                        # deliver it (the cold path gates its models
                        # on observed_device for the same reason)
                        self._cost.observe_resident(
                            len(_batch), gap_ms, lat_ms
                        )
                    elif len(_batch) >= self._cost.chunk:
                        self._cost.observe_host(len(_batch), gap_ms)
                if gap_ms > 0:
                    inst = len(_batch) / (gap_ms / 1000.0)
                    self._ema_qps = (
                        inst if self._ema_qps == 0.0
                        else 0.8 * self._ema_qps + 0.2 * inst
                    )
            with self._cond:
                self._ctl.observe(len(_batch), gap_ms)
                self._inflight -= 1
                self._inflight_items -= len(_batch)
                self._inflight_resident -= 1
                self._cond.notify_all()

        with self._cond:
            self._inflight_resident += 1
        if loop.enqueue(payload, done):
            return True
        with self._cond:
            self._inflight_resident -= 1
        return False

    @staticmethod
    def _pq_used_device(pq) -> bool:
        """Did this submitted batch touch the device?  (A forced host
        batch can still fall back per tier on candidate-cap overflow —
        the router's accounting must see what actually happened.)
        Delegates to _PendingQuery.used_device when available so the
        predicate lives in one place (dar/snapshot.py)."""
        if pq is None:
            return False
        fn = getattr(pq, "used_device", None)
        if fn is not None:
            return bool(fn())
        return any(
            p is not None for p in getattr(pq, "tier_pending", ())
        )

    @staticmethod
    def _pack_args(batch: List[_Item]):
        """Marshal a batch into the array arguments shared by
        query_many / query_many_submit / the mesh fn."""
        return (
            [it.keys for it in batch],
            np.asarray([it.alt_lo for it in batch], np.float32),
            np.asarray([it.alt_hi for it in batch], np.float32),
            np.asarray([it.t_start for it in batch], np.int64),
            np.asarray([it.t_end for it in batch], np.int64),
            np.asarray([it.now for it in batch], np.int64),
            np.asarray([it.owner_id for it in batch], np.int32),
        )

    # -- synchronous execution (inline path + mesh batches) -------------------

    def _execute(self, batch: List[_Item], headroom_ms=None,
                 record_plan: bool = True):
        try:
            b = len(batch)
            traced = any(it.tctx is not None for it in batch)
            # plan the synchronous execution: resident excluded (this
            # runs on the caller's thread — a cold dispatch dressed as
            # the stream would blow the deadline the stream's latency
            # cleared), host_only honored (an event-loop caller never
            # gets the raised-cap forced scans).  record_plan=False on
            # the collect-stage path, whose batch was already planned
            # at pack time.
            if traced:
                tp_w, tp0 = time.time_ns(), time.perf_counter()
            plan = self._planner.plan(
                self._shape_of(batch, inline=True),
                self._capture_state(host_only=budget.is_host_only()),
                headroom_ms,
                allow_resident=False,
                record=record_plan,
            )
            plan_span = None
            if traced:
                plan_span = (
                    "plan", tp_w, (time.perf_counter() - tp0) * 1000,
                    {"route": plan.route},
                )
            if plan.route == "mesh" and self._mesh_fresh():
                try:
                    # chunk to the warmed jit bucket (the replica warms
                    # batch=min_batch per rebuild): a 65..4096 batch
                    # must not stall every caller on a fresh multi-chip
                    # compile for an unwarmed pow2 bucket
                    for start in range(0, b, self._mesh_min):
                        part = batch[start : start + self._mesh_min]
                        keys, lo, hi, t0s, t1s, now, _ = (
                            self._pack_args(part)
                        )
                        if traced:
                            tm_w = time.time_ns()
                            tm0 = time.perf_counter()
                        results = self._mesh_fn(
                            keys, lo, hi, t0s, t1s, now
                        )
                        if traced:
                            self._stamp_spans(part, [plan_span, (
                                "mesh", tm_w,
                                (time.perf_counter() - tm0) * 1000,
                                None,
                            )])
                        for it, res in zip(part, results):
                            it.via_mesh = True  # before event.set()
                            it.result = res
                            it.event.set()
                    self.mesh_offloads += 1
                    return
                except Exception:  # noqa: BLE001 — fall back local
                    import logging

                    logging.getLogger("dss.dar").exception(
                        "mesh offload failed; serving batch locally"
                    )
            keys, lo, hi, t0s, t1s, now, owners = self._pack_args(batch)
            # the plan already honored host-only callers (the event
            # loop's inline-read budget): a host_only state makes the
            # forced-chunk candidate inadmissible, so the auto path's
            # 2^16 cap stays the loop's worst case and anything bigger
            # raises NeedsDevice and re-routes on the executor
            host_route = plan.route == "hostchunk"
            submit = getattr(self._table, "query_many_submit", None)
            t0 = time.perf_counter()
            t0_w = time.time_ns() if traced else 0
            used_device = None
            if submit is not None:
                # run the split halves so the chosen route is
                # observable: inline traffic must feed the cost models
                # too, or a low-load deployment would route on the
                # boot seed forever
                try:
                    if not host_route:
                        chaos.fault_point("device.dispatch")
                    pq = submit(
                        keys, lo, hi, t0s, t1s, now=now,
                        owner_ids=owners, host_route=host_route,
                    )
                    used_device = self._pq_used_device(pq)
                    if traced:
                        disp_ms = (time.perf_counter() - t0) * 1000
                        tc_w, tc0 = time.time_ns(), time.perf_counter()
                    results = self._table.query_many_collect(pq)
                    if traced:
                        spans = [plan_span]
                        if host_route:
                            spans.append((
                                "host.scan", t0_w,
                                disp_ms
                                + (time.perf_counter() - tc0) * 1000,
                                None,
                            ))
                        else:
                            # the dispatch seam (incl. any injected
                            # device.dispatch fault delay) and the
                            # wait+decode, split like the pipeline's
                            spans.append((
                                "device.dispatch", t0_w, disp_ms,
                                {"used_device": bool(used_device)},
                            ))
                            spans.append((
                                "collect", tc_w,
                                (time.perf_counter() - tc0) * 1000,
                                None,
                            ))
                        self._stamp_spans(batch, spans)
                except BaseException as e:
                    if not self._absorb_device_loss(e):
                        raise
                    # device lost under a synchronous caller: retry
                    # once on the pure host route
                    pq = submit(
                        keys, lo, hi, t0s, t1s, now=now,
                        owner_ids=owners, host_route=True,
                    )
                    used_device = False
                    results = self._table.query_many_collect(pq)
            else:
                results = self._table.query_many(
                    keys, lo, hi, t0s, t1s, now=now, owner_ids=owners,
                    host_route=host_route,
                )
                if traced:
                    self._stamp_spans(batch, [plan_span, (
                        "host.scan", t0_w,
                        (time.perf_counter() - t0) * 1000, None,
                    )])
            if used_device is not None:
                total_ms = (time.perf_counter() - t0) * 1000
                with self._slock:
                    if used_device:
                        self._cost.observe_device(b, total_ms)
                    elif host_route or b >= self._cost.chunk:
                        self._cost.observe_host(b, total_ms)
            self._deliver_results(batch, results)
        except BaseException as e:  # noqa: BLE001 — deliver to callers
            self._deliver_error(batch, e)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        """Serving-pipeline gauges (flow into /metrics via the index's
        stats): queue depth, adaptive batch size, per-stage time
        totals, shed count."""
        with self._cond:
            out = {
                "co_queue_depth": len(self._queue),
                "co_queue_cap": self._max_queue,
                "co_inflight": self._inflight,
                "co_inflight_items": self._inflight_items,
                "co_batch_size": self._ctl.cur,
                "co_batch_grows": self._ctl.grows,
                "co_batch_shrinks": self._ctl.shrinks,
                "co_slo_ms": self._slo_ms,
            }
        with self._slock:
            out.update(
                co_batches=self._stat_batches,
                co_items=self._stat_items,
                co_inline=self._stat_inline,
                co_shed=self._stat_shed,
                co_deadline_shed=self._stat_deadline_shed,
                co_route_host_batches=self._stat_route_host,
                co_route_hostchunk_batches=self._stat_route_hostchunk,
                co_route_device_batches=self._stat_route_device,
                co_route_resident_batches=self._stat_route_resident,
                co_device_loss_absorbed=self._stat_device_loss_absorbed,
                co_device_ok=int(self._device_ok()),
                co_pack_ms_total=round(self._stat_pack_ms, 3),
                co_device_ms_total=round(self._stat_device_ms, 3),
                co_collect_ms_total=round(self._stat_collect_ms, 3),
                co_last_batch=self._stat_last_batch,
                co_ema_qps=round(self._ema_qps, 1),
                # live cost-model estimates (the router's inputs);
                # the resident floor is its OWN key — see _CostModel
                co_est_device_floor_ms=round(self._cost.est_floor_ms, 4),
                co_est_device_item_ms=round(self._cost.est_item_ms, 5),
                co_est_host_chunk_ms=round(self._cost.est_chunk_ms, 4),
                co_est_resident_floor_ms=round(
                    self._cost.est_res_floor_ms, 4
                ),
                co_est_resident_lat_ms=round(
                    self._cost.est_res_lat_ms, 4
                ),
            )
        # resident-loop gauges: stable key set whether or not the loop
        # is attached (dashboards and the observability test expect
        # the series to exist on every tpu-backend deployment)
        if self._res_loop is not None:
            rs = self._res_loop.stats()
        else:
            rs = {
                "ring_depth": 0, "ring_cap": 0, "inflight": 0,
                "enqueued": 0, "rejected": 0, "aot_hits": 0,
                "aot_misses": 0, "aot_buckets": 0,
                "aot_compile_ms_total": 0.0,
            }
        out.update(
            co_res_ring_depth=rs["ring_depth"],
            co_res_ring_cap=rs["ring_cap"],
            co_res_inflight=rs["inflight"],
            co_res_enqueued=rs["enqueued"],
            co_res_rejected=rs["rejected"],
            co_res_aot_hits=rs["aot_hits"],
            co_res_aot_misses=rs["aot_misses"],
            co_res_aot_buckets=rs["aot_buckets"],
            co_res_aot_compile_ms_total=rs["aot_compile_ms_total"],
        )
        # planner decision mix (co_plan_*): how often each of the six
        # routes was the chosen plan — the cache row is filled from
        # the read-cache view below (a hit IS a plan, chosen before
        # this pipeline ever sees the query)
        out.update(self._planner.stats())
        # per-class read-cache counters (co_cache_*): stable key set so
        # the /metrics series exist on every tpu-backend deployment
        view = self._cache_view
        if view is not None:
            out.update(view())
        else:
            out.update(
                co_cache_hits=0, co_cache_misses=0,
                co_cache_invalidations=0,
            )
        hits = int(out.get("co_cache_hits", 0) or 0)
        out["co_plan_cache"] += hits
        out["co_plan_total"] += hits
        out["mesh_offloads"] = self.mesh_offloads
        return out
