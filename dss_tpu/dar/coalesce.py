"""Micro-batching query coalescer for DarTable.

The serving-stack glue between request-per-thread handlers and the
batched fused kernel: concurrent callers enqueue single queries; one
worker thread drains whatever is queued and runs it as ONE
DarTable.query_many batch.  Continuous batching — no timing window:

  - a lone caller runs immediately as a batch of 1 (no added latency),
  - while a batch is on the device, new arrivals queue up and form the
    next batch, so concurrency N collapses to ~1 kernel per round trip
    instead of N round trips.

This replaces the reference's per-request SQL round trip to CRDB
(goroutine-per-RPC, pkg/rid/cockroach/identification_service_area.go
:166-197) with the TPU-idiomatic shape: request parallelism becomes
data parallelism over the query batch axis.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from dss_tpu.dar import budget
from dss_tpu.ops.conflict import NO_TIME_HI, NO_TIME_LO

_MAX_BATCH = 4096


class _Item:
    __slots__ = ("keys", "alt_lo", "alt_hi", "t_start", "t_end", "now",
                 "owner_id", "allow_stale", "event", "result", "error")

    def __init__(self, keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
                 allow_stale=False):
        self.keys = keys
        self.alt_lo = -np.inf if alt_lo is None else float(alt_lo)
        self.alt_hi = np.inf if alt_hi is None else float(alt_hi)
        self.t_start = NO_TIME_LO if t_start is None else int(t_start)
        self.t_end = NO_TIME_HI if t_end is None else int(t_end)
        self.now = int(now)
        self.owner_id = -1 if owner_id is None else int(owner_id)
        self.allow_stale = bool(allow_stale)
        self.event = threading.Event()
        self.result: Optional[List[str]] = None
        self.error: Optional[BaseException] = None


class QueryCoalescer:
    """One worker thread per DarTable, batching concurrent queries."""

    def __init__(self, table):
        self._table = table
        self._cond = threading.Condition()
        self._queue: List[_Item] = []
        self._closed = False
        self._busy = False  # a batch is executing on the worker
        self._thread: Optional[threading.Thread] = None
        # optional multi-chip offload: big read-only batches can run on
        # a fresh ShardedReplica mesh instead of the local device
        self._mesh_fn = None
        self._mesh_fresh = None
        self._mesh_min = 64
        self._mesh_max = 256  # beyond this, ONE local fused dispatch
        #                       beats serialized mesh chunk round trips
        self.mesh_offloads = 0

    def set_mesh_delegate(self, fn, fresh_fn, min_batch: int = 64):
        """Route batches of >= min_batch bounded-staleness queries
        (every item flagged allow_stale, no owner filters) to `fn`
        (the ShardedReplica mesh) when fresh_fn() says the replica is
        caught up.  Conflict prechecks never set allow_stale, so
        correctness-critical reads always hit the local table."""
        self._mesh_fn = fn
        self._mesh_fresh = fresh_fn
        self._mesh_min = min_batch

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="dar-coalescer", daemon=True
            )
            self._thread.start()

    def query(
        self,
        keys: np.ndarray,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now: int,
        owner_id=None,
        allow_stale: bool = False,
    ) -> List[str]:
        """Blocking single query, executed as part of a micro-batch."""
        keys = np.asarray(keys, np.int32).ravel()
        if len(keys) == 0:
            return []
        item = _Item(
            keys, alt_lo, alt_hi, t_start, t_end, now, owner_id,
            allow_stale,
        )
        inline = False
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            if not self._busy and not self._queue:
                # lone caller: run inline as a batch of 1 — skips two
                # thread handoffs (~0.15 ms on a loaded host).  Reads
                # are lock-free (immutable state grab), so executing on
                # the caller's thread is safe; `_busy` makes arrivals
                # during execution queue up and batch as before.
                self._busy = True
                inline = True
            else:
                if budget.is_host_only():
                    # event-loop caller would block in event.wait()
                    # behind another thread's (possibly compiling)
                    # batch: bounce to the executor path instead
                    raise budget.NeedsDevice()
                self._queue.append(item)
                self._ensure_thread()
                self._cond.notify()
        if inline:
            try:
                self._execute([item])
            finally:
                with self._cond:
                    self._busy = False
                    if self._queue and not self._closed:
                        self._ensure_thread()
                        self._cond.notify()
        else:
            item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result

    def close(self, join: bool = True, timeout: float = 30.0):
        """Stop accepting queries and (by default) wait for the worker
        to drain — joining prevents the interpreter tearing down the
        device runtime while the worker is mid-dispatch."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            th = self._thread
        if join and th is not None and th is not threading.current_thread():
            th.join(timeout)

    # -- worker --------------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                # also wait while an inline batch is executing: its
                # arrivals should form ONE next batch, not race it
                while (not self._queue or self._busy) and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                batch = self._queue[:_MAX_BATCH]
                del self._queue[:_MAX_BATCH]
                self._busy = True
            try:
                self._execute(batch)
            finally:
                with self._cond:
                    self._busy = False

    def _execute(self, batch: List[_Item]):
        try:
            b = len(batch)
            if (
                self._mesh_fn is not None
                and self._mesh_min <= b <= self._mesh_max
                and all(
                    it.allow_stale and it.owner_id < 0 for it in batch
                )
                and self._mesh_fresh()
            ):
                try:
                    # chunk to the warmed jit bucket (the replica warms
                    # batch=min_batch per rebuild): a 65..4096 batch
                    # must not stall every caller on a fresh multi-chip
                    # compile for an unwarmed pow2 bucket
                    for lo in range(0, b, self._mesh_min):
                        part = batch[lo : lo + self._mesh_min]
                        results = self._mesh_fn(
                            [it.keys for it in part],
                            np.asarray(
                                [it.alt_lo for it in part], np.float32
                            ),
                            np.asarray(
                                [it.alt_hi for it in part], np.float32
                            ),
                            np.asarray(
                                [it.t_start for it in part], np.int64
                            ),
                            np.asarray(
                                [it.t_end for it in part], np.int64
                            ),
                            np.asarray([it.now for it in part], np.int64),
                        )
                        for it, res in zip(part, results):
                            it.result = res
                            it.event.set()
                    self.mesh_offloads += 1
                    return
                except Exception:  # noqa: BLE001 — fall back local
                    import logging

                    logging.getLogger("dss.dar").exception(
                        "mesh offload failed; serving batch locally"
                    )
            results = self._table.query_many(
                [it.keys for it in batch],
                np.asarray([it.alt_lo for it in batch], np.float32),
                np.asarray([it.alt_hi for it in batch], np.float32),
                np.asarray([it.t_start for it in batch], np.int64),
                np.asarray([it.t_end for it in batch], np.int64),
                now=np.asarray([it.now for it in batch], np.int64),
                owner_ids=np.asarray(
                    [it.owner_id for it in batch], np.int32
                ),
            )
            for it, res in zip(batch, results):
                it.result = res
                it.event.set()
        except BaseException as e:  # noqa: BLE001 — deliver to callers
            for it in batch:
                if not it.event.is_set():
                    it.error = e
                    it.event.set()
