"""The store implementation: host-authoritative state + spatial index + WAL.

One implementation serves both backends — the spatial index strategy is
injected (`--storage=memory` -> MemorySpatialIndex linear scans,
`--storage=tpu` -> TpuSpatialIndex HBM DarTable), mirroring how the
reference selects its store behind the repository seam.

Semantics mirrored from the reference:
  - RID fenced writes on the commit-timestamp version
    (pkg/rid/cockroach/identification_service_area.go:97-162)
  - RID notification fanout = bump live subs intersecting cells
    (pkg/rid/cockroach/subscriptions.go:204-219)
  - SCD upsert fencing + OVN key check for Accepted/Activated
    (pkg/scd/store/cockroach/operations.go:304-372)
  - SCD delete with implicit-subscription GC
    (operations.go:239-301)
  - SCD subscription quota / dependent-op delete block
    (subscriptions.go:369-495)

Every mutation appends to the WAL after applying; replay rebuilds the
dicts and the spatial indexes (the HBM snapshot is a cache of the WAL,
the checkpoint/resume story per SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dss_tpu import chaos, errors
from dss_tpu.clock import Clock, to_nanos
from dss_tpu.dar import codec
from dss_tpu.dar import readcache as rcache
from dss_tpu.obs import trace
from dss_tpu.dar.index import MemorySpatialIndex, TpuSpatialIndex
from dss_tpu.dar.store import RIDStore, SCDStore
from dss_tpu.dar.wal import WriteAheadLog
from dss_tpu.geo.covering import canonical_cells
from dss_tpu.models import rid as ridm
from dss_tpu.models import scd as scdm
from dss_tpu.models.core import Version, new_ovn_from_time

MAX_RID_SUBSCRIPTIONS_PER_AREA = 10  # DSS0030
MAX_SCD_SUBSCRIPTIONS_PER_AREA = 10


def _copy_rec(rec):
    """Shallow defensive copy for search-result assembly: callers may
    mutate the returned object (e.g. the SCD service blanks `ovn` for
    non-owners) without touching the shared stored record.  Equivalent
    to `dataclasses.replace(rec)` for these pure-data records but
    ~1.5x cheaper — per-record assembly is the read path's largest
    single cost at poll-heavy hit rates."""
    return copy.copy(rec)


def _lock_txn(lock):
    """Default transaction factory: just the store lock."""

    @contextlib.contextmanager
    def txn():
        with lock:
            yield

    return txn


def _bump_sub(subs: Dict[str, object], sub_id: str):
    """Copy-on-write notification-index bump: replaces the stored record
    (lock-free readers may hold a reference to the current object).
    Returns the bumped record, or None if absent."""
    sub = subs.get(sub_id)
    if sub is None:
        return None
    bumped = dataclasses.replace(
        sub, notification_index=sub.notification_index + 1
    )
    subs[sub_id] = bumped
    return bumped


class _PushMixin:
    """Reverse-query push wiring shared by both sub-stores
    (dss_tpu/push/): DSSStore.attach_push hands the pipeline to the
    unwrapped impls; the notify paths then (a) run subscriber matching
    through the pipeline's rqmatch route instead of the read-side
    coalescer — bit-identical by the MatchStage contract, but priced
    and counted as write-side work — and (b) fan the bumped subscriber
    set into the durable delivery queue after the journal record
    lands.  Without a pipeline everything behaves exactly as before
    push existed."""

    _push = None

    def set_push(self, pipeline) -> None:
        self._push = pipeline

    def _push_match_ids(self, cls, cells, *, alt_lo=None, alt_hi=None,
                        t_start_ns=None, t_end_ns=None):
        """The subscriber-id match for a write volume: the push
        pipeline's MatchStage when attached (planner rqmatch route,
        host-oracle fallback), else the index's own query path.
        Returns ids in arbitrary order — callers sort."""
        push = self._push
        if push is not None and push.bound:
            return push.match_ids(
                cls, cells, alt_lo=alt_lo, alt_hi=alt_hi,
                t_start_ns=t_start_ns, t_end_ns=t_end_ns,
                now_ns=self._now_ns(),
            )
        return self._sub_index.query_ids(
            cells, alt_lo=alt_lo, alt_hi=alt_hi,
            t_start=t_start_ns, t_end=t_end_ns, now=self._now_ns(),
        )

    def _offer_push(self, trigger, entity, subs, *, removed=False,
                    emergency=False, alt_lo=None, alt_hi=None,
                    t_start=None, t_end=None) -> None:
        """Hand the bumped subscriber set to the delivery pipeline —
        post-journal, O(1) per subscriber (durable append + worker
        wake); webhook I/O never runs on the write path."""
        push = self._push
        if push is None or not push.bound:
            return
        push.offer(
            trigger, entity, subs, removed=removed,
            emergency=emergency, alt_lo=alt_lo, alt_hi=alt_hi,
            t_start_ns=None if t_start is None else to_nanos(t_start),
            t_end_ns=None if t_end is None else to_nanos(t_end),
        )


class _TxnTimeMixin:
    """Per-transaction pinned 'now' (the stand-in for CRDB's txn
    timestamp): every visibility/expiry check inside one transaction
    reads the same instant, so a precheck and the mutation that follows
    it can never disagree about which records are visible (a record
    expiring mid-txn would otherwise abort the txn after journaling).
    Thread-local so lock-free readers keep their own wall-clock now."""

    def _init_txn_time(self):
        self._txn_time = threading.local()

    @contextlib.contextmanager
    def _txn_scope(self):
        with self._txn():
            tl = self._txn_time
            outer = getattr(tl, "now", None) is None
            if outer:
                tl.now = to_nanos(self._clock.now())
            try:
                yield
            finally:
                if outer:
                    tl.now = None

    def _now_ns(self) -> int:
        pinned = getattr(self._txn_time, "now", None)
        return pinned if pinned is not None else to_nanos(self._clock.now())

    @contextlib.contextmanager
    def transaction(self):
        with self._txn_scope():
            yield self


class _CachedSearchMixin:
    """The version-fenced read-cache seam shared by both sub-stores.

    `_cached_ids` fronts an index query_ids call: the covering is
    canonicalized (sorted, deduped — the same form the pack path
    assumes), the per-cell clock fence is read BEFORE the fresh query
    runs, and a fenced hit returns in microseconds without ever
    reaching the coalescer — no admission, no deadline stamp, no
    Retry-After backlog contribution, no device.  Misses populate on
    the way out (the coalescer's collect path has already resolved by
    then) unless the answer came from the bounded-stale mesh replica,
    which must never be stamped as fresh."""

    _cache: Optional[rcache.ReadCache] = None
    _epoch_fn = staticmethod(lambda: "")

    def _init_cache(self, cache, epoch_fn):
        self._cache = cache
        if epoch_fn is not None:
            self._epoch_fn = epoch_fn

    def _fenced_index_swap(self, *old_indexes):
        """Fresh indexes for a state reset, carrying the old cell
        clocks with a bump_all() floor — THE mid-resync staleness
        invariant, shared by both store classes so the ordering cannot
        drift apart: flush the cache first (reclaims entries the floor
        is about to orphan), build the replacement indexes BEFORE the
        caller clears its dicts (factory cost stays outside the window
        lock-free readers can observe), adopt each predecessor's clock
        (O(1) — no stamp-array churn in the window), then floor it so
        every fence stamped before the reset fails."""
        if self._cache is not None:
            self._cache.invalidate_all()
        fresh = []
        for ix in old_indexes:
            clock = ix.cell_clock
            new_ix = self._index_factory()
            new_ix.adopt_cell_clock(clock)
            clock.bump_all()
            fresh.append(new_ix)
        return fresh

    def _cached_ids(
        self,
        cls: str,
        index,
        cells,  # canonical uint64 covering
        qkey: tuple,  # class-specific window/alt key components
        now_ns: int,  # the query's `now` (its only time-variant input)
        allow_stale: bool,
        run,  # () -> List[str], the fresh path (index.query_ids)
        t_end_of,  # id -> t_end ns (from the record dict) or None
        owner_id: Optional[int] = None,
    ) -> List[str]:
        cache = self._cache
        clock_fence = getattr(index, "clock_fence", None)
        if (
            cache is None
            or not cache.enabled
            or clock_fence is None
            # near-the-area-cap coverings: the O(|cells|) fence walk
            # stops being "microseconds" — serve fresh rather than
            # cache a key nobody repeats cheaply
            or len(cells) > 16384
        ):
            rcache.take_mesh_served()
            ids = run()
            rcache.note_last_search_meshed(rcache.take_mesh_served())
            return ids
        th = trace.current()
        t_cl_w = t_cl0 = 0
        if th is not None:
            t_cl_w, t_cl0 = time.time_ns(), time.perf_counter()
        epoch = self._epoch_fn()
        fence = clock_fence(cells)
        key = (cls, owner_id, qkey, cells.tobytes())
        ids = cache.lookup(
            cls, key, fence, epoch, int(now_ns), allow_stale
        )
        if th is not None:
            trace.add_span(
                th, "cache.lookup", t_cl_w,
                (time.perf_counter() - t_cl0) * 1000,
                attrs={"cls": cls, "hit": ids is not None},
            )
        if ids is not None:
            rcache.note_search(cls, epoch, fence[2], True)
            rcache.note_last_search_meshed(False)
            return ids
        rcache.take_mesh_served()  # clear any stale flag before running
        ids = run()
        meshed = rcache.take_mesh_served()
        rcache.note_last_search_meshed(meshed)
        if not meshed:
            pairs_ids: List[str] = []
            t1s: List[int] = []
            for i in ids:
                t1 = t_end_of(i)
                if t1 is None:
                    # record vanished between query and assembly: the
                    # concurrent remove's clock bump will fence this
                    # entry out; omitting the id matches what the
                    # fresh path would return right now
                    continue
                pairs_ids.append(i)
                t1s.append(t1)
            try:
                # chaos seam: population is best-effort by contract —
                # an injected failure here leaves the next poll a
                # miss, never a wrong answer
                chaos.fault_point("cache.populate", detail=cls)
                cache.insert(
                    cls, key, fence, epoch, int(now_ns), pairs_ids, t1s
                )
            except chaos.FaultError:
                pass
        rcache.note_search(cls, epoch, fence[2], False)
        return ids


class TimestampOracle:
    """Strictly-increasing commit timestamps (microsecond granularity),
    the stand-in for CRDB's transaction_timestamp()."""

    def __init__(self, clock: Clock):
        self._clock = clock
        self._last: Optional[datetime] = None
        self._lock = threading.Lock()

    def commit_ts(self) -> datetime:
        with self._lock:
            now = self._clock.now()
            if self._last is not None and now <= self._last:
                now = self._last + timedelta(microseconds=1)
            self._last = now
            return now


class OwnerInterner:
    """Thread-safe string->id interner.  Lock-free callers (owner-scoped
    searches) may intern concurrently, so the check-then-set must be
    atomic or two owners could share one id (tenant mixing)."""

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._lock = threading.Lock()

    def intern(self, owner: str) -> int:
        existing = self._ids.get(owner)  # fast path, no lock
        if existing is not None:
            return existing
        with self._lock:
            return self._ids.setdefault(owner, len(self._ids))


class RIDStoreImpl(_PushMixin, _TxnTimeMixin, _CachedSearchMixin, RIDStore):
    def __init__(
        self, *, clock, ts_oracle, owners, lock, journal, index_factory,
        txn=None, capture_undo=False, cache=None, epoch_fn=None,
    ):
        self._clock = clock
        self._ts = ts_oracle
        self._owners = owners
        self._lock = lock
        self._txn = txn if txn is not None else _lock_txn(lock)
        self._journal = journal
        self._index_factory = index_factory
        # region mode: each journal record carries an "undo" list (wal
        # records that revert the mutation) so the coordinator can roll
        # back an aborted txn precisely instead of resyncing from the log
        self._capture_undo = capture_undo
        self._init_txn_time()
        self._init_cache(cache, epoch_fn)
        self._isas: Dict[str, ridm.IdentificationServiceArea] = {}
        self._subs: Dict[str, ridm.Subscription] = {}
        self._isa_index = index_factory()
        self._sub_index = index_factory()

    def reset_state(self):
        """Drop all local state (region resync rebuilds from the log);
        _fenced_index_swap keeps the cache coherent and the readers'
        mid-resync window as narrow as before the cache existed."""
        new_isa, new_sub = self._fenced_index_swap(
            self._isa_index, self._sub_index
        )
        self._isas = {}
        self._subs = {}
        self._isa_index = new_isa
        self._sub_index = new_sub

    def serialize_state(self) -> dict:
        """Full-state snapshot as plain JSON docs (region snapshot
        upload; the CRDB-range-snapshot analog)."""
        return self.serialize_refs(self.snapshot_refs())

    def snapshot_refs(self) -> tuple:
        """Grab record references for a consistent snapshot cut (cheap;
        call under the store lock).  Records are immutable — replaced,
        never mutated — so serialize_refs may run outside the lock."""
        return (list(self._isas.values()), list(self._subs.values()))

    @staticmethod
    def serialize_refs(refs: tuple) -> dict:
        isas, subs = refs
        return {
            "isas": [codec.isa_to_doc(x) for x in isas],
            "subs": [codec.rid_sub_to_doc(x) for x in subs],
        }

    def restore_state(self, state: dict) -> None:
        self.reset_state()
        for d in state.get("isas", []):
            isa = codec.doc_to_isa(d)
            self._isas[isa.id] = isa
            self._index_isa(isa)
        for d in state.get("subs", []):
            sub = codec.doc_to_rid_sub(d)
            self._subs[sub.id] = sub
            self._index_sub(sub)


    # -- ISAs ----------------------------------------------------------------

    def index_stats(self) -> dict:
        return self._isa_index.stats()

    def sub_index_stats(self) -> dict:
        return self._sub_index.stats()

    def get_isa(self, id):
        # lock-free read: dict get is atomic; records are replaced, not
        # mutated, on write
        isa = self._isas.get(id)
        return dataclasses.replace(isa) if isa else None

    def _index_isa(self, isa):
        self._isa_index.put(
            isa.id,
            isa.cells,
            isa.altitude_lo,
            isa.altitude_hi,
            to_nanos(isa.start_time),
            to_nanos(isa.end_time),
            self._owners.intern(isa.owner),
        )

    def insert_isa(self, isa):
        with self._txn_scope():
            old = self._isas.get(isa.id)
            if isa.version is None or isa.version.empty:
                if old is not None:
                    raise errors.internal(
                        "insert of existing ISA (application precheck bypassed)"
                    )
            else:
                if old is None or not isa.version.matches(old.version):
                    return None  # fenced write matched no row
            stored = dataclasses.replace(
                isa, version=Version.from_time(self._ts.commit_ts())
            )
            self._isas[stored.id] = stored
            self._index_isa(stored)
            rec = {"t": "isa_put", "doc": codec.isa_to_doc(stored)}
            if self._capture_undo:
                rec["undo"] = [
                    {"t": "isa_put", "doc": codec.isa_to_doc(old)}
                    if old is not None
                    else {"t": "isa_del", "id": stored.id}
                ]
            self._journal(rec)
            return dataclasses.replace(stored)

    def delete_isa(self, isa):
        with self._txn_scope():
            old = self._isas.get(isa.id)
            if (
                old is None
                or old.owner != isa.owner
                or isa.version is None
                or not isa.version.matches(old.version)
            ):
                return None
            del self._isas[isa.id]
            self._isa_index.remove(isa.id)
            rec = {"t": "isa_del", "id": isa.id}
            if self._capture_undo:
                rec["undo"] = [{"t": "isa_put", "doc": codec.isa_to_doc(old)}]
            self._journal(rec)
            return dataclasses.replace(old)

    def search_isas(self, cells, earliest, latest, *, allow_stale=False):
        # lock-free read against the index's published snapshot;
        # allow_stale additionally permits a fresh mesh-replica answer
        # for oversized coalesced batches (service SEARCH paths only —
        # transactional reads never set it).  The version-fenced cache
        # fronts the whole thing: a fenced hit never reaches the index.
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        if earliest is None:
            raise errors.internal("must call with an earliest start time.")
        cells = canonical_cells(cells)
        e_ns = to_nanos(earliest)
        l_ns = None if latest is None else to_nanos(latest)
        # `earliest` is the query's `now` (the service clamps past
        # starts to the wall clock), and its ONLY effect on the result
        # is the t_end >= earliest expiry filter — which the cache
        # re-applies at now_ns on every hit.  Keying it would stamp
        # the wall clock into the key and make every repeat poll a
        # unique, never-hit line; only `latest` shapes the entry.
        ids = self._cached_ids(
            "isa", self._isa_index, cells,
            qkey=(l_ns,), now_ns=e_ns, allow_stale=allow_stale,
            run=lambda: self._isa_index.query_ids(
                cells, t_start=e_ns, t_end=l_ns, now=e_ns,
                allow_stale=allow_stale,
            ),
            t_end_of=self._isa_t_end,
        )
        out = []
        for i in ids:
            isa = self._isas.get(i)
            if isa is not None:
                out.append(_copy_rec(isa))
        return out

    def _isa_t_end(self, i) -> Optional[int]:
        isa = self._isas.get(i)
        return None if isa is None else to_nanos(isa.end_time)

    def _rid_sub_t_end(self, i) -> Optional[int]:
        sub = self._subs.get(i)
        return None if sub is None else to_nanos(sub.end_time)

    # -- Subscriptions -------------------------------------------------------

    def get_subscription(self, id):
        sub = self._subs.get(id)
        return dataclasses.replace(sub) if sub else None

    def _index_sub(self, sub):
        self._sub_index.put(
            sub.id,
            sub.cells,
            sub.altitude_lo,
            sub.altitude_hi,
            to_nanos(sub.start_time),
            to_nanos(sub.end_time),
            self._owners.intern(sub.owner),
        )

    def insert_subscription(self, sub):
        with self._txn_scope():
            old = self._subs.get(sub.id)
            if sub.version is None or sub.version.empty:
                if old is not None:
                    raise errors.internal(
                        "insert of existing subscription (precheck bypassed)"
                    )
            else:
                if old is None or not sub.version.matches(old.version):
                    return None
            stored = dataclasses.replace(
                sub, version=Version.from_time(self._ts.commit_ts())
            )
            self._subs[stored.id] = stored
            self._index_sub(stored)
            rec = {"t": "rid_sub_put", "doc": codec.rid_sub_to_doc(stored)}
            if self._capture_undo:
                rec["undo"] = [
                    {"t": "rid_sub_put", "doc": codec.rid_sub_to_doc(old)}
                    if old is not None
                    else {"t": "rid_sub_del", "id": stored.id}
                ]
            self._journal(rec)
            return dataclasses.replace(stored)

    def delete_subscription(self, sub):
        with self._txn_scope():
            old = self._subs.get(sub.id)
            if (
                old is None
                or old.owner != sub.owner
                or sub.version is None
                or not sub.version.matches(old.version)
            ):
                return None
            del self._subs[sub.id]
            self._sub_index.remove(sub.id)
            rec = {"t": "rid_sub_del", "id": sub.id}
            if self._capture_undo:
                rec["undo"] = [
                    {"t": "rid_sub_put", "doc": codec.rid_sub_to_doc(old)}
                ]
            self._journal(rec)
            return dataclasses.replace(old)

    def search_subscriptions(self, cells):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("no location provided")
        cells = canonical_cells(cells)
        now = self._now_ns()
        ids = self._cached_ids(
            "rid_sub", self._sub_index, cells,
            qkey=(), now_ns=now, allow_stale=False,
            run=lambda: self._sub_index.query_ids(cells, now=now),
            t_end_of=self._rid_sub_t_end,
        )
        out = []
        for i in ids:
            sub = self._subs.get(i)
            if sub is not None:
                out.append(_copy_rec(sub))
        return out

    def search_subscriptions_by_owner(self, cells, owner):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("no location provided")
        cells = canonical_cells(cells)
        now = self._now_ns()
        oid = self._owners.intern(owner)
        ids = self._cached_ids(
            "rid_sub", self._sub_index, cells,
            qkey=(), now_ns=now, allow_stale=False,
            run=lambda: self._sub_index.query_ids(
                cells, now=now, owner_id=oid
            ),
            t_end_of=self._rid_sub_t_end,
            owner_id=oid,
        )
        out = []
        for i in ids:
            sub = self._subs.get(i)
            if sub is not None:
                out.append(_copy_rec(sub))
        return out

    def max_subscription_count_in_cells_by_owner(self, cells, owner):
        return self._sub_index.max_owner_count(
            cells, self._owners.intern(owner), now=self._now_ns()
        )

    def update_notification_idxs_in_cells(self, cells, *, entity=None,
                                          removed=False):
        """Bump + return RID subscriptions intersecting cells.  The
        service passes the triggering ISA as `entity` so an attached
        push pipeline can fan the bump out as deliveries; without a
        pipeline the extra args are inert."""
        with self._txn_scope():
            ids = self._push_match_ids("rid_sub", cells)
            out = []
            undo = []
            for i in sorted(ids):
                if self._capture_undo:
                    prev = self._subs.get(i)
                    if prev is not None:
                        undo.append(
                            {"t": "rid_sub_put", "doc": codec.rid_sub_to_doc(prev)}
                        )
                bumped = _bump_sub(self._subs, i)
                if bumped is not None:
                    out.append(dataclasses.replace(bumped))
            if out:
                rec = {"t": "rid_sub_bump", "ids": [s.id for s in out]}
                if self._capture_undo:
                    rec["undo"] = undo
                self._journal(rec)
                self._offer_push("rid", entity, out, removed=removed)
            return out

    # -- WAL replay ----------------------------------------------------------

    def apply_wal(self, rec: dict):
        t = rec["t"]
        if t == "isa_put":
            isa = codec.doc_to_isa(rec["doc"])
            self._isas[isa.id] = isa
            self._index_isa(isa)
        elif t == "isa_del":
            self._isas.pop(rec["id"], None)
            self._isa_index.remove(rec["id"])
        elif t == "rid_sub_put":
            sub = codec.doc_to_rid_sub(rec["doc"])
            self._subs[sub.id] = sub
            self._index_sub(sub)
        elif t == "rid_sub_del":
            self._subs.pop(rec["id"], None)
            self._sub_index.remove(rec["id"])
        elif t == "rid_sub_bump":
            for i in rec["ids"]:
                _bump_sub(self._subs, i)


class SCDStoreImpl(_PushMixin, _TxnTimeMixin, _CachedSearchMixin, SCDStore):
    def index_stats(self) -> dict:
        return self._op_index.stats()

    def sub_index_stats(self) -> dict:
        return self._sub_index.stats()

    def cst_index_stats(self) -> dict:
        return self._cst_index.stats()

    def __init__(
        self, *, clock, ts_oracle, owners, lock, journal, index_factory,
        txn=None, capture_undo=False, cache=None, epoch_fn=None,
    ):
        self._clock = clock
        self._ts = ts_oracle
        self._owners = owners
        self._lock = lock
        self._txn = txn if txn is not None else _lock_txn(lock)
        self._journal = journal
        self._index_factory = index_factory
        self._capture_undo = capture_undo
        self._init_txn_time()
        self._init_cache(cache, epoch_fn)
        self._ops: Dict[str, scdm.Operation] = {}
        self._subs: Dict[str, scdm.Subscription] = {}
        self._csts: Dict[str, scdm.Constraint] = {}
        self._op_index = index_factory()
        self._sub_index = index_factory()
        self._cst_index = index_factory()

    def reset_state(self):
        """Drop all local state (region resync rebuilds from the log);
        _fenced_index_swap keeps the cache coherent — see RIDStoreImpl."""
        new_op, new_sub, new_cst = self._fenced_index_swap(
            self._op_index, self._sub_index, self._cst_index
        )
        self._ops = {}
        self._subs = {}
        self._csts = {}
        self._op_index = new_op
        self._sub_index = new_sub
        self._cst_index = new_cst

    def serialize_state(self) -> dict:
        """Full-state snapshot as plain JSON docs (region snapshot
        upload; the CRDB-range-snapshot analog)."""
        return self.serialize_refs(self.snapshot_refs())

    def snapshot_refs(self) -> tuple:
        """Record references for a consistent cut (cheap; call under
        the store lock); serialize_refs may then run outside it."""
        return (
            list(self._ops.values()),
            list(self._subs.values()),
            list(self._csts.values()),
        )

    @staticmethod
    def serialize_refs(refs: tuple) -> dict:
        ops, subs, csts = refs
        return {
            "ops": [codec.op_to_doc(x) for x in ops],
            "subs": [codec.scd_sub_to_doc(x) for x in subs],
            "constraints": [codec.constraint_to_doc(x) for x in csts],
        }

    def restore_state(self, state: dict) -> None:
        self.reset_state()
        for d in state.get("ops", []):
            op = codec.doc_to_op(d)
            self._ops[op.id] = op
            self._index_op(op)
        for d in state.get("subs", []):
            sub = codec.doc_to_scd_sub(d)
            self._subs[sub.id] = sub
            self._index_scd_sub(sub)
        # absent on pre-constraint snapshots (rolling upgrade): .get
        for d in state.get("constraints", []):
            cst = codec.doc_to_constraint(d)
            self._csts[cst.id] = cst
            self._index_cst(cst)


    def _visible_op(self, id) -> Optional[scdm.Operation]:
        """Expired operations are invisible (operations.go:103-112)."""
        op = self._ops.get(id)
        if op is None or to_nanos(op.end_time) < self._now_ns():
            return None
        return op

    def _visible_sub(self, id) -> Optional[scdm.Subscription]:
        sub = self._subs.get(id)
        if sub is None or to_nanos(sub.end_time) < self._now_ns():
            return None
        return sub

    def _visible_cst(self, id) -> Optional[scdm.Constraint]:
        """Expired constraints are invisible, same rule as operations."""
        cst = self._csts.get(id)
        if cst is None or to_nanos(cst.end_time) < self._now_ns():
            return None
        return cst

    # -- Operations ----------------------------------------------------------

    def get_operation(self, id):
        op = self._visible_op(id)
        if op is None:
            raise errors.not_found(id)
        return dataclasses.replace(op)

    def _index_op(self, op):
        self._op_index.put(
            op.id,
            op.cells,
            op.altitude_lower,
            op.altitude_upper,
            to_nanos(op.start_time),
            to_nanos(op.end_time),
            self._owners.intern(op.owner),
        )

    def _index_scd_sub(self, sub):
        self._sub_index.put(
            sub.id,
            sub.cells,
            sub.altitude_lo,
            sub.altitude_hi,
            to_nanos(sub.start_time),
            to_nanos(sub.end_time),
            self._owners.intern(sub.owner),
        )

    def _index_cst(self, cst):
        self._cst_index.put(
            cst.id,
            cst.cells,
            cst.altitude_lower,
            cst.altitude_upper,
            to_nanos(cst.start_time),
            to_nanos(cst.end_time),
            self._owners.intern(cst.owner),
        )

    def _op_t_end(self, i) -> Optional[int]:
        op = self._ops.get(i)
        return None if op is None else to_nanos(op.end_time)

    def _scd_sub_t_end(self, i) -> Optional[int]:
        sub = self._subs.get(i)
        return None if sub is None else to_nanos(sub.end_time)

    def _cst_t_end(self, i) -> Optional[int]:
        cst = self._csts.get(i)
        return None if cst is None else to_nanos(cst.end_time)

    def _search_ops(
        self, cells, alt_lo, alt_hi, earliest, latest, *, allow_stale=False
    ):
        # ONE cached integration point for every operation search:
        # public SEARCH, OVN-conflict prechecks, dependent-operation
        # resolution.  A fenced hit is bit-identical to the fresh path
        # (the precheck runs under the pinned txn timestamp, which is
        # exactly the `now` the cache re-filters at), so serving
        # write-safety checks from it is sound.
        cells = canonical_cells(cells)
        t0_ns = None if earliest is None else to_nanos(earliest)
        t1_ns = None if latest is None else to_nanos(latest)
        now = self._now_ns()
        ids = self._cached_ids(
            "op", self._op_index, cells,
            qkey=(
                None if alt_lo is None else float(alt_lo),
                None if alt_hi is None else float(alt_hi),
                t0_ns, t1_ns,
            ),
            now_ns=now, allow_stale=allow_stale,
            run=lambda: self._op_index.query_ids(
                cells,
                alt_lo=alt_lo,
                alt_hi=alt_hi,
                t_start=t0_ns,
                t_end=t1_ns,
                now=now,
                allow_stale=allow_stale,
            ),
            t_end_of=self._op_t_end,
        )
        # .get(): a concurrent delete between the index query and this
        # assembly must skip, not KeyError (reads are lock-free)
        out = []
        for i in sorted(ids):
            op = self._ops.get(i)
            if op is not None:
                out.append(_copy_rec(op))
        return out

    def search_operations(
        self, cells, alt_lo, alt_hi, earliest, latest, *, allow_stale=False
    ):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        return self._search_ops(
            cells, alt_lo, alt_hi, earliest, latest, allow_stale=allow_stale
        )

    def _search_csts(
        self, cells, alt_lo, alt_hi, earliest, latest, *, allow_stale=False
    ):
        """ONE cached integration point for every constraint search
        (public QUERY + the constraint-aware OVN precheck), the mirror
        of _search_ops: fenced hits are bit-identical to the fresh
        path, so serving write-safety checks from the cache is sound
        for the fifth class exactly as for the other four."""
        cells = canonical_cells(cells)
        t0_ns = None if earliest is None else to_nanos(earliest)
        t1_ns = None if latest is None else to_nanos(latest)
        now = self._now_ns()
        ids = self._cached_ids(
            "constraint", self._cst_index, cells,
            qkey=(
                None if alt_lo is None else float(alt_lo),
                None if alt_hi is None else float(alt_hi),
                t0_ns, t1_ns,
            ),
            now_ns=now, allow_stale=allow_stale,
            run=lambda: self._cst_index.query_ids(
                cells,
                alt_lo=alt_lo,
                alt_hi=alt_hi,
                t_start=t0_ns,
                t_end=t1_ns,
                now=now,
                allow_stale=allow_stale,
            ),
            t_end_of=self._cst_t_end,
        )
        out = []
        for i in sorted(ids):
            cst = self._csts.get(i)
            if cst is not None:
                out.append(_copy_rec(cst))
        return out

    def search_constraints(
        self, cells, alt_lo, alt_hi, earliest, latest, *, allow_stale=False
    ):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        return self._search_csts(
            cells, alt_lo, alt_hi, earliest, latest, allow_stale=allow_stale
        )

    def _notify_subs_locked(
        self, cells, *, trigger: str = "operations",
        alt_lo=None, alt_hi=None, t_start=None, t_end=None,
    ) -> List[scdm.Subscription]:
        """Bump + return live subscriptions intersecting cells whose
        notification trigger matches the writing entity class
        (subscriptions.go:128-173): operation writes bump
        notify_for_operations subscriptions, constraint writes bump
        notify_for_constraints ones.  Constraint callers additionally
        pass the write's altitude/time window so only subscriptions
        whose 4D volumes intersect the constraint fan out (an airport
        closure must not wake a subscriber watching a different
        altitude band).

        With a push pipeline attached the id lookup rides the
        planner's rqmatch route (dss_tpu/push/match.py) — the write IS
        a reverse query — instead of the read-side coalescer; the
        MatchStage contract keeps the id set bit-identical, so the
        returned subscriber list (and the response built from it)
        cannot change."""
        ids = self._push_match_ids(
            "scd_sub", cells, alt_lo=alt_lo, alt_hi=alt_hi,
            t_start_ns=None if t_start is None else to_nanos(t_start),
            t_end_ns=None if t_end is None else to_nanos(t_end),
        )
        want_constraints = trigger == "constraints"
        out = []
        undo = []
        for i in sorted(ids):
            prev = self._subs.get(i)
            if prev is None:
                continue
            if want_constraints:
                if not prev.notify_for_constraints:
                    continue
            elif not prev.notify_for_operations:
                continue
            if self._capture_undo:
                undo.append(
                    {"t": "scd_sub_put", "doc": codec.scd_sub_to_doc(prev)}
                )
            bumped = _bump_sub(self._subs, i)
            if bumped is not None:
                out.append(dataclasses.replace(bumped))
        if out:
            rec = {"t": "scd_sub_bump", "ids": [s.id for s in out]}
            if self._capture_undo:
                rec["undo"] = undo
            self._journal(rec)
        return out

    def _precheck_op_upsert(self, op, key, *, check_key: bool = True):
        """All upsert preconditions (version fencing, ownership, time
        range, OVN key check — operations.go:305-364), no mutation.
        Returns the old record (or None).  check_key=False skips the
        (expensive) OVN conflict search — only valid when the caller
        already ran it inside the same transaction scope (the pinned
        txn timestamp guarantees the same visibility answers)."""
        old = self._visible_op(op.id)
        if old is None and op.version != 0:
            raise errors.not_found(op.id)
        if old is not None and op.version == 0:
            raise errors.already_exists(op.id)
        if old is not None and op.version != old.version:
            raise errors.version_mismatch("old version")
        if old is not None and old.owner != op.owner:
            raise errors.permission_denied(
                f"Operation is owned by {old.owner}"
            )
        op.validate_time_range()

        if check_key and op.state in scdm.OperationState.REQUIRES_KEY:
            conflicting = self._search_ops(
                op.cells,
                op.altitude_lower,
                op.altitude_upper,
                op.start_time,
                op.end_time,
            )
            key_set = set(key)
            missing = [c for c in conflicting if c.ovn not in key_set]
            if op.constraint_aware:
                # constraint-aware deconfliction: the op's USS consumes
                # constraint updates, so its key must also cover every
                # intersecting constraint's OVN — a stale view of an
                # airspace closure is exactly the conflict the key
                # check exists to catch
                missing.extend(
                    c
                    for c in self._search_csts(
                        op.cells,
                        op.altitude_lower,
                        op.altitude_upper,
                        op.start_time,
                        op.end_time,
                    )
                    if c.ovn not in key_set
                )
            if missing:
                raise errors.missing_ovns(missing)
        return old

    def validate_operation_upsert(self, op, key):
        """Read-only precheck, run by the service BEFORE any journaled
        mutation (e.g. the implicit subscription) so a rejected conflict
        — a routine outcome — aborts the transaction with an empty
        journal buffer: nothing to roll back, no region resync.  The
        upsert that follows (with key_checked=True) re-runs only the
        cheap fencing checks; the pinned per-txn timestamp keeps both
        passes' visibility answers identical."""
        with self._txn_scope():
            self._precheck_op_upsert(op, key)

    def upsert_operation(self, op, key, *, key_checked: bool = False):
        with self._txn_scope():
            old = self._precheck_op_upsert(
                op, key, check_key=not key_checked
            )
            ts = self._ts.commit_ts()
            stored = dataclasses.replace(
                op,
                version=(old.version if old else 0) + 1,
                ovn=new_ovn_from_time(ts, op.id),
            )
            if self._capture_undo:
                # exact inverse: restore whatever the id maps to NOW,
                # including an expired (invisible) record `old` misses
                prev_raw = self._ops.get(op.id)
                undo = [
                    {"t": "scd_op_put", "doc": codec.op_to_doc(prev_raw)}
                    if prev_raw is not None
                    else {"t": "scd_op_del", "id": stored.id}
                ]
            self._ops[stored.id] = stored
            self._index_op(stored)
            rec = {"t": "scd_op_put", "doc": codec.op_to_doc(stored)}
            if self._capture_undo:
                rec["undo"] = undo
            self._journal(rec)
            subs = self._notify_subs_locked(stored.cells)
            self._offer_push(
                "operations", stored, subs,
                emergency=stored.state in (
                    scdm.OperationState.NON_CONFORMING,
                    scdm.OperationState.CONTINGENT,
                ),
                alt_lo=stored.altitude_lower,
                alt_hi=stored.altitude_upper,
                t_start=stored.start_time, t_end=stored.end_time,
            )
            return dataclasses.replace(stored), subs

    def delete_operation(self, id, owner):
        with self._txn_scope():
            old = self._visible_op(id)
            if old is None:
                raise errors.not_found(id)
            if old.owner != owner:
                raise errors.permission_denied(f"Operation is owned by {old.owner}")
            subs = self._notify_subs_locked(old.cells)
            del self._ops[id]
            self._op_index.remove(id)
            rec = {"t": "scd_op_del", "id": id}
            if self._capture_undo:
                rec["undo"] = [{"t": "scd_op_put", "doc": codec.op_to_doc(old)}]
            self._journal(rec)
            # implicit-subscription GC (operations.go:249-267,296-298)
            sub = self._subs.get(old.subscription_id)
            if (
                sub is not None
                and sub.implicit_subscription
                and sub.owner == owner
                and not any(
                    o.subscription_id == sub.id for o in self._ops.values()
                )
            ):
                del self._subs[sub.id]
                self._sub_index.remove(sub.id)
                gc_rec = {"t": "scd_sub_del", "id": sub.id}
                if self._capture_undo:
                    gc_rec["undo"] = [
                        {"t": "scd_sub_put", "doc": codec.scd_sub_to_doc(sub)}
                    ]
                self._journal(gc_rec)
            self._offer_push(
                "operations", old, subs, removed=True,
                alt_lo=old.altitude_lower, alt_hi=old.altitude_upper,
                t_start=old.start_time, t_end=old.end_time,
            )
            return dataclasses.replace(old), subs

    # -- Constraints ---------------------------------------------------------
    #
    # The fifth entity class, beyond the reference (which stubs it):
    # same fencing/ownership discipline as operations, fan-out to
    # notify_for_constraints subscriptions whose 4D volumes intersect
    # the write, no OVN key check on the constraint itself.

    def get_constraint(self, id):
        cst = self._visible_cst(id)
        if cst is None:
            raise errors.not_found(id)
        return dataclasses.replace(cst)

    def upsert_constraint(self, cst):
        with self._txn_scope():
            old = self._visible_cst(cst.id)
            if old is None and cst.version != 0:
                raise errors.not_found(cst.id)
            if old is not None and cst.version == 0:
                raise errors.already_exists(cst.id)
            if old is not None and cst.version != old.version:
                raise errors.version_mismatch("old version")
            if old is not None and old.owner != cst.owner:
                raise errors.permission_denied(
                    f"Constraint is owned by {old.owner}"
                )
            cst.validate_time_range()
            ts = self._ts.commit_ts()
            stored = dataclasses.replace(
                cst,
                version=(old.version if old else 0) + 1,
                ovn=new_ovn_from_time(ts, cst.id),
            )
            if self._capture_undo:
                # exact inverse: raw get includes an expired
                # (invisible) record that `old` misses
                prev_raw = self._csts.get(cst.id)
                undo = [
                    {"t": "scd_cst_put",
                     "doc": codec.constraint_to_doc(prev_raw)}
                    if prev_raw is not None
                    else {"t": "scd_cst_del", "id": stored.id}
                ]
            self._csts[stored.id] = stored
            self._index_cst(stored)
            rec = {"t": "scd_cst_put", "doc": codec.constraint_to_doc(stored)}
            if self._capture_undo:
                rec["undo"] = undo
            self._journal(rec)
            subs = self._notify_subs_locked(
                stored.cells, trigger="constraints",
                alt_lo=stored.altitude_lower, alt_hi=stored.altitude_upper,
                t_start=stored.start_time, t_end=stored.end_time,
            )
            self._offer_push(
                "constraints", stored, subs,
                alt_lo=stored.altitude_lower,
                alt_hi=stored.altitude_upper,
                t_start=stored.start_time, t_end=stored.end_time,
            )
            return dataclasses.replace(stored), subs

    def delete_constraint(self, id, owner):
        with self._txn_scope():
            old = self._visible_cst(id)
            if old is None:
                raise errors.not_found(id)
            if old.owner != owner:
                raise errors.permission_denied(
                    f"Constraint is owned by {old.owner}"
                )
            subs = self._notify_subs_locked(
                old.cells, trigger="constraints",
                alt_lo=old.altitude_lower, alt_hi=old.altitude_upper,
                t_start=old.start_time, t_end=old.end_time,
            )
            del self._csts[id]
            self._cst_index.remove(id)
            rec = {"t": "scd_cst_del", "id": id}
            if self._capture_undo:
                rec["undo"] = [
                    {"t": "scd_cst_put", "doc": codec.constraint_to_doc(old)}
                ]
            self._journal(rec)
            self._offer_push(
                "constraints", old, subs, removed=True,
                alt_lo=old.altitude_lower, alt_hi=old.altitude_upper,
                t_start=old.start_time, t_end=old.end_time,
            )
            return dataclasses.replace(old), subs

    # -- Subscriptions -------------------------------------------------------

    def _dependent_ops(self, sub) -> List[str]:
        """The reference populates DependentOperations with the ids of
        operations intersecting the subscription's own 4D volume
        (subscriptions.go:212-249)."""
        if len(np.asarray(sub.cells).ravel()) == 0:
            return []
        ops = self._search_ops(
            sub.cells, sub.altitude_lo, sub.altitude_hi, sub.start_time, sub.end_time
        )
        return [o.id for o in ops]

    def get_subscription(self, id, owner):
        sub = self._visible_sub(id)
        if sub is None or sub.owner != owner:
            raise errors.not_found(id)
        out = dataclasses.replace(sub)
        out.dependent_operations = self._dependent_ops(sub)
        return out

    def upsert_subscription(self, sub):
        with self._txn_scope():
            old = self._visible_sub(sub.id)
            if old is None and sub.version != 0:
                raise errors.not_found(sub.id)
            if old is not None and sub.version == 0:
                raise errors.already_exists(sub.id)
            if old is not None and sub.version != old.version:
                raise errors.version_mismatch("old version")
            if old is not None and old.owner != sub.owner:
                raise errors.permission_denied(
                    f"Subscription is owned by {old.owner}"
                )
            count = self._sub_index.max_owner_count(
                sub.cells, self._owners.intern(sub.owner), now=self._now_ns()
            )
            if count >= MAX_SCD_SUBSCRIPTIONS_PER_AREA:
                msg = "too many existing subscriptions in this area already"
                if old is not None:
                    msg += ", rejecting update request"
                raise errors.exhausted(msg)
            stored = dataclasses.replace(
                sub, version=(old.version if old else 0) + 1
            )
            if self._capture_undo:
                # exact inverse: raw get includes an expired (invisible)
                # record that `old` (visibility-filtered) misses
                prev_raw = self._subs.get(sub.id)
                undo = [
                    {"t": "scd_sub_put", "doc": codec.scd_sub_to_doc(prev_raw)}
                    if prev_raw is not None
                    else {"t": "scd_sub_del", "id": stored.id}
                ]
            self._subs[stored.id] = stored
            self._index_scd_sub(stored)
            rec = {"t": "scd_sub_put", "doc": codec.scd_sub_to_doc(stored)}
            if self._capture_undo:
                rec["undo"] = undo
            self._journal(rec)
            affected = (
                self._search_ops(
                    stored.cells,
                    stored.altitude_lo,
                    stored.altitude_hi,
                    stored.start_time,
                    stored.end_time,
                )
                if len(np.asarray(stored.cells).ravel())
                else []
            )
            return dataclasses.replace(stored), affected

    def delete_subscription(self, id, owner, version):
        with self._txn_scope():
            old = self._visible_sub(id)
            if old is None:
                raise errors.not_found(id)
            if version != 0 and version != old.version:
                raise errors.version_mismatch("old version")
            if old.owner != owner:
                raise errors.permission_denied(f"ISA is owned by {old.owner}")
            if any(o.subscription_id == id for o in self._ops.values()):
                raise errors.bad_request(
                    "failed to delete implicit subscription with active operation"
                )
            del self._subs[id]
            self._sub_index.remove(id)
            rec = {"t": "scd_sub_del", "id": id}
            if self._capture_undo:
                rec["undo"] = [
                    {"t": "scd_sub_put", "doc": codec.scd_sub_to_doc(old)}
                ]
            self._journal(rec)
            return dataclasses.replace(old)

    def search_subscriptions(self, cells, owner):
        """Live subscriptions of `owner` intersecting cells.

        The reference's SQL uses a LEFT JOIN (subscriptions.go:500-521)
        which in effect ignores the cell filter; we implement the
        intended inner-join semantics (cells do filter).
        """
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("no location provided")
        cells = canonical_cells(cells)
        now = self._now_ns()
        oid = self._owners.intern(owner)
        ids = self._cached_ids(
            "scd_sub", self._sub_index, cells,
            qkey=(), now_ns=now, allow_stale=False,
            run=lambda: self._sub_index.query_ids(
                cells, now=now, owner_id=oid
            ),
            t_end_of=self._scd_sub_t_end,
            owner_id=oid,
        )
        out = []
        for i in sorted(ids):
            sub = self._subs.get(i)
            if sub is None:
                continue
            s = _copy_rec(sub)
            # dependent ops resolve fresh each time (and their inner
            # _search_ops calls ride the cache themselves)
            s.dependent_operations = self._dependent_ops(sub)
            out.append(s)
        return out

    # -- WAL replay ----------------------------------------------------------

    def apply_wal(self, rec: dict):
        t = rec["t"]
        if t == "scd_op_put":
            op = codec.doc_to_op(rec["doc"])
            self._ops[op.id] = op
            self._index_op(op)
        elif t == "scd_op_del":
            self._ops.pop(rec["id"], None)
            self._op_index.remove(rec["id"])
        elif t == "scd_sub_put":
            sub = codec.doc_to_scd_sub(rec["doc"])
            self._subs[sub.id] = sub
            self._index_scd_sub(sub)
        elif t == "scd_sub_del":
            self._subs.pop(rec["id"], None)
            self._sub_index.remove(rec["id"])
        elif t == "scd_sub_bump":
            for i in rec["ids"]:
                _bump_sub(self._subs, i)
        elif t == "scd_cst_put":
            cst = codec.doc_to_constraint(rec["doc"])
            self._csts[cst.id] = cst
            self._index_cst(cst)
        elif t == "scd_cst_del":
            self._csts.pop(rec["id"], None)
            self._cst_index.remove(rec["id"])


class DSSStore:
    """One DSS instance's storage: RID + SCD stores sharing a lock, a
    commit-timestamp oracle, an owner interner, and a durable log.

    Two durability modes:
      - standalone (default): a local WriteAheadLog is the source of
        truth; boot replays it.
      - region (`region_url` set): the shared region log
        (dss_tpu.region) is the source of truth; every mutation runs
        as a lease-fenced write-through transaction and a tail poller
        applies remote instances' writes.  The local WAL is disabled
        (the region server owns durability), mirroring the reference
        where instances keep no local state beside the shared CRDB
        cluster (README.md:22-49).
    """

    def __init__(
        self,
        *,
        storage: str = "tpu",
        clock: Optional[Clock] = None,
        wal_path: Optional[str] = None,
        wal_fsync: bool = False,
        region_url: Optional[str] = None,
        region_token: Optional[str] = None,
        region_poll_interval_s: float = 0.05,
        region_snapshot_every: int = 512,
        region_optimistic: bool = True,  # False forces the lease path
        #                    (bench/diagnosis of lease-path round trips)
        instance_id: Optional[str] = None,
    ):
        if storage == "tpu":
            index_factory = TpuSpatialIndex
        elif storage == "memory":
            index_factory = MemorySpatialIndex
        else:
            raise ValueError(f"unknown storage backend {storage!r}")
        if region_url and wal_path:
            raise ValueError(
                "wal_path is unused in region mode: the region log server "
                "owns durability (give the WAL path to the region server)"
            )
        self.storage = storage
        self.clock = clock or Clock()
        # the graceful-degradation ladder (chaos/ladder.py): ONE
        # explicit health state machine for this store — the planner
        # reads device_ok from it, the region client drives
        # REGION_LOG_DOWN into it, and recovery re-warms (AOT grid)
        # before re-admitting routes.  Surfaced in /status,
        # X-DSS-Freshness, and the dss_degraded_mode gauge.
        self.health = chaos.DegradationLadder()
        self.health.on_recover("device_lost", self._rewarm_after_device_loss)
        self.wal = WriteAheadLog(None if region_url else wal_path, fsync=wal_fsync)
        self._lock = threading.RLock()
        self.region = None
        txn = None
        epoch_fn = None
        if region_url:
            from dss_tpu.region.client import RegionClient
            from dss_tpu.region.coordinator import RegionCoordinator

            self._region_client = RegionClient(
                region_url, instance_id, auth_token=region_token,
                health=self.health,
            )
            txn = self._region_txn
            # region epoch joins the cache fence: a promotion or a
            # restored-backup rotation invalidates every cached answer
            epoch_fn = self._region_client.current_epoch
        # version-fenced read cache (dar/readcache.py): one shared
        # instance fronting all five entity classes' search paths;
        # DSS_CACHE_* env knobs, configure_serving(cache=) at runtime
        self.cache = rcache.ReadCache(**rcache.env_knobs())
        # per-key-range query-load EWMA (dar/tiers.py RangeLoad): one
        # shared map across all five classes — they cover one S2 key
        # space and the sharded replica plans ONE boundary map from it.
        # Coalescer-served traffic stamps it below; attach_mesh_replica
        # hands the same instance to the replica so its own serving
        # entry accumulates into the same map.
        from dss_tpu.dar import tiers as _tiersmod

        self.range_load = _tiersmod.RangeLoad()
        ts = TimestampOracle(self.clock)
        owners = OwnerInterner()
        self.rid = RIDStoreImpl(
            clock=self.clock,
            ts_oracle=ts,
            owners=owners,
            lock=self._lock,
            journal=self._journal,
            index_factory=index_factory,
            txn=txn,
            capture_undo=bool(region_url),
            cache=self.cache,
            epoch_fn=epoch_fn,
        )
        self.scd = SCDStoreImpl(
            clock=self.clock,
            ts_oracle=ts,
            owners=owners,
            lock=self._lock,
            journal=self._journal,
            index_factory=index_factory,
            txn=txn,
            capture_undo=bool(region_url),
            cache=self.cache,
            epoch_fn=epoch_fn,
        )
        # per-class cache hit/miss counters ride the coalescer stats
        # path (dss_dar_<class>_co_cache_* in /metrics), so hit rate
        # renders next to the route mix it removes load from
        for index, cls in (
            (self.rid._isa_index, "isa"),
            (self.rid._sub_index, "rid_sub"),
            (self.scd._op_index, "op"),
            (self.scd._sub_index, "scd_sub"),
            (self.scd._cst_index, "constraint"),
        ):
            co = getattr(index, "coalescer", None)
            if co is not None:
                co.set_cache_view(
                    lambda cls=cls: self.cache.class_stats(cls)
                )
                co.set_load_view(self.range_load)
                co.set_health(self.health)
        # multi-region federation (region/federation.py): None until
        # attach_federation wraps the sub-stores with the locality
        # router; stats() exports the stable dss_fed_* key set either
        # way so dashboards never miss a series
        self.federation = None
        # reverse-query push pipeline (push/pipeline.py): None until
        # attach_push wires the durable delivery queue onto the write
        # path; stats() exports the stable dss_push_* key set either way
        self.push = None
        # shared-memory serving front (parallel/shmring.py): None
        # until attach_shm_front makes this process the device owner
        self._shm_owner = None
        # self-tuning controller (tune/controller.py): None until
        # attach_tuner; stats() exports the stable dss_tune_* key set
        # either way (DSS_TUNE=0 builds nothing, installs no hook)
        self.tune = None
        self._replaying = False
        if region_url:
            self.region = RegionCoordinator(
                self._region_client,
                self.rid,
                self.scd,
                self._lock,
                poll_interval_s=region_poll_interval_s,
                snapshot_every=region_snapshot_every,
                optimistic=region_optimistic,
            )
            self.region.bootstrap()
        else:
            self._replay()

    def _region_txn(self):
        return self.region.txn()

    def _rewarm_after_device_loss(self) -> None:
        """Recovery hook (ladder.on_recover): a returning device must
        be warm BEFORE the planner re-admits the device class, or the
        first post-recovery batches pay compile storms inside their
        deadlines.  Best-effort — a failed warm only means lazy
        warm-on-traffic, exactly the cold-boot behavior."""
        try:
            self.warm_resident()
        except Exception:  # noqa: BLE001 — recovery must not wedge
            import logging

            logging.getLogger("dss.chaos").exception(
                "post-device-loss re-warm failed; warming lazily"
            )

    def _journal(self, rec: dict):
        if self._replaying:
            return
        if self.region is not None:
            self.region.journal(rec)
        else:
            self.wal.append(rec)

    def apply_log_record(self, rec: dict) -> None:
        """Apply one WAL/region-log record to the right sub-store
        (caller holds the lock and has set _replaying)."""
        t = rec.get("t", "")
        if t.startswith("isa") or t.startswith("rid"):
            self.rid.apply_wal(rec)
        else:
            self.scd.apply_wal(rec)

    def _replay(self):
        self._replaying = True
        try:
            for rec in self.wal.replay():
                self.apply_log_record(rec)
        finally:
            self._replaying = False

    def configure_serving(self, **knobs) -> None:
        """Fan serving-pipeline knobs (QueryCoalescer.configure:
        min_batch / max_batch / target_batch_ms / queue_depth /
        admission_wait_s / inline / slo_ms — the per-query serving SLO
        driving the deadline router — / resident, the persistent
        device-feeder loop) out to every entity class's coalescer.  Boot-time defaults come from DSS_CO_* env vars
        (coalesce.env_knobs); this is the runtime override for ops
        tuning and tests.  No-op on the memory backend — except
        `cache`, the version-fenced read cache toggle, which applies
        on both backends (disable flushes; see OPERATIONS.md runbook)."""
        cache = knobs.pop("cache", None)
        if cache is not None:
            self.cache.configure(enabled=bool(cache))
        if not knobs:
            return
        for index in (
            self.rid._isa_index, self.rid._sub_index,
            self.scd._op_index, self.scd._sub_index,
            self.scd._cst_index,
        ):
            co = getattr(index, "coalescer", None)
            if co is not None:
                co.configure(**knobs)

    def warm_resident(self) -> int:
        """AOT-compile the resident bucket grid for every entity
        class's current tiers (ops/resident.py).  Call AFTER
        configure_serving(resident=True) attached the loops; runs the
        multi-second XLA compiles off the serving path (the server's
        boot warm thread).  Returns executables built."""
        n = 0
        for index in (
            self.rid._isa_index, self.rid._sub_index,
            self.scd._op_index, self.scd._sub_index,
            self.scd._cst_index,
        ):
            co = getattr(index, "coalescer", None)
            table = getattr(index, "table", None)
            if co is None or table is None:
                continue
            loop = co.resident_loop()
            if loop is None:
                continue
            warm = getattr(table, "warm_resident", None)
            if warm is not None:
                n += warm(loop.kernel)
        return n

    # -- shared-memory serving front (parallel/shmring.py) -------------------

    def _class_index(self, cls: str):
        return {
            "isa": self.rid._isa_index,
            "rid_sub": self.rid._sub_index,
            "op": self.scd._op_index,
            "scd_sub": self.scd._sub_index,
            "constraint": self.scd._cst_index,
        }[cls]

    def shm_serve(self, req) -> Tuple[List[str], List[int], int, int]:
        """Serve one shared-memory ring request (shmring.ShmRequest)
        through the SAME search paths HTTP requests take — admission,
        deadline routing, the planner, and the owner's read cache all
        apply — returning (ids, t_end ns per id, class generation,
        response flags).  The flags carry RESP_F_MESH_SERVED when the
        answer came from the bounded-stale mesh replica: the leader
        refuses to populate its own cache from such answers
        (_cached_ids), and the requesting worker must refuse too.

        Visibility is pinned to the WORKER's `now`: the request's
        clock instant rides the txn-time thread-local, so the answer
        is bit-identical to what the worker's own fresh path would
        have computed at that instant (expiry included).  The
        backwards-clock guards in the read cache already handle
        out-of-order nows across workers — this is the same contract
        as a txn-pinned precheck behind live pollers."""
        from dss_tpu.clock import from_nanos

        cls = req.cls
        cells = canonical_cells(req.cells)
        sub = self.rid if cls in ("isa", "rid_sub") else self.scd
        tl = sub._txn_time
        pinned = getattr(tl, "now", None) is None
        if pinned:
            tl.now = int(req.now_ns)
        try:
            if cls == "isa":
                recs = sub.search_isas(
                    cells, from_nanos(req.t0_ns),
                    None if req.t1_ns is None else from_nanos(req.t1_ns),
                    allow_stale=req.allow_stale,
                )
            elif cls == "rid_sub":
                recs = (
                    sub.search_subscriptions_by_owner(cells, req.owner)
                    if req.owner
                    else sub.search_subscriptions(cells)
                )
            elif cls == "op":
                recs = sub.search_operations(
                    cells, req.alt_lo, req.alt_hi,
                    None if req.t0_ns is None else from_nanos(req.t0_ns),
                    None if req.t1_ns is None else from_nanos(req.t1_ns),
                    allow_stale=req.allow_stale,
                )
            elif cls == "constraint":
                recs = sub.search_constraints(
                    cells, req.alt_lo, req.alt_hi,
                    None if req.t0_ns is None else from_nanos(req.t0_ns),
                    None if req.t1_ns is None else from_nanos(req.t1_ns),
                    allow_stale=req.allow_stale,
                )
            elif cls == "scd_sub":
                # id-level serve: the worker resolves each sub's
                # dependent operations itself (through its own cached
                # op path), so the slot never carries nested lists
                now = int(req.now_ns)
                oid = (
                    sub._owners.intern(req.owner)
                    if req.owner else None
                )
                ids = sub._cached_ids(
                    "scd_sub", sub._sub_index, cells,
                    qkey=(), now_ns=now, allow_stale=False,
                    run=lambda: sub._sub_index.query_ids(
                        cells, now=now, owner_id=oid
                    ),
                    t_end_of=sub._scd_sub_t_end,
                    owner_id=oid,
                )
                out_ids, t1s = [], []
                for i in sorted(ids):
                    t1 = sub._scd_sub_t_end(i)
                    if t1 is None:
                        continue
                    out_ids.append(i)
                    t1s.append(t1)
                gen = sub._sub_index.cell_clock.generation
                return out_ids, t1s, gen, self._shm_resp_flags()
            else:
                raise errors.bad_request(f"unknown shm class {cls!r}")
        finally:
            if pinned:
                tl.now = None
        gen = self._class_index(cls).cell_clock.generation
        _never = np.iinfo(np.int64).max
        return (
            [r.id for r in recs],
            # a record with no end time never expires: int64 max keeps
            # the worker cache's t_end-refilter a no-op for it
            [
                _never if r.end_time is None else to_nanos(r.end_time)
                for r in recs
            ],
            gen,
            self._shm_resp_flags(),
        )

    @staticmethod
    def _shm_resp_flags() -> int:
        from dss_tpu.parallel import shmring

        return (
            shmring.RESP_F_MESH_SERVED
            if rcache.take_last_search_meshed() else 0
        )

    def attach_shm_front(self, region, *, threads: int = None,
                         worker_ttl_s: float = 5.0):
        """Make this store the device owner of a shared-memory serving
        front: every entity class's cell clock broadcasts its bumps
        into the region's fence segment, and a ShmOwner drain serves
        ring requests through shm_serve.  Returns the started owner
        (the caller — cmds/server.py — reclaims dead workers' slots
        via owner.reclaim_worker)."""
        from dss_tpu.parallel import shmring

        if self._shm_owner is not None:
            raise RuntimeError("shm front already attached")
        for idx, cls in enumerate(shmring.SHM_CLASSES):
            self._class_index(cls).cell_clock.attach_mirror(
                shmring.FenceMirror(region, idx)
            )
        owner = shmring.ShmOwner(
            region, self.shm_serve, threads=threads,
            wal_seq_fn=lambda: self.wal.seq,
            worker_ttl_s=worker_ttl_s,
        )
        owner.start()
        self._shm_owner = owner
        return owner

    def attach_federation(self, router) -> None:
        """Put the multi-region FederationRouter in front of the
        store: binds the UNWRAPPED sub-stores for peer-facing serving
        (a remote's query must never recurse through the federation
        layer), wires the degradation ladder (remote-unreachable ->
        FEDERATION_DEGRADED, recovery re-syncs the follower tail
        before re-admission), swaps self.rid/self.scd for the
        federated wrappers (searches federate, cells-carrying writes
        are ownership-guarded), and starts the mirror sync loop.
        Call BEFORE building services — they must see the wrappers."""
        from dss_tpu.region import federation as fedmod

        if self.federation is not None:
            raise RuntimeError("federation already attached")
        epoch_fn = None
        if self.region is not None:
            epoch_fn = self._region_client.current_epoch
        router.bind_local(
            self.rid, self.scd, epoch_fn=epoch_fn,
            wall_clock=self.clock,
        )
        router.set_health(self.health)
        self.federation = router
        self.rid = fedmod.FederatedRIDStore(self.rid, router)
        self.scd = fedmod.FederatedSCDStore(self.scd, router)
        router.start()

    def attach_push(self, pipeline) -> None:
        """Wire the reverse-query push pipeline (push/pipeline.py)
        onto the write path: subscription-match lookups route through
        the pipeline's MatchStages (planner rqmatch candidate -> fused
        device kernel, host oracle fallback — bit-identical either
        way), matched writes fan out through the WAL-backed delivery
        queue, and the delivery workers start.  The sub-store hooks go
        on the UNWRAPPED impls so federated wrappers keep delegating;
        ladder edges (PUSH_DEGRADED) ride the pipeline's own health
        hook.  Safe under federation in either attach order."""
        if self.push is not None:
            raise RuntimeError("push pipeline already attached")
        pipeline.bind_store(self)
        getattr(self.rid, "_local", self.rid).set_push(pipeline)
        getattr(self.scd, "_local", self.scd).set_push(pipeline)
        self.push = pipeline

    def attach_tuner(self, controller) -> None:
        """Arm the self-tuning controller (tune/controller.py): record
        boot knob values (the rollback floor), install the planner
        decision-recorder hook, and start the observe/propose/shadow/
        guard loop.  Exactly one tuner per store — the recorder hook is
        a process-global seam."""
        if self.tune is not None:
            raise RuntimeError("tuner already attached")
        controller.start()
        self.tune = controller

    def tune_knob_values(self) -> dict:
        """Live values of every hot-swappable knob (tune.HOT_KNOBS),
        read off one representative coalescer's cost model + resident
        geometry — the tuner's current_fn, and the 'active' side of
        the Grafana knob panel.  {} on the memory backend (no
        coalescers: the tuner observes but can never propose)."""
        co = getattr(self.rid._isa_index, "coalescer", None)
        if co is None:
            return {}
        cost = co._planner.cost
        return {
            "DSS_CO_EST_FLOOR_MS": float(cost.est_floor_ms),
            "DSS_CO_EST_ITEM_MS": float(cost.est_item_ms),
            "DSS_CO_EST_CHUNK_MS": float(cost.est_chunk_ms),
            "DSS_CO_EST_RES_FLOOR_MS": float(cost.est_res_floor_ms),
            "DSS_CO_EST_RES_LAT_MS": float(cost.est_res_lat_ms),
            "DSS_CO_RES_INFLIGHT": float(co._res_inflight),
            "DSS_CO_RES_RING": float(co._res_ring),
        }

    def attach_mesh_replica(self, replica, min_batch: int = 64) -> None:
        """Route oversized bounded-staleness search batches from each
        entity class's coalescer to the multi-chip replica when it is
        fresh (VERDICT r4 #4).  Only queries flagged allow_stale (the
        service SEARCH paths) are eligible; conflict prechecks and
        transactional reads always serve locally."""
        pairs = [
            (self.rid._isa_index, "isas"),
            (self.rid._sub_index, "rid_subs"),
            (self.scd._op_index, "ops"),
            (self.scd._sub_index, "scd_subs"),
            (self.scd._cst_index, "constraints"),
        ]
        for index, cls in pairs:
            co = getattr(index, "coalescer", None)
            if co is None:
                continue  # memory backend: no coalescer tier

            def make(cls):
                def fn(keys_list, alo, ahi, ts, te, now_arr):
                    return replica.query_batch(
                        keys_list, alo, ahi, ts, te, now=now_arr, cls=cls
                    )

                return fn

            def bgen_fn(_r=replica):
                # plans record the shard placement generation they
                # were decided against (MultihostReplica wraps the
                # inner ShardedReplica that owns the boundary map)
                inner = getattr(_r, "_inner", _r)
                return getattr(inner, "boundary_gen", 0)

            co.set_mesh_delegate(
                make(cls), replica.fresh, min_batch=min_batch,
                bgen_fn=bgen_fn,
            )
        # one load map: coalescer-served AND replica-served traffic
        # accumulate into the store's RangeLoad, which the replica's
        # rebalancer plans from at fold boundaries
        use_load = getattr(replica, "use_load", None)
        if use_load is not None:
            use_load(self.range_load)

    def close(self):
        # tuner first: clears the planner decision hook and stops the
        # loop before the coalescers it actuates start tearing down
        if self.tune is not None:
            self.tune.close()
        if self.push is not None:
            self.push.close()
        if self._shm_owner is not None:
            self._shm_owner.close()
        if self.federation is not None:
            self.federation.close()
        if self.region is not None:
            self.region.close()
        for index in (
            self.rid._isa_index, self.rid._sub_index,
            self.scd._op_index, self.scd._sub_index,
            self.scd._cst_index,
        ):
            closer = getattr(index, "close", None)
            if closer is not None:
                closer()
        self.wal.close()

    def stats(self) -> dict:
        """Per-index gauges for /metrics (dss_dar_* names)."""
        out = {}
        for name, stats in (
            ("isa", self.rid.index_stats),
            ("rid_sub", self.rid.sub_index_stats),
            ("op", self.scd.index_stats),
            ("scd_sub", self.scd.sub_index_stats),
            ("constraint", self.scd.cst_index_stats),
        ):
            for k, v in stats().items():
                out[f"dss_dar_{name}_{k}"] = v
        # store-wide read-cache gauges (stable key set whether the
        # cache is enabled or not — dashboards expect the series)
        for k, v in self.cache.stats().items():
            out[f"dss_cache_{k}"] = v
        # per-key-range load accounting (the skew-aware rebalancer's
        # measurement input)
        for k, v in self.range_load.stats().items():
            out[f"dss_{k}"] = v
        # degradation ladder + fault-injection + breaker gauges: the
        # key set is stable on every deployment (dict-valued entries
        # render as labeled families — dss_breaker_state{remote},
        # dss_fault_injected_total{site})
        out.update(self.health.stats())
        out["dss_fault_injected_total"] = (
            chaos.registry().injected_by_site()
        )
        breakers = {}
        if self.region is not None:
            fn = getattr(self._region_client, "breaker_states", None)
            if fn is not None:
                breakers = fn()
        out["dss_breaker_state"] = breakers
        # federation gauges: the stable key set whether or not a
        # router is attached (dss_fed_peer_state/mirror_lag_s render
        # as labeled families keyed by region)
        from dss_tpu.region import federation as _fedmod

        if self.federation is not None:
            out.update(self.federation.stats())
        else:
            out.update(_fedmod.empty_stats())
        # shared-memory front gauges: same stable-key-set discipline
        # (per-worker counters render as dss_shm_worker_*{process})
        from dss_tpu.parallel import shmring as _shmmod

        if self._shm_owner is not None:
            out.update(self._shm_owner.stats())
        else:
            out.update(_shmmod.empty_stats())
        # push-pipeline gauges: stable key set whether or not the
        # pipeline is attached (dss_push_breaker_state renders as a
        # labeled family keyed by uss)
        from dss_tpu import push as _pushmod

        if self.push is not None:
            out.update(self.push.stats())
        else:
            out.update(_pushmod.empty_stats())
        # self-tuning gauges: stable key set whether or not a tuner is
        # attached (dss_tune_knob_active/_proposed render as labeled
        # families keyed by knob)
        from dss_tpu import tune as _tunemod

        if self.tune is not None:
            out.update(self.tune.stats())
        else:
            out.update(_tunemod.empty_stats())
        # trace recorder gauges (obs/trace.py): sampling config, kept/
        # dropped counters, ring depth, and the allocation counter the
        # zero-cost-when-disabled contract is asserted against
        out.update(trace.stats())
        if self.region is not None:
            out.update(self.region.stats())
        return out

    def freshness_status(self) -> dict:
        """Operator view of the version-fence state (GET /status):
        region epoch, per-class write generation + cell-clock
        high-water mark, and the cache counters — enough to verify
        fence behaviour without reading code."""
        classes = {}
        for name, index in (
            ("isa", self.rid._isa_index),
            ("rid_sub", self.rid._sub_index),
            ("op", self.scd._op_index),
            ("scd_sub", self.scd._sub_index),
            ("constraint", self.scd._cst_index),
        ):
            clock = getattr(index, "cell_clock", None)
            classes[name] = {
                "generation": 0 if clock is None else clock.generation,
                "cell_clock_high_water": (
                    0 if clock is None else clock.high_water
                ),
                "live_records": index.stats().get("live_records", 0),
            }
        epoch = ""
        if self.region is not None:
            epoch = self._region_client.current_epoch()
        return {
            "storage": self.storage,
            "epoch": epoch,
            "cache": self.cache.stats(),
            "classes": classes,
            # the degradation ladder's operator view: current mode +
            # every active condition with its age and reason
            "degraded_mode": self.health.mode_name(),
            "degraded": self.health.active(),
            # multi-region view: local region id, peer breaker states,
            # mirror lags — the partition drill's observability seam
            "federation": (
                None if self.federation is None
                else self.federation.status()
            ),
            # push-pipeline view: queue depth/lag, breaker states,
            # parked count — the delivery-backlog runbook's first stop
            "push": None if self.push is None else self.push.status(),
        }
