"""Repository interfaces — the storage seam.

Mirrors the seam in the reference where a new backend plugs in
(pkg/rid/repos/repo.go:6-18, pkg/scd/store/store.go:53-130).  Two
implementations ship:

  - MemoryStore (memory_store.py): pure-python linear scans, the analog
    of the reference's in-memory test fakes
    (pkg/rid/application/isa_test.go:29-77) — also the oracle in store
    contract tests.
  - DarStore (dar_store.py): host-authoritative dicts + write-ahead log
    + the HBM DarTable spatial index for every search (the --storage=tpu
    backend).

Concurrency model: the reference pushes races into CockroachDB
serializable transactions; here each store serializes logical
transactions through a re-entrant lock exposed as `transaction()`.
Handlers run their whole action inside it, which gives the same
read-your-writes + fencing behavior as the reference's
InTxnRetrier/PerformOperationWithRetries without needing retries.
"""

from __future__ import annotations

import abc
import contextlib
from datetime import datetime
from typing import List, Optional, Tuple

import numpy as np

from dss_tpu.models import rid as ridm
from dss_tpu.models import scd as scdm


class RIDStore(abc.ABC):
    """Storage for RID ISAs + subscriptions (pkg/rid/repos)."""

    @abc.abstractmethod
    def transaction(self) -> contextlib.AbstractContextManager:
        ...

    # ISAs
    @abc.abstractmethod
    def get_isa(self, id: str) -> Optional[ridm.IdentificationServiceArea]:
        ...

    @abc.abstractmethod
    def insert_isa(
        self, isa: ridm.IdentificationServiceArea
    ) -> Optional[ridm.IdentificationServiceArea]:
        """Insert (version empty) or fenced update (version set); returns
        None when the fencing predicate matches no row (stale version)."""

    @abc.abstractmethod
    def delete_isa(
        self, isa: ridm.IdentificationServiceArea
    ) -> Optional[ridm.IdentificationServiceArea]:
        """Fenced delete; None when no row matches id/owner/version."""

    @abc.abstractmethod
    def search_isas(
        self,
        cells: np.ndarray,
        earliest: datetime,
        latest: Optional[datetime],
    ) -> List[ridm.IdentificationServiceArea]:
        """ISAs intersecting cells with ends_at >= earliest and
        (starts_at <= latest or latest is None)."""

    # Subscriptions
    @abc.abstractmethod
    def get_subscription(self, id: str) -> Optional[ridm.Subscription]:
        ...

    @abc.abstractmethod
    def insert_subscription(
        self, sub: ridm.Subscription
    ) -> Optional[ridm.Subscription]:
        ...

    @abc.abstractmethod
    def delete_subscription(
        self, sub: ridm.Subscription
    ) -> Optional[ridm.Subscription]:
        ...

    @abc.abstractmethod
    def search_subscriptions(self, cells: np.ndarray) -> List[ridm.Subscription]:
        """Live (non-expired) subscriptions intersecting cells."""

    @abc.abstractmethod
    def search_subscriptions_by_owner(
        self, cells: np.ndarray, owner: str
    ) -> List[ridm.Subscription]:
        ...

    @abc.abstractmethod
    def max_subscription_count_in_cells_by_owner(
        self, cells: np.ndarray, owner: str
    ) -> int:
        """DSS0030: max per-cell count of the owner's live subscriptions."""

    @abc.abstractmethod
    def update_notification_idxs_in_cells(
        self, cells: np.ndarray, *, entity=None, removed: bool = False
    ) -> List[ridm.Subscription]:
        """Bump notification_index of all live subscriptions intersecting
        cells; return them post-bump.  `entity`/`removed` describe the
        triggering ISA for the push pipeline's fan-out (push/) — the
        bump + returned list are unchanged whether or not they are
        given."""


class SCDStore(abc.ABC):
    """Storage for SCD operations + subscriptions (pkg/scd/store)."""

    @abc.abstractmethod
    def transaction(self) -> contextlib.AbstractContextManager:
        ...

    # Operations
    @abc.abstractmethod
    def get_operation(self, id: str) -> Optional[scdm.Operation]:
        """By id, only while ends_at >= now (expired ops are invisible,
        operations.go:103-112)."""

    @abc.abstractmethod
    def upsert_operation(
        self, op: scdm.Operation, key: List[str], *, key_checked: bool = False
    ) -> Tuple[scdm.Operation, List[scdm.Subscription]]:
        """Fenced upsert with the OVN key check for Accepted/Activated
        states; returns (op, subscriptions-to-notify, post-bump).
        key_checked=True skips the OVN conflict search — only valid
        when validate_operation_upsert already ran inside the same
        transaction (the pinned txn timestamp keeps answers equal)."""

    @abc.abstractmethod
    def validate_operation_upsert(self, op: scdm.Operation, key: List[str]) -> None:
        """Read-only run of upsert_operation's preconditions (version
        fencing, ownership, time range, OVN key check).  Must be called
        inside the same transaction as the upsert so the answers agree;
        lets the service reject conflicts before journaling anything."""

    @abc.abstractmethod
    def delete_operation(
        self, id: str, owner: str
    ) -> Tuple[scdm.Operation, List[scdm.Subscription]]:
        ...

    @abc.abstractmethod
    def search_operations(
        self,
        cells: np.ndarray,
        alt_lo: Optional[float],
        alt_hi: Optional[float],
        earliest: Optional[datetime],
        latest: Optional[datetime],
    ) -> List[scdm.Operation]:
        ...

    # Subscriptions
    @abc.abstractmethod
    def get_subscription(self, id: str, owner: str) -> scdm.Subscription:
        ...

    @abc.abstractmethod
    def upsert_subscription(
        self, sub: scdm.Subscription
    ) -> Tuple[scdm.Subscription, List[scdm.Operation]]:
        ...

    @abc.abstractmethod
    def delete_subscription(
        self, id: str, owner: str, version: int
    ) -> scdm.Subscription:
        ...

    @abc.abstractmethod
    def search_subscriptions(
        self, cells: np.ndarray, owner: str
    ) -> List[scdm.Subscription]:
        ...

    # Constraints (beyond the reference: constraints_handler.go:12-30
    # stubs these; here they are a first-class fifth entity class)
    @abc.abstractmethod
    def get_constraint(self, id: str) -> scdm.Constraint:
        """By id, only while ends_at >= now (same visibility rule as
        operations)."""

    @abc.abstractmethod
    def upsert_constraint(
        self, cst: scdm.Constraint
    ) -> Tuple[scdm.Constraint, List[scdm.Subscription]]:
        """Fenced upsert (int32 version; 0 = insert).  Returns
        (constraint, notify_for_constraints subscriptions whose 4D
        volumes intersect the write, post-bump).  No OVN key check —
        constraints deconflict operations, not each other."""

    @abc.abstractmethod
    def delete_constraint(
        self, id: str, owner: str
    ) -> Tuple[scdm.Constraint, List[scdm.Subscription]]:
        ...

    @abc.abstractmethod
    def search_constraints(
        self,
        cells: np.ndarray,
        alt_lo: Optional[float],
        alt_hi: Optional[float],
        earliest: Optional[datetime],
        latest: Optional[datetime],
    ) -> List[scdm.Constraint]:
        ...
