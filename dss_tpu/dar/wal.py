"""Append-only write-ahead log: the durable source of truth.

Plays the role CockroachDB plays in the reference (the DAR snapshot is
a cache rebuilt from it; see SURVEY.md §5 checkpoint/resume).  Records
are JSON lines {"seq": n, "t": type, ...}; replay applies them in order
to rebuild store state.  fsync per append is configurable (off by
default: group-commit style durability is the deployment's call, like
the reference's reliance on CRDB commit semantics).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, Optional

from dss_tpu.chaos import fault_point

# Log format version.  A head record {"t": "__format__", "version": N}
# gates boot: replaying a log written by an incompatible future format
# must refuse loudly instead of rebuilding garbage state — the
# reference's schema gate (MustSupportSchema,
# /root/reference/cmds/grpc-backend/main.go:75-86,
# pkg/rid/cockroach/store.go:165-187).  Logs predating versioning
# (no head record) read as version 0, which is compatible.
FORMAT_VERSION = 1
FORMAT_RECORD_TYPE = "__format__"


class LogFormatError(RuntimeError):
    """The log was written by an unsupported (newer) format."""


class LogCorruptError(RuntimeError):
    """The log has an undecodable region FOLLOWED by valid records —
    mid-log corruption (bit rot, partial page write), not a crash-torn
    tail.  Truncating here would silently delete fsync-acked records,
    so boot refuses instead; the file is left byte-for-byte intact for
    repair/forensics (the quarantine).  Operators repair or move the
    file aside explicitly to proceed."""


def format_record() -> dict:
    return {"t": FORMAT_RECORD_TYPE, "version": FORMAT_VERSION}


def check_format_record(rec: Optional[dict], path: str) -> None:
    """Raise LogFormatError if the head record declares an unsupported
    version.  rec=None (legacy headerless log) is accepted."""
    if rec is None or rec.get("t") != FORMAT_RECORD_TYPE:
        return
    v = rec.get("version", 0)
    if not isinstance(v, int) or v > FORMAT_VERSION:
        raise LogFormatError(
            f"log {path} has format version {v}, but this binary "
            f"supports <= {FORMAT_VERSION}; refusing to start "
            "(upgrade the binary or restore a compatible log)"
        )


class WriteAheadLog:
    def __init__(self, path: Optional[str], fsync: bool = False):
        """path=None -> disabled (in-memory deployments / tests)."""
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None
        # True when boot recovery truncated a torn tail: with fsync
        # off, acked records may have been lost with the tear, so the
        # log's history is no longer guaranteed to be a superset of
        # what readers saw.  The region log rotates its persisted
        # epoch on this signal (and ONLY this signal or promotion) so
        # clean restarts no longer fence every writer.
        self.recovered_truncation = False
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            if os.path.exists(path) and os.path.getsize(path) > 0:
                # one recovery pass: format gate + seq recovery + the
                # valid-prefix length.  A crash can leave a torn final
                # line; appending after it would MERGE the next record
                # into one garbage line that a later replay drops
                # (silent loss of that write and everything after it),
                # so truncate to the last complete record first.
                # Truncation is ONLY legal when the invalid region
                # extends to EOF (a true crash tear): valid records
                # after an undecodable line mean mid-log corruption,
                # and deleting them would be silent loss of
                # fsync-acked writes — refuse to start instead.
                valid, self._seq = self._recover(path)
                if valid < os.path.getsize(path):
                    if self._valid_records_after(path, valid):
                        raise LogCorruptError(
                            f"log {path} is corrupt at byte {valid}: "
                            "valid records exist after an undecodable "
                            "region (mid-log corruption, not a crash "
                            "tear).  Refusing to truncate acked "
                            "records; repair the file or move it "
                            "aside to proceed."
                        )
                    with open(path, "r+b") as fh:
                        fh.truncate(valid)
                    self.recovered_truncation = True
            # re-stat AFTER truncation: a fully-torn header line must
            # count as a fresh log and get a fresh format header
            fresh = os.path.getsize(path) == 0 if os.path.exists(
                path
            ) else True
            self._fh = open(path, "a", encoding="utf-8")
            if fresh:
                # header carries no seq: user records stay 1-based
                self._fh.write(
                    json.dumps(format_record(), separators=(",", ":"))
                    + "\n"
                )
                self._fh.flush()

    @staticmethod
    def _recover(path: str) -> tuple:
        """-> (valid prefix bytes, max seq) in ONE pass, mirroring
        replay()'s tolerance exactly (blank lines pass; the first
        undecodable or newline-less line ends the prefix).  Applies
        the head format gate — an unsupported log version raises
        LogFormatError here, refusing boot."""
        valid = 0
        seq = 0
        first = True
        with open(path, "rb") as fh:
            while True:
                line = fh.readline()
                if not line:
                    break
                if not line.endswith(b"\n"):
                    break  # torn tail (no newline): not complete
                stripped = line.strip()
                if stripped:
                    try:
                        rec = json.loads(stripped)
                    except ValueError:
                        # JSONDecodeError, or UnicodeDecodeError from
                        # json's encoding sniff on rotted bytes (e.g.
                        # NUL runs look like UTF-32) — both ValueError
                        break
                    if not isinstance(rec, dict):
                        break  # rot that decodes as a JSON scalar
                    if first:
                        first = False
                        check_format_record(rec, path)
                    seq = max(seq, rec.get("seq", 0))
                valid = fh.tell()
        return valid, seq

    @staticmethod
    def _valid_records_after(path: str, offset: int) -> bool:
        """True when any complete, decodable JSON record line exists
        AFTER the undecodable line at `offset` — the mid-log-corruption
        discriminator.  A torn tail (the common crash shape) has
        nothing decodable after it; bit rot in the middle does."""
        with open(path, "rb") as fh:
            fh.seek(offset)
            bad = fh.readline()
            if not bad.endswith(b"\n"):
                return False  # the bad region runs to EOF: a tear
            while True:
                line = fh.readline()
                if not line:
                    return False
                if not line.endswith(b"\n"):
                    return False  # only a torn tail remains
                stripped = line.strip()
                if not stripped:
                    continue
                try:
                    rec = json.loads(stripped)
                except ValueError:  # undecodable bytes or bad JSON
                    continue  # more damage; keep scanning
                if isinstance(rec, dict):
                    return True

    @property
    def seq(self) -> int:
        """Last assigned sequence number (leader-side freshness stamp)."""
        return self._seq

    def append(self, record: dict) -> int:
        with self._lock:
            # chaos seam BEFORE the seq assignment/write: an injected
            # append error leaves no half-recorded state, and a delay
            # models a slow disk stalling the writer
            fault_point("wal.append")
            self._seq += 1
            record = dict(record, seq=self._seq)
            if self._fh is not None:
                self._fh.write(json.dumps(record, separators=(",", ":")) + "\n")
                self._fh.flush()
                if self.fsync:
                    fault_point("wal.fsync")
                    os.fsync(self._fh.fileno())
            return self._seq

    def sync(self) -> None:
        """fsync the log regardless of the per-append fsync setting —
        for rare, must-survive records (epoch rotations) on deployments
        that run with fsync off for throughput."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                fault_point("wal.fsync")
                os.fsync(self._fh.fileno())

    def replay(self) -> Iterator[dict]:
        """Yield records in order; tolerates a torn final line.  Raises
        LogFormatError if the head record declares an unsupported
        format (the boot gate)."""
        if self.path is None or not os.path.exists(self.path):
            return
        first = True
        with open(self.path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # torn tail write (crash mid-append): stop replay here
                    return
                if not isinstance(rec, dict):
                    return  # same: not a complete record
                if first:
                    first = False
                    check_format_record(rec, self.path)
                if rec.get("t") == FORMAT_RECORD_TYPE:
                    continue  # gate metadata, not store state
                yield rec

    def adopt(self, tmp_path: str, seq: int) -> None:
        """Swap a fully-written, fsynced replacement log into place:
        rename over the old log and reopen for append.  The caller
        guarantees no append races the swap (e.g. by staging the swap
        on the thread that owns all appends)."""
        if self.path is None:
            return
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            os.replace(tmp_path, self.path)
            self._seq = seq
            self._fh = open(self.path, "a", encoding="utf-8")

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
