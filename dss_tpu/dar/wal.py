"""Append-only write-ahead log: the durable source of truth.

Plays the role CockroachDB plays in the reference (the DAR snapshot is
a cache rebuilt from it; see SURVEY.md §5 checkpoint/resume).  Records
are JSON lines {"seq": n, "t": type, ...}; replay applies them in order
to rebuild store state.  fsync per append is configurable (off by
default: group-commit style durability is the deployment's call, like
the reference's reliance on CRDB commit semantics).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, Optional


class WriteAheadLog:
    def __init__(self, path: Optional[str], fsync: bool = False):
        """path=None -> disabled (in-memory deployments / tests)."""
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            # recover the sequence number from an existing log
            if os.path.exists(path):
                for rec in self.replay():
                    self._seq = max(self._seq, rec.get("seq", 0))
            self._fh = open(path, "a", encoding="utf-8")

    @property
    def seq(self) -> int:
        """Last assigned sequence number (leader-side freshness stamp)."""
        return self._seq

    def append(self, record: dict) -> int:
        with self._lock:
            self._seq += 1
            record = dict(record, seq=self._seq)
            if self._fh is not None:
                self._fh.write(json.dumps(record, separators=(",", ":")) + "\n")
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
            return self._seq

    def replay(self) -> Iterator[dict]:
        """Yield records in order; tolerates a torn final line."""
        if self.path is None or not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    # torn tail write (crash mid-append): stop replay here
                    return

    def adopt(self, tmp_path: str, seq: int) -> None:
        """Swap a fully-written, fsynced replacement log into place:
        rename over the old log and reopen for append.  The caller
        guarantees no append races the swap (e.g. by staging the swap
        on the thread that owns all appends)."""
        if self.path is None:
            return
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            os.replace(tmp_path, self.path)
            self._seq = seq
            self._fh = open(self.path, "a", encoding="utf-8")

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
