"""Host-only read budget: lets the event loop run reads inline safely.

The inline-reads optimization (api/app.py `_call_read`) executes a read
handler directly on the event loop — a win on single-core hosts where
the two executor handoffs are pure overhead — but ONLY host-bounded
work may run there: a device dispatch (tunneled round trip ~100 ms) or
a fresh XLA compile (tens of seconds) on the loop would starve
/healthy and every other request.

The loop-side caller sets the thread-local host_only flag; the store
layers raise NeedsDevice instead of entering any path that would
dispatch to the device or block on another thread's batch.  The caller
catches NeedsDevice and re-runs the (pure) read on the executor.
"""

from __future__ import annotations

import threading

_tls = threading.local()


class NeedsDevice(Exception):
    """Read would leave the host-bounded budget; re-run off the loop."""


def set_host_only(flag: bool) -> None:
    _tls.host_only = flag


def is_host_only() -> bool:
    return bool(getattr(_tls, "host_only", False))
