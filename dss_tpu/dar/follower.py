"""WalFollower: tail a leader's write-ahead log into a read replica.

The multi-worker serving architecture (SURVEY §1 L1 scale-out; the
role goroutine-per-RPC + CRDB ranges play in the reference,
cmds/grpc-backend/main.go:201-214): one leader process owns all
mutations + the WAL; N read-worker processes each hold a full DSSStore
replica rebuilt by replaying the WAL and kept fresh by tailing it.
Readers get lock-free local serving; staleness is bounded by the poll
interval (+ a read-your-writes wait on proxied mutations, see
cmds/server.py worker mode).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from dss_tpu.parallel.replica import _WalTail

log = logging.getLogger("dss.follower")


class WalFollower:
    """Applies a WAL file's records into a DSSStore as they appear."""

    def __init__(self, store, wal_path: str, interval_s: float = 0.02):
        self._store = store
        self._tail = _WalTail(wal_path)
        self._interval = interval_s
        self._applied_seq = 0
        self._apply_errors = 0
        self._stop = threading.Event()
        self._seq_cond = threading.Condition()
        # serializes tail reads: the background loop and wait_for's
        # active catchup share one _WalTail (stateful file offset)
        self._poll_mutex = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def applied_seq(self) -> int:
        return self._applied_seq

    def poll_once(self) -> int:
        """Apply any new records; -> count applied.  A single bad
        record is skipped and counted — it must not wedge the tail."""
        recs = self._tail.poll()
        if not recs:
            return 0
        store = self._store
        with store._lock:
            store._replaying = True
            try:
                for rec in recs:
                    try:
                        store.apply_log_record(rec)
                    except Exception:  # noqa: BLE001 — isolate bad records
                        self._apply_errors += 1
                        log.exception(
                            "follower failed to apply %r; skipped",
                            rec.get("t"),
                        )
            finally:
                store._replaying = False
        with self._seq_cond:
            self._applied_seq = max(
                self._applied_seq, max(r.get("seq", 0) for r in recs)
            )
            self._seq_cond.notify_all()
        return len(recs)

    def wait_for(self, seq: int, timeout_s: float = 1.0) -> bool:
        """Block until the replica has applied WAL seq >= seq (the
        read-your-writes courtesy after a proxied mutation, and the
        shm ring's record-assembly bound).  False on timeout — the
        caller proceeds with bounded staleness.

        Catchup is ACTIVE: a behind caller pulls the tail itself
        instead of sleeping until the next background tick, so the
        wait is bounded by a page-cache file read (the target records
        are already appended — the leader's seq only moves after the
        append), not by the poll interval.  Under a miss burst the
        mutex collapses concurrent pullers into one read; the rest
        wake on the same seq condition."""
        if self._applied_seq >= seq:
            return True
        deadline = time.monotonic() + timeout_s
        while True:
            if self._poll_mutex.acquire(timeout=0.005):
                try:
                    if self._applied_seq < seq:
                        self.poll_once()
                except Exception:  # noqa: BLE001 — keep serving
                    log.exception("active catchup poll failed")
                finally:
                    self._poll_mutex.release()
            if self._applied_seq >= seq:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return self._applied_seq >= seq
            with self._seq_cond:
                self._seq_cond.wait_for(
                    lambda: self._applied_seq >= seq,
                    min(remaining, 0.02),
                )

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self._interval):
                try:
                    with self._poll_mutex:
                        self.poll_once()
                except Exception:  # noqa: BLE001 — keep the tailer alive
                    log.exception("follower poll failed")

        # initial full replay happens on the first poll (offset 0)
        self.poll_once()
        self._thread = threading.Thread(
            target=loop, name="wal-follower", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def stats(self) -> dict:
        return {
            "follower_applied_seq": self._applied_seq,
            "follower_apply_errors": self._apply_errors,
        }
