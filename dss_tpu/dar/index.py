"""Spatial index strategies behind the store implementations.

MemorySpatialIndex — pure-python linear scan (the reference's in-memory
test-fake analog, pkg/rid/application/isa_test.go:29-77).

TpuSpatialIndex — the DarTable HBM index (dss_tpu.dar.snapshot); cell
ids are compressed to int32 DAR keys on the way in.

Both expose identical query semantics (the SQL COALESCE rules); the
store contract tests run every scenario against both.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from dss_tpu.dar import oracle
from dss_tpu.dar import tiers as tiersmod
from dss_tpu.dar.coalesce import QueryCoalescer
from dss_tpu.dar.coalesce import env_knobs as coalesce_env_knobs
from dss_tpu.dar.oracle import Record
from dss_tpu.dar.snapshot import DarTable
from dss_tpu.geo import s2cell


def _to_keys(cells_u64: np.ndarray) -> np.ndarray:
    return s2cell.cell_to_dar_key(np.asarray(cells_u64, dtype=np.uint64))


class MemorySpatialIndex:
    def __init__(self):
        self._recs: Dict[str, Record] = {}
        # same per-cell write clock as the DarTable backend, so the
        # version-fenced read cache (dar/readcache.py) is exact on
        # both storage strategies
        self.cell_clock = tiersmod.CellClock()

    def put(self, id, cells_u64, alt_lo, alt_hi, t_start, t_end, owner_id):
        keys = np.unique(_to_keys(cells_u64))
        old = self._recs.get(id)
        self._recs[id] = Record(
            entity_id=id,
            keys=keys,
            alt_lo=-np.inf if alt_lo is None else float(alt_lo),
            alt_hi=np.inf if alt_hi is None else float(alt_hi),
            t_start=int(t_start),
            t_end=int(t_end),
            owner_id=int(owner_id),
        )
        # bump after the mutation (fail-closed for lock-free readers);
        # old + new coverings both change their cells' answers
        self.cell_clock.bump(None if old is None else old.keys, keys)

    def remove(self, id):
        old = self._recs.pop(id, None)
        if old is not None:
            self.cell_clock.bump(old.keys)

    def clock_fence(self, cells_u64) -> "tuple[int, int, int, int]":
        """(incarnation, max stamp, generation, floor) over the
        covering — the read cache's O(|cells|) validity check."""
        return self.cell_clock.fence(_to_keys(cells_u64))

    def adopt_cell_clock(self, clock: tiersmod.CellClock) -> None:
        """Carry a predecessor index's clock across a state reset
        (region resync): the caller bump_all()s it, which floors every
        older fence — O(1), no stamp-array reallocation inside the
        resync swap window lock-free readers can observe."""
        self.cell_clock = clock

    def query_ids(
        self,
        cells_u64,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now,
        owner_id=None,
        allow_stale=False,  # no replica tier here; same-freshness reads
    ) -> List[str]:
        keys = _to_keys(cells_u64)
        recs = {i: r for i, r in enumerate(self._recs.values())}
        slots = oracle.search(
            recs, keys, alt_lo, alt_hi, t_start, t_end, now, owner_id
        )
        return [recs[s].entity_id for s in slots]

    def max_owner_count(self, cells_u64, owner_id, *, now) -> int:
        keys = _to_keys(cells_u64)
        recs = {i: r for i, r in enumerate(self._recs.values())}
        return oracle.max_count_per_cell(recs, keys, owner_id, now)

    def stats(self) -> dict:
        return {
            "live_records": len(self._recs),
            "write_generation": self.cell_clock.generation,
            "cell_clock_high_water": self.cell_clock.high_water,
        }


class TpuSpatialIndex:
    def __init__(self, **table_kwargs):
        self._table = DarTable(**table_kwargs)
        # concurrent readers (one thread per in-flight request) are
        # micro-batched into single fused kernel launches; serving
        # knobs come from DSS_CO_* env vars (docs/SERVING.md) and can
        # be adjusted at runtime via DSSStore.configure_serving
        self._coalescer = QueryCoalescer(
            self._table, **coalesce_env_knobs()
        )

    def put(self, id, cells_u64, alt_lo, alt_hi, t_start, t_end, owner_id):
        self._table.upsert(
            id, _to_keys(cells_u64), alt_lo, alt_hi, int(t_start), int(t_end), owner_id
        )

    def remove(self, id):
        self._table.remove(id)

    def query_ids(
        self,
        cells_u64,
        alt_lo=None,
        alt_hi=None,
        t_start=None,
        t_end=None,
        *,
        now,
        owner_id=None,
        allow_stale=False,
    ) -> List[str]:
        return self._coalescer.query(
            _to_keys(cells_u64),
            alt_lo,
            alt_hi,
            None if t_start is None else int(t_start),
            None if t_end is None else int(t_end),
            now=int(now),
            owner_id=owner_id,
            allow_stale=allow_stale,
        )

    def max_owner_count(self, cells_u64, owner_id, *, now) -> int:
        return self._table.max_owner_count(
            _to_keys(cells_u64), owner_id, now=int(now)
        )

    @property
    def cell_clock(self) -> tiersmod.CellClock:
        return self._table.cell_clock

    def clock_fence(self, cells_u64) -> "tuple[int, int, int, int]":
        """(incarnation, max stamp, generation, floor) over the
        covering — the read cache's O(|cells|) validity check."""
        return self._table.cell_clock.fence(_to_keys(cells_u64))

    def adopt_cell_clock(self, clock: tiersmod.CellClock) -> None:
        """See MemorySpatialIndex.adopt_cell_clock."""
        self._table.cell_clock = clock

    def stats(self) -> dict:
        out = self._table.stats()
        # serving-pipeline gauges (queue depth, adaptive batch size,
        # pack/device/collect stage totals, shed count) ride along and
        # land in /metrics as dss_dar_<class>_co_* via DSSStore.stats()
        out.update(self._coalescer.stats())
        return out

    @property
    def table(self) -> DarTable:
        return self._table

    @property
    def coalescer(self) -> QueryCoalescer:
        return self._coalescer

    def close(self):
        self._coalescer.close()
        self._table.close()
