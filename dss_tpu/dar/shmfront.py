"""Worker-side stores of the shared-memory serving front.

A read worker used to answer searches by re-scanning its own WAL-tail
replica: bounded-stale, uncached, and paying the full index scan per
poll.  These wrappers replace that hot path with the PR 7 read-cache
discipline replicated per worker:

  1. worker-local version-fenced ReadCache (dar/readcache.py — the
     EXACT same class), fenced on the owner's broadcast segment
     (shmring.WorkerFenceView) instead of an in-process CellClock.
     Fence-read-before-populate: the fence is read BEFORE the request
     is enqueued, so a write landing during the ring round trip can
     only make the entry look too old — never fresher than its data.
     Repeat polls are answered locally in microseconds with NO TTL and
     never across a stale fence.
  2. miss -> one shared-memory ring round trip to the device owner
     (zero marshal: raw covering run in, (id, t_end) pairs out).  The
     response's WAL sequence bounds a replica-catchup wait before
     record assembly, so the records the worker serializes are exactly
     the docs the leader would have served (read-your-writes across
     the front included).
  3. ring full / owner dead / injected `shm.ring.enqueue` fault ->
     ShmFallback, which the worker's proxy middleware (api/app.py)
     turns into the pre-existing loopback-HTTP proxy to the leader —
     never a block, never a 5xx.

Record assembly happens HERE, from the worker's replica dicts, in the
exact per-class order the leader-side store methods use — so a
worker-served response is bit-identical to a leader-served one at the
same state (tests/test_shmring.py pins this across folds, compactions
and tombstones).

Subscription classes (rid_sub / scd_sub) deliberately skip the
worker-local cache: their records carry notification indexes that
writes bump WITHOUT touching the cell clock (by design — see
readcache.py), so only the ring path's wal-seq catchup keeps a
worker-served sub response as fresh as the leader's.  SCD dependent
operations resolve through the worker's own cached op path, one id
list per sub, exactly as the leader's nested `_search_ops` does.
"""

from __future__ import annotations

import copy
import time
from typing import List, Optional

import numpy as np

from dss_tpu import chaos, errors
from dss_tpu.clock import to_nanos
from dss_tpu.dar import budget as _budget
from dss_tpu.dar import readcache as rcache
from dss_tpu.geo import s2cell
from dss_tpu.geo.covering import canonical_cells
from dss_tpu.obs import stages as _stages
from dss_tpu.obs import trace as _trace
from dss_tpu.parallel import shmring
from dss_tpu.plan import shmroute

__all__ = [
    "ShmFallback",
    "ShmSearchFront",
    "ShmRIDStore",
    "ShmSCDStore",
]


class ShmFallback(Exception):
    """Serve this search over the loopback proxy instead (ring full,
    owner unreachable, oversized payload, or an injected enqueue
    fault).  The worker proxy middleware catches it; it must never
    surface as a 5xx."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class ShmSearchFront:
    """Shared machinery of the worker-side wrappers: worker-local
    fenced cache, the ring client, the route decision, and the
    replica-catchup wait."""

    def __init__(self, region: shmring.ShmRegion,
                 client: shmring.ShmWorkerClient, follower, clock, *,
                 cache: Optional[rcache.ReadCache] = None,
                 costs: Optional[shmroute.WorkerCostModel] = None,
                 catchup_s: float = 1.0, owner_ttl_s: float = 5.0,
                 owner_threads: int = 2):
        self.region = region
        self.client = client
        self.follower = follower
        self.clock = clock
        self.fence_view = shmring.WorkerFenceView(region)
        self.cache = cache if cache is not None else rcache.ReadCache(
            **rcache.env_knobs()
        )
        self.costs = costs if costs is not None else (
            shmroute.WorkerCostModel()
        )
        self.catchup_s = float(catchup_s)
        self.owner_ttl_s = float(owner_ttl_s)
        self.owner_threads = int(owner_threads)

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        out = {f"shm_cache_{k}": v for k, v in self.cache.stats().items()}
        out.update(self.costs.stats())
        for k, v in self.client.stats().items():
            out[f"shm_{k}"] = v
        # whole-front dss_shm_* families straight from the shared
        # region: the owner serves no public port, so any worker's
        # scrape must present one coherent view of the entire front
        out.update(shmring.front_stats(self.region))
        return out

    def now_ns(self) -> int:
        return to_nanos(self.clock.now())

    # -- the serve path ------------------------------------------------------

    def _headroom_ms(self) -> Optional[float]:
        from dss_tpu.dar import deadline as _deadline

        dl = _deadline.get_route_deadline()
        if dl is None:
            return None
        return max(0.0, (dl - time.monotonic()) * 1000.0)

    def serve(self, cls: str, cells: np.ndarray, *, qkey: tuple,
              now_ns: int, alt_lo=None, alt_hi=None, t0_ns=None,
              t1_ns=None, owner: str = None, allow_stale: bool = False,
              cacheable: bool = True) -> List[str]:
        """-> the authoritative id list for this search (cache hit or
        ring round trip).  Raises ShmFallback for the proxy path and
        StatusError for admission/deadline verdicts — the same errors
        the leader-side path raises."""
        client = self.client
        dar_keys = s2cell.cell_to_dar_key(cells)
        fence = epoch = key = None
        th = _trace.current()
        use_cache = cacheable and self.cache.enabled
        if use_cache:
            if th is not None:
                t_cl_w, t_cl0 = time.time_ns(), time.perf_counter()
            # fence-read-BEFORE-enqueue: a write landing between this
            # read and the owner's query can only age the entry
            fence = self.fence_view.fence(cls, dar_keys)
            epoch = self.fence_view.epoch()
            key = (cls, owner, qkey, cells.tobytes())
            ids = self.cache.lookup(
                cls, key, fence, epoch, int(now_ns), allow_stale
            )
            if th is not None:
                _trace.add_span(
                    th, "cache.lookup", t_cl_w,
                    (time.perf_counter() - t_cl0) * 1000,
                    attrs={"cls": cls, "hit": ids is not None,
                           "proc": "worker"},
                )
            if ids is not None:
                client.stat_add(shmring.WS_CACHE_HITS)
                rcache.note_search(cls, epoch, fence[2], True)
                return ids

        # Optimistic inline reads (api/app._call_read): a worker cache
        # hit is host-bounded microseconds and safe on the event loop,
        # but everything past this point blocks — the ring round trip
        # and the replica-catchup wait.  Escalate to the executor the
        # same way a leader-side read escalates off a device dispatch.
        if _budget.is_host_only():
            raise _budget.NeedsDevice("shm ring round trip")
        if use_cache:
            client.stat_add(shmring.WS_CACHE_MISSES)

        headroom = self._headroom_ms()
        state = self.costs.state(
            ring_in_flight=client.in_flight(),
            ring_depth=self.region.depth,
            owner_threads=self.owner_threads,
            owner_alive=(
                self.region.owner_heartbeat_age_s() < self.owner_ttl_s
            ),
        )
        plan = shmroute.decide_worker(state, headroom)
        if plan.route != "shm":
            client.stat_add(shmring.WS_PLAN_PROXY)
            client.stat_add(shmring.WS_PROXY_FALLBACKS)
            raise ShmFallback(plan.reason)
        client.stat_add(shmring.WS_PLAN_SHM)

        t0 = time.perf_counter()
        t0_w = time.time_ns() if th is not None else 0
        try:
            resp = client.call(
                cls=cls, cells=cells, alt_lo=alt_lo, alt_hi=alt_hi,
                t0_ns=t0_ns, t1_ns=t1_ns, now_ns=now_ns, owner=owner,
                allow_stale=allow_stale,
                deadline_s=None if headroom is None
                else headroom / 1000.0,
                # the trace id + record bit ride the slot's reserved
                # words; the owner then returns its span slots
                # (stitched below).  The bit is set whenever THIS
                # request is recording — head-sampled OR armed for
                # DSS_TRACE_SLOW_MS tail capture, where the keep
                # decision is retroactive and the owner cannot know in
                # advance whether its timings will be needed
                trace_id=None if th is None else th.ctx.trace_id,
                trace_sampled=th is not None,
            )
        except (shmring.RingFull, shmring.RingOversize,
                shmring.RingTimeout, chaos.FaultError) as e:
            client.stat_add(shmring.WS_PROXY_FALLBACKS)
            raise ShmFallback(type(e).__name__)
        if resp.status == shmring.ST_OVERLOADED:
            # the owner's admission verdict rides the slot: same 429 +
            # Retry-After the leader would have returned in-process
            raise errors.OverloadedError(
                "serving queue at capacity (shm front)",
                retry_after_s=resp.retry_after_s or 1.0,
            )
        if resp.status == shmring.ST_DEADLINE:
            raise errors.deadline_exceeded(
                "request deadline expired in the shm ring"
            )
        if resp.status != shmring.ST_OK:
            client.stat_add(shmring.WS_PROXY_FALLBACKS)
            raise ShmFallback(f"status-{resp.status}")
        rtt_ms = (time.perf_counter() - t0) * 1000.0
        self.costs.observe_shm(rtt_ms)
        _stages.mark("shm_ring_ms", rtt_ms, span=False)
        if th is not None:
            # ONE stitched trace across the process boundary: the ring
            # round trip is a span, and the owner's span-slot
            # durations (obs/trace.OWNER_SLOTS, carried back in the
            # response's reserved words) become its children
            ring_sid = _trace.add_span(
                th, "shm.ring", t0_w, rtt_ms,
                attrs={"cls": cls, "worker": client.worker},
            )
            if resp.trace_ns and ring_sid is not None:
                off_ns = t0_w
                for idx, ns in enumerate(resp.trace_ns):
                    if ns <= 0:
                        continue
                    _trace.add_span(
                        th, _trace.OWNER_SLOTS[idx], off_ns,
                        ns / 1e6, parent=ring_sid,
                        attrs={"proc": "owner"},
                    )
        client.stat_add(shmring.WS_SERVED)
        if resp.wal_seq:
            # replica catchup: assemble records at least as new as the
            # answer (bounded — a timeout proceeds with the replica's
            # bounded staleness, same contract as the write proxy)
            t_cu_w, t_cu0 = time.time_ns(), time.perf_counter()
            self.follower.wait_for(int(resp.wal_seq), self.catchup_s)
            cu_ms = (time.perf_counter() - t_cu0) * 1000.0
            _stages.mark("catchup_ms", cu_ms, span=False)
            if th is not None:
                _trace.add_span(th, "replica.catchup", t_cu_w, cu_ms)
        if use_cache and not resp.mesh_served:
            # a bounded-stale mesh answer must not be stamped fresh
            # behind the fence (the fence cannot see the replica's
            # lag) — the leader's _cached_ids refuses it for its own
            # cache, and the flag carries that refusal across the ring
            try:
                chaos.fault_point("cache.populate", detail=f"shm:{cls}")
                self.cache.insert(
                    cls, key, fence, epoch, int(now_ns),
                    resp.ids, resp.t1s,
                )
            except chaos.FaultError:
                pass
        rcache.note_search(cls, epoch or self.fence_view.epoch(),
                           resp.gen, False)
        return resp.ids

    def assemble(self, ids: List[str], recs: dict) -> list:
        """Order-preserving record assembly from the worker replica's
        dict — the same shallow-copy discipline as the leader's
        search assembly.  A missing record (replica catchup timed out
        mid-burst) is skipped and counted, exactly like the leader's
        vanished-mid-assembly case."""
        out = []
        for i in ids:
            rec = recs.get(i)
            if rec is None:
                self.client.stat_add(shmring.WS_ASSEMBLY_MISSES)
                continue
            out.append(copy.copy(rec))
        return out


class _Wrapper:
    """Delegating base: everything not overridden reaches the inner
    replica store (stats, index introspection, freshness plumbing)."""

    def __init__(self, inner, front: ShmSearchFront):
        self._inner = inner
        self._front = front

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ShmRIDStore(_Wrapper):
    """RID search surface over the ring; every other method delegates
    to the WAL-tail replica store."""

    def search_isas(self, cells, earliest, latest, *, allow_stale=False):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        if earliest is None:
            raise errors.internal("must call with an earliest start time.")
        cells = canonical_cells(cells)
        e_ns = to_nanos(earliest)
        l_ns = None if latest is None else to_nanos(latest)
        # qkey mirrors the leader's _cached_ids discipline: `earliest`
        # is the query's `now` (clamped by the service) and only
        # drives the t_end >= now filter the cache re-applies at
        # lookup — keying it would make every repeat poll a unique,
        # never-hit line
        ids = self._front.serve(
            "isa", cells, qkey=(l_ns,), now_ns=e_ns,
            t0_ns=e_ns, t1_ns=l_ns, allow_stale=allow_stale,
            cacheable=True,
        )
        return self._front.assemble(ids, self._inner._isas)

    def search_subscriptions_by_owner(self, cells, owner):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("no location provided")
        cells = canonical_cells(cells)
        now = self._front.now_ns()
        ids = self._front.serve(
            "rid_sub", cells, qkey=(), now_ns=now, owner=owner,
            cacheable=False,  # notification indexes: see module doc
        )
        return self._front.assemble(ids, self._inner._subs)


class ShmSCDStore(_Wrapper):
    """SCD search surface over the ring; every other method delegates
    to the WAL-tail replica store."""

    @staticmethod
    def _op_qkey(alt_lo, alt_hi, t0_ns, t1_ns) -> tuple:
        # the leader-side _search_ops qkey, bit for bit, so worker
        # cache keys partition the same way the owner's do
        return (
            None if alt_lo is None else float(alt_lo),
            None if alt_hi is None else float(alt_hi),
            t0_ns, t1_ns,
        )

    def search_operations(self, cells, alt_lo, alt_hi, earliest,
                          latest, *, allow_stale=False):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        return self._search_ops_ids_to_recs(
            canonical_cells(cells), alt_lo, alt_hi,
            None if earliest is None else to_nanos(earliest),
            None if latest is None else to_nanos(latest),
            self._front.now_ns(), allow_stale,
        )

    def _search_ops_ids_to_recs(self, cells, alt_lo, alt_hi, t0_ns,
                                t1_ns, now_ns, allow_stale):
        ids = self._front.serve(
            "op", cells,
            qkey=self._op_qkey(alt_lo, alt_hi, t0_ns, t1_ns),
            now_ns=now_ns, alt_lo=alt_lo, alt_hi=alt_hi,
            t0_ns=t0_ns, t1_ns=t1_ns, allow_stale=allow_stale,
            cacheable=True,
        )
        return self._front.assemble(ids, self._inner._ops)

    def search_constraints(self, cells, alt_lo, alt_hi, earliest,
                           latest, *, allow_stale=False):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("missing cell IDs for query")
        cells = canonical_cells(cells)
        t0_ns = None if earliest is None else to_nanos(earliest)
        t1_ns = None if latest is None else to_nanos(latest)
        ids = self._front.serve(
            "constraint", cells,
            qkey=self._op_qkey(alt_lo, alt_hi, t0_ns, t1_ns),
            now_ns=self._front.now_ns(), alt_lo=alt_lo, alt_hi=alt_hi,
            t0_ns=t0_ns, t1_ns=t1_ns, allow_stale=allow_stale,
            cacheable=True,
        )
        return self._front.assemble(ids, self._inner._csts)

    def search_subscriptions(self, cells, owner):
        if len(np.asarray(cells).ravel()) == 0:
            raise errors.bad_request("no location provided")
        cells = canonical_cells(cells)
        now = self._front.now_ns()
        ids = self._front.serve(
            "scd_sub", cells, qkey=(), now_ns=now, owner=owner,
            cacheable=False,  # notification indexes: see module doc
        )
        subs = self._front.assemble(ids, self._inner._subs)
        for s in subs:
            s.dependent_operations = self._dependent_op_ids(s, now)
        return subs

    def _dependent_op_ids(self, sub, now_ns: int) -> List[str]:
        """The leader's `_dependent_ops`, routed through the worker's
        own cached op path: one id list per sub, each inner search a
        cache hit after the first resolution."""
        if len(np.asarray(sub.cells).ravel()) == 0:
            return []
        cells = canonical_cells(sub.cells)
        t0_ns = to_nanos(sub.start_time)
        t1_ns = to_nanos(sub.end_time)
        return self._front.serve(
            "op", cells,
            qkey=self._op_qkey(sub.altitude_lo, sub.altitude_hi,
                               t0_ns, t1_ns),
            now_ns=now_ns, alt_lo=sub.altitude_lo,
            alt_hi=sub.altitude_hi, t0_ns=t0_ns, t1_ns=t1_ns,
            cacheable=True,
        )
