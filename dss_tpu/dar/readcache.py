"""Version-fenced read-result cache: repeat polls in microseconds.

The north-star traffic model is millions of USS clients *polling* the
same metro-area coverings at ~100:1 read-to-write ratios; before this
module every poll ran the full pipeline (admission, coalescer, route
choice, kernel or host scan).  The cache sits in the store's search
paths, IN FRONT of the coalescer: a hit never enqueues, never takes a
deadline stamp, never counts against the Retry-After backlog, and
never touches a device.

Correct by construction, not by TTL.  Every entry is stamped with

    (region epoch, index incarnation, cell-clock max, generation)

read from the per-cell write clock (tiers.CellClock) BEFORE the fresh
query ran.  A hit is served only when the fence holds:

  - the region epoch is unchanged (promotion/restore rotates it), and
  - the index incarnation is unchanged (region resync / restore_state
    replaces the index wholesale), and
  - no cell in the entry's covering has a newer clock stamp — the
    clock counter is global per index, so any later write touching any
    of the covering's cells stamps strictly past the entry's max.

`allow_stale` lookups additionally tolerate a bounded generation lag
(DSS_CACHE_STALE_LAG writes): the same bounded-staleness contract the
mesh-replica path already grants those queries.  Strict lookups are
bit-identical to the fresh path by the fence argument above plus one
time rule: the only clock-dependence of a search is `t_end >= now`
(records only ever EXPIRE out of a fixed 4D window), so entries carry
each hit's t_end and a hit re-applies the filter at the query's `now`.

Invalidation is the existing write path: DarTable.upsert/remove and
MemorySpatialIndex.put/remove bump the cell clock — locally, on WAL
replay, on region-log tail application at mirrors, everywhere writes
already flow.  No invalidation bus, no TTL, no background sweeper.

Why no TTL: a TTL trades staleness for hit rate and still re-runs the
query on every expiry; the fence serves indefinitely while the area is
quiet (the common poll case) and invalidates exactly on the write that
changed the answer.

Structure: a sharded-lock LRU (DSS_CACHE_SHARDS shards, each an
OrderedDict under its own lock) bounded by DSS_CACHE_CAP entries
total, keyed by (entity class, owner scope, query window, canonical
covering bytes) — the covering is canonicalized once at query ingress
(geo.covering.canonical_cells), shared with the pack path, so two
syntactically different requests for the same area hit the same line.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np


class _Entry(NamedTuple):
    epoch: str
    inc: int  # CellClock incarnation
    stamp: int  # cell-clock max over the covering at stamp time
    gen: int  # index generation at stamp time (stale-lag basis)
    now0: int  # the `now` (ns) the fresh answer was computed at
    min_t1: int  # min t_end over hits (fast path: no filtering needed)
    ids: Tuple[str, ...]
    t1s: np.ndarray  # i64 per id: t_end ns (the one time-variant filter)
    nbytes: int


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an int")


def env_knobs() -> dict:
    """ReadCache constructor kwargs from DSS_CACHE_* env vars
    (docs/OPERATIONS.md): capacity (entries), lock shards, the
    allow_stale generation-lag tolerance, and the enable switch."""
    # same boolean semantics (and typo rejection) as every other
    # DSS_* boolean knob
    from dss_tpu.dar.coalesce import _env_bool

    raw = os.environ.get("DSS_CACHE_ENABLE")
    try:
        enabled = True if raw is None else _env_bool(raw)
    except ValueError:
        raise ValueError(
            f"DSS_CACHE_ENABLE={raw!r} is not a valid boolean"
        )
    return {
        "capacity": _env_int("DSS_CACHE_CAP", 8192),
        "shards": _env_int("DSS_CACHE_SHARDS", 8),
        "stale_lag": _env_int("DSS_CACHE_STALE_LAG", 0),
        "enabled": enabled,
    }


class ReadCache:
    """Sharded-lock LRU of version-fenced search results.  One
    instance per DSSStore, shared by all four entity classes (the
    class is part of the key; per-class hit/miss counters feed the
    coalescer stats path so dashboards see hits next to route mix)."""

    def __init__(self, *, capacity: int = 8192, shards: int = 8,
                 stale_lag: int = 0, enabled: bool = True):
        shards = max(1, int(shards))
        self._locks = [threading.Lock() for _ in range(shards)]
        self._maps: List[OrderedDict] = [
            OrderedDict() for _ in range(shards)
        ]
        self._bytes = [0] * shards
        self.capacity = max(1, int(capacity))
        self.stale_lag = max(0, int(stale_lag))
        self.enabled = bool(enabled)
        # counters: per-shard (guarded by the shard lock, summed by
        # stats()) so the hit path never contends on a global lock —
        # including the per-class [hits, misses, invalidations] rows
        # the coalescer stats view reads
        self._hits = [0] * shards
        self._misses = [0] * shards
        self._evictions = [0] * shards
        self._invalidations = [0] * shards
        self._stale_hits = [0] * shards
        self._cls: List[Dict[str, List[int]]] = [
            {} for _ in range(shards)
        ]

    # -- internals -----------------------------------------------------------

    def _shard(self, key) -> int:
        return hash(key) % len(self._maps)

    @staticmethod
    def _cls_count(cls_map: Dict[str, List[int]], cls: str,
                   slot: int) -> None:
        """Bump one per-class counter row (caller holds the shard
        lock that owns cls_map)."""
        row = cls_map.get(cls)
        if row is None:
            row = cls_map[cls] = [0, 0, 0]
        row[slot] += 1

    def _per_shard_cap(self) -> int:
        return max(1, self.capacity // len(self._maps))

    # -- the read path -------------------------------------------------------

    def lookup(
        self,
        cls: str,
        key,
        fence: Tuple[int, int, int, int],  # (inc, max stamp, gen, floor)
        epoch: str,
        now_ns: int,
        allow_stale: bool = False,
    ) -> Optional[List[str]]:
        """-> the cached id list (time-refiltered at now_ns) when the
        fence holds, else None.  Every outcome is counted."""
        if not self.enabled:
            return None
        s = self._shard(key)
        inc, stamp, gen, floor = fence
        with self._locks[s]:
            od = self._maps[s]
            cls_map = self._cls[s]
            e = od.get(key)
            if e is None:
                self._misses[s] += 1
                self._cls_count(cls_map, cls, 1)
                return None
            ok = e.epoch == epoch and e.inc == inc
            stale_served = False
            if ok and stamp > e.stamp:
                # a covering cell advanced: exact fence fails.  A
                # bounded-staleness query may still ride the entry when
                # the write lag stays inside the contract — but NEVER
                # across a wholesale invalidation (e.stamp < floor
                # means the entry predates a bump_all, whose "one
                # generation" stands for unbounded change).
                if (
                    allow_stale
                    and self.stale_lag > 0
                    and gen - e.gen <= self.stale_lag
                    and e.stamp >= floor
                ):
                    stale_served = True
                else:
                    ok = False
            if not ok:
                del od[key]
                self._bytes[s] -= e.nbytes
                self._invalidations[s] += 1
                self._misses[s] += 1
                self._cls_count(cls_map, cls, 1)
                self._cls_count(cls_map, cls, 2)
                return None
            if now_ns < e.now0:
                # the query's clock is BEHIND the entry's: records the
                # entry already dropped as expired cannot be
                # resurrected — fall through to the fresh path (keep
                # the entry for forward-clock pollers)
                self._misses[s] += 1
                self._cls_count(cls_map, cls, 1)
                return None
            od.move_to_end(key)
            self._hits[s] += 1
            if stale_served:
                self._stale_hits[s] += 1
            self._cls_count(cls_map, cls, 0)
            ids, t1s, min_t1 = e.ids, e.t1s, e.min_t1
        if now_ns <= min_t1:
            return list(ids)
        # re-apply the ONE time-variant filter (t_end >= now): as now
        # advances, hits can only expire out — exactly what the fresh
        # path would drop
        keep = t1s >= now_ns
        return [i for i, k in zip(ids, keep.tolist()) if k]

    def insert(
        self,
        cls: str,
        key,
        fence: Tuple[int, int, int, int],
        epoch: str,
        now_ns: int,
        ids: Sequence[str],
        t1s: Sequence[int],
    ) -> None:
        """Populate after a miss.  `fence` MUST have been read before
        the fresh query ran: a write landing between the stamp read
        and the query can then only make the entry look too old (next
        fence check discards it), never fresher than its data."""
        if not self.enabled:
            return
        t1arr = np.asarray(t1s, np.int64)
        nbytes = (
            int(t1arr.nbytes)
            + sum(len(i) for i in ids)
            + 64 * max(1, len(ids))
            + 256
        )
        inc, stamp, gen, _floor = fence
        e = _Entry(
            epoch=epoch, inc=inc, stamp=stamp, gen=gen,
            now0=int(now_ns),
            min_t1=int(t1arr.min()) if len(t1arr) else np.iinfo(np.int64).max,
            ids=tuple(ids), t1s=t1arr, nbytes=nbytes,
        )
        s = self._shard(key)
        cap = self._per_shard_cap()
        with self._locks[s]:
            od = self._maps[s]
            old = od.get(key)
            if (
                old is not None
                and old.now0 > e.now0
                and old.stamp >= e.stamp
                and old.inc == e.inc
                and old.epoch == e.epoch
            ):
                # a backwards-clock miss (e.g. a txn-pinned precheck
                # behind live pollers) must not displace the entry the
                # lookup path deliberately kept for forward pollers
                return
            if old is not None:
                del od[key]
                self._bytes[s] -= old.nbytes
            od[key] = e
            self._bytes[s] += nbytes
            while len(od) > cap:
                _, ev = od.popitem(last=False)
                self._bytes[s] -= ev.nbytes
                self._evictions[s] += 1

    # -- control -------------------------------------------------------------

    def invalidate_all(self) -> int:
        """Flush every entry (region resync, cache-disable runbook).
        -> entries dropped (counted as invalidations)."""
        dropped = 0
        for s, lock in enumerate(self._locks):
            with lock:
                n = len(self._maps[s])
                self._maps[s].clear()
                self._bytes[s] = 0
                self._invalidations[s] += n
                dropped += n
        return dropped

    def configure(self, *, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  stale_lag: Optional[int] = None) -> None:
        """Runtime knob surface (DSSStore.configure_serving(cache=)).
        Disabling flushes: a re-enable must start from an empty cache,
        not from entries whose fences were stamped before the gap."""
        if capacity is not None:
            self.capacity = max(1, int(capacity))
        if stale_lag is not None:
            self.stale_lag = max(0, int(stale_lag))
        if enabled is not None:
            enabled = bool(enabled)
            if self.enabled and not enabled:
                self.invalidate_all()
            self.enabled = enabled

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        return {
            "hits": sum(self._hits),
            "misses": sum(self._misses),
            "evictions": sum(self._evictions),
            "invalidations": sum(self._invalidations),
            "stale_hits": sum(self._stale_hits),
            "entries": sum(len(m) for m in self._maps),
            "bytes": sum(self._bytes),
            "capacity": self.capacity,
            "enabled": int(self.enabled),
        }

    def class_stats(self, cls: str) -> dict:
        """co_cache_* gauges for one entity class — wired into that
        class's QueryCoalescer stats (coalesce.set_cache_view) so hit
        rate renders next to the route mix in /metrics."""
        h = m = i = 0
        for s, lock in enumerate(self._locks):
            with lock:
                row = self._cls[s].get(cls)
                if row is not None:
                    h += row[0]
                    m += row[1]
                    i += row[2]
        return {
            "co_cache_hits": h,
            "co_cache_misses": m,
            "co_cache_invalidations": i,
        }


# -- per-request freshness plumbing (thread-local) ---------------------------
#
# The store's search path runs synchronously on one thread (an executor
# worker or, with inline reads, the event loop).  It records here what
# the response-layer needs for the X-DSS-Freshness header; api/app.py
# takes the note after the service call returns on the SAME thread.

_tls = threading.local()


def note_search(cls: str, epoch: str, generation: int, hit: bool) -> None:
    """First search of the request wins: an SCD subscription query
    runs dependent-operation sub-searches after the outer one, and the
    header should describe the OUTER answer."""
    if getattr(_tls, "note", None) is None:
        _tls.note = {
            "cls": cls, "epoch": epoch, "gen": int(generation),
            "hit": bool(hit),
        }


def take_note() -> Optional[dict]:
    n = getattr(_tls, "note", None)
    _tls.note = None
    return n


def note_mesh_served() -> None:
    """Set by the coalescer when a query was answered by the sharded
    mesh replica (bounded-stale).  The store must NOT populate the
    cache from it: the fence would stamp a possibly-lagging answer as
    fresh, and a later strict hit would violate the exactness
    contract."""
    _tls.mesh = True


def take_mesh_served() -> bool:
    m = getattr(_tls, "mesh", False)
    _tls.mesh = False
    return bool(m)


def note_last_search_meshed(meshed: bool) -> None:
    """Sticky per-thread record of whether the MOST RECENT search on
    this thread was mesh-served (bounded-stale).  take_mesh_served is
    consumed inside _cached_ids to gate the leader's own cache
    population; this flag survives one level up so the shm owner can
    tell the REQUESTING WORKER not to populate its cache either —
    otherwise a lagging mesh answer would be stamped fresh behind a
    fence that cannot detect it."""
    _tls.last_mesh = bool(meshed)


def take_last_search_meshed() -> bool:
    m = getattr(_tls, "last_mesh", False)
    _tls.last_mesh = False
    return bool(m)
