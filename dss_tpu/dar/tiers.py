"""Tiered snapshots: the LSM-style tier stack behind DarTable.

The single-snapshot DarTable paid O(table) per fold: every overlay
flush repacked ALL records and re-uploaded the whole postings table to
HBM (fold_ms_mean ~10 s at 1M intents — ~100 s extrapolated at 10M,
during which the overlay and every query's host-scan cost grow without
bound).  The reference gets compaction for free from CockroachDB's LSM
(implementation_details.md:3-8); this module is the equivalent, built
as a first-class subsystem:

  L0 (base)   — one large, rarely-rewritten snapshot.  Holds every
                record as of the last MAJOR compaction.
  L1 (delta)  — one small snapshot absorbing minor folds: all records
                written/updated since L0 was built.  Rebuilt from the
                writer-tracked delta set on every fold — O(overlay+L1),
                never O(table).
  overlay     — unchanged: records since the last fold, spliced O(Δ)
                per write (dar/snapshot.py).

Shadowing (newest tier wins) is enforced at WRITE time, not query
time: updating or removing an entity marks its slot dead in every tier
that still holds it live, so each visible entity is live in exactly
one tier (or the overlay) and the query path simply merges per-tier
hits after per-tier dead filtering.  Tombstones accumulate in the
per-tier dead sets and are garbage-collected by the next major
compaction, which rebuilds L0 from the authoritative record dict.

Major compactions (L1 + tombstones merged into a fresh L0) trigger on
the churn ratio: when |delta records| + |shadowed rows| exceeds
DSS_TIER_RATIO x |L0| the amortized O(table) rebuild is paid once,
exactly like an LSM size-ratio trigger.  Why not full LSM levels: a
DAR serves point/area lookups over a covering index where every extra
tier costs one more host range-lookup + (possibly) one more device
window pass per query — two tiers bound that cost while already making
folds O(delta); more levels would buy lower write amplification this
workload (bounded by the WAL, not the fold) does not need.

Knobs (env, read at DarTable construction; docs/OPERATIONS.md):

  DSS_TIER_RATIO   — churn ratio triggering a major compaction
                     (default 0.25; 0 disables tiering: every fold is
                     a full rebuild, the pre-tier behavior).
  DSS_TIER_MIN_L0  — below this many L0 records every fold is major
                     (default 0; small tables repack in microseconds,
                     so tier bookkeeping can be skipped).
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from dss_tpu.dar.oracle import Record
from dss_tpu.dar.pack import pack_records
from dss_tpu.ops.fastpath import FastTable

# CellClock incarnations are process-unique: a rebuilt/replaced index
# (region resync, restore_state) gets a fresh clock whose stamps start
# over, and the read cache must never compare stamps across clocks —
# the incarnation in the fence makes cross-clock comparison impossible
# (id() reuse after GC would not).
_INCARNATIONS = itertools.count(1)


class CellClock:
    """Per-cell monotonic write clock — the exact-invalidation currency
    of the version-fenced read cache (dar/readcache.py).

    One global counter per clock; every write bumps it once (the
    `generation`) and stamps that value onto each affected DAR key's
    slot.  Because the counter is shared, `max over a covering's
    cells` is a sufficient fence: any later write touching ANY of
    those cells stamps a value strictly greater than every earlier
    max — so a cached entry needs only the scalar max, not the
    per-cell vector.

    Stamps live in a FIXED hashed-slot int64 array (default 2^20
    slots, 8 MB), not a dict: a 10M-entity table touching millions of
    distinct cells must not grow clock bookkeeping without bound, and
    the bump under the write lock becomes one vectorized scatter
    instead of a Python per-key loop.  Two cells sharing a slot can
    only OVER-invalidate (a fence sees a too-new stamp and the cache
    re-runs the query) — collisions are a hit-rate tax, never a
    staleness bug.

    Stamps survive minor folds and major compactions by construction:
    the clock lives on the writer (DarTable / MemorySpatialIndex), not
    in the published snapshot state, so fold/compaction swaps never
    touch it.  Wholesale replacements (bulk_load) bump the `floor`
    instead of walking every record — every fence computed afterwards
    is at least the floor, which invalidates all earlier entries in
    O(1).

    Writers bump under their own write lock; `fence` is lock-free (a
    racing scatter shows each slot either the old or the new stamp —
    a newer value fails the fence, which is the safe direction)."""

    __slots__ = ("_clock", "_mask", "_gen", "_high", "_floor",
                 "incarnation", "_lock", "_mirror")

    SLOTS = 1 << 20  # per-class stamp array (8 MB); power of two

    def __init__(self, slots: Optional[int] = None):
        n = self.SLOTS if slots is None else int(slots)
        assert n & (n - 1) == 0, "slot count must be a power of two"
        # LAZY: the 8 MB stamp array materializes on the first bump.
        # Construction must stay ~free — index factories run inside
        # the region-resync swap, where every extra millisecond widens
        # the window lock-free readers can observe mid-rebuild (and a
        # store that never writes a class shouldn't pay the pages).
        self._clock: Optional[np.ndarray] = None
        self._mask = np.int64(n - 1)
        self._gen = 0
        self._high = 0  # highest stamp handed out to a cell slot
        self._floor = 0  # generation of the last wholesale bump_all
        self._lock = threading.Lock()
        self.incarnation = next(_INCARNATIONS)
        # optional broadcast hook (parallel/shmring.FenceMirror): the
        # shared-memory serving front mirrors every bump into the shm
        # fence segment so worker-local read caches fence on it.  One
        # None check per bump when no front is attached.
        self._mirror = None

    def _slots_of(self, keys) -> np.ndarray:
        return np.asarray(keys, np.int64).ravel() & self._mask

    def bump(self, *key_arrays) -> None:
        """One write: stamp every DAR key in the given arrays with a
        fresh generation.  An UPDATE must pass both the old and the new
        covering — a record that moved out of cell X changes X's
        answers just as much as moving in."""
        with self._lock:
            self._gen += 1
            g = self._gen
            self._high = g
            if self._clock is None:
                self._clock = np.zeros(int(self._mask) + 1, np.int64)
            for keys in key_arrays:
                if keys is None:
                    continue
                self._clock[self._slots_of(keys)] = g
            if self._mirror is not None:
                self._mirror.on_bump(key_arrays, g)

    def bump_all(self) -> None:
        """Wholesale invalidation (bulk_load / replayed snapshot):
        raise the floor so every fence computed afterwards exceeds any
        stamp handed out before — O(1), no per-record walk."""
        with self._lock:
            self._gen += 1
            self._floor = self._gen
            if self._mirror is not None:
                self._mirror.on_bump_all(self._gen)

    def attach_mirror(self, mirror) -> None:
        """Install the shared-memory fence broadcast hook and publish
        the clock's current fence metadata.  Under the bump lock so
        the initial sync and the first mirrored bump cannot race."""
        with self._lock:
            self._mirror = mirror
            if mirror is not None:
                mirror.sync(self)

    @property
    def floor(self) -> int:
        """Generation of the last wholesale invalidation."""
        return self._floor

    def fence(self, keys) -> "tuple[int, int, int, int]":
        """-> (incarnation, max stamp over keys, generation, floor).
        One vectorized gather+max per lookup; lock-free.  The floor is
        the generation of the last WHOLESALE invalidation: the cache's
        bounded-stale tolerance must refuse entries stamped before it
        (a bump_all advances the generation by one but represents
        unbounded change — counting it as one write of lag would let
        a stale hit serve the entire pre-replacement dataset)."""
        arr = self._clock  # one read: bump may swap it in concurrently
        m = self._floor
        if arr is not None:
            slots = self._slots_of(keys)
            if len(slots):
                m = max(m, int(arr[slots].max()))
        return (self.incarnation, m, self._gen, self._floor)

    @property
    def generation(self) -> int:
        """Total write operations (cell-stamping AND wholesale)."""
        return self._gen

    @property
    def high_water(self) -> int:
        """Highest stamp handed out to a cell slot — the generation of
        the last cell-stamping write.  Diverges from `generation` when
        wholesale invalidations (bump_all) have run since."""
        return self._high


class RangeLoad:
    """Per-key-range query-load EWMA — the measurement half of
    skew-aware shard placement (parallel/sharded.py weighted split).

    DAR keys bucket by prefix (`key >> shift`, default 12: ~4096
    adjacent level-13 cells per bucket, roughly a metro-scale S2
    region).  Every coalescer-served query stamps its covering's
    buckets with its measured candidate work (result count; PR 7 cache
    hits never reach a shard and therefore never stamp).  The
    accumulated load decays exponentially (`decay_factor`) at the
    rebalance-planning cadence — once per DSS_SHARD_MOVE_INTERVAL_S,
    applied by `plan_rebalance` — so the map tracks RECENT traffic: a
    hot spot that moved cities stops pinning shards to the old metro
    within a few planning intervals.

    Bucket count is bounded (`max_buckets`): when the dict overflows,
    the coldest half is dropped — losing cold-bucket precision only
    degrades the split toward equal-count, never correctness (placement
    is a performance mapping; answers never depend on it).

    Thread-safe: writers stamp under the lock from serving threads;
    `weights_for` / `bucket_loads` take a consistent snapshot."""

    __slots__ = ("shift", "decay_factor", "max_buckets", "_load",
                 "_queries", "_lock")

    def __init__(
        self,
        shift: Optional[int] = None,
        decay_factor: Optional[float] = None,
        max_buckets: int = 1 << 16,
    ):
        if shift is None:
            shift = int(os.environ.get("DSS_SHARD_LOAD_SHIFT", 12))
        if decay_factor is None:
            decay_factor = float(
                os.environ.get("DSS_SHARD_LOAD_DECAY", 0.5)
            )
        self.shift = int(shift)
        self.decay_factor = float(decay_factor)
        self.max_buckets = int(max_buckets)
        self._load: Dict[int, float] = {}
        self._queries = 0
        self._lock = threading.Lock()

    def record(self, keys, work: float = 1.0) -> None:
        """One served query: spread its measured work over the buckets
        its covering touches.  `work` is the candidate/result count
        (floored at 1 so pure-miss traffic still registers — an empty
        hot area still costs per-shard gather work)."""
        b = np.unique(np.asarray(keys, np.int64).ravel() >> self.shift)
        if not len(b):
            return
        w = max(float(work), 1.0) / len(b)
        with self._lock:
            self._queries += 1
            load = self._load
            for k in b.tolist():
                load[k] = load.get(k, 0.0) + w
            if len(load) > self.max_buckets:
                # drop the coldest half: bounded bookkeeping, and the
                # split degrades toward equal-count for cold ranges
                keep = sorted(
                    load.items(), key=lambda kv: kv[1], reverse=True
                )[: self.max_buckets // 2]
                self._load = dict(keep)

    def decay(self) -> None:
        """One fold boundary: age the EWMA.  Buckets decayed below
        noise are dropped so a vacated hot spot releases its shards."""
        with self._lock:
            f = self.decay_factor
            self._load = {
                k: v * f for k, v in self._load.items() if v * f > 1e-3
            }

    def total(self) -> float:
        with self._lock:
            return sum(self._load.values())

    @property
    def queries(self) -> int:
        return self._queries

    def bucket_loads(self) -> "Tuple[np.ndarray, np.ndarray]":
        """-> (sorted bucket ids i64, loads f64) — a consistent
        snapshot for split planning."""
        with self._lock:
            if not self._load:
                return _EMPTY_I64, np.zeros(0, np.float64)
            ks = np.asarray(sorted(self._load), np.int64)
            vs = np.asarray([self._load[int(k)] for k in ks], np.float64)
        return ks, vs

    def weights_for(self, post_key: np.ndarray) -> np.ndarray:
        """Per-posting load weight: w[i] = EWMA load of posting i's
        bucket, 0 for never-stamped buckets.  The splitter adds its
        own count baseline, so zero-load (cold start) degrades to the
        equal-count split exactly."""
        ks, vs = self.bucket_loads()
        pk = np.asarray(post_key, np.int64) >> self.shift
        if not len(ks):
            return np.zeros(len(pk), np.float64)
        pos = np.searchsorted(ks, pk)
        pos[pos == len(ks)] = 0
        w = vs[pos].copy()
        w[ks[pos] != pk] = 0.0
        return w

    def stats(self) -> dict:
        with self._lock:
            return {
                "shard_load_buckets": len(self._load),
                "shard_load_total": round(sum(self._load.values()), 2),
                "shard_load_queries": self._queries,
            }


class TierSnapshot(NamedTuple):
    """One immutable device snapshot (the former dar.snapshot._Snapshot,
    generalized: L0 and L1 are both instances of this)."""

    fast: Optional[FastTable]
    owner: Optional[np.ndarray]  # i32 per slot
    ids: List[str]  # slot -> entity_id
    slot_of: Dict[str, int]  # entity_id -> slot
    recs: Dict[str, Record]  # id -> Record at build time (immutable)


EMPTY_SNAPSHOT = TierSnapshot(None, None, [], {}, {})


# shadowed-slot bookkeeping: dead slots live in TWO sorted int64
# arrays per tier — a small `dead_recent` (grown by O(recent) insert
# per write) and a large, stable `dead_base`.  When recent crosses
# this threshold it folds into base (one O(base) union).  This bounds
# the per-write copy AND the per-query filter cost to O(threshold) no
# matter how much churn accumulates between major compactions — a
# single frozenset would degrade both to O(accumulated churn) at 10M
# scale (dead sets persist until a major compaction now, unlike the
# pre-tier design where every fold reset them).
DEAD_FOLD_THRESHOLD = 4096

_EMPTY_I64 = np.zeros(0, np.int64)


class Tier(NamedTuple):
    """One published tier: an immutable snapshot plus the slots
    superseded/removed since it was built (never mutated — writers
    publish a replacement Tier with grown dead arrays)."""

    snap: TierSnapshot
    dead_recent: np.ndarray  # i64 sorted, small (<= threshold-ish)
    dead_base: np.ndarray  # i64 sorted, stable between threshold folds

    @property
    def dead(self) -> frozenset:
        """All shadowed slots (diagnostic/test view — the hot paths
        use the sorted arrays directly)."""
        return frozenset(
            int(s) for s in np.concatenate([self.dead_recent, self.dead_base])
        )

    @property
    def dead_count(self) -> int:
        return len(self.dead_recent) + len(self.dead_base)


def make_tier(snap: TierSnapshot, dead_slots=()) -> Tier:
    """A fresh Tier whose dead set starts as `dead_slots` (mid-fold
    reconciliation output)."""
    arr = np.asarray(sorted(dead_slots), np.int64)
    return Tier(snap, arr, _EMPTY_I64)


def _sorted_contains(arr: np.ndarray, v: int) -> bool:
    i = int(np.searchsorted(arr, v))
    return i < len(arr) and int(arr[i]) == v


def slot_dead(tier: Tier, slot: int) -> bool:
    return _sorted_contains(tier.dead_recent, slot) or _sorted_contains(
        tier.dead_base, slot
    )


def filter_dead(tier: Tier, qidx: np.ndarray, slots: np.ndarray):
    """Drop (qidx, slot) hits whose slot is shadowed in this tier.
    Both dead arrays are pre-sorted, so membership is a searchsorted
    pass per array — O(H log D), no per-query set conversion."""
    keep = None
    for arr in (tier.dead_recent, tier.dead_base):
        if not len(arr):
            continue
        pos = np.searchsorted(arr, slots)
        pos[pos == len(arr)] = 0  # any in-range index; compare below
        hit = arr[pos] == slots
        keep = ~hit if keep is None else keep & ~hit
    if keep is None:
        return qidx, slots
    return qidx[keep], slots[keep]


class TierPolicy(NamedTuple):
    ratio: float  # major compaction when churn > ratio * |L0|
    min_l0: int  # L0 sizes below this always compact major


def env_policy() -> TierPolicy:
    """Tier policy from DSS_TIER_* env vars (deployment-level knobs,
    docs/OPERATIONS.md); unset variables keep the defaults."""
    try:
        ratio = float(os.environ.get("DSS_TIER_RATIO", 0.25))
    except ValueError:
        raise ValueError(
            f"DSS_TIER_RATIO={os.environ['DSS_TIER_RATIO']!r} is not a float"
        )
    try:
        min_l0 = int(os.environ.get("DSS_TIER_MIN_L0", 0))
    except ValueError:
        raise ValueError(
            f"DSS_TIER_MIN_L0={os.environ['DSS_TIER_MIN_L0']!r} is not an int"
        )
    return TierPolicy(ratio=ratio, min_l0=min_l0)


def build_snapshot(live: List[Record]) -> TierSnapshot:
    """Pack records into one device-resident snapshot (postings +
    exact attribute columns + host decode state)."""
    if not live:
        return EMPTY_SNAPSHOT
    packed = pack_records(live, pad_postings=False)
    pe = packed.post_ent
    ft = FastTable(
        packed.post_key,
        pe,
        packed.alt_lo[pe],
        packed.alt_hi[pe],
        packed.t_start[pe],
        packed.t_end[pe],
        packed.active[pe],
        slot_exact={
            "alt_lo": packed.alt_lo,
            "alt_hi": packed.alt_hi,
            "t0": packed.t_start,
            "t1": packed.t_end,
            "live": packed.active.copy(),
        },
    )
    ids = [r.entity_id for r in live]
    return TierSnapshot(
        fast=ft,
        owner=packed.owner,
        ids=ids,
        slot_of={eid: i for i, eid in enumerate(ids)},
        recs={r.entity_id: r for r in live},
    )


def mark_dead(tiers: Tuple[Tier, ...], entity_id: str) -> Tuple[Tier, ...]:
    """Shadow an entity everywhere: mark its slot dead in every tier
    that still holds it live.  Returns the input tuple unchanged when
    nothing needed marking (no allocation on the brand-new-entity fast
    path).  Per-write cost is O(len(dead_recent)) <= O(threshold) — a
    small sorted insert — never O(accumulated churn); a recent array
    crossing the threshold folds into the base once (O(base))."""
    out = None
    for i, t in enumerate(tiers):
        s = t.snap.slot_of.get(entity_id)
        if s is None or slot_dead(t, s):
            continue
        recent = np.insert(
            t.dead_recent, int(np.searchsorted(t.dead_recent, s)), s
        )
        base = t.dead_base
        if len(recent) > DEAD_FOLD_THRESHOLD:
            # amortized: one O(base) merge per threshold shadowings
            base = np.union1d(base, recent)
            recent = _EMPTY_I64
        if out is None:
            out = list(tiers)
        out[i] = Tier(t.snap, recent, base)
    return tiers if out is None else tuple(out)


def resolve_record(
    tiers: Tuple[Tier, ...], entity_id: str
) -> Optional[Record]:
    """The entity's visible record across the tier stack, newest tier
    first (an id live in two tiers would be a shadowing bug; dead
    filtering makes the newest copy the only live one)."""
    for t in reversed(tiers):
        s = t.snap.slot_of.get(entity_id)
        if s is not None and not slot_dead(t, s):
            return t.snap.recs.get(entity_id)
    return None


def stats(tiers: Tuple[Tier, ...]) -> dict:
    """Gauge-ready tier metrics (flow into /metrics as
    dss_dar_<class>_tier_* via the index stats)."""
    l0 = len(tiers[0].snap.ids) if tiers else 0
    l1 = sum(len(t.snap.ids) for t in tiers[1:])
    shadowed = sum(t.dead_count for t in tiers)
    return {
        "tier_count": len(tiers),
        "tier_l0_records": l0,
        "tier_l1_records": l1,
        "tier_l0_dead": tiers[0].dead_count if tiers else 0,
        "tier_shadowed_rows": shadowed,
    }
