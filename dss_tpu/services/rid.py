"""RID service: ISA + Subscription application logic and handlers.

Combines the reference's handler layer (pkg/rid/server) and application
layer (pkg/rid/application): version/ownership fencing prechecks,
AdjustTimeRange, the DSS0030 subscription quota, and notification-index
fanout over the union of old+new cells on ISA mutation.  Requests and
responses are proto-JSON-shaped dicts (the REST wire format).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dss_tpu import errors
from dss_tpu.clock import Clock
from dss_tpu.dar.store import RIDStore
from dss_tpu.geo import covering as geo_covering
from dss_tpu.models import rid as ridm
from dss_tpu.models.core import Version, validate_uuid
from dss_tpu.obs import stages
from dss_tpu.services import serialization as ser

MAX_SUBSCRIPTIONS_PER_AREA = 10  # DSS0030 (pkg/rid/application/subscription.go)


def _area_to_cells(area: str) -> np.ndarray:
    try:
        # canonical (sorted, deduped) at ingress: cache keying and the
        # pack path share one covering form (geo_covering.canonical_cells)
        return geo_covering.canonical_cells(
            geo_covering.area_to_cell_ids(area)
        )
    except geo_covering.AreaTooLargeError as e:
        raise errors.area_too_large(f"bad area: {e}")
    except geo_covering.BadAreaError as e:
        raise errors.bad_request(f"bad area: {e}")


def _parse_version(version: Optional[str]) -> Optional[Version]:
    if version is None:
        return None
    try:
        return Version.from_string(version)
    except ValueError as e:
        raise errors.bad_request(f"bad version: {e}")


class RIDService:
    def __init__(self, store: RIDStore, clock: Clock):
        self.store = store
        self.clock = clock

    # -- ISAs (pkg/rid/server/isa_handler.go + application/isa.go) ----------

    def get_isa(self, id: str) -> dict:
        validate_uuid(id)
        isa = self.store.get_isa(id)
        if isa is None:
            raise errors.not_found(id)
        return {"service_area": ser.isa_to_json(isa)}

    def _put_isa(
        self,
        id: str,
        version: Optional[Version],
        extents_json: dict,
        flights_url: str,
        owner: str,
    ) -> dict:
        validate_uuid(id)
        if not flights_url:
            raise errors.bad_request("missing required flightsURL")
        if extents_json is None:
            raise errors.bad_request("missing required extents")
        isa = ridm.IdentificationServiceArea(
            id=id, owner=owner, url=flights_url, version=version
        )
        try:
            with stages.stage("covering_ms"):
                isa.set_extents(ser.volume4d_from_rid_json(extents_json))
        except geo_covering.AreaTooLargeError as e:
            raise errors.area_too_large(f"bad extents: {e}")
        except geo_covering.BadAreaError as e:
            raise errors.bad_request(f"bad extents: {e}")

        with self.store.transaction():
            old = self.store.get_isa(isa.id)
            if old is None and isa.version is not None and not isa.version.empty:
                raise errors.not_found(isa.id)
            if old is not None and (isa.version is None or isa.version.empty):
                raise errors.already_exists(isa.id)
            if old is not None and not isa.version.matches(old.version):
                raise errors.version_mismatch("old version")
            if old is not None and old.owner != isa.owner:
                raise errors.permission_denied(f"ISA is owned by {old.owner}")
            isa.adjust_time_range(self.clock.now(), old)
            # fanout over union of old+new cells (application/isa.go:120-141)
            cells = isa.cells
            if old is not None:
                cells = np.union1d(
                    np.asarray(old.cells, np.uint64), np.asarray(isa.cells, np.uint64)
                )
            subs = self.store.update_notification_idxs_in_cells(
                cells, entity=isa
            )
            ret = self.store.insert_isa(isa)
            if ret is None:
                raise errors.version_mismatch("old version")
        return {
            "service_area": ser.isa_to_json(ret),
            "subscribers": [ser.rid_sub_to_notify_json(s) for s in subs],
        }

    @errors.retry_write_conflicts
    def create_isa(self, id: str, params: dict, owner: str) -> dict:
        return self._put_isa(
            id, None, params.get("extents"), params.get("flights_url", ""), owner
        )

    @errors.retry_write_conflicts
    def update_isa(self, id: str, version: str, params: dict, owner: str) -> dict:
        v = _parse_version(version or "")
        return self._put_isa(
            id, v, params.get("extents"), params.get("flights_url", ""), owner
        )

    @errors.retry_write_conflicts
    def delete_isa(self, id: str, version: str, owner: str) -> dict:
        validate_uuid(id)
        v = _parse_version(version or "")
        with self.store.transaction():
            old = self.store.get_isa(id)
            if old is None:
                raise errors.not_found(id)
            if v is not None and not v.empty and not v.matches(old.version):
                raise errors.version_mismatch("old version")
            if old.owner != owner:
                raise errors.permission_denied(f"ISA is owned by {old.owner}")
            subs = self.store.update_notification_idxs_in_cells(
                old.cells, entity=old, removed=True
            )
            isa = self.store.delete_isa(
                dataclasses.replace(old, owner=owner, version=old.version)
            )
            if isa is None:
                raise errors.version_mismatch("old version")
        return {
            "service_area": ser.isa_to_json(isa),
            "subscribers": [ser.rid_sub_to_notify_json(s) for s in subs],
        }

    def search_isas(
        self,
        area: str,
        earliest_time: Optional[str] = None,
        latest_time: Optional[str] = None,
    ) -> dict:
        with stages.stage("covering_ms"):
            cells = _area_to_cells(area or "")
        earliest = latest = None
        if earliest_time:
            try:
                earliest = ser.parse_time(earliest_time)
            except ValueError as e:
                raise errors.bad_request(f"bad earliest_time: {e}")
        if latest_time:
            try:
                latest = ser.parse_time(latest_time)
            except ValueError as e:
                raise errors.bad_request(f"bad latest_time: {e}")
        # clamp earliest to now (application/isa.go:38-45)
        now = self.clock.now()
        if earliest is None or earliest < now:
            earliest = now
        with stages.stage("store_ms"):
            # allow_stale: a public search may ride the mesh replica
            # when its batch is oversized and the replica is fresh
            isas = self.store.search_isas(
                cells, earliest, latest, allow_stale=True
            )
        with stages.stage("serialize_ms"):
            return {"service_areas": [ser.isa_to_json(i) for i in isas]}

    # -- Subscriptions (subscription_handler.go + application/subscription.go)

    def get_subscription(self, id: str) -> dict:
        validate_uuid(id)
        sub = self.store.get_subscription(id)
        if sub is None:
            raise errors.not_found(id)
        return {"subscription": ser.rid_sub_to_json(sub)}

    def _put_subscription(
        self,
        id: str,
        version: Optional[Version],
        callbacks: Optional[dict],
        extents_json: dict,
        owner: str,
    ) -> dict:
        validate_uuid(id)
        if callbacks is None:
            raise errors.bad_request("missing required callbacks")
        if extents_json is None:
            raise errors.bad_request("missing required extents")
        sub = ridm.Subscription(
            id=id,
            owner=owner,
            url=callbacks.get("identification_service_area_url", ""),
            version=version,
        )
        try:
            with stages.stage("covering_ms"):
                sub.set_extents(ser.volume4d_from_rid_json(extents_json))
        except geo_covering.AreaTooLargeError as e:
            raise errors.area_too_large(f"bad extents: {e}")
        except geo_covering.BadAreaError as e:
            raise errors.bad_request(f"bad extents: {e}")

        with self.store.transaction():
            old = self.store.get_subscription(sub.id)
            if old is None and sub.version is not None and not sub.version.empty:
                raise errors.not_found(sub.id)
            if old is not None and (sub.version is None or sub.version.empty):
                raise errors.already_exists(sub.id)
            if old is not None and not sub.version.matches(old.version):
                raise errors.version_mismatch("old version")
            if old is not None and old.owner != sub.owner:
                raise errors.permission_denied(f"s is owned by {old.owner}")
            sub.adjust_time_range(self.clock.now(), old)
            count = self.store.max_subscription_count_in_cells_by_owner(
                sub.cells, sub.owner
            )
            if count >= MAX_SUBSCRIPTIONS_PER_AREA:
                raise errors.exhausted(
                    "too many existing subscriptions in this area already"
                )
            inserted = self.store.insert_subscription(sub)
            if inserted is None:
                raise errors.version_mismatch("old version")
            # affected ISAs in the subscription's area (earliest clamps to now)
            isas = self.store.search_isas(sub.cells, self.clock.now(), None)
        return {
            "subscription": ser.rid_sub_to_json(inserted),
            "service_areas": [ser.isa_to_json(i) for i in isas],
        }

    @errors.retry_write_conflicts
    def create_subscription(self, id: str, params: dict, owner: str) -> dict:
        return self._put_subscription(
            id, None, params.get("callbacks"), params.get("extents"), owner
        )

    @errors.retry_write_conflicts
    def update_subscription(
        self, id: str, version: str, params: dict, owner: str
    ) -> dict:
        v = _parse_version(version or "")
        return self._put_subscription(
            id, v, params.get("callbacks"), params.get("extents"), owner
        )

    @errors.retry_write_conflicts
    def delete_subscription(self, id: str, version: str, owner: str) -> dict:
        validate_uuid(id)
        _parse_version(version or "")  # must parse; reference app ignores it
        with self.store.transaction():
            old = self.store.get_subscription(id)
            if old is None:
                raise errors.not_found(id)
            if old.owner != owner:
                raise errors.permission_denied(f"ISA is owned by {old.owner}")
            # the reference deletes at the *current* version regardless of
            # the supplied one (application/subscription.go:84-100)
            deleted = self.store.delete_subscription(old)
            if deleted is None:
                raise errors.version_mismatch("old version")
        return {"subscription": ser.rid_sub_to_json(deleted)}

    def search_subscriptions(self, area: str, owner: str) -> dict:
        with stages.stage("covering_ms"):
            cells = _area_to_cells(area or "")
        with stages.stage("store_ms"):
            subs = self.store.search_subscriptions_by_owner(cells, owner)
        with stages.stage("serialize_ms"):
            return {"subscriptions": [ser.rid_sub_to_json(s) for s in subs]}
