"""Service layer: RID application logic + SCD handlers.

The analog of pkg/rid/{server,application} and pkg/scd in the
reference: owner/version fencing prechecks, time-range adjustment,
quotas, notification fanout, OVN key checks, and proto-JSON-shaped
request/response assembly for the REST gateway.
"""
