"""SCD service: operation references + subscriptions + constraints.

Mirrors pkg/scd: PutOperationReference with multi-volume extent union,
implicit subscriptions, OVN key checks with the AirspaceConflict
response on missing OVNs (operations_handler.go:171-309), subscription
lifecycle (subscriptions_handler.go).  Constraint references go BEYOND
the reference (constraints_handler.go:12-30 raises "not yet
implemented" on all four endpoints): real CRUD/query with the same
owner/int32-version/OVN discipline as operations, notification fan-out
to notify_for_constraints subscriptions, and constraint-aware
operation deconfliction (docs/DESIGN.md "Constraint references").
"""

from __future__ import annotations

import contextlib
import uuid as uuidlib
from typing import List, Optional

import numpy as np

from dss_tpu import errors
from dss_tpu.clock import Clock
from dss_tpu.dar.store import SCDStore
from dss_tpu.geo import covering as geo_covering
from dss_tpu.models import scd as scdm
from dss_tpu.models.core import validate_uss_base_url
from dss_tpu.models.volumes import union_volumes_4d
from dss_tpu.obs import stages
from dss_tpu.services import serialization as ser


def _area_error(e: Exception):
    if isinstance(e, geo_covering.AreaTooLargeError):
        return errors.area_too_large(str(e))
    return errors.bad_request(f"bad area: {e}")


def _missing_ovns_response(
    ops: List[scdm.Operation], csts: List[scdm.Constraint] = (),
) -> dict:
    """The AirspaceConflictResponse body (pkg/scd/errors/errors.go:22-53);
    OVNs of other owners' operations are included — that is the point of
    the response (the caller needs them for its key).  Constraint-aware
    upserts additionally list intersecting constraints the key missed —
    and the message names what is actually missing, so a client acting
    on it re-queries the right entity class."""
    missing = [w for w, lst in (
        ("operation", ops), ("constraint", csts),
    ) if lst]
    what = " or ".join(missing) if missing else "operation"
    return {
        "message": (
            f"at least one current {what} is missing from the key; "
            "no changes have been made"
        ),
        "entity_conflicts": [
            {"operation_reference": ser.op_to_json(op)} for op in ops
        ]
        + [
            {"constraint_reference": ser.constraint_to_json(c)}
            for c in csts
        ],
    }


def _extents_to_covering(params: dict):
    """Union a PUT's multi-volume `extents` and compute the covering —
    the shared ingress path of operation AND constraint upserts.
    Returns (union Volume4D, cells); raises the same wire errors for
    both entity classes so a fix to one cannot miss the other."""
    extents_json = params.get("extents") or []
    extents = [ser.volume4d_from_scd_json(e) for e in extents_json]
    try:
        u_extent = union_volumes_4d(extents)
    except geo_covering.AreaTooLargeError as e:
        raise errors.area_too_large(str(e))
    except (geo_covering.BadAreaError, ValueError) as e:
        raise errors.bad_request(f"failed to union extents: {e}")
    if u_extent.start_time is None:
        raise errors.bad_request("missing time_start from extents")
    if u_extent.end_time is None:
        raise errors.bad_request("missing time_end from extents")
    try:
        with stages.stage("covering_ms"):
            cells = u_extent.calculate_spatial_covering()
    except (
        geo_covering.AreaTooLargeError,
        geo_covering.BadAreaError,
        ValueError,
    ) as e:
        raise _area_error(e)
    return u_extent, cells


def _aoi_to_covering(params: dict):
    """Parse a query's `area_of_interest` and compute the covering —
    the shared ingress path of every SCD search/query endpoint."""
    aoi = params.get("area_of_interest")
    if aoi is None:
        raise errors.bad_request("missing area_of_interest")
    vol4 = ser.volume4d_from_scd_json(aoi)
    try:
        with stages.stage("covering_ms"):
            cells = vol4.calculate_spatial_covering()
    except (
        geo_covering.AreaTooLargeError,
        geo_covering.BadAreaError,
        ValueError,
    ) as e:
        raise _area_error(e)
    return vol4, cells


class SCDService:
    def __init__(self, store: SCDStore, clock: Clock):
        self.store = store
        self.clock = clock

    # -- Operation references ------------------------------------------------

    @errors.retry_write_conflicts
    def put_operation(self, entity_uuid: str, params: dict, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Operation ID")
        if not params.get("uss_base_url"):
            raise errors.bad_request("missing required UssBaseUrl")
        u_extent, cells = _extents_to_covering(params)

        subscription_id = params.get("subscription_id") or ""
        key = [str(k) for k in (params.get("key") or [])]

        op = scdm.Operation(
            id=entity_uuid,
            owner=owner,
            version=ser.int_field(params.get("old_version"), "old_version"),
            start_time=u_extent.start_time,
            end_time=u_extent.end_time,
            altitude_lower=u_extent.spatial_volume.altitude_lo,
            altitude_upper=u_extent.spatial_volume.altitude_hi,
            cells=cells,
            uss_base_url=params["uss_base_url"],
            subscription_id=subscription_id,
            state=params.get("state", ""),
        )

        new_sub = params.get("new_subscription") or {}
        if not subscription_id:
            try:
                validate_uss_base_url(new_sub.get("uss_base_url", ""))
            except ValueError as e:
                raise errors.bad_request(str(e))
            # constraint awareness rides the subscription the op rides:
            # a USS that asked for constraint notifications consumes
            # constraint updates and must key against them
            op.constraint_aware = bool(
                new_sub.get("notify_for_constraints", False)
            )

        @contextlib.contextmanager
        def conflict_details():
            """On MISSING_OVNS, attach the AirspaceConflictResponse
            payload with the full conflict set
            (operations_handler.go:268-280) — operations always,
            intersecting constraints when the op is constraint-aware."""
            try:
                yield
            except errors.StatusError as e:
                if e.code == errors.Code.MISSING_OVNS:
                    ops = self.store.search_operations(
                        cells,
                        u_extent.spatial_volume.altitude_lo,
                        u_extent.spatial_volume.altitude_hi,
                        u_extent.start_time,
                        u_extent.end_time,
                    )
                    csts = (
                        self.store.search_constraints(
                            cells,
                            u_extent.spatial_volume.altitude_lo,
                            u_extent.spatial_volume.altitude_hi,
                            u_extent.start_time,
                            u_extent.end_time,
                        )
                        if op.constraint_aware
                        else []
                    )
                    e.details = _missing_ovns_response(ops, csts)
                raise

        with self.store.transaction():
            if subscription_id:
                # explicit subscription: awareness comes from ITS
                # notify_for_constraints, resolved inside the txn so
                # the precheck and the flag agree on one sub version.
                # A missing/foreign subscription propagates (404): a
                # typoed id must not silently downgrade the op to
                # non-aware AND persist a dangling reference the USS
                # thinks is delivering its notifications.
                op.constraint_aware = self.store.get_subscription(
                    subscription_id, owner
                ).notify_for_constraints
            with conflict_details():
                # Validate (incl. the OVN key check) BEFORE journaling
                # the implicit subscription: a rejected conflict is a
                # routine outcome and must leave nothing to roll back.
                self.store.validate_operation_upsert(op, key)

            if not subscription_id:
                sub, _ = self.store.upsert_subscription(
                    scdm.Subscription(
                        id=str(uuidlib.uuid4()),
                        owner=owner,
                        start_time=u_extent.start_time,
                        end_time=u_extent.end_time,
                        altitude_lo=u_extent.spatial_volume.altitude_lo,
                        altitude_hi=u_extent.spatial_volume.altitude_hi,
                        cells=cells,
                        base_url=new_sub.get("uss_base_url", ""),
                        notify_for_operations=True,
                        notify_for_constraints=new_sub.get(
                            "notify_for_constraints", False
                        ),
                        implicit_subscription=True,
                    )
                )
                op.subscription_id = sub.id

            with conflict_details():
                # key_checked: the OVN search already ran in this txn
                # scope (pinned timestamp -> same visibility answers)
                stored, subs = self.store.upsert_operation(
                    op, key, key_checked=True
                )
        return {
            "operation_reference": ser.op_to_json(stored),
            "subscribers": ser.scd_subscribers_to_notify_json(subs),
        }

    def get_operation(self, entity_uuid: str, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Operation ID")
        op = self.store.get_operation(entity_uuid)
        if op.owner != owner:
            op.ovn = ""  # OVNs are private to the owner
        return {"operation_reference": ser.op_to_json(op)}

    @errors.retry_write_conflicts
    def delete_operation(self, entity_uuid: str, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Operation ID")
        with self.store.transaction():
            op, subs = self.store.delete_operation(entity_uuid, owner)
        return {
            "operation_reference": ser.op_to_json(op),
            "subscribers": ser.scd_subscribers_to_notify_json(subs),
        }

    def search_operations(self, params: dict, owner: str) -> dict:
        vol4, cells = _aoi_to_covering(params)
        sv = vol4.spatial_volume
        # allow_stale: public search may ride the mesh replica for
        # oversized batches (the conflict-response listing at :117 must
        # NOT — it feeds the OVN key the client will retry with)
        ops = self.store.search_operations(
            cells, sv.altitude_lo, sv.altitude_hi, vol4.start_time,
            vol4.end_time, allow_stale=True,
        )
        out = []
        for op in ops:
            if op.owner != owner:
                op.ovn = ""
            out.append(ser.op_to_json(op))
        return {"operation_references": out}

    # -- Subscriptions -------------------------------------------------------

    @errors.retry_write_conflicts
    def put_subscription(self, subscription_id: str, params: dict, owner: str) -> dict:
        if not subscription_id:
            raise errors.bad_request("missing Subscription ID")
        extents = ser.volume4d_from_scd_json(params.get("extents") or {})
        try:
            cells = (
                extents.calculate_spatial_covering()
                if extents.spatial_volume and extents.spatial_volume.footprint
                else np.array([], np.uint64)
            )
        except (
            geo_covering.AreaTooLargeError,
            geo_covering.BadAreaError,
            ValueError,
        ) as e:
            raise _area_error(e)
        sub = scdm.Subscription(
            id=subscription_id,
            owner=owner,
            version=ser.int_field(params.get("old_version"), "old_version"),
            start_time=extents.start_time,
            end_time=extents.end_time,
            altitude_lo=(
                extents.spatial_volume.altitude_lo if extents.spatial_volume else None
            ),
            altitude_hi=(
                extents.spatial_volume.altitude_hi if extents.spatial_volume else None
            ),
            cells=cells,
            base_url=params.get("uss_base_url", ""),
            notify_for_operations=bool(params.get("notify_for_operations", False)),
            notify_for_constraints=bool(params.get("notify_for_constraints", False)),
        )
        if not sub.notify_for_operations and not sub.notify_for_constraints:
            raise errors.bad_request(
                "no notification triggers requested for Subscription"
            )
        # NOTE: the reference passes the new subscription as its own `old`
        # here (subscriptions_handler.go:76), which nil-derefs when
        # time_start is omitted; we use the sane old=None defaulting.
        sub.adjust_time_range(self.clock.now(), None)
        with self.store.transaction():
            stored, ops = self.store.upsert_subscription(sub)
        result = {"subscription": ser.scd_sub_to_json(stored), "operations": []}
        for op in ops:
            if op.owner != owner:
                op.ovn = ""
            result["operations"].append(ser.op_to_json(op))
        return result

    def get_subscription(self, subscription_id: str, owner: str) -> dict:
        if not subscription_id:
            raise errors.bad_request("missing Subscription ID")
        sub = self.store.get_subscription(subscription_id, owner)
        return {"subscription": ser.scd_sub_to_json(sub)}

    def query_subscriptions(self, params: dict, owner: str) -> dict:
        _, cells = _aoi_to_covering(params)
        subs = self.store.search_subscriptions(cells, owner)
        return {"subscriptions": [ser.scd_sub_to_json(s) for s in subs]}

    @errors.retry_write_conflicts
    def delete_subscription(self, subscription_id: str, owner: str) -> dict:
        if not subscription_id:
            raise errors.bad_request("missing Subscription ID")
        with self.store.transaction():
            sub = self.store.delete_subscription(subscription_id, owner, 0)
        return {"subscription": ser.scd_sub_to_json(sub)}

    # -- Constraints (beyond the reference, which stubs these:
    # constraints_handler.go:12-30) ------------------------------------------

    @errors.retry_write_conflicts
    def put_constraint(self, entity_uuid: str, params: dict, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Constraint ID")
        if not params.get("uss_base_url"):
            raise errors.bad_request("missing required UssBaseUrl")
        u_extent, cells = _extents_to_covering(params)

        cst = scdm.Constraint(
            id=entity_uuid,
            owner=owner,
            version=ser.int_field(params.get("old_version"), "old_version"),
            start_time=u_extent.start_time,
            end_time=u_extent.end_time,
            altitude_lower=u_extent.spatial_volume.altitude_lo,
            altitude_upper=u_extent.spatial_volume.altitude_hi,
            cells=cells,
            uss_base_url=params["uss_base_url"],
        )
        with self.store.transaction():
            stored, subs = self.store.upsert_constraint(cst)
        return {
            "constraint_reference": ser.constraint_to_json(stored),
            "subscribers": ser.scd_subscribers_to_notify_json(subs),
        }

    def get_constraint(self, entity_uuid: str, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Constraint ID")
        cst = self.store.get_constraint(entity_uuid)
        if cst.owner != owner:
            cst.ovn = ""  # OVNs are private to the owner
        return {"constraint_reference": ser.constraint_to_json(cst)}

    @errors.retry_write_conflicts
    def delete_constraint(self, entity_uuid: str, owner: str) -> dict:
        if not entity_uuid:
            raise errors.bad_request("missing Constraint ID")
        with self.store.transaction():
            cst, subs = self.store.delete_constraint(entity_uuid, owner)
        return {
            "constraint_reference": ser.constraint_to_json(cst),
            "subscribers": ser.scd_subscribers_to_notify_json(subs),
        }

    def query_constraints(self, params: dict, owner: str) -> dict:
        vol4, cells = _aoi_to_covering(params)
        sv = vol4.spatial_volume
        # allow_stale: public QUERY may ride the mesh replica; the
        # constraint-aware precheck listing never sets it (it feeds
        # the OVN key the client will retry with)
        csts = self.store.search_constraints(
            cells, sv.altitude_lo, sv.altitude_hi, vol4.start_time,
            vol4.end_time, allow_stale=True,
        )
        out = []
        for cst in csts:
            if cst.owner != owner:
                cst.ovn = ""
            out.append(ser.constraint_to_json(cst))
        return {"constraint_references": out}

    def make_dss_report(self, *_args, **_kw):
        raise errors.bad_request("not yet implemented")
