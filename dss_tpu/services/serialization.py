"""JSON (proto-JSON wire shapes) <-> model conversion.

The wire shapes follow the reference's generated protos as rendered by
grpc-gateway (pkg/api/v1/ridpb, scdpb): snake_case fields, RFC3339
timestamps; SCD wraps times as {"value": ..., "format": "RFC3339"} and
altitudes as {"value": ..., "reference": "W84", "units": "M"}
(pkg/models/geo.go:510-580).
"""

from __future__ import annotations

import re
from datetime import datetime, timezone
from typing import Optional

from dss_tpu import errors
from dss_tpu.models import rid as ridm
from dss_tpu.models import scd as scdm
from dss_tpu.models.volumes import (
    GeoCircle,
    GeoPolygon,
    LatLngPoint,
    Volume3D,
    Volume4D,
)

TIME_FORMAT_RFC3339 = "RFC3339"


def num(v, what: str, default: float = 0.0) -> float:
    """Coerce an untrusted JSON scalar to float; 400 on garbage."""
    if v is None:
        v = default
    try:
        return float(v)
    except (TypeError, ValueError):
        raise errors.bad_request(f"bad {what}: {v!r}")


def int_field(v, what: str, default: int = 0) -> int:
    """Coerce an untrusted JSON scalar to int; 400 on garbage."""
    if v is None:
        v = default
    try:
        return int(v)
    except (TypeError, ValueError):
        raise errors.bad_request(f"bad {what}: {v!r}")


def _dict_field(v, what: str) -> dict:
    """Untrusted JSON object field: None -> {}, non-dict -> 400."""
    if v is None:
        return {}
    if not isinstance(v, dict):
        raise errors.bad_request(f"bad {what}: expected object")
    return v


def _list_field(v, what: str) -> list:
    """Untrusted JSON array field: None -> [], non-list -> 400; every
    element must be an object."""
    if v is None:
        return []
    if not isinstance(v, list) or any(not isinstance(e, dict) for e in v):
        raise errors.bad_request(f"bad {what}: expected array of objects")
    return v


def parse_time(s: str) -> datetime:
    """RFC3339 -> aware UTC datetime."""
    if not isinstance(s, str) or not s:
        raise ValueError(f"bad timestamp: {s!r}")
    raw = s.strip()
    if raw.endswith(("z", "Z")):
        raw = raw[:-1] + "+00:00"
    # Python < 3.11 fromisoformat only accepts 3- or 6-digit fractional
    # seconds; RFC3339 allows any width (format_time itself emits
    # trailing-zero-stripped fractions) — pad to 6
    m = re.fullmatch(r"(.*T\d\d:\d\d:\d\d)\.(\d+)(.*)", raw)
    if m and len(m.group(2)) not in (3, 6):
        frac = (m.group(2) + "000000")[:6]
        raw = f"{m.group(1)}.{frac}{m.group(3)}"
    t = datetime.fromisoformat(raw)
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t.astimezone(timezone.utc)


def format_time(t: Optional[datetime]) -> Optional[str]:
    if t is None:
        return None
    t = t.astimezone(timezone.utc)
    if t.microsecond:
        return t.strftime("%Y-%m-%dT%H:%M:%S.%f").rstrip("0") + "Z"
    return t.strftime("%Y-%m-%dT%H:%M:%SZ")


# ---------------------------------------------------------------------------
# RID shapes (ridpb)
# ---------------------------------------------------------------------------


def volume4d_from_rid_json(d: dict) -> Volume4D:
    """ridpb.Volume4D: spatial_volume{footprint{vertices[{lat,lng}]},
    altitude_lo, altitude_hi}, time_start, time_end."""
    if not isinstance(d, dict):
        raise errors.bad_request("bad extents")
    result = Volume4D()
    if d.get("time_start") is not None:
        try:
            result.start_time = parse_time(d["time_start"])
        except ValueError as e:
            raise errors.bad_request(f"bad extents: {e}")
    if d.get("time_end") is not None:
        try:
            result.end_time = parse_time(d["time_end"])
        except ValueError as e:
            raise errors.bad_request(f"bad extents: {e}")
    space = d.get("spatial_volume")
    if space is None:
        raise errors.bad_request("bad extents: missing required spatial_volume")
    space = _dict_field(space, "spatial_volume")
    footprint = space.get("footprint")
    if footprint is None:
        raise errors.bad_request(
            "bad extents: spatial_volume missing required footprint"
        )
    footprint = _dict_field(footprint, "footprint")
    vertices = [
        LatLngPoint(lat=num(v.get("lat"), "vertex lat"), lng=num(v.get("lng"), "vertex lng"))
        for v in _list_field(footprint.get("vertices"), "vertices")
    ]
    result.spatial_volume = Volume3D(
        footprint=GeoPolygon(vertices=vertices),
        # proto3 scalars default to 0 when omitted (reference keeps them)
        altitude_lo=num(space.get("altitude_lo"), "altitude_lo"),
        altitude_hi=num(space.get("altitude_hi"), "altitude_hi"),
    )
    return result


def isa_to_json(isa: ridm.IdentificationServiceArea) -> dict:
    out = {
        "id": isa.id,
        "owner": isa.owner,
        "flights_url": isa.url,
        "version": str(isa.version) if isa.version else "",
    }
    if isa.start_time is not None:
        out["time_start"] = format_time(isa.start_time)
    if isa.end_time is not None:
        out["time_end"] = format_time(isa.end_time)
    return out


def rid_sub_to_json(sub: ridm.Subscription) -> dict:
    out = {
        "id": sub.id,
        "owner": sub.owner,
        "callbacks": {"identification_service_area_url": sub.url},
        "notification_index": sub.notification_index,
        "version": str(sub.version) if sub.version else "",
    }
    if sub.start_time is not None:
        out["time_start"] = format_time(sub.start_time)
    if sub.end_time is not None:
        out["time_end"] = format_time(sub.end_time)
    return out


def rid_sub_to_notify_json(sub: ridm.Subscription) -> dict:
    """ridpb.SubscriberToNotify (rid/models/subscriptions.go:55-65)."""
    return {
        "url": sub.url,
        "subscriptions": [
            {
                "notification_index": sub.notification_index,
                "subscription_id": sub.id,
            }
        ],
    }


# ---------------------------------------------------------------------------
# SCD shapes (scdpb)
# ---------------------------------------------------------------------------


def _scd_time(d) -> Optional[datetime]:
    if d is None:
        return None
    value = d.get("value") if isinstance(d, dict) else d
    if value is None:
        return None
    try:
        return parse_time(value)
    except ValueError as e:
        raise errors.bad_request(f"bad time: {e}")


def scd_time_json(t: Optional[datetime]) -> Optional[dict]:
    if t is None:
        return None
    return {"value": format_time(t), "format": TIME_FORMAT_RFC3339}


def _altitude_value(d) -> Optional[float]:
    if d is None:
        return None
    if isinstance(d, dict):
        return num(d.get("value"), "altitude value")
    return num(d, "altitude")


def altitude_json(v: Optional[float]) -> Optional[dict]:
    if v is None:
        return None
    return {"reference": "W84", "units": "M", "value": float(v)}


def volume4d_from_scd_json(d: dict) -> Volume4D:
    """scdpb.Volume4D: volume{outline_polygon|outline_circle,
    altitude_lower, altitude_upper}, time_start, time_end
    (pkg/models/geo.go:428-508)."""
    if not isinstance(d, dict):
        raise errors.bad_request("bad volume")
    result = Volume4D(
        start_time=_scd_time(d.get("time_start")),
        end_time=_scd_time(d.get("time_end")),
    )
    vol3 = _dict_field(d.get("volume"), "volume")
    polygon = vol3.get("outline_polygon")
    circle = vol3.get("outline_circle")
    if polygon is not None and circle is not None:
        raise errors.bad_request(
            "both circle and polygon specified in outline geometry"
        )
    footprint = None
    if polygon is not None:
        polygon = _dict_field(polygon, "outline_polygon")
        footprint = GeoPolygon(
            vertices=[
                LatLngPoint(
                    lat=num(v.get("lat"), "vertex lat"),
                    lng=num(v.get("lng"), "vertex lng"),
                )
                for v in _list_field(polygon.get("vertices"), "vertices")
            ]
        )
    elif circle is not None:
        circle = _dict_field(circle, "outline_circle")
        center = _dict_field(circle.get("center"), "circle center")
        radius = circle.get("radius") or {}
        units = radius.get("units", "M") if isinstance(radius, dict) else "M"
        factor = 1.0 if units == "M" else 0.0  # unknown units -> 0 (reference map)
        footprint = GeoCircle(
            center=LatLngPoint(
                lat=num(center.get("lat"), "circle center lat"),
                lng=num(center.get("lng"), "circle center lng"),
            ),
            radius_meter=factor
            * num(radius.get("value") if isinstance(radius, dict) else radius, "circle radius"),
        )
    result.spatial_volume = Volume3D(
        footprint=footprint,
        altitude_lo=_altitude_value(vol3.get("altitude_lower")),
        altitude_hi=_altitude_value(vol3.get("altitude_upper")),
    )
    return result


def op_to_json(op: scdm.Operation) -> dict:
    out = {
        "id": op.id,
        "ovn": op.ovn,
        "owner": op.owner,
        "version": op.version,
        "uss_base_url": op.uss_base_url,
        "subscription_id": op.subscription_id,
    }
    if op.start_time is not None:
        out["time_start"] = scd_time_json(op.start_time)
    if op.end_time is not None:
        out["time_end"] = scd_time_json(op.end_time)
    return out


def constraint_to_json(cst: scdm.Constraint) -> dict:
    """scdpb.ConstraintReference wire shape — the same field set as an
    operation reference minus state/subscription (a constraint is not a
    negotiated intent)."""
    out = {
        "id": cst.id,
        "ovn": cst.ovn,
        "owner": cst.owner,
        "version": cst.version,
        "uss_base_url": cst.uss_base_url,
    }
    if cst.start_time is not None:
        out["time_start"] = scd_time_json(cst.start_time)
    if cst.end_time is not None:
        out["time_end"] = scd_time_json(cst.end_time)
    return out


def scd_sub_to_json(sub: scdm.Subscription) -> dict:
    out = {
        "id": sub.id,
        "version": sub.version,
        "notification_index": sub.notification_index,
        "uss_base_url": sub.base_url,
        "notify_for_operations": sub.notify_for_operations,
        "notify_for_constraints": sub.notify_for_constraints,
        "implicit_subscription": sub.implicit_subscription,
        "dependent_operations": list(sub.dependent_operations),
    }
    if sub.start_time is not None:
        out["time_start"] = scd_time_json(sub.start_time)
    if sub.end_time is not None:
        out["time_end"] = scd_time_json(sub.end_time)
    return out


def scd_subscribers_to_notify_json(subs) -> list:
    """Group subscription states by USS base URL (pkg/scd/server.go:31-50)."""
    by_url = {}
    for sub in subs:
        by_url.setdefault(sub.base_url, []).append(
            {
                "subscription_id": sub.id,
                "notification_index": sub.notification_index,
            }
        )
    return [
        {"uss_base_url": url, "subscriptions": states}
        for url, states in by_url.items()
    ]
