# TPU-native DSS server image (the analog of the reference's
# single-binary Dockerfile).  The CPU jax wheel is installed by
# default; on TPU hosts swap in the libtpu wheel at build time:
#   docker build --build-arg JAX_EXTRA="jax[tpu]" .

# Stage 1: compile the native host kernels (covering, host query,
# window pack/decode).  The runtime image is slim (no toolchain), so
# relying on the lazy in-process g++ build would silently fall back
# to the numpy paths — a 3-26x slowdown on the serving hot paths.
FROM python:3.12-slim AS native-build
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
COPY dss_tpu/native /src/native
# _buildlib is the same stdlib-only builder the lazy in-process path
# uses: one source list, and it writes the content-digest sidecar the
# runtime loader validates (mtimes don't survive pip installs)
RUN python /src/native/_buildlib.py /src/native

FROM python:3.12-slim

ARG JAX_EXTRA=""

WORKDIR /app
COPY pyproject.toml README.md ./
COPY dss_tpu ./dss_tpu
COPY --from=native-build /src/native/libdsscover.so \
    /src/native/libdsscover.so.sha ./dss_tpu/native/
RUN pip install --no-cache-dir . ${JAX_EXTRA}

# build info (the reference's -ldflags -X injection, pkg/build) — after
# the install layers so a changing commit never busts the pip cache
ARG BUILD_COMMIT=unknown
ARG BUILD_TIME=unknown
ENV DSS_BUILD_COMMIT=${BUILD_COMMIT} DSS_BUILD_TIME=${BUILD_TIME}

# flags mirror cmds/grpc-backend (see dss_tpu/cmds/server.py --help)
EXPOSE 8082
ENTRYPOINT ["dss-server"]
CMD ["--addr", ":8082", "--enable_scd", "--storage", "tpu", \
     "--insecure_no_auth"]
