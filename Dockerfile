# TPU-native DSS server image (the analog of the reference's
# single-binary Dockerfile).  The CPU jax wheel is installed by
# default; on TPU hosts swap in the libtpu wheel at build time:
#   docker build --build-arg JAX_EXTRA="jax[tpu]" .
FROM python:3.12-slim

ARG JAX_EXTRA=""

WORKDIR /app
COPY pyproject.toml README.md ./
COPY dss_tpu ./dss_tpu
RUN pip install --no-cache-dir . ${JAX_EXTRA}

# build info (the reference's -ldflags -X injection, pkg/build) — after
# the install layers so a changing commit never busts the pip cache
ARG BUILD_COMMIT=unknown
ARG BUILD_TIME=unknown
ENV DSS_BUILD_COMMIT=${BUILD_COMMIT} DSS_BUILD_TIME=${BUILD_TIME}

# flags mirror cmds/grpc-backend (see dss_tpu/cmds/server.py --help)
EXPOSE 8082
ENTRYPOINT ["dss-server"]
CMD ["--addr", ":8082", "--enable_scd", "--storage", "tpu", \
     "--insecure_no_auth"]
