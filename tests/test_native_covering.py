"""Differential tests: native (C++) covering vs the numpy reference.

The native kernel (dss_tpu/native/covering.cc) claims bit-identical
verdicts with dss_tpu/geo/covering.py's single-face rect fast path.
These tests pin that cell-for-cell over random polygons and circles,
plus the documented fallback conditions.
"""

from __future__ import annotations

import numpy as np
import pytest

from dss_tpu import native
from dss_tpu.geo import covering
from dss_tpu.geo.covering import (
    MAX_AREA_KM2,
    Loop,
    covering_circle,
    covering_polygon,
    loop_area_km2,
)
from dss_tpu.geo.s2cell import latlng_to_xyz

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native covering lib unavailable"
)


def _numpy_loop_covering(loop):
    """The pure-numpy covering, bypassing the native dispatch."""
    vertex_ids = covering.cell_id_from_point(
        loop.v, level=covering.DAR_LEVEL
    )
    loop_vertex_cells = {int(c) for c in np.atleast_1d(vertex_ids)}
    return covering._loop_covering_bfs(loop, loop_vertex_cells)


def _native_loop_covering(loop):
    return native.loop_covering(
        loop.v, loop_area_km2(loop) <= MAX_AREA_KM2
    )


def _rand_small_polygon(rng):
    """Random star polygon that stays simple ON THE SPHERE: geodesic
    edges bow away from their lat/lng chords by up to ~3e-6 rad at high
    latitude, so thin slivers (near-equal vertex angles or tiny radii)
    can self-intersect spherically even when the lat/lng polygon is
    simple — invalid input for loop semantics (ours and the
    reference's S2 alike).  Min radius + min angular gap keep every
    feature far wider than the bowing."""
    lat0 = float(rng.uniform(-60, 60))
    lng0 = float(rng.uniform(-179, 179))
    n = int(rng.integers(3, 8))
    gaps = rng.uniform(1.0, 2.0, n)
    angles = np.cumsum(gaps) / np.sum(gaps) * 2 * np.pi
    radii = rng.uniform(0.02, 0.08, n)  # degrees
    pts = [
        (lat0 + r * np.sin(a), lng0 + r * np.cos(a))
        for a, r in zip(angles, radii)
    ]
    return pts


def test_differential_random_polygons():
    rng = np.random.default_rng(7)
    checked = 0
    for _ in range(150):
        pts = _rand_small_polygon(rng)
        loop = Loop(np.asarray([latlng_to_xyz(la, ln) for la, ln in pts]))
        if loop_area_km2(loop) > MAX_AREA_KM2:
            loop = Loop(loop.v[::-1])
        if not (0 < loop_area_km2(loop) <= MAX_AREA_KM2):
            continue
        got = _native_loop_covering(loop)
        if got is None:
            continue  # fallback condition (multi-face etc.)
        want = _numpy_loop_covering(loop)
        np.testing.assert_array_equal(got, want)
        assert got.size > 0
        checked += 1
    assert checked > 100  # the fast path must actually engage


def test_differential_circles():
    rng = np.random.default_rng(21)
    checked = 0
    for _ in range(60):
        lat = float(rng.uniform(-65, 65))
        lng = float(rng.uniform(-179, 179))
        radius = float(rng.uniform(50, 8000))
        want_cells = covering_circle(lat, lng, radius)
        # covering_circle dispatches through the native path when
        # available; recompute via the BFS reference
        center = covering.latlng_to_xyz(lat, lng)
        import math

        z = center
        x = covering._ortho(z)
        y = covering._cross3(z, x)
        y = y / np.linalg.norm(y)
        ra = radius / covering.RADIUS_EARTH_METER
        pts = []
        for k in range(20):
            th = 2.0 * math.pi * k / 20.0
            p = math.cos(ra) * z + math.sin(ra) * (
                math.cos(th) * x + math.sin(th) * y
            )
            pts.append(p / np.linalg.norm(p))
        loop = Loop(np.asarray(pts))
        if loop_area_km2(loop) <= 0:
            continue
        want = _numpy_loop_covering(loop)
        np.testing.assert_array_equal(want_cells, want)
        checked += 1
    assert checked > 40


def test_multiface_falls_back():
    # a polygon straddling a face boundary must return None (BFS path)
    pts = [(0.5, 44.9), (0.5, 45.1), (0.6, 45.1), (0.6, 44.9)]
    loop = Loop(np.asarray([latlng_to_xyz(la, ln) for la, ln in pts]))
    faces = covering.xyz_to_face_uv(loop.v)[0]
    if len(set(int(f) for f in np.atleast_1d(faces))) > 1:
        assert _native_loop_covering(loop) is None


def test_area_gate_falls_back():
    pts = [(0.0, 0.0), (0.0, 0.05), (0.05, 0.05), (0.05, 0.0)]
    loop = Loop(np.asarray([latlng_to_xyz(la, ln) for la, ln in pts]))
    assert native.loop_covering(loop.v, area_ok=False) is None


def test_points_covering_full_path_differential():
    """dss_points_covering (winding retry + area gate + rect) vs the
    pure-Python covering_from_loop_points internals."""
    rng = np.random.default_rng(99)
    checked = 0
    for _ in range(80):
        pts = _rand_small_polygon(rng)
        if rng.random() < 0.5:
            pts = pts[::-1]  # CW input exercises the winding retry
        xyz = np.asarray([latlng_to_xyz(la, ln) for la, ln in pts])
        try:
            got = native.points_covering(xyz, MAX_AREA_KM2)
        except native.AreaTooLarge:
            got = "too_large"
        except native.Degenerate:
            got = "degenerate"
        if got is None:
            continue
        # python reference (bypassing the native dispatch)
        loop = Loop(xyz)
        area = loop_area_km2(loop)
        if area > MAX_AREA_KM2:
            loop = Loop(xyz[::-1])
            area = loop_area_km2(loop)
        if area > MAX_AREA_KM2:
            want = "too_large"
        elif area <= 0:
            want = "degenerate"
        else:
            want = _numpy_loop_covering(loop)
        if isinstance(want, str) or isinstance(got, str):
            assert got == want if isinstance(want, str) else False
        else:
            np.testing.assert_array_equal(got, want)
        checked += 1
    assert checked > 50


def test_points_covering_area_gate_and_message():
    # a ~60 km square: over the 2500 km2 gate in BOTH windings
    pts = [(0.0, 0.0), (0.0, 1.0), (1.0, 1.0), (1.0, 0.0)]
    xyz = np.asarray([latlng_to_xyz(la, ln) for la, ln in pts])
    import dss_tpu.geo.covering as C

    try:
        C.covering_from_loop_points(xyz)
        raised = False
    except C.AreaTooLargeError as e:
        raised = True
        assert "area is too large" in str(e)
    assert raised


def test_polygon_end_to_end_matches_bfs():
    # full covering_polygon path (native engaged) vs forced-BFS result
    pts = [(37.0, -122.0), (37.05, -122.0), (37.05, -122.05), (37.0, -122.05)]
    got = covering_polygon(pts)
    loop = Loop(np.asarray([latlng_to_xyz(la, ln) for la, ln in pts]))
    want = _numpy_loop_covering(loop)
    np.testing.assert_array_equal(got, want)
