"""Deterministic fault injection, the shared retry policy, and the
graceful-degradation ladder (ISSUE 11 tentpole).

Unit tier: FaultPlan scheduling is replayable byte-for-byte, the three
legacy retry loops (RegionClient transport, mirror sender, coordinator
conflict cool-down) ride ONE jittered policy with pinned bounds,
circuit breakers walk closed/open/half-open, and the ladder makes the
planner's device-class routes inadmissible under DEVICE_LOST while the
coalescer absorbs in-flight device losses (host re-run, no caller
error).  The store-level differential (faulted run == no-fault oracle)
lives in test_store_fuzz; the replicate-link-flap promotion fencing in
test_region_mirror; the end-to-end scenarios in bench.py --leg chaos.
"""

from __future__ import annotations

import asyncio
import threading
import time

import numpy as np
import pytest

from dss_tpu import chaos, errors


@pytest.fixture(autouse=True)
def _clean_registry():
    """The fault registry is process-global: every test starts and
    ends with no plan installed and fresh counters."""
    chaos.clear_plan()
    chaos.registry().reset_counters()
    yield
    chaos.clear_plan()
    chaos.registry().reset_counters()


# -- fault plans -------------------------------------------------------------


def test_fault_point_is_noop_without_plan():
    chaos.fault_point("wal.fsync")
    chaos.fault_point("device.dispatch")
    # the zero-overhead gate: no plan -> not even a hit is counted
    assert chaos.registry().hits_by_site() == {}


def test_event_after_count_window():
    chaos.install_plan(
        {"events": [{"site": "s", "action": "error", "after": 2,
                     "count": 2}]}
    )
    fired = []
    for i in range(6):
        try:
            chaos.fault_point("s")
            fired.append(False)
        except chaos.FaultError:
            fired.append(True)
    # hits 1-2 skipped, 3-4 inject, 5-6 exhausted
    assert fired == [False, False, True, True, False, False]
    assert chaos.registry().injected_by_site() == {"s": 2}
    assert chaos.registry().hits_by_site() == {"s": 6}


def test_match_filters_on_detail():
    chaos.install_plan(
        {"events": [{"site": "s", "match": "/replicate", "count": -1}]}
    )
    chaos.fault_point("s", detail="http://a/mirror/register")  # no match
    with pytest.raises(chaos.FaultError):
        chaos.fault_point("s", detail="http://a/replicate")


def test_actions_raise_typed_errors():
    chaos.install_plan(
        {"events": [
            {"site": "a", "action": "device_lost", "count": -1},
            {"site": "b", "action": "partition", "count": -1},
        ]}
    )
    with pytest.raises(chaos.DeviceLostError):
        chaos.fault_point("a")
    with pytest.raises(chaos.FaultError) as ei:
        chaos.fault_point("b")
    assert ei.value.kind == "partition"
    assert chaos.is_device_loss(chaos.DeviceLostError("a"))
    assert not chaos.is_device_loss(RuntimeError("x"))


def test_delay_action_sleeps():
    chaos.install_plan(
        {"events": [{"site": "s", "action": "delay",
                     "delay_s": 0.05, "count": 1}]}
    )
    t0 = time.perf_counter()
    chaos.fault_point("s")
    assert time.perf_counter() - t0 >= 0.045
    t0 = time.perf_counter()
    chaos.fault_point("s")  # exhausted: no delay
    assert time.perf_counter() - t0 < 0.02


def test_async_fault_point_delay_and_error():
    chaos.install_plan(
        {"events": [
            {"site": "s", "action": "delay", "delay_s": 0.03, "count": 1},
            {"site": "s", "action": "error", "count": 1},
        ]}
    )

    async def run():
        t0 = time.perf_counter()
        await chaos.async_fault_point("s")
        assert time.perf_counter() - t0 >= 0.025
        with pytest.raises(chaos.FaultError):
            await chaos.async_fault_point("s")

    asyncio.run(run())


def test_probabilistic_events_replay_byte_identical():
    """Same seed + same hit sequence -> the SAME injections, run after
    run — the replayability contract the fuzz oracle depends on."""

    def run_once():
        plan = chaos.FaultPlan.from_dict(
            {"seed": 42, "events": [
                {"site": "s", "p": 0.5, "count": -1},
            ]}
        )
        chaos.install_plan(plan)
        out = []
        for _ in range(64):
            try:
                chaos.fault_point("s")
                out.append(0)
            except chaos.FaultError:
                out.append(1)
        chaos.clear_plan()
        return out

    a, b = run_once(), run_once()
    assert a == b
    assert 0 < sum(a) < 64  # p=0.5 actually thins

    # a different seed draws a different schedule
    plan = chaos.FaultPlan.from_dict(
        {"seed": 43, "events": [{"site": "s", "p": 0.5, "count": -1}]}
    )
    chaos.install_plan(plan)
    c = []
    for _ in range(64):
        try:
            chaos.fault_point("s")
            c.append(0)
        except chaos.FaultError:
            c.append(1)
    assert c != a


def test_env_plan_inline_json(monkeypatch):
    monkeypatch.setenv(
        "DSS_FAULT_PLAN",
        '{"seed": 1, "events": [{"site": "s", "count": 1}]}',
    )
    assert chaos.load_env_plan()
    with pytest.raises(chaos.FaultError):
        chaos.fault_point("s")


def test_env_plan_file(tmp_path, monkeypatch):
    p = tmp_path / "plan.json"
    p.write_text('{"events": [{"site": "s", "count": 1}]}')
    monkeypatch.setenv("DSS_FAULT_PLAN", str(p))
    assert chaos.load_env_plan()
    with pytest.raises(chaos.FaultError):
        chaos.fault_point("s")


def test_unknown_action_rejected():
    with pytest.raises(ValueError):
        chaos.FaultEvent("s", "explode")


# -- retry policy ------------------------------------------------------------


def test_retry_policy_bounds_and_cap():
    pol = chaos.RetryPolicy(
        base_s=0.1, cap_s=2.0, multiplier=2.0, jitter=0.5
    )
    for attempt, raw in ((0, 0.1), (1, 0.2), (2, 0.4), (10, 2.0)):
        assert pol.raw_backoff_s(attempt) == pytest.approx(raw)
        for _ in range(32):
            d = pol.backoff_s(attempt)
            assert raw * 0.5 <= d <= raw * 1.5


def test_retry_policy_survives_unbounded_attempt_counters():
    """Callers feed raw failure streaks (a mirror flapping for an
    hour): the exponent must clamp before exponentiating, or the
    backoff call itself raises OverflowError inside the retry loop."""
    pol = chaos.RetryPolicy(base_s=0.1, cap_s=2.0)
    for attempt in (1_000, 10_000, 10**9):
        assert pol.raw_backoff_s(attempt) == 2.0
        assert 1.0 <= pol.backoff_s(attempt) <= 3.0


def test_retry_policy_seeded_determinism():
    a = chaos.RetryPolicy(base_s=0.1, cap_s=1.0, seed=7)
    b = chaos.RetryPolicy(base_s=0.1, cap_s=1.0, seed=7)
    assert [a.backoff_s(i) for i in range(8)] == [
        b.backoff_s(i) for i in range(8)
    ]


def test_retry_policy_sleep_respects_deadline():
    pol = chaos.RetryPolicy(base_s=10.0, cap_s=10.0, jitter=0.0)
    slept = []
    d = chaos.Deadline(0.02)
    assert pol.sleep(0, d, sleep_fn=slept.append) <= 0.02
    assert len(slept) == 1 and slept[0] <= 0.02
    time.sleep(0.025)
    assert d.expired()
    assert pol.sleep(0, d, sleep_fn=slept.append) == 0.0
    assert len(slept) == 1  # expired budget -> no sleep at all


# -- circuit breaker ---------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_transitions():
    clk = FakeClock()
    b = chaos.CircuitBreaker(fail_threshold=3, reset_s=5.0, clock=clk)
    assert b.state == chaos.BREAKER_CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == chaos.BREAKER_CLOSED  # below threshold
    b.record_failure()
    assert b.state == chaos.BREAKER_OPEN
    assert not b.allow()
    assert b.cooldown_remaining_s() == pytest.approx(5.0)
    clk.t += 5.1
    # cooldown elapsed: half-open, probes allowed
    assert b.state == chaos.BREAKER_HALF_OPEN
    assert b.allow()
    # failed probe re-opens immediately (no threshold re-accumulation)
    b.record_failure()
    assert b.state == chaos.BREAKER_OPEN
    clk.t += 5.1
    assert b.allow()
    b.record_success()
    assert b.state == chaos.BREAKER_CLOSED
    assert b.trips == 2


def test_breaker_registry_all_open_and_cooldown():
    clk = FakeClock()
    reg = chaos.BreakerRegistry(fail_threshold=1, reset_s=4.0, clock=clk)
    assert not reg.all_open()  # no remotes yet
    reg.get("a").record_failure()
    assert reg.all_open()
    reg.get("b")  # second remote, closed
    assert not reg.all_open()
    reg.get("b").record_failure()
    assert reg.all_open()
    assert reg.states() == {
        "a": chaos.BREAKER_OPEN, "b": chaos.BREAKER_OPEN,
    }
    assert reg.min_cooldown_s() == pytest.approx(4.0)


# -- degradation ladder ------------------------------------------------------


def test_ladder_severity_and_recovery_order():
    clk = FakeClock()
    lad = chaos.DegradationLadder(clock=clk)
    assert lad.mode() == chaos.HEALTHY
    assert lad.device_ok() and lad.region_ok()

    order = []
    lad.on_recover("device_lost", lambda: order.append("rewarm"))

    assert lad.enter("device_lost", "injected")
    assert not lad.enter("device_lost", "again")  # idempotent
    assert lad.mode() == chaos.DEVICE_LOST
    assert not lad.device_ok()

    lad.enter("region_log_down", "breakers open")
    assert lad.mode() == chaos.REGION_LOG_DOWN  # worst active wins
    lad.enter("mesh_degraded", "peer lost")
    assert lad.mode() == chaos.REGION_LOG_DOWN

    lad.exit("region_log_down")
    assert lad.mode() == chaos.MESH_DEGRADED
    lad.exit("mesh_degraded")
    assert lad.mode() == chaos.DEVICE_LOST

    # re-warm runs BEFORE the condition clears (re-admission gating)
    lad.on_recover(
        "device_lost",
        lambda: order.append(
            "still-lost" if not lad.device_ok() else "cleared-early"
        ),
    )
    clk.t += 3.0
    assert lad.exit("device_lost")
    assert order == ["rewarm", "still-lost"]
    assert not lad.exit("device_lost")  # already clear
    assert lad.mode() == chaos.HEALTHY
    assert lad.dwell_s("device_lost") == pytest.approx(3.0)
    st = lad.stats()
    assert st["dss_degraded_mode"] == 0.0
    assert st["dss_degraded_transitions"] == 6.0


def test_ladder_rejects_unknown_condition():
    lad = chaos.DegradationLadder()
    with pytest.raises(ValueError):
        lad.enter("flux_capacitor")


# -- planner under DEVICE_LOST ----------------------------------------------


def test_planner_device_lost_inadmissibility():
    from dss_tpu.plan import BatchShape
    from dss_tpu.plan.planner import (
        decide,
        enumerate_candidates,
        plan_drain_cap,
        state_of,
    )
    from dss_tpu.plan.costs import CostModel

    cost = CostModel(floor_ms=20.0, item_ms=0.02, chunk_ms=0.3)
    lost = state_of(
        cost, resident_ready=True, mesh_ready=True, device_ok=False
    )
    shape = BatchShape(n=128, all_stale=True)
    cand = enumerate_candidates(shape, lost, None)
    assert cand["device"] is None
    assert cand["resident"] is None
    assert cand["mesh"] is None  # the mesh is local device compute
    assert cand["hostchunk"] is not None

    # bulk and deadline drains both land on the host
    assert decide(shape, lost, None).route == "hostchunk"
    assert decide(BatchShape(n=128), lost, 50.0).route == "hostchunk"
    # lone small caller keeps the inline exact path
    assert decide(
        BatchShape(n=4, inline=True), lost, 50.0
    ).route == "inline"
    # inline under host_only (event loop) still picks inline, never a
    # device candidate that does not exist
    only = state_of(cost, device_ok=False, host_only=True)
    assert decide(
        BatchShape(n=4, inline=True), only, 50.0
    ).route == "inline"

    # drain caps size against the host when the device class is gone
    healthy = state_of(cost, device_ok=True)
    assert plan_drain_cap(512, 1000.0, healthy) == 512
    capped = plan_drain_cap(512, 10.0, lost)
    assert capped <= 512  # host sizing engaged, never the AIMD bypass

    # default is unchanged: device_ok=True reproduces the old policy
    assert decide(shape, state_of(cost), None).route == "device"


# -- coalescer absorbs device loss -------------------------------------------


class _FakePq:
    def __init__(self, results, fail=False):
        self._results = results
        self._fail = fail

    def wait_device(self):
        if self._fail:
            raise chaos.DeviceLostError("device.dispatch", "mid-flight")

    def used_device(self):
        return not self._fail


class _FakeTable:
    """query_many_submit/collect pair the coalescer drives; host_route
    submissions always succeed (the pure-host path)."""

    def __init__(self):
        self.host_batches = 0
        self.device_batches = 0
        self.fail_next_collect = False

    def _answers(self, keys_list):
        return [[f"id{int(k[0])}"] for k in keys_list]

    def query_many_submit(self, keys_list, lo, hi, t0s, t1s, *, now,
                          owner_ids=None, host_route=False, kernel=None):
        if host_route:
            self.host_batches += 1
            return _FakePq(self._answers(keys_list))
        self.device_batches += 1
        fail = self.fail_next_collect
        self.fail_next_collect = False
        return _FakePq(self._answers(keys_list), fail=fail)

    def query_many_collect(self, pq):
        pq.wait_device()
        return pq._results


def _mk_coalescer(table, **kw):
    from dss_tpu.dar.coalesce import QueryCoalescer

    kw.setdefault("min_batch", 1)
    kw.setdefault("inline", False)
    # device strongly preferred so the plan is deterministic
    kw.setdefault("est_floor_ms", 0.01)
    kw.setdefault("est_chunk_ms", 1000.0)
    return QueryCoalescer(table, **kw)


def test_coalescer_absorbs_injected_dispatch_loss():
    """An injected device loss at the cold dispatch seam: the batch is
    re-served as host chunks, callers get correct answers (no error),
    the ladder flips DEVICE_LOST, and the planner stops offering the
    device class until recovery."""
    table = _FakeTable()
    co = _mk_coalescer(table)
    lad = chaos.DegradationLadder()
    co.set_health(lad)
    chaos.install_plan(
        {"events": [{"site": "device.dispatch",
                     "action": "device_lost", "count": 1}]}
    )
    res = co.query(
        np.asarray([7], np.int32), None, None, None, None, now=0,
        allow_stale=True,
    )
    assert res == ["id7"]  # absorbed: the caller never saw the loss
    assert lad.is_active("device_lost")
    assert table.host_batches >= 1
    st = co.stats()
    assert st["co_device_loss_absorbed"] == 1
    assert st["co_device_ok"] == 0
    assert not co._capture_state().device_ok

    # while DEVICE_LOST, new batches plan hostward (no device submits)
    dev_before = table.device_batches
    res = co.query(
        np.asarray([9], np.int32), None, None, None, None, now=0,
        allow_stale=True,
    )
    assert res == ["id9"]
    assert table.device_batches == dev_before

    # recovery re-admits the device class
    lad.exit("device_lost")
    assert co.stats()["co_device_ok"] == 1
    res = co.query(
        np.asarray([3], np.int32), None, None, None, None, now=0,
        allow_stale=True,
    )
    assert res == ["id3"]
    assert table.device_batches == dev_before + 1
    co.close()


def test_coalescer_absorbs_collect_stage_loss():
    """Device loss AFTER submit (the in-flight batch's wait fails):
    the collect stage re-runs the batch on the host — the admitted
    caller still resolves with the right answer."""
    table = _FakeTable()
    table.fail_next_collect = True
    co = _mk_coalescer(table)
    lad = chaos.DegradationLadder()
    co.set_health(lad)
    res = co.query(
        np.asarray([5], np.int32), None, None, None, None, now=0,
        allow_stale=True,
    )
    assert res == ["id5"]
    assert lad.is_active("device_lost")
    assert co.stats()["co_device_loss_absorbed"] == 1
    assert table.host_batches == 1
    co.close()


def test_coalescer_delivers_non_loss_errors_unchanged():
    """Only device-loss shapes are absorbed: an ordinary failure still
    surfaces to the caller (no silent retry of arbitrary errors)."""
    table = _FakeTable()
    co = _mk_coalescer(table)
    chaos.install_plan(
        {"events": [{"site": "device.dispatch", "action": "error",
                     "count": 1}]}
    )
    with pytest.raises(chaos.FaultError):
        co.query(
            np.asarray([1], np.int32), None, None, None, None, now=0,
            allow_stale=True,
        )
    co.close()


# -- region client: shared policy + breakers + ladder ------------------------


class _FakeResponse:
    def __init__(self, status=200, body=None):
        self.status_code = status
        self._body = body or {}
        self.text = "x"

    def json(self):
        return self._body


class _FakeSession:
    """Scripted per-endpoint transport for RegionClient."""

    def __init__(self, behavior):
        # url-prefix -> callable() -> _FakeResponse (or raises)
        self.behavior = behavior
        self.headers = {}
        self.calls = []

    def request(self, method, url, timeout=None, **kw):
        self.calls.append(url)
        for prefix, fn in self.behavior.items():
            if url.startswith(prefix):
                return fn()
        raise AssertionError(f"unscripted url {url}")


def _conn_err():
    import requests

    raise requests.ConnectionError("refused")


def test_client_failover_prefers_closed_breakers():
    from dss_tpu.region.client import RegionClient

    c = RegionClient(
        "http://a:1,http://b:1", "i", retry_deadline_s=5.0,
        max_retries=4,
    )
    c._retry_policy = chaos.RetryPolicy(base_s=0.0, cap_s=0.0)
    sess = _FakeSession({
        "http://a:1": _conn_err,
        "http://b:1": lambda: _FakeResponse(200, {"head": 3}),
    })
    c._session = sess
    # first call fails over a -> b and succeeds
    r = c._request("GET", "/records")
    assert r.status_code == 200
    states = c.breaker_states()
    assert states["http://b:1"] == chaos.BREAKER_CLOSED
    # burn a's breaker open, then verify fresh calls go straight to b
    for _ in range(4):
        try:
            c._active = 0
            c._request("GET", "/records")
        except Exception:
            pass
    assert c.breaker_states()["http://a:1"] == chaos.BREAKER_OPEN
    c._active = 1  # active endpoint is b after the failovers
    sess.calls.clear()
    assert c._request("GET", "/records").status_code == 200
    assert all(u.startswith("http://b:1") for u in sess.calls)


def test_client_outage_drives_ladder_and_retry_after():
    from dss_tpu.region.client import RegionClient, RegionError

    lad = chaos.DegradationLadder()
    c = RegionClient(
        "http://a:1", "i", retry_deadline_s=0.2, max_retries=1,
        health=lad,
    )
    c._retry_policy = chaos.RetryPolicy(base_s=0.0, cap_s=0.0)
    c._session = _FakeSession({"http://a:1": _conn_err})
    # enough failed calls to open the only breaker (threshold 3)
    for _ in range(3):
        with pytest.raises(RegionError):
            c._request("GET", "/records")
    assert lad.is_active("region_log_down")
    assert lad.mode() == chaos.REGION_LOG_DOWN
    assert c.retry_after_s() >= 0.5
    # recovery: one success walks the ladder back down
    c._session = _FakeSession(
        {"http://a:1": lambda: _FakeResponse(200, {"head": 0})}
    )
    c._request("GET", "/records")
    assert not lad.is_active("region_log_down")
    assert lad.mode() == chaos.HEALTHY


def test_client_injected_partition_retries_like_transport():
    from dss_tpu.region.client import RegionClient

    c = RegionClient("http://a:1", "i", retry_deadline_s=5.0)
    c._retry_policy = chaos.RetryPolicy(base_s=0.0, cap_s=0.0)
    c._session = _FakeSession(
        {"http://a:1": lambda: _FakeResponse(200, {"head": 0})}
    )
    chaos.install_plan(
        {"events": [{"site": "region.client.request",
                     "action": "partition", "count": 2}]}
    )
    # two injected partitions, then the transport recovers: the call
    # succeeds without surfacing anything
    assert c._request("GET", "/records").status_code == 200
    assert chaos.registry().injected_by_site()[
        "region.client.request"
    ] == 2


# -- coordinator conflict cool-down ------------------------------------------


class _StubRegionClient:
    lease_ttl_s = 10.0

    def retry_after_s(self):
        return 2.5

    def release_lease(self, token):
        pass


def _mk_coordinator(cap=2.0):
    from dss_tpu.region.coordinator import RegionCoordinator

    return RegionCoordinator(
        _StubRegionClient(), None, None, threading.RLock(),
        conflict_backoff_s=cap,
    )


def test_conflict_backoff_jittered_growing_capped():
    coord = _mk_coordinator(cap=2.0)
    d0 = coord._conflict_cooldown_s()
    assert 0.25 <= d0 <= 0.75  # base 0.5, jitter +/-50%
    d1 = coord._conflict_cooldown_s()
    assert 0.5 <= d1 <= 1.5  # doubled
    # the streak caps (never exceeds cap * (1+jitter))
    draws = [coord._conflict_cooldown_s() for _ in range(16)]
    assert all(d <= 2.0 * 1.5 + 1e-9 for d in draws)
    assert all(d >= 2.0 * 0.5 - 1e-9 for d in draws[2:])
    # colliding coordinators cannot re-collide in lockstep: repeated
    # draws at the same streak depth are not one constant
    assert len({round(d, 6) for d in draws}) > 1
    # a successful optimistic commit resets the streak
    coord._conflict_streak = 0
    assert coord._conflict_cooldown_s() <= 0.75


def test_coordinator_unavailable_carries_retry_after():
    coord = _mk_coordinator()
    e = coord._unavailable("region log down")
    assert isinstance(e, errors.StatusError)
    assert e.http_status == 503
    assert e.retry_after_s == 2.5


# -- mirror sender backoff ---------------------------------------------------


def test_mirror_sender_backoff_capped_and_exported():
    from dss_tpu.region import mirror as mirror_mod
    from dss_tpu.region.log_server import RegionLog
    from dss_tpu.region.mirror import RegionNode, _MirrorPeer

    pol = mirror_mod._SENDER_BACKOFF
    # fails=1 draws the base; deep fail streaks cap at 2.0 (+jitter)
    assert 0.05 <= pol.backoff_s(0) <= 0.15
    for k in range(12):
        assert pol.backoff_s(k) <= 2.0 * 1.5 + 1e-9

    node = RegionNode(RegionLog(None))
    m = _MirrorPeer("http://m", 0, epoch=node.log.epoch)
    m.backoff_s = 1.25
    node.mirrors = {m.url: m}
    text = node.render_metrics()
    assert "region_mirror_backoff_s 1.25" in text
    assert node.status()["mirrors"]["http://m"]["backoff_s"] == 1.25


# -- wal fault sites ---------------------------------------------------------


def test_wal_append_fault_leaves_log_consistent(tmp_path):
    from dss_tpu.dar.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "w.log"))
    chaos.install_plan(
        {"events": [{"site": "wal.append", "count": 1}]}
    )
    with pytest.raises(chaos.FaultError):
        wal.append({"t": "x"})
    # the injected failure happened BEFORE any bytes or seq: the next
    # append is record 1 and replay sees exactly one record
    assert wal.append({"t": "y"}) == 1
    wal.close()
    wal2 = WriteAheadLog(str(tmp_path / "w.log"))
    assert [r["t"] for r in wal2.replay()] == ["y"]
    wal2.close()


def test_wal_fsync_stall_injection(tmp_path):
    from dss_tpu.dar.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "w.log"), fsync=True)
    chaos.install_plan(
        {"events": [{"site": "wal.fsync", "action": "delay",
                     "delay_s": 0.05, "count": 1}]}
    )
    t0 = time.perf_counter()
    wal.append({"t": "x"})
    stalled = time.perf_counter() - t0
    t0 = time.perf_counter()
    wal.append({"t": "y"})
    clean = time.perf_counter() - t0
    assert stalled >= 0.045 and stalled > clean
    assert chaos.registry().injected_by_site()["wal.fsync"] == 1
    wal.close()


# -- store surface -----------------------------------------------------------


def test_store_exports_health_and_fault_gauges():
    from dss_tpu.dar.dss_store import DSSStore

    store = DSSStore(storage="memory")
    try:
        st = store.stats()
        assert st["dss_degraded_mode"] == 0.0
        assert st["dss_breaker_state"] == {}
        assert isinstance(st["dss_fault_injected_total"], dict)
        fs = store.freshness_status()
        assert fs["degraded_mode"] == "healthy"
        assert fs["degraded"] == {}

        store.health.enter("device_lost", "injected")
        assert store.stats()["dss_degraded_mode"] == float(chaos.DEVICE_LOST)
        fs = store.freshness_status()
        assert fs["degraded_mode"] == "device_lost"
        assert fs["degraded"]["device_lost"]["reason"] == "injected"
        store.health.exit("device_lost")
    finally:
        store.close()


def test_cache_populate_fault_degrades_to_miss(monkeypatch):
    """An injected cache-population failure must cost a future miss,
    never a wrong or failed answer."""
    from datetime import datetime, timedelta, timezone

    monkeypatch.setenv("DSS_CACHE_ENABLE", "1")
    from dss_tpu.dar.dss_store import DSSStore

    store = DSSStore(storage="memory")
    try:
        import uuid

        from dss_tpu.geo.covering import canonical_cells
        from dss_tpu.models import rid as ridm

        now = datetime.now(timezone.utc)
        isa = ridm.IdentificationServiceArea(
            id=str(uuid.uuid4()), owner="u1", url="https://u/f",
            cells=np.asarray([123], np.uint64),
            altitude_lo=0.0, altitude_hi=100.0,
            start_time=now - timedelta(minutes=1),
            end_time=now + timedelta(hours=1),
            version=None,
        )
        assert store.rid.insert_isa(isa) is not None
        cells = canonical_cells(np.asarray([123], np.uint64))
        chaos.install_plan(
            {"events": [{"site": "cache.populate", "count": 1}]}
        )
        a = [x.id for x in store.rid.search_isas(cells, now, None)]
        assert a == [isa.id]  # the answer survived the injection
        st0 = store.cache.stats()
        assert st0["entries"] == 0  # population was dropped
        # next search is a miss again, then populates normally
        b = [x.id for x in store.rid.search_isas(cells, now, None)]
        assert b == a
        assert store.cache.stats()["entries"] == 1
    finally:
        store.close()
