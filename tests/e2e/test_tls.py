"""HTTPS smoke: the real server binaries terminate TLS themselves when
given --tls_cert/--tls_key, consuming deploy/make_certs.py output (the
direct-TLS alternative to ingress termination; VERDICT r5 ask #9)."""

from __future__ import annotations

import subprocess
import sys
import time

import pytest
import requests

from tests.e2e.conftest import REPO, Proc, free_port


def _openssl_trust(out) -> None:
    """Fallback CA + localhost server cert via the openssl CLI, in the
    same file layout make_certs.py emits (the provisioning tool needs
    the `cryptography` package; the TLS listeners themselves must stay
    testable without it)."""

    def run(*argv):
        r = subprocess.run(argv, capture_output=True, timeout=60)
        assert r.returncode == 0, r.stderr.decode()

    ext = out / "san.cnf"
    ext.write_text("subjectAltName=DNS:localhost\n")
    run(
        "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(out / "ca.key"), "-out", str(out / "ca.crt"),
        "-days", "30", "-subj", "/CN=dss-test-ca",
    )
    run(
        "openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(out / "server.key"),
        "-out", str(out / "server.csr"),
        "-subj", "/CN=localhost",
    )
    run(
        "openssl", "x509", "-req", "-in", str(out / "server.csr"),
        "-CA", str(out / "ca.crt"), "-CAkey", str(out / "ca.key"),
        "-CAcreateserial", "-out", str(out / "server.crt"),
        "-days", "30", "-extfile", str(ext),
    )


@pytest.fixture(scope="module")
def tls_trust(tmp_path_factory):
    """deploy/make_certs.py trust material with a localhost SAN (or an
    openssl-CLI equivalent when `cryptography` is unavailable)."""
    out = tmp_path_factory.mktemp("trust")
    try:
        import cryptography  # noqa: F401
    except ImportError:
        import shutil

        if shutil.which("openssl") is None:
            pytest.skip("needs cryptography or the openssl CLI")
        _openssl_trust(out)
        return out
    r = subprocess.run(
        [
            sys.executable,
            str(REPO / "deploy" / "make_certs.py"),
            "--out", str(out),
            "--hosts", "localhost",
        ],
        capture_output=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr.decode()
    return out


def _wait_https(base: str, ca: str, proc, what: str, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            err = proc.stderr.read().decode(errors="replace")[-4000:]
            raise RuntimeError(f"{what} exited at startup:\n{err}")
        try:
            r = requests.get(f"{base}/healthy", verify=ca, timeout=1)
            if r.status_code == 200:
                return r
        except requests.RequestException:
            pass
        time.sleep(0.1)
    raise RuntimeError(f"{what} never served HTTPS at {base}")


def test_dss_server_serves_https(tls_trust):
    port = free_port()
    p = Proc(
        [
            "dss_tpu.cmds.server",
            "--addr", f"127.0.0.1:{port}",
            "--storage", "memory",
            "--insecure_no_auth",
            "--tls_cert", str(tls_trust / "server.crt"),
            "--tls_key", str(tls_trust / "server.key"),
        ],
        "dss-server-tls",
    )
    ca = str(tls_trust / "ca.crt")
    base = f"https://localhost:{port}"
    try:
        r = _wait_https(base, ca, p.p, "dss-server-tls")
        assert r.status_code == 200
        # the chain must actually verify against OUR CA, not be
        # accepted blindly: default trust roots reject it
        with pytest.raises(requests.exceptions.SSLError):
            requests.get(f"{base}/healthy", timeout=2)
        # and a plaintext client on the same port gets no HTTP answer
        with pytest.raises(requests.RequestException):
            requests.get(f"http://127.0.0.1:{port}/healthy", timeout=2)
    finally:
        p.stop()


def test_region_server_serves_https(tls_trust):
    port = free_port()
    p = Proc(
        [
            "dss_tpu.cmds.region_server",
            "--addr", f"127.0.0.1:{port}",
            "--tls_cert", str(tls_trust / "server.crt"),
            "--tls_key", str(tls_trust / "server.key"),
        ],
        "region-server-tls",
    )
    ca = str(tls_trust / "ca.crt")
    base = f"https://localhost:{port}"
    try:
        _wait_https(base, ca, p.p, "region-server-tls")
    finally:
        p.stop()
