"""Kill-the-primary failover e2e (the ISSUE 2 acceptance run): a
replicated region — primary + two mirrors, every one a real OS process
of the deployable binary — takes quorum=2 writes, loses the primary to
SIGKILL mid-traffic, promotes the most-caught-up mirror through the
`--promote` CLI, and proves the replication contract end to end:

  - zero acked writes lost (every quorum-acked entry is on the new
    primary, byte-for-byte, at its original index);
  - the promotion bumped the PERSISTED epoch generation;
  - the multi-URL RegionClient fails over automatically and resumes
    committing, as does a full DSS instance riding the coordinator;
  - the dead primary, restarted on its own WAL, is FENCED: it can
    never ack a write again (quorum unreachable — its mirrors moved
    on), and re-mirroring it on a fresh WAL converges it to the new
    primary's log.

The in-process tier of the same machinery (quorum math, epoch rules,
catch-up, stale-primary rejection) lives in tests/test_region_mirror.py.
"""

from __future__ import annotations

import subprocess
import sys
import time
import uuid

import requests

from dss_tpu.region.client import EpochChanged, RegionClient, RegionError
from dss_tpu.region.log_server import epoch_gen
from tests.e2e.conftest import REPO, Proc, free_port, wait_healthy
from tests.e2e.test_blackbox import isa_params

DEADLINE_S = 30.0


def wait_until(fn, deadline_s=DEADLINE_S, what="condition"):
    t0 = time.monotonic()
    while True:
        v = fn()
        if v is not None:
            return v
        if time.monotonic() - t0 > deadline_s:
            raise AssertionError(f"{what} not reached in {deadline_s}s")
        time.sleep(0.05)


def region_proc(port, wal, *, quorum=None, mirror_of=None, what="region"):
    argv = [
        "dss_tpu.cmds.region_server",
        "--addr", f":{port}",
        "--wal_path", str(wal),
        "--repl_timeout", "2.0",
    ]
    if quorum is not None:
        argv += ["--quorum", str(quorum)]
    if mirror_of is not None:
        argv += ["--mirror_of", mirror_of]
    p = Proc(argv, what)
    wait_healthy(f"http://127.0.0.1:{port}/healthy", p.p, what)
    return p


def status(url):
    return requests.get(f"{url}/status", timeout=5).json()


def test_kill_primary_promote_mirror_no_acked_write_lost(tmp_path_factory):
    d = tmp_path_factory.mktemp("failover")
    pp, mp1, mp2 = free_port(), free_port(), free_port()
    p_url = f"http://127.0.0.1:{pp}"
    m_urls = [f"http://127.0.0.1:{mp1}", f"http://127.0.0.1:{mp2}"]

    procs = []
    instance = None
    try:
        primary = region_proc(
            pp, d / "p.wal", quorum=2, what="region-primary"
        )
        procs.append(primary)
        # mirrors also carry --quorum 2: it is what they will ENFORCE
        # once promoted (a failed-over region keeps its durability bar)
        for port, wal, what in (
            (mp1, d / "m1.wal", "region-mirror-1"),
            (mp2, d / "m2.wal", "region-mirror-2"),
        ):
            procs.append(
                region_proc(port, wal, quorum=2, mirror_of=p_url, what=what)
            )

        # a DSS instance joined through the full endpoint list rides
        # the same failover at the coordinator tier
        iport = free_port()
        instance = Proc(
            [
                "dss_tpu.cmds.server",
                "--addr", f":{iport}",
                "--storage", "memory",
                "--region_url", ",".join([p_url] + m_urls),
                "--region_poll_interval", "0.02",
                "--instance_id", "failover-dss",
                "--insecure_no_auth",
                "--no_warmup",
            ],
            "failover-dss",
        )
        ibase = f"http://127.0.0.1:{iport}"
        wait_healthy(f"{ibase}/healthy", instance.p, "failover-dss")

        isa1 = str(uuid.uuid4())
        r = requests.put(
            f"{ibase}/v1/dss/identification_service_areas/{isa1}",
            json=isa_params(lat=48.7),
            timeout=30,
        )
        assert r.status_code == 200, r.text

        # -- traffic: every ack is recorded; the server must never
        # lose one past this point ------------------------------------
        writer = RegionClient(
            [p_url] + m_urls, "e2e-writer",
            retry_deadline_s=2.0, max_retries=3, acquire_timeout_s=5.0,
        )
        acked = {}  # entry index -> payload i

        def try_write(i):
            try:
                tok, _ = writer.acquire_lease()
                idx = writer.append(
                    tok, [{"t": "traffic", "i": i}], release=True
                )
                acked[idx] = i
                return True
            except EpochChanged:
                writer.adopt_epoch()
                return None
            except RegionError:
                return None

        for i in range(8):
            wait_until(lambda i=i: try_write(i), what=f"write {i}")
        old_epoch = writer._epoch
        assert old_epoch is not None

        # -- SIGKILL the primary mid-traffic ---------------------------
        primary.p.kill()
        primary.p.wait(timeout=10)
        # in-flight/new writes fail while there is no primary; none of
        # these may land as acks
        for i in range(100, 103):
            assert try_write(i) is None

        # -- promote the most-caught-up mirror (the runbook) -----------
        heads = {u: status(u)["head"] for u in m_urls}
        new_primary = max(m_urls, key=lambda u: heads[u])
        other = next(u for u in m_urls if u != new_primary)
        # quorum=2 acks guarantee the max-head survivor holds EVERY
        # acked entry — the zero-loss core of the acceptance criteria
        assert heads[new_primary] >= max(acked) + 1

        out = subprocess.run(
            [
                sys.executable, "-m", "dss_tpu.cmds.region_server",
                "--promote",
                "--addr", f":{new_primary.rsplit(':', 1)[1]}",
            ],
            cwd=REPO, capture_output=True, timeout=30,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        st = status(new_primary)
        assert st["role"] == "primary"
        assert epoch_gen(st["epoch"]) == epoch_gen(old_epoch) + 1
        r = requests.post(
            f"{other}/repoint", json={"primary": new_primary}, timeout=5
        )
        assert r.status_code == 200, r.text

        # -- clients fail over and commits resume ----------------------
        for i in range(8, 12):
            wait_until(lambda i=i: try_write(i), what=f"post-failover {i}")
        assert writer.base == new_primary
        assert writer.failovers >= 1

        # ZERO acked writes lost: every acked index holds its exact
        # payload on the new primary
        probe = RegionClient(new_primary, "e2e-probe")
        entries, head = probe.fetch(0)
        by_idx = {idx: recs for idx, recs in entries}
        for idx, i in sorted(acked.items()):
            assert by_idx.get(idx) == [{"t": "traffic", "i": i}], (
                f"acked entry {idx} (payload {i}) lost or rewritten"
            )
        assert not any(
            rec.get("i", 0) >= 100
            for recs in by_idx.values() for rec in recs
            if rec.get("t") == "traffic"
        ), "an unacked write from the dead window leaked into the log"

        # the DSS instance (coordinator tier) resyncs to the new epoch
        # and resumes committing; the pre-failover ISA survived
        isa2 = str(uuid.uuid4())
        def instance_write():
            r = requests.put(
                f"{ibase}/v1/dss/identification_service_areas/{isa2}",
                json=isa_params(lat=49.9),
                timeout=30,
            )
            return True if r.status_code == 200 else None
        wait_until(instance_write, what="instance write after failover")
        r = requests.get(
            f"{ibase}/v1/dss/identification_service_areas/{isa1}",
            timeout=5,
        )
        assert r.status_code == 200, r.text

        # -- the dead primary returns... and is fenced -----------------
        # A supervisor restarts it AS A PRIMARY on its own WAL.  The
        # SIGKILL left no clean-shutdown marker, so boot rotates the
        # epoch — and a replicated primary (quorum>=2) that booted
        # through a recovery rotation refuses primacy outright until
        # an operator confirms it: no write can ever be acked, no
        # push can wipe a mirror.  Split-brain becomes unavailability
        # on the stale side, not divergence.
        zombie = region_proc(
            pp, d / "p.wal", quorum=2, what="region-zombie"
        )
        procs.append(zombie)
        zst = status(p_url)
        assert zst["role"] == "demoted" and zst["diverged"], zst
        pinned = RegionClient(
            p_url, "e2e-zombie-writer",
            retry_deadline_s=1.0, max_retries=1, acquire_timeout_s=3.0,
        )
        try:
            tok, _ = pinned.acquire_lease()
            pinned.append(tok, [{"t": "fenced"}], release=True)
            raise AssertionError("stale primary acked a write")
        except RegionError:
            pass
        zombie.stop()

        # -- re-mirror the old primary (runbook final step): fresh WAL,
        # --mirror_of the new primary; it converges to the region log
        remirrored = region_proc(
            pp, d / "p2.wal", mirror_of=new_primary,
            what="region-remirrored",
        )
        procs.append(remirrored)
        want_head = status(new_primary)["head"]
        wait_until(
            lambda: (
                status(p_url)["head"] >= want_head
                and status(p_url)["epoch"] == st["epoch"]
            ) or None,
            what="re-mirrored ex-primary catch-up",
        )
        entries, _ = RegionClient(p_url, "e2e-probe2").fetch(0)
        assert not any(
            rec.get("t") == "fenced" for _, recs in entries for rec in recs
        ), "the fenced write escaped into the region's history"
    finally:
        if instance is not None:
            instance.stop()
        for p in procs:
            p.stop()
