"""Prober-parity black-box suite against the live binaries.

Port of the reference's prober scenarios to the REST surface, run over
real sockets against real processes (tests/e2e/conftest.py):

  - ISA lifecycle + search-window expiry
    (monitoring/prober/rid/test_isa_simple.py)
  - subscription <-> ISA notification-index interplay
    (monitoring/prober/rid/test_subscription_isa_interactions.py)
  - two-USS OVN conflict flow with the AirspaceConflictResponse wire
    body (monitoring/prober/scd/test_operations_simple.py)
  - WAL checkpoint/resume through a real process restart
  - the same two-USS conflict ACROSS two DSS instances of one region
    (test/interoperability/interop_test_suite.py)
  - region log server SIGKILL + recovery (reads keep serving, failed
    writes roll back, the region resumes on the same WAL)
  - --workers multi-process serving with read-your-writes through the
    SO_REUSEPORT read workers
  - the --sharded_replica mesh surface
"""

from __future__ import annotations

import time
import uuid

import pytest
import requests

from tests.e2e.conftest import AUD, Proc, free_port, wait_healthy

RID_SCOPE = (
    "dss.read.identification_service_areas "
    "dss.write.identification_service_areas"
)
SCD_SCOPE = "utm.strategic_coordination"

# generous vs the 20 ms tail poll: only costs time on the failure path
# (see tests/test_region.py — contended 1-core CI hosts starve server
# processes for seconds mid-suite)
VISIBILITY_DEADLINE_S = 15.0


def now_iso(offset_s=0):
    t = time.time() + offset_s
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + "Z"


def isa_params(t0=60, t1=3600, lat=40.0, lng=-100.0):
    return {
        "extents": {
            "spatial_volume": {
                "footprint": {
                    "vertices": [
                        {"lat": lat, "lng": lng},
                        {"lat": lat + 0.02, "lng": lng},
                        {"lat": lat + 0.02, "lng": lng + 0.02},
                        {"lat": lat, "lng": lng + 0.02},
                    ]
                },
                "altitude_lo": 20.0,
                "altitude_hi": 400.0,
            },
            "time_start": now_iso(t0),
            "time_end": now_iso(t1),
        },
        "flights_url": "https://uss1.example.com/flights",
    }


def area_str(lat=40.0, lng=-100.0):
    return (
        f"{lat},{lng},{lat + 0.02},{lng},{lat + 0.02},{lng + 0.02},"
        f"{lat},{lng + 0.02}"
    )


def scd_extent(t0=60, t1=3600, lat=40.0, lng=-100.0):
    return {
        "volume": {
            "outline_polygon": {
                "vertices": [
                    {"lat": lat, "lng": lng},
                    {"lat": lat + 0.02, "lng": lng},
                    {"lat": lat + 0.02, "lng": lng + 0.02},
                    {"lat": lat, "lng": lng + 0.02},
                ]
            },
            "altitude_lower": {"value": 50.0, "reference": "W84", "units": "M"},
            "altitude_upper": {"value": 200.0, "reference": "W84", "units": "M"},
        },
        "time_start": {"value": now_iso(t0), "format": "RFC3339"},
        "time_end": {"value": now_iso(t1), "format": "RFC3339"},
    }


def op_body(uss="uss1", lat=40.0, key=None):
    return {
        "extents": [scd_extent(lat=lat)],
        "uss_base_url": f"https://{uss}.example.com",
        "new_subscription": {
            "uss_base_url": f"https://{uss}.example.com",
            "notify_for_constraints": False,
        },
        "state": "Accepted",
        "old_version": 0,
        "key": key or [],
    }


def test_healthy_and_validate_oauth(stack):
    base, oauth = stack["base"], stack["oauth"]
    assert requests.get(f"{base}/healthy", timeout=5).status_code == 200
    # no token -> 401 (interceptor chain order: auth before handler)
    r = requests.get(f"{base}/aux/v1/validate_oauth", timeout=5)
    assert r.status_code == 401
    r = requests.get(
        f"{base}/aux/v1/validate_oauth",
        headers=oauth.hdr(RID_SCOPE, sub="probe-user"),
        timeout=5,
    )
    assert r.status_code == 200, r.text


def test_isa_lifecycle_notifications_and_expiry(stack):
    """prober/rid: ISA CRUD; a subscription overlapping the ISA's area
    is returned as a subscriber-to-notify with a bumped
    notification_index on both create and delete; a search window past
    the ISA's end excludes it."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(RID_SCOPE)
    sub_id = str(uuid.uuid4())
    isa_id = str(uuid.uuid4())
    lat = 41.3  # own area: keep scenarios independent

    # subscription first (prober order), covering the same area
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{sub_id}",
        json={
            "extents": isa_params(lat=lat)["extents"],
            "callbacks": {
                "identification_service_area_url": "https://u2.example.com/isa"
            },
        },
        headers=oauth.hdr(RID_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    assert r.json()["subscription"]["notification_index"] == 0

    # ISA create notifies the subscriber with index 1
    r = requests.put(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        json=isa_params(lat=lat),
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200, r.text
    out = r.json()
    version = out["service_area"]["version"]
    subscribers = out["subscribers"]
    assert any(
        s["subscriptions"][0]["subscription_id"] == sub_id
        and s["subscriptions"][0]["notification_index"] == 1
        for s in subscribers
    ), subscribers

    # search finds it in-window...
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas",
        params={"area": area_str(lat=lat)},
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200
    assert any(
        s["id"] == isa_id for s in r.json()["service_areas"]
    )
    # ...and not when the window starts after the ISA ends (expiry)
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas",
        params={
            "area": area_str(lat=lat),
            "earliest_time": now_iso(4000),
            "latest_time": now_iso(5000),
        },
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200
    assert not any(
        s["id"] == isa_id for s in r.json()["service_areas"]
    )

    # delete (version-fenced) notifies again with index 2
    r = requests.delete(
        f"{base}/v1/dss/identification_service_areas/{isa_id}/{version}",
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200, r.text
    subscribers = r.json()["subscribers"]
    assert any(
        s["subscriptions"][0]["subscription_id"] == sub_id
        and s["subscriptions"][0]["notification_index"] == 2
        for s in subscribers
    ), subscribers


def test_two_uss_ovn_conflict_over_http(stack):
    """prober/scd/test_operations_simple.py: USS2 cannot claim airspace
    overlapping USS1's operation without presenting its OVN; the 409
    body is the AirspaceConflictResponse and hands USS2 the OVN it
    needs (pkg/scd/errors/errors.go:22-53)."""
    base, oauth = stack["base"], stack["oauth"]
    lat = 42.7
    op1, op2 = str(uuid.uuid4()), str(uuid.uuid4())

    r = requests.put(
        f"{base}/dss/v1/operation_references/{op1}",
        json=op_body("uss1", lat=lat),
        headers=oauth.hdr(SCD_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    ovn1 = r.json()["operation_reference"]["ovn"]
    assert ovn1

    # USS2, no key -> 409 AirspaceConflictResponse listing op1 + its OVN
    r = requests.put(
        f"{base}/dss/v1/operation_references/{op2}",
        json=op_body("uss2", lat=lat),
        headers=oauth.hdr(SCD_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 409, r.text
    body = r.json()
    assert body["message"]
    conflicts = body["entity_conflicts"]
    refs = [c["operation_reference"] for c in conflicts]
    assert any(ref["id"] == op1 for ref in refs), body
    assert ovn1 in [ref.get("ovn") for ref in refs], body

    # with the OVN as key, the claim succeeds
    r = requests.put(
        f"{base}/dss/v1/operation_references/{op2}",
        json=op_body("uss2", lat=lat, key=[ovn1]),
        headers=oauth.hdr(SCD_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 200, r.text


def test_wal_survives_process_restart(certs, oauth, tmp_path_factory):
    """Checkpoint/resume at the binary level: kill the server process,
    relaunch on the same WAL, state is intact (SURVEY.md §5)."""
    wal = tmp_path_factory.mktemp("restartwal") / "dss.wal"
    isa_id = str(uuid.uuid4())

    def launch():
        port = free_port()
        p = Proc(
            [
                "dss_tpu.cmds.server",
                "--addr", f":{port}",
                "--storage", "memory",
                "--wal_path", str(wal),
                "--public_key_files", str(certs / "oauth.pem"),
                "--accepted_jwt_audiences", "localhost",
            ],
            "dss-restart",
        )
        base = f"http://127.0.0.1:{port}"
        wait_healthy(f"{base}/healthy", p.p, "dss-restart")
        return p, base

    p, base = launch()
    try:
        r = requests.put(
            f"{base}/v1/dss/identification_service_areas/{isa_id}",
            json=isa_params(lat=43.9),
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        version = r.json()["service_area"]["version"]
    finally:
        p.stop()

    p, base = launch()
    try:
        r = requests.get(
            f"{base}/v1/dss/identification_service_areas/{isa_id}",
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        assert r.json()["service_area"]["version"] == version
    finally:
        p.stop()


def test_region_two_instance_interop_over_http(region_stack):
    """interop_test_suite.py over the wire: write on instance A, read
    on instance B; then the two-USS OVN conflict where each USS talks
    to a DIFFERENT DSS instance of the region."""
    a, b = region_stack["bases"]
    oauth = region_stack["oauth"]
    lat = 44.9

    # RID: create on A, visible on B (bounded staleness)
    isa_id = str(uuid.uuid4())
    r = requests.put(
        f"{a}/v1/dss/identification_service_areas/{isa_id}",
        json=isa_params(lat=lat),
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    version = r.json()["service_area"]["version"]

    _wait_visible(b, isa_id, oauth, version=version)

    # SCD: USS1 -> instance A; USS2 -> instance B without the key: 409
    op1, op2 = str(uuid.uuid4()), str(uuid.uuid4())
    r = requests.put(
        f"{a}/dss/v1/operation_references/{op1}",
        json=op_body("uss1", lat=lat),
        headers=oauth.hdr(SCD_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    ovn1 = r.json()["operation_reference"]["ovn"]

    deadline = time.monotonic() + VISIBILITY_DEADLINE_S
    while True:
        r = requests.put(
            f"{b}/dss/v1/operation_references/{op2}",
            json=op_body("uss2", lat=lat),
            headers=oauth.hdr(SCD_SCOPE, sub="uss2"),
            timeout=5,
        )
        if r.status_code == 409:
            refs = [
                c["operation_reference"]
                for c in r.json()["entity_conflicts"]
            ]
            assert any(ref["id"] == op1 for ref in refs)
            assert ovn1 in [ref.get("ovn") for ref in refs]
            break
        # A's write may not have tailed to B yet: a 200 here would be
        # a real conflict-miss bug once B is caught up, so only accept
        # it before the deadline
        assert r.status_code == 200, r.text
        requests.delete(
            f"{b}/dss/v1/operation_references/{op2}",
            headers=oauth.hdr(SCD_SCOPE, sub="uss2"),
            timeout=5,
        )
        assert time.monotonic() < deadline, (
            "conflict never detected across instances"
        )
        time.sleep(0.05)

    # with the key, accepted on B
    r = requests.put(
        f"{b}/dss/v1/operation_references/{op2}",
        json=op_body("uss2", lat=lat, key=[ovn1]),
        headers=oauth.hdr(SCD_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 200, r.text


@pytest.fixture(scope="module")
def replica_stack(certs, oauth, tmp_path_factory):
    """One server binary with --sharded_replica: it tails its own WAL
    into per-class ShardedDars on an 8-virtual-device mesh and serves
    /aux/v1/replica/{surface} for ALL FOUR entity classes.  The
    fixture seeds one entity per class at the same lat."""
    wal = tmp_path_factory.mktemp("replicawal") / "dss.wal"
    port = free_port()
    p = Proc(
        [
            "dss_tpu.cmds.server",
            "--addr", f":{port}",
            "--enable_scd",
            "--storage", "memory",
            "--wal_path", str(wal),
            "--virtual_cpu_devices", "8",
            "--sharded_replica", "2,4",
            "--replica_refresh_interval", "0.1",
            "--public_key_files", str(certs / "oauth.pem"),
            "--accepted_jwt_audiences", AUD,
        ],
        "dss-replica",
    )
    base = f"http://127.0.0.1:{port}"
    try:
        wait_healthy(f"{base}/healthy", p.p, "dss-replica")
        lat = 46.3
        op_id = str(uuid.uuid4())
        r = requests.put(
            f"{base}/dss/v1/operation_references/{op_id}",
            json=op_body("uss1", lat=lat),
            headers=oauth.hdr(SCD_SCOPE, sub="uss1"),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        isa_id = str(uuid.uuid4())
        r = requests.put(
            f"{base}/v1/dss/identification_service_areas/{isa_id}",
            json=isa_params(lat=lat),
            headers=oauth.hdr(RID_SCOPE, sub="uss1"),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        rid_sub_id = str(uuid.uuid4())
        sub_params = isa_params(lat=lat)
        del sub_params["flights_url"]
        sub_params["callbacks"] = {
            "identification_service_area_url":
                "https://uss1.example.com/isa"
        }
        r = requests.put(
            f"{base}/v1/dss/subscriptions/{rid_sub_id}",
            json=sub_params,
            headers=oauth.hdr(RID_SCOPE, sub="uss1"),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        scd_sub_id = str(uuid.uuid4())
        r = requests.put(
            f"{base}/dss/v1/subscriptions/{scd_sub_id}",
            json={
                "extents": scd_extent(lat=lat),
                "uss_base_url": "https://uss1.example.com",
                "notify_for_operations": True,
                "old_version": 0,
            },
            headers=oauth.hdr(SCD_SCOPE, sub="uss1"),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        yield {
            "base": base,
            "oauth": oauth,
            "area": area_str(lat=lat),
            "expect": {
                "operations": op_id,
                "identification_service_areas": isa_id,
                "subscriptions": rid_sub_id,
                "scd_subscriptions": scd_sub_id,
            },
        }
    finally:
        p.stop()


# surface -> (response key, read scope, owner-scoped, stats class)
REPLICA_SURFACES = {
    "operations": ("operation_ids", SCD_SCOPE, False, "ops"),
    "identification_service_areas": (
        "service_area_ids", RID_SCOPE, False, "isas"
    ),
    "subscriptions": ("subscription_ids", RID_SCOPE, True, "rid_subs"),
    "scd_subscriptions": (
        "subscription_ids", SCD_SCOPE, True, "scd_subs"
    ),
}


@pytest.mark.parametrize("surface", sorted(REPLICA_SURFACES))
def test_sharded_replica_surface_serves_every_class(
    replica_stack, surface
):
    """/aux/v1/replica/{surface} end to end for ALL FOUR entity
    classes (api/app.py replica_surfaces): the entity written through
    the normal API tails into the mesh replica and comes back from the
    sharded query; subscription surfaces are owner-scoped."""
    base, oauth = replica_stack["base"], replica_stack["oauth"]
    area = replica_stack["area"]
    out_key, scope, owner_scoped, stats_cls = REPLICA_SURFACES[surface]
    want = replica_stack["expect"][surface]
    deadline = time.monotonic() + 120  # first mesh compile is slow
    while True:
        r = requests.get(
            f"{base}/aux/v1/replica/{surface}",
            params={"area": area},
            headers=oauth.hdr(scope, sub="uss1"),
            timeout=90,
        )
        # a cold larger-K bucket may still be compiling: a 504
        # (deadline) is acceptable while polling, anything else is a
        # bug
        if r.status_code == 504:
            assert time.monotonic() < deadline, "compile never finished"
            time.sleep(0.3)
            continue
        assert r.status_code == 200, r.text
        body = r.json()
        if want in body[out_key]:
            break
        assert time.monotonic() < deadline, body
        time.sleep(0.3)
    assert (
        body["replica"][f"replica_{stats_cls}_snapshot_records"] >= 1
    )
    if owner_scoped:
        # subscription ids are owner-private: a different owner must
        # not see this one
        r = requests.get(
            f"{base}/aux/v1/replica/{surface}",
            params={"area": area},
            headers=oauth.hdr(scope, sub="ussother"),
            timeout=90,
        )
        assert r.status_code == 200, r.text
        assert want not in r.json()[out_key]
    # auth enforced on every replica surface
    assert (
        requests.get(
            f"{base}/aux/v1/replica/{surface}",
            params={"area": area},
            timeout=5,
        ).status_code
        == 401
    )


def test_region_log_server_crash_and_recovery(
    certs, oauth, tmp_path_factory
):
    """Failure detection + recovery at the process level (SURVEY.md
    §5): SIGKILL the region log server mid-region.  Instances keep
    serving reads (bounded staleness), writes fail with a 5xx instead
    of corrupting state, and after the log server restarts on the same
    WAL the region resumes: old data intact, new writes commit and
    replicate cross-instance."""
    wal = tmp_path_factory.mktemp("regioncrash") / "region.wal"
    log_port = free_port()
    log_base = f"http://127.0.0.1:{log_port}"

    log_procs = []

    def launch_log():
        p = Proc(
            [
                "dss_tpu.cmds.region_server",
                "--addr", f":{log_port}",
                "--wal_path", str(wal),
            ],
            "region-server-crash",
        )
        log_procs.append(p)  # tracked before health wait: no leak path
        wait_healthy(f"{log_base}/healthy", p.p, "region-server-crash")
        return p

    instances, bases = [], []
    try:
        log_proc = launch_log()
        for i in range(2):
            port = free_port()
            p = Proc(
                [
                    "dss_tpu.cmds.server",
                    "--addr", f":{port}",
                    "--enable_scd",
                    "--storage", "memory",
                    "--region_url", log_base,
                    "--region_poll_interval", "0.02",
                    "--instance_id", f"crash-dss-{i}",
                    "--public_key_files", str(certs / "oauth.pem"),
                    "--accepted_jwt_audiences", AUD,
                ],
                f"crash-dss-{i}",
            )
            instances.append(p)
            bases.append(f"http://127.0.0.1:{port}")
        for i, b in enumerate(bases):
            wait_healthy(f"{b}/healthy", instances[i].p, f"crash-dss-{i}")
        a, b = bases
        lat = 46.3

        isa1 = str(uuid.uuid4())
        r = requests.put(
            f"{a}/v1/dss/identification_service_areas/{isa1}",
            json=isa_params(lat=lat),
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        assert r.status_code == 200, r.text
        _wait_visible(b, isa1, oauth)

        # hard-kill the log server (no drain, no snapshot upload)
        log_proc.p.kill()
        log_proc.p.wait(timeout=10)

        # writes now fail loudly with a 5xx...
        isa_failed = str(uuid.uuid4())
        r = requests.put(
            f"{a}/v1/dss/identification_service_areas/{isa_failed}",
            json=isa_params(lat=lat + 0.5),
            headers=oauth.hdr(RID_SCOPE),
            timeout=15,
        )
        assert r.status_code >= 500, r.text
        # ...while reads keep serving the replicated state
        r = requests.get(
            f"{a}/v1/dss/identification_service_areas/{isa1}",
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        assert r.status_code == 200, r.text

        # restart on the same WAL: the region resumes
        log_proc = launch_log()
        isa2 = str(uuid.uuid4())
        deadline = time.monotonic() + 20.0
        while True:
            r = requests.put(
                f"{a}/v1/dss/identification_service_areas/{isa2}",
                json=isa_params(lat=lat + 1.0),
                headers=oauth.hdr(RID_SCOPE),
                timeout=15,
            )
            if r.status_code == 200:
                break
            assert time.monotonic() < deadline, (
                f"write never recovered: {r.status_code} {r.text}"
            )
            time.sleep(0.25)
        # old data intact everywhere, new write replicates to B, and
        # the failed-during-outage write was rolled back, not
        # half-applied (undo-list rollback, region/coordinator.py)
        for base in (a, b):
            r = requests.get(
                f"{base}/v1/dss/identification_service_areas/{isa1}",
                headers=oauth.hdr(RID_SCOPE),
                timeout=5,
            )
            assert r.status_code == 200, (base, r.text)
            r = requests.get(
                f"{base}/v1/dss/identification_service_areas/{isa_failed}",
                headers=oauth.hdr(RID_SCOPE),
                timeout=5,
            )
            assert r.status_code == 404, (base, r.text)
        _wait_visible(b, isa2, oauth)
    finally:
        for p in instances:
            p.stop()
        for p in log_procs:
            p.stop()


def _wait_visible(base, isa_id, oauth, version=None):
    """Poll until the ISA is GETtable on `base` (bounded-staleness
    replication deadline); optionally pin the replicated version."""
    deadline = time.monotonic() + VISIBILITY_DEADLINE_S
    while True:
        r = requests.get(
            f"{base}/v1/dss/identification_service_areas/{isa_id}",
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        if r.status_code == 200:
            if version is not None:
                assert r.json()["service_area"]["version"] == version
            return
        assert time.monotonic() < deadline, f"{isa_id} never visible"
        time.sleep(0.05)


def test_multiworker_serving_read_your_writes(
    certs, oauth, tmp_path_factory
):
    """--workers N at the binary level (the goroutine-per-RPC scale-out
    analog, grpc-backend main.go:201-214): the leader owns mutations,
    SO_REUSEPORT read workers serve searches from a WAL-tail replica
    and proxy writes.  Pins: (a) a client that keeps its connection
    sees its own writes immediately (the proxying worker waits for its
    tail to reach the leader's WAL seq), (b) fresh connections see the
    write within the bounded-staleness deadline, (c) deletes propagate
    the same way."""
    wal = tmp_path_factory.mktemp("workerswal") / "dss.wal"
    port = free_port()
    base = f"http://127.0.0.1:{port}"
    p = Proc(
        [
            "dss_tpu.cmds.server",
            "--addr", f":{port}",
            "--storage", "memory",
            "--wal_path", str(wal),
            "--workers", "2",
            "--follower_poll_interval", "0.02",
            "--public_key_files", str(certs / "oauth.pem"),
            "--accepted_jwt_audiences", AUD,
        ],
        "dss-workers",
    )
    try:
        wait_healthy(f"{base}/healthy", p.p, "dss-workers")
        lat = 48.6
        h = oauth.hdr(RID_SCOPE)

        # (a) same-connection write -> immediate search must hit,
        # repeatedly (the kernel spreads fresh connections across the
        # listeners; a kept session stays on whichever it landed on)
        for i in range(6):
            s = requests.Session()
            isa_id = str(uuid.uuid4())
            r = s.put(
                f"{base}/v1/dss/identification_service_areas/{isa_id}",
                json=isa_params(lat=lat),
                headers=h,
                timeout=10,
            )
            assert r.status_code == 200, (i, r.text)
            version = r.json()["service_area"]["version"]
            r = s.get(
                f"{base}/v1/dss/identification_service_areas",
                params={"area": area_str(lat=lat)},
                headers=h,
                timeout=10,
            )
            assert r.status_code == 200, (i, r.text)
            found = {a["id"] for a in r.json()["service_areas"]}
            assert isa_id in found, (
                f"iteration {i}: read-your-writes violated"
            )
            # (c) delete through the same connection, same guarantee
            r = s.delete(
                f"{base}/v1/dss/identification_service_areas/"
                f"{isa_id}/{version}",
                headers=h,
                timeout=10,
            )
            assert r.status_code == 200, (i, r.text)
            r = s.get(
                f"{base}/v1/dss/identification_service_areas",
                params={"area": area_str(lat=lat)},
                headers=h,
                timeout=10,
            )
            assert r.status_code == 200, (i, r.text)
            assert isa_id not in {
                a["id"] for a in r.json()["service_areas"]
            }, f"iteration {i}: deleted ISA still served"
            s.close()

        # (b) fresh connections (no session reuse): bounded staleness
        isa_id = str(uuid.uuid4())
        r = requests.put(
            f"{base}/v1/dss/identification_service_areas/{isa_id}",
            json=isa_params(lat=lat),
            headers=h,
            timeout=10,
        )
        assert r.status_code == 200, r.text
        # requests without a Session open a new connection each call
        _wait_visible(base, isa_id, oauth)
    finally:
        p.stop()
