"""More prober-parity black-box scenarios against the live binaries.

Ports of the remaining reference prober files to the REST surface:
  - monitoring/prober/rid/test_token_validation.py (DSS0010 auth)
  - monitoring/prober/rid/test_subscription_simple.py
  - monitoring/prober/rid/test_isa_validation.py
  - monitoring/prober/scd/test_subscription_simple.py
"""

from __future__ import annotations

import os
import uuid

import pytest
import requests

from tests.e2e.test_blackbox import (
    RID_SCOPE,
    SCD_SCOPE,
    area_str,
    isa_params,
    now_iso,
    scd_extent,
)

RID_READ = "dss.read.identification_service_areas"


def test_token_validation_dss0010(stack):
    """DSS0010: no token, undecodable token, wrong-scope writes."""
    base, oauth = stack["base"], stack["oauth"]
    isa_id = str(uuid.uuid4())
    url = f"{base}/v1/dss/identification_service_areas/{isa_id}"

    # no token -> 401
    assert requests.get(url, timeout=5).status_code == 401
    # garbage token -> 401
    r = requests.get(
        url, headers={"Authorization": "Bearer not.a.jwt"}, timeout=5
    )
    assert r.status_code == 401
    # read-only scope cannot write -> 403
    r = requests.put(
        url,
        json=isa_params(lat=47.1),
        headers=oauth.hdr(RID_READ),
        timeout=5,
    )
    assert r.status_code == 403
    # validate_oauth owner mismatch -> 403; match -> 200
    r = requests.get(
        f"{base}/aux/v1/validate_oauth",
        params={"owner": "bad_user"},
        headers=oauth.hdr(RID_SCOPE, sub="fake_uss"),
        timeout=5,
    )
    assert r.status_code == 403
    r = requests.get(
        f"{base}/aux/v1/validate_oauth",
        params={"owner": "fake_uss"},
        headers=oauth.hdr(RID_SCOPE, sub="fake_uss"),
        timeout=5,
    )
    assert r.status_code == 200


def test_rid_subscription_lifecycle(stack):
    """prober/rid/test_subscription_simple.py over the wire."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(RID_SCOPE, sub="uss-sub")
    sub_id = str(uuid.uuid4())
    lat = 47.5
    url = f"{base}/v1/dss/subscriptions/{sub_id}"

    # does not exist yet
    assert requests.get(url, headers=h, timeout=5).status_code == 404

    body = {
        "extents": isa_params(lat=lat)["extents"],
        "callbacks": {
            "identification_service_area_url": "https://u.example/isa"
        },
    }
    r = requests.put(url, json=body, headers=h, timeout=5)
    assert r.status_code == 200, r.text
    version = r.json()["subscription"]["version"]
    assert version

    # get by id + by search
    r = requests.get(url, headers=h, timeout=5)
    assert r.status_code == 200
    assert r.json()["subscription"]["version"] == version
    r = requests.get(
        f"{base}/v1/dss/subscriptions",
        params={"area": area_str(lat=lat)},
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200
    assert any(
        s["id"] == sub_id for s in r.json()["subscriptions"]
    )
    # huge search area -> 413 (test_get_sub_by_searching_huge_area)
    huge = "-1,-1,-1,1,1,1,1,-1"
    r = requests.get(
        f"{base}/v1/dss/subscriptions",
        params={"area": huge},
        headers=h,
        timeout=5,
    )
    assert r.status_code == 413, r.text

    # unparseable version -> 400 (reference prober
    # test_delete_sub_wrong_version; the reference app otherwise
    # ignores the supplied version on sub delete —
    # application/subscription.go:84-100, reproduced)
    r = requests.delete(f"{url}/fake_version", headers=h, timeout=5)
    assert r.status_code == 400, r.text
    r = requests.delete(f"{url}/{version}", headers=h, timeout=5)
    assert r.status_code == 200, r.text
    # gone from get + search
    assert requests.get(url, headers=h, timeout=5).status_code == 404
    r = requests.get(
        f"{base}/v1/dss/subscriptions",
        params={"area": area_str(lat=lat)},
        headers=h,
        timeout=5,
    )
    assert not any(
        s["id"] == sub_id for s in r.json()["subscriptions"]
    )


def test_isa_validation_rejections(stack):
    """prober/rid/test_isa_validation.py: malformed/oversized ISAs."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(RID_SCOPE)

    def put(body):
        return requests.put(
            f"{base}/v1/dss/identification_service_areas/{uuid.uuid4()}",
            json=body,
            headers=h,
            timeout=5,
        )

    good = isa_params(lat=48.0)

    # huge area -> 413
    huge = isa_params(lat=48.0)
    huge["extents"]["spatial_volume"]["footprint"]["vertices"] = [
        {"lat": -1.0, "lng": -1.0},
        {"lat": -1.0, "lng": 1.0},
        {"lat": 1.0, "lng": 1.0},
        {"lat": 1.0, "lng": -1.0},
    ]
    assert put(huge).status_code == 413

    # empty vertices -> 400
    bad = isa_params(lat=48.0)
    bad["extents"]["spatial_volume"]["footprint"]["vertices"] = []
    assert put(bad).status_code == 400

    # missing footprint -> 400
    bad = isa_params(lat=48.0)
    del bad["extents"]["spatial_volume"]["footprint"]
    assert put(bad).status_code == 400

    # missing extents entirely -> 400
    assert put({"flights_url": "https://x/f"}).status_code == 400

    # start after end -> 400
    bad = isa_params(lat=48.0)
    bad["extents"]["time_start"] = now_iso(3600)
    bad["extents"]["time_end"] = now_iso(60)
    assert put(bad).status_code == 400

    # off-earth coordinates -> 400
    bad = isa_params(lat=48.0)
    bad["extents"]["spatial_volume"]["footprint"]["vertices"] = [
        {"lat": 130.0, "lng": 250.0},
        {"lat": 131.0, "lng": 250.0},
        {"lat": 131.0, "lng": 251.0},
    ]
    assert put(bad).status_code == 400

    # the good one still goes through (the gate rejects, not the stack)
    assert put(good).status_code == 200


def test_scd_subscription_lifecycle(stack):
    """prober/scd/test_subscription_simple.py over the wire."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(SCD_SCOPE, sub="uss-scd-sub")
    sub_id = str(uuid.uuid4())
    lat = 48.7
    url = f"{base}/dss/v1/subscriptions/{sub_id}"

    body = {
        "extents": scd_extent(lat=lat),
        "uss_base_url": "https://uss.example.com",
        "notify_for_operations": True,
        "notify_for_constraints": False,
        "old_version": 0,
    }
    r = requests.put(url, json=body, headers=h, timeout=5)
    assert r.status_code == 200, r.text
    assert r.json()["subscription"]["id"] == sub_id

    r = requests.get(url, headers=h, timeout=5)
    assert r.status_code == 200
    assert r.json()["subscription"]["notify_for_operations"] is True

    # query by area
    r = requests.post(
        f"{base}/dss/v1/subscriptions/query",
        json={"area_of_interest": scd_extent(lat=lat)},
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200, r.text
    assert any(
        s["id"] == sub_id for s in r.json()["subscriptions"]
    )

    r = requests.delete(url, headers=h, timeout=5)
    assert r.status_code == 200, r.text
    assert requests.get(url, headers=h, timeout=5).status_code == 404


_FIXTURES = "/root/reference/monitoring/prober/scd/resources"


def _load_fixture(name):
    import json

    with open(f"{_FIXTURES}/{name}.json") as fh:
        return json.load(fh)


def _refresh_times(req):
    for e in req.get("extents", []):
        e["time_start"]["value"] = now_iso(60)
        e["time_end"]["value"] = now_iso(3600)
    aoi = req.get("area_of_interest")
    if aoi:
        aoi["time_start"]["value"] = now_iso(60)
        aoi["time_end"]["value"] = now_iso(3600)
    return req


@pytest.mark.skipif(
    not os.path.isdir(_FIXTURES),
    reason="reference prober fixtures not present on this machine",
)
def test_scd_operation_fixture_requests(stack):
    """prober/scd/test_operation_special_cases.py with the reference's
    own canned request bodies (resources/op_request_*.json), timestamps
    refreshed (the originals are from 2020).

    op_request_1 (5-volume union): accepted then deleted, as in the
    reference.  op_request_2 (a ~1500 km degenerate sliver quad): we
    reject it 413 AreaTooLarge — the prober expected 400 from the
    deployed 2020 build via a path not reproducible from the reference
    source (geo.Covering maps oversized loops to 413 and performs no
    loop validation); either way the request is refused with a 4xx and
    no state change.  op_request_3 (a query whose polygon is one point
    repeated three times — zero area): the polyline fallback answers
    200, as in the reference."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(SCD_SCOPE, sub="fixture-uss")

    req = _refresh_times(_load_fixture("op_request_1"))
    op_id = str(uuid.uuid4())
    r = requests.put(
        f"{base}/dss/v1/operation_references/{op_id}",
        json=req,
        headers=h,
        timeout=10,
    )
    assert r.status_code == 200, r.text
    r = requests.delete(
        f"{base}/dss/v1/operation_references/{op_id}",
        headers=h,
        timeout=10,
    )
    assert r.status_code == 200, r.text

    req = _refresh_times(_load_fixture("op_request_2"))
    r = requests.put(
        f"{base}/dss/v1/operation_references/{uuid.uuid4()}",
        json=req,
        headers=h,
        timeout=10,
    )
    assert r.status_code == 413, r.text  # our documented mapping

    req = _refresh_times(_load_fixture("op_request_3"))
    r = requests.post(
        f"{base}/dss/v1/operation_references/query",
        json=req,
        headers=h,
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert "operation_references" in r.json()


def test_isa_expiry(stack):
    """prober/rid/test_isa_expiry.py: an expired ISA stays GETtable by
    id but disappears from search results."""
    import time as _time

    base, oauth = stack["base"], stack["oauth"]
    isa_id = str(uuid.uuid4())
    lat = 44.2
    body = isa_params(t0=0, t1=6, lat=lat)  # expires in ~6s
    r = requests.put(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        json=body,
        headers=oauth.hdr(RID_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text

    # valid immediately: by id AND by search
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    assert r.status_code == 200
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas",
        params={"area": area_str(lat=lat)},
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    assert isa_id in [x["id"] for x in r.json()["service_areas"]]

    # wait out the expiry (poll instead of a fixed sleep: a loaded
    # host must not flake this)
    deadline = _time.monotonic() + 30
    while True:
        r = requests.get(
            f"{base}/v1/dss/identification_service_areas",
            params={"area": area_str(lat=lat)},
            headers=oauth.hdr(RID_SCOPE),
            timeout=5,
        )
        if isa_id not in [x["id"] for x in r.json()["service_areas"]]:
            break
        assert _time.monotonic() < deadline, "ISA never expired"
        _time.sleep(0.5)

    # still returned by id (reference: expired ISAs remain GETtable)...
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    assert r.status_code == 200


def test_subscription_isa_interactions(stack):
    """prober/rid/test_subscription_isa_interactions.py: the
    notification-index increments ride the ISA mutation responses with
    the reference's exact subscriber shape."""
    base, oauth = stack["base"], stack["oauth"]
    lat = 45.6
    isa_id = str(uuid.uuid4())
    sub_id = str(uuid.uuid4())

    r = requests.put(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        json=isa_params(lat=lat),
        headers=oauth.hdr(RID_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text

    # subscription creation response includes the overlapping ISA and
    # starts at notification_index 0
    sub_body = {
        "extents": isa_params(lat=lat)["extents"],
        "callbacks": {
            "identification_service_area_url": "https://example.com/foo"
        },
    }
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{sub_id}",
        json=sub_body,
        headers=oauth.hdr(RID_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    data = r.json()
    assert data["subscription"]["notification_index"] == 0
    assert isa_id in [x["id"] for x in data["service_areas"]]

    # modifying the ISA bumps the sub to index 1, with the reference's
    # exact subscriber shape (url + [{notification_index, subscription_id}])
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    version = r.json()["service_area"]["version"]
    r = requests.put(
        f"{base}/v1/dss/identification_service_areas/{isa_id}/{version}",
        json=isa_params(lat=lat),
        headers=oauth.hdr(RID_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    assert {
        "url": "https://example.com/foo",
        "subscriptions": [
            {"notification_index": 1, "subscription_id": sub_id},
        ],
    } in r.json()["subscribers"]

    # deleting the ISA bumps it to 2
    r = requests.get(
        f"{base}/v1/dss/identification_service_areas/{isa_id}",
        headers=oauth.hdr(RID_SCOPE),
        timeout=5,
    )
    version = r.json()["service_area"]["version"]
    r = requests.delete(
        f"{base}/v1/dss/identification_service_areas/{isa_id}/{version}",
        headers=oauth.hdr(RID_SCOPE, sub="uss1"),
        timeout=5,
    )
    assert r.status_code == 200, r.text
    assert {
        "url": "https://example.com/foo",
        "subscriptions": [
            {"notification_index": 2, "subscription_id": sub_id},
        ],
    } in r.json()["subscribers"]

    # cleanup: delete the subscription at its current version
    r = requests.get(
        f"{base}/v1/dss/subscriptions/{sub_id}",
        headers=oauth.hdr(RID_SCOPE, sub="uss2"),
        timeout=5,
    )
    version = r.json()["subscription"]["version"]
    r = requests.delete(
        f"{base}/v1/dss/subscriptions/{sub_id}/{version}",
        headers=oauth.hdr(RID_SCOPE, sub="uss2"),
        timeout=5,
    )
    assert r.status_code == 200, r.text


def test_rid_subscription_validation(stack):
    """prober/rid/test_subscription_validation.py over the wire:
    DSS0050 per-area quota (11th subscription in one area -> 429),
    DSS0060 max duration (>24h -> 400), and footprint validation
    (empty vertices -> 400), mirroring the reference's expectations
    (test_create_too_many_subs, test_create_sub_with_too_long_end_time,
    test_create_sub_empty_vertices)."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(RID_SCOPE, sub="quota-uss")
    lat = 44.25  # an area no other test touches

    def sub_body(**kw):
        return {
            "extents": isa_params(lat=lat, **kw)["extents"],
            "callbacks": {
                "identification_service_area_url": "https://u.example/i"
            },
        }

    # DSS0060: duration beyond 24h is refused outright
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{uuid.uuid4()}",
        json=sub_body(t1=25 * 3600),
        headers=h,
        timeout=5,
    )
    assert r.status_code == 400, r.text

    # footprint with no vertices is a 400, not a covering crash
    bad = sub_body()
    bad["extents"]["spatial_volume"]["footprint"]["vertices"] = []
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{uuid.uuid4()}",
        json=bad,
        headers=h,
        timeout=5,
    )
    assert r.status_code == 400, r.text

    # DSS0050: ten subscriptions in one area succeed, the eleventh is
    # rejected 429 and the successful ten remain intact
    created = []
    for i in range(10):
        sid = str(uuid.uuid4())
        r = requests.put(
            f"{base}/v1/dss/subscriptions/{sid}",
            json=sub_body(),
            headers=h,
            timeout=5,
        )
        assert r.status_code == 200, (i, r.text)
        created.append((sid, r.json()["subscription"]["version"]))
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{uuid.uuid4()}",
        json=sub_body(),
        headers=h,
        timeout=5,
    )
    assert r.status_code == 429, r.text
    r = requests.get(
        f"{base}/v1/dss/subscriptions",
        params={"area": area_str(lat=lat)},
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200
    got = {s["id"] for s in r.json()["subscriptions"]}
    assert {sid for sid, _ in created} <= got
    # quota releases as subscriptions are deleted
    sid0, ver0 = created[0]
    r = requests.delete(
        f"{base}/v1/dss/subscriptions/{sid0}/{ver0}",
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200, r.text
    sid_extra = str(uuid.uuid4())
    r = requests.put(
        f"{base}/v1/dss/subscriptions/{sid_extra}",
        json=sub_body(),
        headers=h,
        timeout=5,
    )
    assert r.status_code == 200, r.text
    created = created[1:] + [
        (sid_extra, r.json()["subscription"]["version"])
    ]
    # cleanup: leave the area empty so re-runs (and future tests using
    # this latitude) don't start at full quota
    for sid, ver in created:
        r = requests.delete(
            f"{base}/v1/dss/subscriptions/{sid}/{ver}",
            headers=h,
            timeout=5,
        )
        assert r.status_code == 200, r.text


def test_scd_subscription_id_conversion(stack):
    """prober/scd/test_subscription_id_conversion.py (reference issue
    #314): create an SCD subscription under a fixed UUID, then update
    it with old_version=1 — both PUTs must succeed and keep the same
    id.  Note the reference accepts a plain-http uss_base_url on
    explicit subscriptions (only operations' implicit subscriptions
    validate https, operations_handler.go:221), reproduced here."""
    base, oauth = stack["base"], stack["oauth"]
    h = oauth.hdr(SCD_SCOPE, sub="conv-uss")
    sub_id = "b61a6450-db42-4d0d-91f2-7c1334eda399"
    url = f"{base}/dss/v1/subscriptions/{sub_id}"
    body = {
        "extents": scd_extent(lat=41.68, lng=-91.49),
        "old_version": 0,
        "uss_base_url": "http://localhost:12012/services/uss/public/uss/v1/",
        "notify_for_constraints": True,
    }
    r = requests.put(url, json=body, headers=h, timeout=5)
    assert r.status_code == 200, r.text
    assert r.json()["subscription"]["id"] == sub_id

    body["extents"] = scd_extent(t0=120, lat=41.68, lng=-91.49)
    body["old_version"] = 1
    r = requests.put(url, json=body, headers=h, timeout=5)
    assert r.status_code == 200, r.text
    got = r.json()["subscription"]
    assert got["id"] == sub_id
    # cleanup so other SCD tests see a clean area
    r = requests.delete(url, headers=h, timeout=5)
    assert r.status_code == 200, r.text
